"""Game server — hosts a :class:`World` and connects it to the cluster.

Reference being rebuilt: ``components/game/GameService.go`` (the packet
switch + tick serve loop, ``:77-190``) and ``components/game/game.go``
(boot sequence ``:65-135``). The reference's single logic goroutine becomes
a single logic *thread* driving ``World.tick()``; asyncio networking runs on
a background thread and exchanges packets with the logic thread through a
queue — the same "logic is single-threaded, I/O is concurrent" shape
(``SURVEY.md#1``).

Outbound client traffic: per-record messages (create/destroy/attr/rpc) are
sent as they happen; position sync records batch per gate per tick into one
``MT_SYNC_POSITION_YAW_ON_CLIENTS`` packet (the reference collects these in
``CollectEntitySyncInfos`` and ships per-gate packets, ``Entity.go:1208-1267``).
"""

from __future__ import annotations

import asyncio
import gc as _gc
import os
import threading
import time
from typing import Callable

import numpy as np

from goworld_tpu.entity.entity import Entity, GameClient
from goworld_tpu.entity.manager import World
from goworld_tpu.net import codec, proto
from goworld_tpu.net.cluster import DispatcherCluster, DispatcherConn
from goworld_tpu.net.packet import Packet, new_packet
from goworld_tpu.utils import consts, faults, flightrec, log, metrics, \
    opmon, overload, syncage, tracing

logger = log.get("game")

# module-level like the opmon.expose twins (one game per process; tests
# drive _mh_drain_pending on stubs that bypass __init__)
_m_mh_backlog_pkts = metrics.gauge("mh_mutation_backlog_packets")
_m_mh_backlog_bytes = metrics.gauge("mh_mutation_backlog_bytes")

# Dispatcher packets that MUTATE the World. Under a multi-controller
# (multihost) World these land on ONE controller's dispatcher connection
# but must be applied on ALL controllers in the same tick (the SPMD
# contract, parallel/multihost.py) — so they are queued raw and exchanged
# through a per-tick allgather before World.tick (see
# _mh_exchange_mutations). The reference has no analog: its dispatcher
# star routes each packet to the single game hosting the entity
# (DispatcherService.go); here one World spans every controller.
_MH_WORLD_MSGTYPES = frozenset({
    proto.MT_NOTIFY_CLIENT_CONNECTED,
    proto.MT_NOTIFY_CLIENT_DISCONNECTED,
    proto.MT_NOTIFY_GATE_DISCONNECTED,
    proto.MT_SYNC_POSITION_YAW_FROM_CLIENT,
    proto.MT_CALL_ENTITY_METHOD,
    proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT,
    proto.MT_CREATE_ENTITY_ANYWHERE,
    proto.MT_LOAD_ENTITY_ANYWHERE,
    proto.MT_CALL_NIL_SPACES,
    # kvreg updates drive service-shard decisions (entity/service.py);
    # logging them makes the kvreg mirror SPMD-consistent at the
    # tick-driven reconcile points, so every controller of the group
    # reaches the same claim/create conclusions
    proto.MT_KVREG_REGISTER,
})

# The subset of _MH_WORLD_MSGTYPES the dispatcher BROADCASTS to every
# game connection (the rest are eid/owner-routed and reach exactly one
# controller). Each of a group's N controllers receives its own copy,
# so only the LEADER logs them — otherwise the allgather union would
# replay every broadcast N times (N nil-space invocations per
# call_nil_spaces, N-fold kvreg watcher fires, ...).
_MH_BROADCAST_MSGTYPES = frozenset({
    proto.MT_KVREG_REGISTER,
    proto.MT_CALL_NIL_SPACES,
    proto.MT_NOTIFY_GATE_DISCONNECTED,
})


class GameServer:
    """One game process: a World + connections to every dispatcher."""

    def __init__(
        self,
        game_id: int,
        world: World,
        dispatcher_addrs: list[tuple[str, int]],
        *,
        boot_entity: str = "Account",
        ban_boot: bool = False,
        tick_interval: float = 1.0 / consts.TICK_HZ,
        freeze_dir: str = ".",
        restore: bool = False,
        checkpoint_interval: float = 0.0,
        gc_freeze_on_boot: bool = True,
        pend_max_packets: int = consts.MAX_RECONNECT_PEND_PACKETS,
        pend_max_bytes: int = consts.MAX_RECONNECT_PEND_BYTES,
        overload_enabled: bool = True,
        overload_up_ticks: int = consts.OVERLOAD_UP_TICKS,
        overload_down_ticks: int = consts.OVERLOAD_DOWN_TICKS,
        overload_latency_ratio: float = consts.OVERLOAD_LATENCY_RATIO,
        degraded_sync_stride: int = consts.DEGRADED_SYNC_STRIDE,
        degraded_event_coalesce: int = consts.DEGRADED_EVENT_COALESCE_TICKS,
        flightrec_ring: int = flightrec.DEFAULT_RING,
        flightrec_cooldown_secs: float = flightrec.DEFAULT_COOLDOWN_SECS,
        sync_delta: bool = False,
        sync_keyframe_every: int = 16,
        sync_age: bool = True,
        governor_enabled: bool = False,
        governor_window_ticks: int = 64,
        governor_up_windows: int = 2,
        governor_down_windows: int = 2,
        governor_cooldown_windows: int = 4,
        governor_regret_pct: float = 0.25,
        governor_table: str = "",
        audit_scrub_every: int = 0,
        standby_of: int = 0,
        replication_keyframe_every: int = 0,
        replication_queue: int = 4,
        replication_lag_budget_ticks: int = 16,
        rebalance_enabled: bool = False,
        rebalance_batch: int = 64,
    ):
        self.game_id = game_id
        self.world = world
        # SnapshotChain CRC-scrub cadence (ticks; 0 = off): every Nth
        # tick the audit worker walks this game's chain files with
        # read_freeze_file, turning latent on-disk corruption into a
        # named snapshot_crc violation instead of a surprise at the
        # next -restore boot (utils/audit.py, ISSUE 17)
        self.audit_scrub_every = max(0, int(audit_scrub_every))
        self.gc_freeze_on_boot = gc_freeze_on_boot
        self.boot_entity = boot_entity
        self.ban_boot = ban_boot
        self.tick_interval = tick_interval
        # freeze/restore (reference GameService.go:220-313 rs* states)
        self.freeze_dir = freeze_dir
        self.run_state = "running"  # running | freezing | frozen | stopped
        self._freeze_acks: set[int] = set()
        # periodic crash-recovery checkpoint cadence (seconds; 0 = off)
        self.checkpoint_interval = checkpoint_interval
        self._last_ckpt_mono = time.monotonic()
        self._is_restore = False
        if restore:
            from goworld_tpu import freeze as _freeze

            _freeze.restore_from_file(world, freeze_dir)
            self._is_restore = True

        # prioritized ingress: bounded per-class queues drained
        # highest-priority first, so a sync/event flood can neither
        # evict nor delay-behind-it the migration/RPC control plane
        # (utils/overload.py; replaces the old single FIFO queue)
        self._packet_q = overload.ClassQueues(stage="game_queue")
        # overload ladder: observed once per serve-loop tick; NORMAL
        # when disabled (observe() is simply never called)
        self.overload = overload.register(overload.OverloadGovernor(
            f"game{game_id}",
            up_ticks=overload_up_ticks,
            down_ticks=overload_down_ticks,
            latency_ratio=overload_latency_ratio,
        ))
        self.overload_enabled = overload_enabled
        self.degraded_sync_stride = max(1, int(degraded_sync_stride))
        self.degraded_event_coalesce = max(1, int(degraded_event_coalesce))
        self._fanout_tick = 0  # coalesce phase counter (DEGRADED+)
        # shed counters captured at the last sustained-backlog alarm so
        # the alarm can report what was shed SINCE the previous interval
        self._shed_at_alarm: dict[str, float] = {}
        self.cluster = DispatcherCluster(
            dispatcher_addrs, self._on_packet_netthread, self._handshake,
            edge="game->dispatcher",
            pend_max_packets=pend_max_packets,
            pend_max_bytes=pend_max_bytes,
        )
        self._loop: asyncio.AbstractEventLoop | None = None
        self._net_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.deployment_ready = False
        self.ready_event = threading.Event()
        # dispatcher ids that acked our SET_GAME_ID (handshake barrier)
        self.handshake_acks: set[int] = set()
        self.kvreg: dict[str, str] = {}
        # cluster view (reference gameService.onlineGames / GetOnlineGames)
        self.online_games: set[int] = {game_id}
        self.kvreg_watchers: list[Callable[[str, str], None]] = []
        # in-flight outbound migrations: eid -> (entity, space_id, pos)
        self._migrating_out: dict[str, tuple[Entity, str, tuple]] = {}
        # per-gate downstream sync batches for the current tick
        self._sync_out: dict[int, list] = {}
        # delta-compressed sync fan-out (ISSUE 12, [gameN] sync_delta):
        # per-gate DeltaSyncEncoder state; step derived from the
        # world's precision lattice when active (ONE quantizer across
        # device, wire and snapshots), else from the world extent
        self.sync_delta = bool(sync_delta)
        self.sync_keyframe_every = max(1, int(sync_keyframe_every))
        self._sync_encoders: dict[int, "codec.DeltaSyncEncoder"] = {}
        # end-to-end sync-age stamping (utils/syncage.py, [gameN]
        # sync_age, default ON): every sync fan-out batch carries the
        # device-tick epoch that produced it as a 45 B flagged trailer;
        # the gate turns it into age-at-delivery histograms. Off =
        # byte-identical legacy wire.
        self.sync_age = bool(sync_age)
        # downstream sync bytes split BY WIRE MODE so the age plane can
        # correlate staleness with what actually went on the wire:
        # full 48 B records vs delta-codec keyframe vs delta records
        self._m_sync_bytes = {
            kind: metrics.counter(
                "sync_bytes_out",
                help="downstream sync payload bytes by wire mode",
                kind=kind)
            for kind in ("full", "keyframe", "delta")
        }
        self._sync_bytes_mark = {"keyframe": 0, "delta": 0}
        # per-gate ordered (inner_msgtype, body) client messages staged
        # this tick; flushed as ONE MT_CLIENT_EVENTS_BATCH packet per
        # gate (before syncs, so a create precedes its entity's first
        # position sync). Emission order per gate is preserved, so the
        # per-client message order matches the per-message path.
        self._events_out: dict[int, list] = {}
        self._event_recs_flushed = 0  # per-tick gauge accumulator
        # trace context staged per gate for the current tick's client
        # event bundle: set by _client_sink when a traced handler emits
        # client messages, applied to the bundle packet at flush so the
        # gate's egress span stays linked to the inbound RPC's trace
        self._events_trace: dict[int, tracing.TraceContext] = {}
        self.on_deployment_ready: Callable[[], None] | None = None
        # multihost World-mutation log (see _MH_WORLD_MSGTYPES)
        self._mh_pending: list[tuple[int, bytes]] = []
        self._mh_backlog_ticks = 0  # consecutive ticks with carry-over
        self._mh_replaying = False
        self._mh_all_ready = False       # allgathered group readiness
        self._mh_leader_game_id = self.game_id  # allgathered, row 0
        self._mh_freeze_requested = False  # leader sets; exchange spreads
        self._mh_ckpt_due = False          # leader's wall-clock verdict

        # scrapeable serve-loop series (debug_http /metrics): tick
        # latency distribution, fell-behind backlog, queue depths and
        # drop counters — every silent saturation signal gets a name
        self._m_tick_hist = metrics.histogram(
            "tick_latency_ms", help="serve-loop tick wall time")
        # the /costs SLO verdict reads tick_latency_ms against this
        # process's OWN budget (one tick interval) — the paper's 16 ms
        # at the default 60 Hz (utils/devprof, cli status)
        from goworld_tpu.utils import devprof

        devprof.set_slo_target(1000.0 * self.tick_interval)
        self._m_backlog = metrics.gauge(
            "backlog_ticks",
            help="ticks the serve loop is behind its cadence")
        self._m_queue_depth = metrics.gauge(
            "input_queue_depth", help="pending dispatcher packets")
        self._m_pkt_drop = metrics.counter(
            "packet_queue_drop_total",
            help="dispatcher packets dropped on a full input queue")
        self._m_event_records = metrics.counter(
            "client_event_records_total",
            help="client event records flushed downstream")

        # incident flight recorder + live workload signature (ISSUE 11,
        # utils/flightrec.py): one correlated frame per tick; an SLO
        # breach vs this process's OWN tick budget, an overload-ladder
        # transition, an over_cap-after-quiet oracle anomaly or a
        # signature class change freezes a ring-tail bundle served at
        # debug-http /incidents. flightrec_ring=0 disables. Weakrefs
        # throughout: the registries are process-global and must never
        # pin a discarded server's World (the devprof convention).
        import weakref

        wself = weakref.ref(self)
        self.flightrec: flightrec.FlightRecorder | None = None
        self._last_sig: str | None = None
        from goworld_tpu.utils import devprof as _devprof

        # tolerate stub worlds (tests drive GameServer with bare
        # namespaces that carry no device config)
        grid = getattr(getattr(world, "cfg", None), "grid", None)
        self._kernel_key = ",".join(
            f"{k}={v}" for k, v in sorted(
                _devprof.grid_config_key(grid).items())
        ) if grid is not None else "unknown"
        if flightrec_ring > 0:

            def _ctx() -> dict:
                s = wself()
                return {} if s is None else s._incident_context()

            self.flightrec = flightrec.register(
                f"game{game_id}",
                flightrec.FlightRecorder(
                    ring=flightrec_ring,
                    cooldown_secs=flightrec_cooldown_secs,
                    context_fn=_ctx,
                ),
            )

        def _workload() -> dict | None:
            s = wself()
            return None if s is None else s.world.workload_signature()

        flightrec.set_workload_provider(_workload)

        # online kernel governor (ISSUE 13, goworld_tpu/autotune): the
        # workload signature hot-swaps the resolved tick config between
        # ticks. Only the single-shard non-mesh shape qualifies (the
        # candidates toggle the skin's runtime branches); ineligible or
        # telemetry-less worlds get a loud warning, never a crash.
        self.governor = None
        self._gov_last_win = -1
        self._gov_hist_mark: list | None = None
        if governor_enabled:
            try:
                from goworld_tpu import autotune

                table = dict(autotune.seed_table())
                if governor_table:
                    table.update(autotune.parse_table(governor_table))
                self.governor = autotune.register(
                    f"game{game_id}",
                    autotune.KernelGovernor(
                        world, name=f"game{game_id}", table=table,
                        up_windows=governor_up_windows,
                        down_windows=governor_down_windows,
                        cooldown_windows=governor_cooldown_windows,
                        regret_pct=governor_regret_pct,
                    ),
                )
                # the governor's window IS the signature rotation: one
                # decision per drained window (instance attr shadows
                # the class default)
                world.SIG_WINDOW_TICKS = max(8,
                                             int(governor_window_ticks))
                if not getattr(world, "telemetry_live", False):
                    logger.warning(
                        "game%d: governor enabled but telemetry_live "
                        "is off — no signature windows will arrive, "
                        "the config stays static", game_id,
                    )
            except Exception as exc:
                logger.warning(
                    "game%d: kernel governor disabled (%s)", game_id,
                    exc,
                )

        # hot-standby replication (ISSUE 18, goworld_tpu/replication/):
        # primary side lazily builds a bounded worker when a standby
        # subscribes or the chain-checkpoint cadence fires; standby
        # side ([gameN] standby_of = M) mirrors the primary's frame
        # stream instead of ticking, until promoted
        self.standby_of = int(standby_of)
        self.replication_keyframe_every = int(replication_keyframe_every)
        self.replication_queue = int(replication_queue)
        self.repl_worker = None
        self._repl_subscribers: set[int] = set()
        self._repl_disk_due = False
        self._repl_late_frames = 0
        self._standby_applier = None
        self.standby_tracker = None
        self._promoted = False
        self._promote_pending: int | None = None
        self._promote_claim: str | None = None
        self._promote_epoch = 0
        self._promote_log = None
        self._repl_attached = False
        self._repl_resub = 0
        self._standby_warmed = False
        if self.standby_of:
            if world._multihost:
                raise ValueError(
                    "standby_of is single-controller only (a multihost "
                    "group's collectives cannot pause for mirroring)")
            from goworld_tpu.replication import standby as _standby

            self.standby_tracker = _standby.register(
                f"game{game_id}",
                _standby.StandbyTracker(
                    game_id, self.standby_of,
                    tick_hz=1.0 / max(tick_interval, 1e-6),
                    lag_budget_ticks=int(replication_lag_budget_ticks),
                ),
            )
            self._standby_applier = _standby.StandbyApplier(
                world, self.standby_of, tracker=self.standby_tracker)
            self.standby_tracker.on_promote = self._request_promotion
            self.kvreg_watchers.append(self._on_promotion_kvreg)

        # self-healing rebalance plane (ISSUE 19, goworld_tpu/
        # rebalance/): a per-game handoff agent drives bounded entity
        # cohorts to an underloaded peer through the PRODUCTION
        # migration protocol (wire mode: the agent only initiates
        # _remote_enter_space; the QUERY_SPACE -> MIGRATE_REQUEST ->
        # REAL_MIGRATE handlers do the removal, so an abandoned move
        # leaves the entity live on the source by construction). The
        # agent also answers the /rebalance?handoff= manual drain.
        self.rebalance_enabled = bool(rebalance_enabled)
        self.rebalance_agent = None
        self._rebalance_pub_tick = 0
        self._rebalance_paused_pub = False
        if self.rebalance_enabled:
            from goworld_tpu import rebalance as _rebalance

            self.rebalance_agent = _rebalance.register(
                f"game{game_id}",
                _rebalance.HandoffExecutor(
                    world, game_id=game_id,
                    batch=max(1, int(rebalance_batch))))
            _rebalance.set_handoff_hook(self._request_handoff)

        # wire the world's pluggable edges to the cluster
        w = world
        w.client_sink = self._client_sink
        w.sync_sink = self._sync_sink
        w.remote_router = self._remote_call
        w.remote_space_router = self._remote_enter_space
        w.filtered_sink = self._filtered_sink
        w.on_entity_created = self._notify_entity_created
        w.on_entity_destroyed = self._notify_entity_destroyed

    # ==================================================================
    # lifecycle
    # ==================================================================
    def start_network(self) -> None:
        """Spawn the asyncio networking thread and connect to dispatchers."""
        started = threading.Event()

        def run() -> None:
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            self.cluster.start()
            started.set()
            self._loop.run_forever()

        self._net_thread = threading.Thread(
            target=run, name=f"game{self.game_id}-net", daemon=True
        )
        self._net_thread.start()
        started.wait()

    def stop(self) -> None:
        from goworld_tpu.net.loops import drain_and_close

        self._stop.set()
        drain_and_close(self._loop, self._net_thread,
                        pre_stop=self.cluster.stop)

    def serve_forever(self) -> None:
        """The logic loop: drain packets, tick the world, repeat."""
        if self.gc_freeze_on_boot:
            # Move everything alive at boot (the spawned entity
            # population, attr trees, numpy mirrors, handler tables)
            # into the GC's permanent generation: a gen-2 collection
            # otherwise walks the whole world — ~100 ms at a 131K-entity
            # shard, the p95 frame spike tools/probe_fanout.py measured
            # (the 16 ms frame can't absorb a 6x stall). Post-boot
            # allocations stay tracked, so normal churn still collects;
            # ini [gameN] gc_freeze=false opts out.
            _gc.collect()
            _gc.freeze()
            logger.info(
                "game%d: froze %d boot objects out of the collector",
                self.game_id, _gc.get_freeze_count(),
            )
        next_tick = time.monotonic()
        tl = metrics.timeline
        while not self._stop.is_set():
            if faults.active:
                # chaos crashpoint: `crash:game.tick@n=N` dies at the
                # Nth serve-loop iteration (deterministic, unlike a
                # wall-clock kill racing the boot compile)
                faults.maybe_crash("game.tick")
            # the serve loop owns the tick record: the pump and fan-out
            # spans land in the same trace row as the World's phases
            tl.begin_tick()
            self._m_queue_depth.set(self._packet_q.qsize())
            # residency accounting (utils/residency.py): the pump below
            # is useful host work between device dispatches, the pacing
            # sleep at the bottom is idle by design — declare both so
            # neither reads as a bubble
            rt = getattr(self.world, "residency", None)
            t_pump = time.perf_counter()
            with tl.span("drain_inputs"):
                # 1.5 frames of handler work per tick keeps the loop
                # observing (and the p99 near 2x the interval) under a
                # flood; the surplus waits in the class queues
                self.pump(
                    budget=1.5 * self.tick_interval
                    if self.overload_enabled else None
                )
            if rt is not None:
                rt.add_host(time.perf_counter() - t_pump)
            self.tick()
            dur = tl.end_tick()
            if dur is not None:
                self._m_tick_hist.observe(dur * 1e3)
            if self.run_state == "freezing":
                self._do_freeze()
                return
            next_tick += self.tick_interval
            delay = next_tick - time.monotonic()
            backlog = max(0.0, -delay / self.tick_interval)
            self._m_backlog.set(backlog)
            if self.overload_enabled:
                self._observe_overload(dur, backlog)
            if delay > 0:
                time.sleep(delay)
                if rt is not None:
                    rt.add_idle(delay)
            else:
                next_tick = time.monotonic()  # fell behind; don't spiral

    def _observe_overload(self, dur: float | None,
                          backlog: float) -> None:
        """Feed this tick's measured signals to the overload governor
        and push the resulting degradation knobs into the fan-out."""
        pend_frac = 0.0
        for c in self.cluster.conns:
            if c.pend_max_bytes > 0:
                pend_frac = max(
                    pend_frac, c._pending_bytes / c.pend_max_bytes
                )
        st = self.overload.observe(
            (dur / self.tick_interval) if dur else 0.0,
            backlog,
            self._packet_q.depth_frac(),
            pend_frac,
        )
        # DEGRADED+: AOI/attr-sync fan-out strides entity cohorts
        # (entity/manager.py applies the mask vectorized); back to 1 the
        # tick the ladder returns to NORMAL
        self.world.sync_stride = (
            self.degraded_sync_stride if st >= overload.DEGRADED else 1
        )

    # ==================================================================
    # freeze (hot reload; reference GameService.go:220-313, SURVEY.md#3.6)
    # ==================================================================
    def request_freeze(self) -> None:
        """Ask every dispatcher to block this game's traffic; freezing
        starts once all of them ack (reference ``startFreeze``,
        ``GameService.go:474-478``)."""
        if self.run_state != "running":
            return
        if self.world._multihost and self.world.mh_rank != 0:
            # the CLI signals the LEADER; a follower cannot drive the
            # dispatcher ack dance (its wire id owns no entity routes)
            logger.warning(
                "game%d: multihost freeze must be requested on the "
                "leader controller", self.game_id,
            )
            return
        self._freeze_acks.clear()
        p = new_packet(proto.MT_START_FREEZE_GAME)
        for conn in self.cluster.conns:
            self._send(conn, Packet(bytes(p.buf)))
        p.release()

    def _do_freeze(self) -> None:
        """All dispatchers acked: drain deferred work, snapshot, exit.
        The CLI restarts the process with ``-restore``."""
        import os

        from goworld_tpu import freeze as _freeze

        w = self.world
        w.post_q.tick()
        # the deferred work just drained may have staged client
        # messages; the tick loop will never flush again, so do it now
        # (pre-batching they were sent immediately)
        self._flush_sync_out(force=True)
        # an in-flight ASYNC checkpoint must finish before the freeze
        # file is written: its atomic rename landing afterwards would
        # give an OLDER-state checkpoint a NEWER mtime, and the
        # -restore boot picks snapshots by mtime
        # (freeze.latest_snapshot_path)
        deadline = time.monotonic() + 30.0
        while getattr(w, "_ckpt_inflight", False) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        # snapshot FIRST: OnFreeze hooks may enqueue storage saves, which
        # the drain below must still execute (reference doFreeze ordering).
        # Multihost: EVERY controller reaches here after the same tick
        # (the exchange spread the decision) and freeze_world's device
        # snapshot is an allgather, so all ranks hold the identical
        # global snapshot — the LEADER alone writes the file, which every
        # rank reads back on the -restore start.
        data = _freeze.freeze_world(w)
        if w.storage is not None:
            w.storage.shutdown()
        path = os.path.join(
            self.freeze_dir, _freeze.freeze_filename(w.game_id)
        )
        if not self._mh_follower():
            _freeze.write_freeze_file(path, data)
            logger.info("game%d: frozen to %s", self.game_id, path)
        # OnFreeze hooks may have emitted client messages after the
        # first flush — put them on the wire before exiting
        self._flush_sync_out(force=True)
        self.run_state = "frozen"
        self.stop()

    def pump(self, budget: float | None = None) -> int:
        """Drain and handle queued dispatcher packets (logic thread),
        highest traffic class first — under backlog the migration/RPC
        control plane is applied before sync/event noise.

        ``budget`` (seconds) TIME-BOXES the drain: without it, an
        arrival rate above the service rate turns one "tick" into a
        minutes-long grind — the tick deadline is obliterated AND the
        overload governor starves (one observation per mega-tick, so
        the ladder can never climb). With a budget the loop returns
        mid-queue once the box is spent; the remainder stays queued
        (bounded per class) for the next tick, the serve loop keeps
        its cadence, and sustained pressure becomes a SIGNAL instead
        of a stall."""
        n = 0
        deadline = (
            time.monotonic() + budget if budget is not None else None
        )
        while True:
            try:
                didx, msgtype, pkt = self._packet_q.pop()
            except IndexError:
                return n
            try:
                self._handle_packet(didx, msgtype, pkt)
            except Exception:
                logger.exception(
                    "game%d: handler for msgtype %d failed",
                    self.game_id, msgtype,
                )
            n += 1
            if deadline is not None and time.monotonic() > deadline:
                return n

    def tick(self) -> None:
        if self._standby_applier is not None and not self._promoted:
            # a standby's world evolves ONLY by applied frames (the
            # pump above already ran the applier); no device tick, no
            # fan-out, until promotion flips this gate off
            self._standby_tick()
            return
        # wall clock measured HERE (not in serve_forever) so manual
        # pump()/tick() loops — tests, embedded harnesses — feed the
        # flight recorder the same SLO signal as the real serve loop
        t0 = time.perf_counter()
        tl = metrics.timeline
        rt = getattr(self.world, "residency", None)
        if self.world._multihost:
            # the exchange also publishes world.mh_group_ready, which
            # gates the World's own tick-cadence service reconcile
            with tl.span("mh_exchange"):
                self._mh_exchange_mutations()
            if rt is not None:
                rt.add_host(time.perf_counter() - t0)
        self.world.tick()
        # everything from here to the end of tick() is useful host work
        # between device dispatches — declared to the residency plane
        # so the bubble verdict only counts genuinely idle time
        t_host = time.perf_counter()
        with tl.span("fan_out"):
            self._flush_sync_out()
            self._maybe_checkpoint()
            self._replication_pump()
        if self.rebalance_agent is not None:
            try:
                self._rebalance_service()
            except Exception:  # must never break a tick
                logger.exception("rebalance service failed")
        ap = getattr(self.world, "audit", None)
        if (ap is not None and self.audit_scrub_every > 0
                and self.world.tick_count % self.audit_scrub_every == 0):
            # hand the chain walk to the audit worker — file IO + CRC
            # math never touch the tick; a busy worker drops the walk
            gid, fdir, tick = (self.game_id, self.freeze_dir,
                               self.world.tick_count)
            ap.submit(lambda: ap.scrub_snapshots(fdir, gid, tick))
        gov_ev = None
        if self.governor is not None:
            # between-ticks commit point: the world's device step for
            # this tick is done, the next tick runs the (possibly)
            # swapped executable
            try:
                gov_ev = self._drive_governor()
            except Exception:  # the governor must never break a tick
                logger.exception("kernel governor window failed")
        if self.flightrec is not None:
            # own span: frame cost stays attributed in the timeline's
            # >=95% per-tick coverage bound
            with tl.span("flightrec"):
                try:
                    self._flightrec_frame(time.perf_counter() - t0,
                                          gov_ev)
                except Exception:  # must never break the tick
                    logger.exception("flight-recorder frame failed")
        if rt is not None:
            rt.add_host(time.perf_counter() - t_host)

    # workload-signature refresh cadence (ticks): how often the tick
    # loop re-reduces the signature for the flight-recorder frame and
    # the [gameN] recommendation line (the /workload endpoint always
    # reduces fresh on demand)
    SIG_LOG_TICKS = 64
    # residency windowed-verdict cadence (ticks): how often the frame
    # carries the bubble p99 of the ticks since the previous window —
    # the residency_regression trigger's input (utils/flightrec.py)
    RESIDENCY_WIN_TICKS = 16
    # rebalance send-window cadence (ticks): a busy handoff agent
    # initiates at most one rate-limited batch window per this many
    # ticks, so the migration path never becomes its own overload
    REBALANCE_PUMP_TICKS = 16
    # kvreg advert cadence for this game's receiving space
    REBALANCE_PUB_TICKS = 64

    def _rebalance_service(self) -> None:
        """Per-tick rebalance housekeeping (logic thread): advertise
        this game's receiving space in kvreg, pump the active handoff
        one send window on its cadence, observe wire completions, and
        publish/clear the deployment-wide admission pause."""
        agent = self.rebalance_agent
        w = self.world
        tick = w.tick_count
        if self._rebalance_pub_tick == 0 \
                or tick - self._rebalance_pub_tick \
                >= self.REBALANCE_PUB_TICKS:
            self._rebalance_pub_tick = max(1, tick)
            nil_id = getattr(w.nil_space, "id", None)
            sid = next(
                (s for s in sorted(w.spaces) if s != nil_id), None)
            if sid is not None:
                self.kvreg_register(
                    f"rebalance/space/game{self.game_id}", sid,
                    force=True)
        if agent.busy:
            if tick % self.REBALANCE_PUMP_TICKS == 0:
                agent.pump()
            agent.wire_poll(self._migrating_out)
        paused = agent.busy
        if paused != self._rebalance_paused_pub:
            self._rebalance_paused_pub = paused
            self.kvreg_register(
                f"rebalance/pause/game{self.game_id}",
                "1" if paused else "0", force=True)

    def _request_handoff(self, target: int,
                         batch: int | None = None) -> dict:
        """The ``/rebalance?handoff=GAMEID`` poke (debug-http thread):
        validate against the kvreg mirror, then post the actual start
        onto the logic thread — the world is single-threaded."""
        agent = self.rebalance_agent
        if agent is None:
            return {"error": "rebalance disabled on this process"}
        if int(target) == self.game_id:
            return {"error": "cannot hand off to self"}
        space = self.kvreg.get(f"rebalance/space/game{int(target)}")
        if not space:
            return {"error":
                    f"game{int(target)} advertises no receiving space"}
        if agent.busy:
            return {"error": "a handoff is already in flight"}
        tgt, sp = int(target), space

        def _start() -> None:
            if agent.busy:
                return
            try:
                n = agent.start(
                    tgt, "manual",
                    send=lambda eid, e: self._remote_enter_space(
                        e, sp, tuple(e.position)),
                    batch=batch, detach=False)
                logger.info(
                    "game%d: manual handoff of %d entities to game%d "
                    "(space %s)", self.game_id, n, tgt, sp)
            except Exception:
                logger.exception("game%d: manual handoff failed",
                                 self.game_id)

        self.world.post_q.post(_start)
        return {"requested": True, "target": f"game{tgt}",
                "space": sp, "batch": int(batch or agent.batch)}

    def _drive_governor(self):
        """One governor observation per rotated signature window: hand
        it the freshest signature + this window's measured tick-ms p90
        (from the tick_latency_ms histogram delta — wall truth, not the
        modeled device lane), and commit/revert whatever it decides.
        Returns the swap event (stamped into the flight-recorder frame
        as the ``governor_swap`` trigger) or None."""
        w = self.world
        win_tick = getattr(w, "_telem_win_tick", 0)
        if win_tick == self._gov_last_win:
            return None
        self._gov_last_win = win_tick
        from goworld_tpu.utils import devprof

        snap = self._m_tick_hist.snapshot()
        counts = [c for _u, c in snap["buckets"]] + [snap["inf"]]
        p90 = None
        if self._gov_hist_mark is not None \
                and len(self._gov_hist_mark) == len(counts):
            delta = [max(0, a - b) for a, b in
                     zip(counts, self._gov_hist_mark)]
            if sum(delta) > 0:
                # INTERPOLATED quantile: the regret guard compares two
                # p90s, and 2x-spaced bucket UPPER edges would make
                # regret_pct unenforceable (any cross-bucket move reads
                # as >= 2x, any within-bucket regression as 0). An inf
                # p90 (mass beyond the top bucket) is KEPT — it is the
                # strongest possible regression signal and must revert,
                # not disarm; only NaN (impossible here) drops.
                p90 = devprof.hist_quantile_interp(
                    [u for u, _c in snap["buckets"]], delta, 0.9)
                if p90 != p90:
                    p90 = None
        self._gov_hist_mark = counts
        ev = self.governor.on_window(w.window_signature(),
                                     tick_ms_p90=p90)
        if ev is not None:
            # the commit itself RESET the world's signature window
            # (apply_tick_config): resync so the reset is never
            # misread as a rotation one tick later — a 1-sample
            # "window" of pre-swap latency would feed the regret
            # guard garbage. The hist mark drops too: the next
            # genuine window judges a full post-swap distribution.
            self._gov_last_win = getattr(w, "_telem_win_tick",
                                         win_tick)
            self._gov_hist_mark = None
            # the resolved kernel key follows the swap (incident
            # context + the recommendation log line read it)
            self._kernel_key = ",".join(
                f"{k}={v}" for k, v in sorted(
                    devprof.grid_config_key(w.cfg.grid).items()))
            logger.info(
                "[game%d] governor swap %s -> %s (%s); resolved %s",
                self.world.game_id, ev["from"], ev["to"],
                ev["reason"], self._kernel_key,
            )
        return ev

    def _flightrec_frame(self, dur_s: float, gov_ev=None) -> None:
        """One correlated flight-recorder frame per tick: measured tick
        wall time vs this process's budget, ladder stage, AOI oracle
        gauges and event volumes (all host-resident already — zero
        device traffic), plus the workload-signature class string on
        its refresh cadence. A signature class change stamps the
        ``[gameN]`` kernel-config recommendation line — the exact input
        ROADMAP item 2's governor will consume (recommend, not swap)."""
        w = self.world
        st = getattr(w, "op_stats", None) or {}
        tick = getattr(w, "tick_count", 0)
        frame = {
            "tick": tick,
            "tick_ms": round(dur_s * 1e3, 3),
            "budget_ms": round(self.tick_interval * 1e3, 3),
            "stage": self.overload.state_name,
            "over_k": int(st.get("aoi_over_k_rows", 0)),
            "over_cap": int(st.get("aoi_over_cap_cells", 0)),
            "enter": int(st.get("aoi_enter_events", 0)),
            "leave": int(st.get("aoi_leave_events", 0)),
            "backlog": float(self._m_backlog.value),
        }
        if gov_ev is not None:
            # fires the flight recorder's governor_swap trigger: the
            # decision context (signature, from/to, reason, regret
            # numbers) freezes into the incident bundle
            frame["governor"] = (
                f"{gov_ev['from']}->{gov_ev['to']} ({gov_ev['reason']})"
            )
        ap = getattr(w, "audit", None)
        if ap is not None:
            # each recorded violation fires the audit_violation trigger
            # at most once: the ledger tail + cohort diff freeze with
            # the bundle (utils/flightrec.py)
            av = ap.take_violation()
            if av is not None:
                frame["audit_violation"] = av
        if self.rebalance_agent is not None:
            # each terminal handoff transition (start/done/abort) fires
            # the rebalance_action trigger at most once
            ra = self.rebalance_agent.take_action_note()
            if ra is not None:
                frame["rebalance"] = ra
        rt = getattr(w, "residency", None)
        if rt is not None and tick % self.RESIDENCY_WIN_TICKS == 0:
            # windowed bubble verdict on a cadence: the p99 of the host
            # bubble over the ticks since the previous window, vs the
            # tracker's budget — fires the residency_regression trigger
            p99, n_win = rt.window_verdict()
            if p99 is not None and n_win > 0:
                frame["residency_bubble_p99_ms"] = (
                    "inf" if p99 == float("inf") else round(p99, 3))
                frame["residency_bubble_budget_ms"] = rt.bubble_budget_ms
                frame["residency_window"] = n_win
        if getattr(w, "telemetry_live", False) \
                and tick % self.SIG_LOG_TICKS == 0:
            sig = w.workload_signature()
            if sig and "sig" in sig:
                frame["signature"] = sig["sig"]
                if sig["sig"] != self._last_sig:
                    self._last_sig = sig["sig"]
                    rec = " ".join(
                        f"{k}={v}" for k, v in
                        sig.get("recommendation", {}).items())
                    logger.info(
                        "[game%d] workload signature %s -> "
                        "recommend: %s (resolved %s)",
                        self.game_id, sig["sig"], rec or "none",
                        self._kernel_key,
                    )
        self.flightrec.record(frame)

    def _incident_context(self) -> dict:
        """Correlation payload attached to a frozen incident bundle
        (paid at freeze time only, never per tick): the resolved
        kernel config, ladder stage, the last sampled trace ids and
        the freshest workload signature."""
        ctx: dict = {
            "kernel_config": self._kernel_key,
            "overload": self.overload.state_name,
        }
        tail = tracing.recorder.tail(8)
        if tail:
            ctx["trace_ids"] = sorted({t[2] for t in tail})
        sig = self.world.workload_signature()
        if sig:
            ctx["workload_signature"] = sig
        ap = getattr(self.world, "audit", None)
        if ap is not None:
            # ledger event tail + oracle/probe stats: an
            # audit_violation incident answers "which EntityID, which
            # hook sequence" from the bundle alone
            ctx["audit"] = ap.incident_context()
        if self.governor is not None:
            # the governor's decision context, frozen with the bundle
            # (a governor_swap incident answers "why did it swap" from
            # the bundle alone)
            g = self.governor.snapshot()
            ctx["governor"] = {
                "current": g["current"],
                "pending": g["pending"],
                "swaps": g["swaps"][-8:],
                "decisions": g["policy"]["transitions"][-8:],
                "regret_guard": g["regret_guard"],
            }
        return ctx

    def _maybe_checkpoint(self) -> None:
        """Periodic crash-recovery snapshot (``checkpoint_interval`` ini
        knob; VERDICT r3 #4): keeps a restorable file fresh so `ctl
        watchdog` can tear down a crashed game (or multihost group) and
        restart it ``-restore`` without losing the world since the last
        reload. Single-controller games snapshot asynchronously
        (``freeze.checkpoint_async``: tick loop keeps running through
        the device fetch + file write). Multihost groups snapshot
        SYNCHRONOUSLY at a tick-count cadence — the snapshot's device
        fetch is a collective every rank must reach at the same tick, so
        a wall-clock timer (per-rank instants differ) could deadlock;
        all ranks pack the identical global snapshot, the leader writes."""
        if self.checkpoint_interval <= 0 or self.run_state != "running":
            return
        from goworld_tpu import freeze as _freeze

        w = self.world
        if w._multihost:
            # the leader's wall-clock verdict arrived through this
            # tick's exchange, so EVERY rank reaches the snapshot's
            # collectives here at the same tick
            if not self._mh_ckpt_due:
                return
            self._mh_ckpt_due = False
            self._last_ckpt_mono = time.monotonic()
            data = _freeze.freeze_world(w, run_hooks=False)
            if not self._mh_follower():
                _freeze.write_freeze_file(
                    os.path.join(
                        self.freeze_dir,
                        _freeze.checkpoint_filename(w.game_id),
                    ),
                    data,
                )
            return
        now = time.monotonic()
        if now - self._last_ckpt_mono < self.checkpoint_interval \
                or getattr(w, "_ckpt_inflight", False):
            return
        self._last_ckpt_mono = now
        try:
            if getattr(w, "snapshot_keyframe_every", 0) > 0:
                # delta-compressed chain (ISSUE 12), now routed through
                # the bounded replication worker (ISSUE 18): the tick
                # thread stages one cheap capture in _replication_pump;
                # device fetch, quantize/diff and the disk write run
                # off-thread — the PR 12 tick-thread write is retired
                self._repl_disk_due = True
            else:
                _freeze.checkpoint_async(w, self.freeze_dir)
        except Exception:
            logger.exception("game%d: periodic checkpoint failed",
                             self.game_id)

    # ==================================================================
    # hot-standby replication (ISSUE 18, goworld_tpu/replication/)
    # ==================================================================
    # standby re-subscribe cadence (serve-loop iterations) while
    # unattached or healing from a torn stream
    REPL_RESUB_TICKS = 64

    def _ensure_repl_worker(self):
        if self.repl_worker is None:
            from goworld_tpu import freeze as _freeze
            from goworld_tpu.replication.worker import ReplicationWorker

            w = self.world
            kf = (self.replication_keyframe_every
                  or getattr(w, "snapshot_keyframe_every", 0) or 8)
            self.repl_worker = ReplicationWorker(
                _freeze.SnapshotChain(w, self.freeze_dir,
                                      keyframe_every=kf),
                game_id=self.game_id,
                queue_max=self.replication_queue,
                send_fn=self._send_repl_frame,
            )
        return self.repl_worker

    def _replication_pump(self) -> None:
        """Tick-thread side of the chain/stream plane: ONE cheap
        host-record capture per due tick, handed to the bounded worker
        (device fetch, quantize/diff, disk write and stream send all
        run off-thread). Queue full = the capture is dropped with a
        loud counter and the stream degrades to keyframe cadence —
        never the tick (docs/ROBUSTNESS.md)."""
        stream = bool(self._repl_subscribers)
        disk = self._repl_disk_due
        if not stream and not disk:
            return
        self._repl_disk_due = False
        try:
            worker = self._ensure_repl_worker()
            worker.submit(worker.chain.capture(),
                          to_disk=disk, to_stream=stream)
        except Exception:
            logger.exception("game%d: replication capture failed",
                             self.game_id)

    def _send_repl_frame(self, blob: bytes, kind: str,
                         tick: int) -> None:
        """Stream send (runs on the WORKER thread): one packet per
        subscriber, each pinned to a deterministic dispatcher leg so
        per-standby frame order is preserved end to end."""
        for sgid in sorted(self._repl_subscribers):
            conn = self.cluster.conns[sgid % len(self.cluster.conns)]
            self._send(conn,
                       proto.pack_replication_frame(sgid, self.game_id,
                                                    blob))

    def _standby_tick(self) -> None:
        """The standby's serve-loop body: keep the subscription alive
        (attach + torn-stream resync both re-request a keyframe) and
        drive a staged promotion claim on the logic thread."""
        if not self._standby_warmed:
            # pre-warm the jit'd tick program ON the still-empty world
            # (SoA shapes are capacity-static, so the compile is the
            # same one the promoted tick needs). Without this the first
            # post-promotion tick pays seconds of compile — the cold
            # restore cost hot standby exists to avoid. Must run before
            # the first frame applies: a tick would ADVANCE a populated
            # mirror past its primary.
            self._standby_warmed = True
            if not self.world.spaces:
                try:
                    self.world.tick()
                    self.world.tick_count = 0
                except Exception:
                    logger.exception(
                        "game%d: standby warmup tick failed",
                        self.game_id)
        self._repl_resub -= 1
        dec = self._standby_applier.decoder
        if self._repl_resub <= 0 and (
                not self._repl_attached or dec.needs_keyframe):
            if self.cluster.conns:
                self._send(
                    self.cluster.conns[
                        self.game_id % len(self.cluster.conns)],
                    proto.pack_replication_subscribe(self.standby_of,
                                                     self.game_id))
            self._repl_resub = self.REPL_RESUB_TICKS
        if self._promote_pending is not None \
                and self._promote_claim is None:
            self._claim_promotion()

    def _request_promotion(self, epoch: int | None = None) -> dict:
        """Promotion hook installed on the standby tracker — reached
        from the debug-http thread (``/standby?promote=1``, the
        supervisor's poke). Only STAGES the request; the kvreg claim
        runs on the logic thread (_standby_tick). epoch None = derive
        from the last observed promotion round."""
        if self._standby_applier is None:
            return {"error": "not a standby"}
        if self._promoted:
            return {"status": "already_promoted",
                    "epoch": self._promote_epoch}
        if self._promote_pending is None:
            self._promote_pending = -1 if epoch is None else int(epoch)
        return {"status": "claiming", "epoch": self._promote_pending,
                "applied_tick":
                    self._standby_applier.decoder.applied_tick}

    def _claim_promotion(self) -> None:
        from goworld_tpu.replication import promote as _promote

        key = _promote.claim_key(self.standby_of)
        epoch = self._promote_pending
        if epoch is None:
            return
        if epoch < 0:
            cur = _promote.parse_claim(self.kvreg.get(key, ""))
            epoch = (cur["epoch"] + 1) if cur else 1
        self._promote_epoch = int(epoch)
        dec = self._standby_applier.decoder
        self._promote_claim = _promote.claim_value(
            self.game_id, self._promote_epoch, dec.applied_seq)
        self._promote_log = _promote.DecisionLog()
        self._promote_log.note(
            "claim", key=key, value=self._promote_claim,
            applied_tick=dec.applied_tick,
            applied_seq=dec.applied_seq)
        self.kvreg_register(key, self._promote_claim)

    def _on_promotion_kvreg(self, key: str, val: str) -> None:
        """kvreg watcher (logic thread): adjudicate the dispatcher's
        broadcast for our promotion claim — first-writer-wins plus the
        epoch guard covering BOTH stale-replay orders
        (replication/promote.py)."""
        from goworld_tpu.replication import promote as _promote

        if self._promote_claim is None or self._promoted \
                or key != _promote.claim_key(self.standby_of):
            return
        verdict = _promote.adjudicate(val, self._promote_claim)
        self._promote_log.note("adjudicate", winner=val,
                               mine=self._promote_claim,
                               verdict=verdict)
        if verdict == "won":
            self._finish_promotion()
        elif verdict == "stale_winner":
            # a replayed stale claim landed first: force-overwrite is
            # legitimate exactly and only now
            self._promote_log.note("force_reregister",
                                   value=self._promote_claim)
            self.kvreg_register(key, self._promote_claim, force=True)
        else:
            self._promote_log.note("stand_down", winner=val)
            self._write_promotion_log()
            self._promote_pending = None
            self._promote_claim = None

    def _finish_promotion(self) -> None:
        w = self.world
        dec = self._standby_applier.decoder
        self._promoted = True
        tick = max(int(dec.applied_tick), 0)
        # resume ticking FROM the last applied frame: staged mirror
        # state flushes into the device SoA on the first real tick
        # (the restore_world contract)
        w.tick_count = max(int(w.tick_count), tick)
        self.standby_tracker.note_promoted(self._promote_epoch, tick)
        self._promote_log.note(
            "promoted", epoch=self._promote_epoch, tick=tick,
            seq=dec.applied_seq,
            entities=len([e for e in w.entities.values()
                          if not e.destroyed]))
        self._write_promotion_log()
        # re-point the dispatcher's EntityID routing at this process: a
        # fresh census handshake over every leg (the dead primary's
        # routes dropped with its connection, so the census claims
        # them; conflicts come back as rejects). Clients re-handshake
        # through the same census path.
        census = list(w.entities.keys())
        for conn in self.cluster.conns:
            self._send(conn, proto.pack_set_game_id(
                self.game_id, is_reconnect=True, is_restore=True,
                ban_boot=self.ban_boot, entity_ids=census))
        if self.flightrec is not None:
            # fires the standby_promoted trigger: the promotion context
            # freezes into an incident bundle on OUR side (the dead
            # primary's ring froze at its crash)
            self.flightrec.record({
                "tick": tick,
                "standby_promoted": (
                    f"game{self.game_id} epoch {self._promote_epoch} "
                    f"seq {dec.applied_seq} tick {tick}"),
            })
        logger.warning(
            "game%d: PROMOTED to primary for game%d at epoch %d "
            "(frame seq %d, tick %d) — resuming ticking",
            self.game_id, self.standby_of, self._promote_epoch,
            dec.applied_seq, tick,
        )

    def _write_promotion_log(self) -> None:
        """Persist the byte-replayable decision log next to the
        snapshots (chaos_soak replays it; ops read it after the
        fact)."""
        if self._promote_log is None:
            return
        try:
            with open(os.path.join(
                    self.freeze_dir,
                    f"game{self.game_id}_promotion.log"), "wb") as f:
                f.write(self._promote_log.dump())
        except OSError:
            logger.exception("game%d: promotion log write failed",
                             self.game_id)

    # cap on raw mutation bytes shipped per controller per tick; the
    # surplus stays queued IN ORDER for the next tick (backpressure —
    # an unbounded allgather payload would stall every controller)
    MH_LOG_BYTES_PER_TICK = 1 << 20

    def _mh_drain_pending(self) -> bytearray:
        blob = bytearray()
        import struct as _st

        taken = 0
        for mt, payload in self._mh_pending:
            if taken and len(blob) + 6 + len(payload) > \
                    self.MH_LOG_BYTES_PER_TICK:
                logger.warning(
                    "game%d: multihost mutation log full; deferring %d "
                    "packets to the next tick", self.game_id,
                    len(self._mh_pending) - taken,
                )
                break
            blob += _st.pack("<HI", mt, len(payload))
            blob += payload
            taken += 1
        del self._mh_pending[:taken]
        # backlog observability (VERDICT r3 #6): the ordered carry-over
        # keeps correctness under overflow, but a backlog that GROWS
        # tick over tick means the cluster plane produces mutations
        # faster than 1 MB/controller/tick forever — surfaced as gauges
        # (debug_http /vars) + a rate-limited alarm, never silently
        backlog_b = sum(6 + len(p) for _, p in self._mh_pending)
        opmon.expose("mh_mutation_backlog_packets", len(self._mh_pending))
        opmon.expose("mh_mutation_backlog_bytes", backlog_b)
        _m_mh_backlog_pkts.set(len(self._mh_pending))
        _m_mh_backlog_bytes.set(backlog_b)
        self.world.op_stats["mh_mutation_backlog_bytes"] = backlog_b
        if self._mh_pending:
            self._mh_backlog_ticks += 1
            if self._mh_backlog_ticks >= 8 \
                    and self._mh_backlog_ticks % 64 == 8:
                # the alarm reports what the overload plane is actually
                # DOING about it (state + per-class sheds since the
                # last alarm interval) instead of advising "shed load"
                # with no mechanism behind the words
                shed_now = overload.shed_snapshot()
                delta = {
                    k: v - self._shed_at_alarm.get(k, 0.0)
                    for k, v in shed_now.items()
                    if v > self._shed_at_alarm.get(k, 0.0)
                }
                self._shed_at_alarm = shed_now
                logger.warning(
                    "game%d: multihost mutation backlog sustained for "
                    "%d ticks (%d packets / %d bytes queued): the "
                    "cluster plane outruns MH_LOG_BYTES_PER_TICK "
                    "(%d B/tick) — overload state %s; shed last "
                    "interval: %s",
                    self.game_id, self._mh_backlog_ticks,
                    len(self._mh_pending), backlog_b,
                    self.MH_LOG_BYTES_PER_TICK,
                    self.overload.state_name,
                    delta or "nothing (raise the cap or add controllers)",
                )
        else:
            self._mh_backlog_ticks = 0
        return blob

    def _mh_exchange_mutations(self) -> None:
        """Multi-controller mutation exchange: allgather every controller's
        queued World-mutating packets and replay the union in process
        order, so all controllers apply IDENTICAL mutations this tick no
        matter whose dispatcher connection a packet arrived on. Runs every
        tick on every controller (the collectives must pair up); the
        blocking allgather also keeps the controllers' tick loops in
        lockstep — the host-plane counterpart of the device step's own
        collectives."""
        import struct as _st

        from jax.experimental import multihost_utils

        blob = self._mh_drain_pending()
        # (blob length, deployment-ready flag, game id): the extra
        # fields ride the same collective so every controller derives
        # the SAME "whole group is ready" fact and the SAME leader game
        # id at the same tick — wall-clock readiness differs per
        # controller and must never gate SPMD decisions directly
        # checkpoint cadence is WALL-CLOCK on the leader, spread through
        # this same collective (like the freeze flag): tick counts drift
        # from wall time under load, and per-rank clocks differ — the
        # leader's verdict riding the exchange is the only instant every
        # controller observes at the same tick
        ckpt_due = int(
            not self._mh_follower()
            and self.checkpoint_interval > 0
            and self.run_state == "running"
            and time.monotonic() - self._last_ckpt_mono
            >= self.checkpoint_interval
        )
        meta = np.asarray(
            multihost_utils.process_allgather(
                np.asarray([len(blob), int(self.deployment_ready),
                            self.game_id,
                            int(self._mh_freeze_requested),
                            ckpt_due], np.int32)
            )
        ).reshape(-1, 5)
        self._mh_ckpt_due = bool(meta[:, 4].any())
        self.world.mh_group_ready = self._mh_all_ready = \
            bool(meta[:, 1].all())
        self._mh_leader_game_id = int(meta[0, 2])
        if meta[:, 3].any() and self.run_state == "running":
            # coordinated freeze: every controller learns the fact from
            # the SAME collective, so all of them run _do_freeze after
            # this very tick and the freeze_world snapshot's own
            # collectives pair up
            self.run_state = "freezing"
        lengths = meta[:, 0]
        max_len = int(lengths.max())
        if max_len == 0:
            return
        padded = np.zeros(max_len, np.uint8)
        if blob:
            padded[: len(blob)] = np.frombuffer(bytes(blob), np.uint8)
        all_blobs = np.asarray(multihost_utils.process_allgather(padded))
        self._mh_replaying = True
        try:
            for pid in range(all_blobs.shape[0]):
                data = all_blobs[pid].tobytes()[: int(lengths[pid])]
                off = 0
                while off + 6 <= len(data):
                    mt, ln = _st.unpack_from("<HI", data, off)
                    off += 6
                    try:
                        self._handle_packet(
                            -1, mt, Packet(data[off:off + ln])
                        )
                    except Exception:
                        logger.exception(
                            "game%d: multihost replay of msgtype %d "
                            "failed", self.game_id, mt,
                        )
                    off += ln
        finally:
            self._mh_replaying = False

    # ==================================================================
    # networking thread side
    # ==================================================================
    async def _handshake(self, conn: DispatcherConn) -> None:
        # multihost followers register NO entities: the leader alone
        # represents the shared World in the dispatcher's entity table
        # (eid-routed packets then reach exactly one controller and are
        # replicated from there via _mh_exchange_mutations)
        # an UNPROMOTED standby registers NO entities (its mirror copies
        # belong to the live primary — claiming them would fork routing)
        # and is never boot-eligible; promotion re-handshakes with the
        # real census (_finish_promotion)
        is_standby = (self._standby_applier is not None
                      and not self._promoted)
        census = (
            [] if self._mh_follower() or is_standby
            else list(self.world.entities.keys())
        )
        p = proto.pack_set_game_id(
            self.game_id, is_reconnect=self.deployment_ready,
            is_restore=self._is_restore,
            ban_boot=self.ban_boot or is_standby,
            entity_ids=census,
        )
        conn.conn.send(p)
        await conn.conn.drain()

    def _on_packet_netthread(self, didx: int, msgtype: int,
                             pkt: Packet) -> None:
        cls = overload.classify(msgtype)
        if self.overload_enabled and self.overload.should_shed(cls):
            # SHEDDING/REJECTING: the cheapest classes are dropped at
            # ingress, before any logic-thread work; every drop counted
            overload.shed_counter(cls, "game_ingress").inc()
            return
        if not self._packet_q.offer(cls, (didx, msgtype, pkt)):
            # class queue full (offer counted the shed); the old
            # aggregate drop counter keeps its series alive
            self._m_pkt_drop.inc()
            if int(self._m_pkt_drop.value) % 1024 == 1:
                logger.error(
                    "game%d: %s input queue full; dropping msgtype %d "
                    "(counted in shed_total)", self.game_id,
                    overload.CLASS_NAMES[cls], msgtype,
                )

    def _send(self, conn: DispatcherConn, p: Packet) -> None:
        """Thread-safe send from the logic thread."""
        if self._loop is None:
            conn.send(p)
            return
        try:
            self._loop.call_soon_threadsafe(conn.send, p)
        except RuntimeError:
            # loop closed mid-stop (SIGTERM lands between ticks): the
            # interrupted serve iteration must still unwind to the
            # hard-exit path, not die on a send
            pass

    # ==================================================================
    # world -> cluster edges (logic thread)
    # ==================================================================
    def _client_sink(self, gate_id: int, client_id: str, msg: dict) -> None:
        t = msg["type"]
        if t == "create_entity":
            p = proto.pack_create_entity_on_client(
                gate_id, client_id, msg["eid"], msg["etype"],
                msg["is_player"], msg["attrs"], msg["pos"], msg["yaw"],
            )
        elif t == "destroy_entity":
            p = proto.pack_destroy_entity_on_client(
                gate_id, client_id, msg["eid"], msg["is_player"]
            )
        elif t == "attrs":
            p = proto.pack_notify_attr_change_on_client(
                gate_id, client_id, msg["eid"], msg["deltas"]
            )
        elif t == "rpc":
            p = proto.pack_call_entity_method_on_client(
                gate_id, client_id, msg["eid"], msg["method"],
                tuple(msg["args"]),
            )
        elif t == "filter_prop":
            # gate-service message (mutates the gate's FilterIndex, no
            # client relay) — not part of the per-client event stream
            p = proto.pack_set_client_filter_prop(
                gate_id, client_id, msg["key"], msg["val"]
            )
            self._send(self.cluster.select_by_gate_id(gate_id), p)
            return
        elif t == "sync":
            self._sync_out.setdefault(gate_id, []).append(
                (client_id, msg["eid"],
                 (*msg["pos"], msg["yaw"]))
            )
            return
        else:
            logger.warning("game%d: unknown client msg type %r",
                           self.game_id, t)
            return
        # Stage into the per-gate per-tick bundle instead of sending a
        # dispatcher packet per message: a churn-heavy AOI tick emits
        # thousands of create/destroy/attr messages and per-message
        # framing through two hops dominated the gate leg. The record
        # body is the packed message minus its [u16 msgtype][u16
        # gate_id] prefix — byte-identical to what the gate's relay
        # forwards to the client. (buf layout: new_packet wrote the
        # u16 msgtype first, the pack_* helper the u16 gate_id next.)
        mt = int.from_bytes(bytes(p.buf[0:2]), "little")
        self._events_out.setdefault(gate_id, []).append(
            (mt, bytes(memoryview(p.buf)[4:]))
        )
        if tracing.active:
            # remember the emitting span so the flushed bundle carries
            # it (records are raw bytes; last traced emitter wins)
            ctx = tracing.current()
            if ctx is not None:
                self._events_trace[gate_id] = ctx
        # the packed message was copied into the record — return the
        # pooled packet (the per-message path's _send released it)
        p.release()

    def _sync_sink(self, gate_id: int, cids: list, eids: list,
                   vals: np.ndarray) -> None:
        self._sync_out.setdefault(gate_id, []).append((cids, eids, vals))

    _EVENT_BATCH_BYTES = 4 * 1024 * 1024  # chunk bound, well under the
                                          # 32M packet cap

    def _flush_events_out(self) -> None:
        """Put the staged per-gate client event bundles on the wire.
        Called from the per-tick flush, and EAGERLY by any send whose
        gate-side handling depends on the staged events having been
        applied (e.g. a filtered broadcast resolving cp.owner_eid set
        by a staged create_entity)."""
        for gate_id, recs in self._events_out.items():
            if not recs:
                continue
            # accumulated across eager mid-tick flushes; exposed (and
            # zeroed) once per tick by _flush_sync_out
            self._event_recs_flushed += len(recs)
            conn = self.cluster.select_by_gate_id(gate_id)
            trace_ctx = self._events_trace.pop(gate_id, None)
            chunk: list = []
            size = 0
            for rec in recs:
                chunk.append(rec)
                size += 6 + len(rec[1])
                if size >= self._EVENT_BATCH_BYTES:
                    p = proto.pack_client_events_batch(gate_id, chunk)
                    p.trace = trace_ctx
                    self._send(conn, p)
                    chunk, size = [], 0
            if chunk:
                p = proto.pack_client_events_batch(gate_id, chunk)
                p.trace = trace_ctx
                self._send(conn, p)
        self._events_out.clear()

    def _flush_sync_out(self, force: bool = False) -> None:
        self._fanout_tick += 1
        if (not force and self.overload_enabled
                and self.overload.state >= overload.DEGRADED
                and self.degraded_event_coalesce > 1
                and self._fanout_tick % self.degraded_event_coalesce):
            # DEGRADED batch coalescing: hold this tick's staged events
            # AND syncs (held together so a staged create still
            # precedes its entity's first sync) and flush them with the
            # next tick's — half the downstream packets at twice the
            # batch size. Eager mid-tick event flushes (filtered
            # broadcasts) still happen; freeze passes force=True.
            return
        # client event bundles FIRST: a create_entity staged this tick
        # must reach the client before the same entity's first position
        # sync record (flushed below)
        self._flush_events_out()
        # per-tick total (incl. eager mid-tick flushes), exposed
        # unconditionally so idle ticks read 0, like the mh_* gauges
        opmon.expose("client_event_batch_records",
                     self._event_recs_flushed)
        if self._event_recs_flushed:
            self._m_event_records.inc(self._event_recs_flushed)
        self._event_recs_flushed = 0
        # sync-age stamp base for this flush: the world's device-tick
        # anchor (epoch seq + tick-start + fetch instants) plus the
        # flush-start instant closing the drain_decode lane. One
        # time.time() per flush + 45 B per gate packet — the always-on
        # budget (utils/syncage.py; bench stamps the measured overhead)
        age_anchor = (
            getattr(self.world, "sync_age_anchor", None)
            if self.sync_age else None
        )
        t_stage_us = syncage.now_us() if age_anchor is not None else 0
        for gate_id, chunks in self._sync_out.items():
            # per-chunk ARRAYS concatenated once — never element-wise
            # Python appends (the world's mirror path hands us S16
            # batches; decomposing them would reintroduce the per-record
            # cost that path exists to remove)
            cids: list = []
            eids: list = []
            vals: list = []
            for c in chunks:
                if isinstance(c[0], (list, np.ndarray)):
                    if len(c[0]) == 0:
                        continue
                    cids.append(np.asarray(c[0], "S16"))
                    eids.append(np.asarray(c[1], "S16"))
                    vals.append(
                        np.asarray(c[2], np.float32).reshape(-1, 4)
                    )
                else:                        # single legacy record
                    cids.append(np.asarray([c[0]], "S16"))
                    eids.append(np.asarray([c[1]], "S16"))
                    vals.append(
                        np.asarray(c[2], np.float32).reshape(1, 4)
                    )
            if not cids:
                continue
            cid_b = np.concatenate(cids) if len(cids) > 1 else cids[0]
            eid_b = np.concatenate(eids) if len(eids) > 1 else eids[0]
            val_b = np.concatenate(vals) if len(vals) > 1 else vals[0]
            if self.sync_delta:
                # delta-compressed leg (ISSUE 12): int16 deltas against
                # per-(client, entity) baselines with in-band keyframes
                # — the gate's DeltaSyncDecoder reconstructs
                # bit-deterministically and relays the same records
                enc = self._sync_encoder(gate_id)
                p = new_packet(
                    proto.MT_SYNC_POSITION_YAW_DELTA_ON_CLIENTS)
                p.append_u16(gate_id)
                # sender id: every game runs its OWN handle space, and
                # a gate fans in from many games — the decoder keys its
                # state per sender so handles can never collide
                p.append_u16(self.game_id & 0xFFFF)
                p.append_bytes(enc.encode_batch(
                    cid_b, eid_b, val_b, self._fanout_tick))
            else:
                p = new_packet(proto.MT_SYNC_POSITION_YAW_ON_CLIENTS)
                p.append_u16(gate_id)
                body = codec.encode_client_sync_batch(cid_b, eid_b,
                                                      val_b)
                p.append_bytes(body)
                self._m_sync_bytes["full"].inc(len(body))
            if age_anchor is not None:
                p.age = syncage.SyncAgeStamp(
                    age_anchor[0], age_anchor[1], age_anchor[2],
                    t_stage_us, syncage.now_us())
            self._send(self.cluster.select_by_gate_id(gate_id), p)
        if self.sync_delta and self._sync_encoders:
            # byte-saving gauges (scraped next to the SLO line) —
            # summed across ALL per-gate encoders, exposed once, so a
            # multi-gate deployment never reports just the last gate
            opmon.expose("sync_delta_wire_bytes", sum(
                e.stats["wire_bytes"]
                for e in self._sync_encoders.values()))
            opmon.expose("sync_delta_full_bytes", sum(
                e.stats["full_bytes"]
                for e in self._sync_encoders.values()))
            # keyframe vs delta wire bytes split into their own series
            # (sync_bytes_out{kind}): the old single wire-bytes gauge
            # hid which mode the bytes travelled as — the age plane
            # correlates staleness against exactly this split
            for kind in ("keyframe", "delta"):
                total = sum(e.stats[f"{kind}_bytes"]
                            for e in self._sync_encoders.values())
                d = total - self._sync_bytes_mark[kind]
                if d > 0:
                    self._m_sync_bytes[kind].inc(d)
                self._sync_bytes_mark[kind] = total
        self._sync_out.clear()

    def _sync_encoder(self, gate_id: int) -> "codec.DeltaSyncEncoder":
        enc = self._sync_encoders.get(gate_id)
        if enc is None:
            # the step IS the world's precision lattice step (GridSpec.
            # quant_step is defined for every grid — precision=q16
            # worlds ship exact lattice deltas, f32 worlds get the same
            # power-of-two step as a sub-resolution wire quantization)
            grid = self.world.cfg.grid
            enc = self._sync_encoders[gate_id] = codec.DeltaSyncEncoder(
                grid.quant_step,
                keyframe_every=self.sync_keyframe_every,
            )
        return enc

    def _remote_call(self, eid: str, method: str, args: tuple,
                     from_client: str | None) -> None:
        if self._mh_follower():
            return  # SPMD-replicated call; the leader sends it once
        p = proto.pack_call_entity_method(eid, method, args, from_client)
        self._send(self.cluster.select_by_entity_id(eid), p)

    def _filtered_sink(self, key: str, op: str, val: str, method: str,
                       args: tuple) -> None:
        if self._mh_follower():
            return
        # a filtered RPC is addressed on the gate via cp.owner_eid,
        # which a create_entity staged THIS tick may set — flush the
        # event bundles first so the broadcast observes them in order
        # (the per-message path sent everything in emission order)
        self._flush_events_out()
        p = proto.pack_call_filtered_clients(key, op, val, "", method, args)
        self._send(self.cluster.conns[0], p)

    def _mh_follower(self) -> bool:
        """True on non-leader controllers of a multihost World. Cluster
        messages originated by SPMD-replicated host code (entity
        registration, anywhere-placement, filtered broadcasts) would be
        sent once per controller; only the leader (process 0) puts them on
        the wire. Client-bound traffic is NOT gated here — it is deduped
        per-entity by World.client_emit_ok (the shard owner emits)."""
        return self.world._multihost and self.world.mh_rank != 0

    def _notify_entity_created(self, e: Entity) -> None:
        if self._mh_follower():
            return  # the leader alone owns the dispatcher entity table
        p = new_packet(proto.MT_NOTIFY_CREATE_ENTITY)
        p.append_entity_id(e.id)
        p.append_u16(self.game_id)
        self._send(self.cluster.select_by_entity_id(e.id), p)

    def _notify_entity_destroyed(self, e: Entity) -> None:
        if self._mh_follower():
            return
        p = new_packet(proto.MT_NOTIFY_DESTROY_ENTITY)
        p.append_entity_id(e.id)
        self._send(self.cluster.select_by_entity_id(e.id), p)

    # -- public cluster-wide API (the goworld.go facade calls these) ----
    def create_entity_anywhere(self, type_name: str,
                               attrs: dict | None = None,
                               gameid: int = 0) -> None:
        """Reference ``CreateEntityAnywhere`` (``goworld.go``): placement
        decided by the dispatcher's load heap; nonzero ``gameid`` pins
        the target (``CreateEntityOnGame`` / ``CreateSpaceOnGame``)."""
        from goworld_tpu.utils import ids as _ids

        if self._mh_follower():
            return  # replicated caller; leader alone requests placement
        eid = _ids.gen_entity_id()
        p = proto.pack_create_entity_anywhere(type_name, attrs or {}, eid,
                                              gameid)
        self._send(self.cluster.select_by_entity_id(eid), p)

    def load_entity_anywhere(self, type_name: str, eid: str,
                             gameid: int = 0) -> None:
        if self._mh_follower():
            return
        p = proto.pack_load_entity_anywhere(type_name, eid, gameid)
        self._send(self.cluster.select_by_entity_id(eid), p)

    def kvreg_register(self, key: str, val: str, force: bool = False) -> None:
        if self._mh_follower():
            return  # the leader writes once on the whole group's behalf
        p = proto.pack_kvreg_register(key, val, force)
        self._send(self.cluster.select_by_srv_id(key), p)

    def kvreg_traverse(self, prefix: str, cb) -> None:
        """Walk the local kvreg mirror by key prefix (reference
        ``kvreg.TraverseByPrefix``, ``kvreg.go:23``)."""
        for k, v in sorted(self.kvreg.items()):
            if k.startswith(prefix):
                cb(k, v)

    def setup_services(self) -> "object":
        """Attach a kvreg-backed ServiceManager (reference ``service.Setup``,
        started on deployment-ready)."""
        from goworld_tpu.entity.service import ServiceManager

        return ServiceManager(
            self.world, game_id=self.game_id,
            kv_write=lambda k, v: self.kvreg_register(k, v),
            kv_get=self.kvreg.get,
            # multihost: the whole controller group claims shards as ONE
            # entity under the LEADER's game id (allgathered each tick —
            # unique per group, unlike World.game_id which defaults to 1)
            claim_token=(
                (lambda: f"mh:{self._mh_leader_game_id}")
                if self.world._multihost else None
            ),
        )

    def call_nil_spaces(self, method: str, *args) -> None:
        if self._mh_follower():
            return
        p = proto.pack_call_nil_spaces(method, args)
        self._send(self.cluster.conns[0], p)

    # ==================================================================
    # migration, outbound (reference Entity.go:1006-1101)
    # ==================================================================
    def _remote_enter_space(self, e: Entity, space_id: str,
                            pos: tuple) -> None:
        self._migrating_out[e.id] = (e, space_id, pos)
        if tracing.active and tracing.current() is None:
            # migration not already under a traced RPC: root its own
            # trace (sampled at the same rate) so the whole protocol —
            # QUERY_SPACE_GAMEID -> MIGRATE_REQUEST -> REAL_MIGRATE,
            # acks included — appears as ONE causally-linked trace; the
            # chain continues automatically because every ack comes
            # back traced and re-enters the handle/route hops
            root = tracing.maybe_sample()
            if root is not None:
                with tracing.root("migrate_out", f"game{self.game_id}",
                                  root, eid=e.id, space=space_id):
                    p = proto.pack_query_space_gameid(space_id, e.id)
                    self._send(
                        self.cluster.select_by_entity_id(space_id), p)
                return
        p = proto.pack_query_space_gameid(space_id, e.id)
        self._send(self.cluster.select_by_entity_id(space_id), p)

    # ==================================================================
    # cluster -> world packet handlers (logic thread)
    # ==================================================================
    def _handle_packet(self, didx: int, msgtype: int, pkt: Packet) -> None:
        ctx = pkt.trace
        if ctx is not None and ctx.sampled:
            # one handle span per traced inbound packet, parented to the
            # sender's span; installing it as current makes every
            # outbound packet the handler creates (entity RPC forwards,
            # migration acks, staged client events) carry OUR span
            with tracing.hop("handle", f"game{self.game_id}", ctx,
                             msgtype=msgtype) as my:
                pkt.trace = my
                return self._handle_packet_body(didx, msgtype, pkt)
        return self._handle_packet_body(didx, msgtype, pkt)

    def _handle_packet_body(self, didx: int, msgtype: int,
                            pkt: Packet) -> None:
        w = self.world
        if w._multihost and not self._mh_replaying \
                and msgtype in _MH_WORLD_MSGTYPES:
            if msgtype in _MH_BROADCAST_MSGTYPES \
                    and self._mh_follower():
                return  # broadcast copy; the leader's is the one logged
            # defer to the per-tick allgather so every controller applies
            # this mutation, in the same order, in the same tick
            self._mh_pending.append(
                (msgtype, bytes(memoryview(pkt.buf)[pkt.rpos:]))
            )
            return
        if msgtype == proto.MT_SET_GAME_ID_ACK:
            disp_id = pkt.read_u16()
            self.handshake_acks.add(disp_id)
            kv = pkt.read_data()
            rejects = pkt.read_data()
            self.online_games.update(pkt.read_data())
            self.kvreg.update(kv)
            for eid in rejects:
                e = w.entities.get(eid)
                if e is not None:
                    logger.warning(
                        "game%d: entity %s rejected by dispatcher; "
                        "destroying stale copy", self.game_id, eid,
                    )
                    e.destroy()
            return
        if msgtype == proto.MT_NOTIFY_DEPLOYMENT_READY:
            if not self.deployment_ready:
                self.deployment_ready = True
                # reference exposes this via gwvar/expvar (gwvar.go:1-29)
                opmon.expose("IsDeploymentReady", True)
                self.ready_event.set()
                for sp in list(w.spaces.values()):
                    sp.OnGameReady()
                if w.service_mgr is not None:
                    # reference service.OnDeploymentReady -> checkServices
                    w.service_mgr.start()
                if self.on_deployment_ready is not None:
                    self.on_deployment_ready()
            return
        if msgtype == proto.MT_CALL_ENTITY_METHOD:
            eid = pkt.read_entity_id()
            method = pkt.read_var_str()
            args = pkt.read_args()
            e = w.entities.get(eid)
            if e is not None:
                w._invoke(e, method, tuple(args), None)
            else:
                logger.warning("game%d: RPC to unknown entity %s.%s",
                               self.game_id, eid, method)
            return
        if msgtype == proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT:
            eid = pkt.read_entity_id()
            client_id = pkt.read_entity_id()
            method = pkt.read_var_str()
            args = pkt.read_args()
            e = w.entities.get(eid)
            if e is not None:
                w._invoke(e, method, tuple(args), client_id)
            return
        if msgtype == proto.MT_NOTIFY_CLIENT_CONNECTED:
            boot_eid = pkt.read_entity_id()
            client_id = pkt.read_entity_id()
            gate_id = pkt.read_u16()
            w.create_entity(
                self.boot_entity, eid=boot_eid,
                client=GameClient(gate_id, client_id, w),
            )
            return
        if msgtype == proto.MT_NOTIFY_CLIENT_DISCONNECTED:
            client_id = pkt.read_entity_id()
            owner = pkt.read_var_str()
            if self.sync_delta:
                # forget the departed client's delta-sync baselines
                # (its pairs simply re-keyframe if it reconnects;
                # bounds encoder state without waiting for the
                # max_entries hard reset)
                for enc in self._sync_encoders.values():
                    enc.drop_client(client_id)
            targets = (
                [w.entities.get(owner)] if owner else list(w.entities.values())
            )
            for e in targets:
                if e is not None and e.client is not None \
                        and e.client.client_id == client_id:
                    e.client = None  # connection already gone: quiet unbind
                    w._mirror_client(e)
                    if e.slot is not None and e.shard is not None:
                        w._staged_client.append(
                            (e.shard, e.slot, False, -1)
                        )
                    e.OnClientDisconnected()
            return
        if msgtype == proto.MT_SYNC_POSITION_YAW_FROM_CLIENT:
            eids, vals = codec.decode_sync_batch(
                memoryview(pkt.buf)[pkt.rpos:]
            )
            # vectorized: one searchsorted resolves the whole batch to
            # (shard, slot) rows; no per-record Python (the host wall at
            # 10K+ clients — reference decodes per record in Go,
            # GameService.go:395-407)
            w.stage_pos_sync_batch(eids, vals)
            return
        if msgtype == proto.MT_CREATE_ENTITY_ANYWHERE:
            pkt.read_u16()  # routing gameid (consumed by the dispatcher)
            type_name = pkt.read_var_str()
            eid = pkt.read_var_str()
            attrs = pkt.read_data()
            desc = (w.registry.get(type_name)
                    if type_name in w.registry else None)
            if desc is not None and desc.is_space:
                # CreateSpaceAnywhere rides the same placement path
                # (reference goworld.go CreateSpaceAnywhere); attrs go
                # as a dict, never as kwargs (wire attr names may
                # collide with parameter names)
                w.create_space(type_name, attrs=attrs, eid=eid or None)
            else:
                w.create_entity(type_name, eid=eid or None, attrs=attrs)
            return
        if msgtype == proto.MT_LOAD_ENTITY_ANYWHERE:
            pkt.read_u16()  # routing gameid
            type_name = pkt.read_var_str()
            eid = pkt.read_entity_id()
            w.load_entity(type_name, eid)
            return
        if msgtype == proto.MT_KVREG_REGISTER:
            key = pkt.read_var_str()
            val = pkt.read_var_str()
            pkt.read_bool()
            self.kvreg[key] = val
            for cb in self.kvreg_watchers:
                cb(key, val)
            return
        if msgtype == proto.MT_QUERY_SPACE_GAMEID_FOR_MIGRATE_ACK:
            self._h_query_space_ack(pkt)
            return
        if msgtype == proto.MT_MIGRATE_REQUEST_ACK:
            self._h_migrate_request_ack(pkt)
            return
        if msgtype == proto.MT_REAL_MIGRATE:
            self._h_real_migrate(pkt)
            return
        if msgtype == proto.MT_CALL_NIL_SPACES:
            method = pkt.read_var_str()
            args = pkt.read_args()
            if w.nil_space is not None:
                w._invoke(w.nil_space, method, tuple(args), None)
            return
        if msgtype == proto.MT_START_FREEZE_GAME_ACK:
            disp_id = pkt.read_u16()
            self._freeze_acks.add(disp_id)
            if len(self._freeze_acks) >= len(self.cluster.conns) \
                    and self.run_state == "running":
                # every dispatcher is now blocking us: safe to snapshot
                if w._multihost:
                    # spread the decision through the NEXT exchange so
                    # the whole controller group freezes at one tick
                    self._mh_freeze_requested = True
                else:
                    self.run_state = "freezing"
            return
        if msgtype == proto.MT_NOTIFY_GAME_CONNECTED:
            self.online_games.add(pkt.read_u16())
            return
        if msgtype == proto.MT_NOTIFY_GAME_DISCONNECTED:
            self.online_games.discard(pkt.read_u16())
            return
        if msgtype == proto.MT_REPLICATION_SUBSCRIBE:
            pkt.read_u16()  # routing target (this game)
            sgid = pkt.read_u16()
            self._repl_subscribers.add(sgid)
            try:
                # attach (and torn-stream resync) always restarts the
                # standby from a self-contained frame
                self._ensure_repl_worker().request_keyframe()
            except Exception:
                logger.exception(
                    "game%d: replication subscribe from game%d failed",
                    self.game_id, sgid)
            return
        if msgtype == proto.MT_REPLICATION_FRAME:
            pkt.read_u16()  # routing target (this game)
            pgid = pkt.read_u16()
            blob = pkt.read_bytes(pkt.read_u32())
            if (self._standby_applier is None or self._promoted
                    or pgid != self.standby_of):
                # a frame for a role we no longer (or never) hold — a
                # zombie primary streaming at a promoted standby lands
                # here, counted, never applied
                self._repl_late_frames += 1
                return
            self._repl_attached = True
            self._standby_applier.apply(blob)
            return
        if msgtype == proto.MT_NOTIFY_GATE_DISCONNECTED:
            gate_id = pkt.read_u16()
            for e in list(w.entities.values()):
                if e.client is not None and e.client.gate_id == gate_id:
                    e.client = None
                    w._mirror_client(e)
                    if e.slot is not None and e.shard is not None:
                        w._staged_client.append(
                            (e.shard, e.slot, False, -1)
                        )
                    e.OnClientDisconnected()
            return
        logger.warning("game%d: unhandled msgtype %d", self.game_id, msgtype)

    # -- migration handlers ---------------------------------------------
    def _h_query_space_ack(self, pkt: Packet) -> None:
        space_id = pkt.read_entity_id()
        eid = pkt.read_entity_id()
        game_id = pkt.read_u16()
        pending = self._migrating_out.get(eid)
        if pending is None:
            return
        e, want_space, _pos = pending
        if want_space != space_id:
            return
        if game_id == 0:
            logger.warning(
                "game%d: space %s not found for migration of %s",
                self.game_id, space_id, eid,
            )
            del self._migrating_out[eid]
            return
        if e.destroyed:
            del self._migrating_out[eid]
            return
        p = proto.pack_migrate_request(eid, space_id, game_id)
        self._send(self.cluster.select_by_entity_id(eid), p)

    def _h_migrate_request_ack(self, pkt: Packet) -> None:
        eid = pkt.read_entity_id()
        space_id = pkt.read_entity_id()
        game_id = pkt.read_u16()
        pending = self._migrating_out.pop(eid, None)
        if pending is None:
            return
        e, _space, pos = pending
        if e.destroyed:
            self._send(
                self.cluster.select_by_entity_id(eid),
                proto.pack_cancel_migrate(eid),
            )
            return
        data = self.world.get_migrate_data(e)
        data["space_id"] = space_id
        data["pos"] = list(pos)
        # target stamped into the ledger's in-flight record: the
        # conservation verdict and the /audit plane can then name
        # WHERE an unmatched out-record was headed
        self.world.remove_for_migration(e, target=game_id)
        p = proto.pack_real_migrate(eid, game_id, data)
        self._send(self.cluster.select_by_entity_id(eid), p)

    def _h_real_migrate(self, pkt: Packet) -> None:
        eid = pkt.read_entity_id()
        pkt.read_u16()  # target game (us)
        data = pkt.read_data()
        space = self.world.spaces.get(data.get("space_id", ""))
        if space is None:
            logger.warning(
                "game%d: migrate-in %s: space %s vanished; entering nil "
                "space", self.game_id, eid, data.get("space_id"),
            )
        self.world.restore_from_migration(data, space=space)
