"""Networking: wire protocol, gate (client edge), dispatcher (router),
game server host, and the bot client harness.

Reference being rebuilt: ``engine/netutil`` (packet framing),
``engine/proto`` (message space), ``components/{dispatcher,gate}`` and the
game side of ``components/game`` (``GameService.go``), plus
``examples/test_client`` (bot swarm).

The device mesh replaces the dispatcher *within* one game process
(:mod:`goworld_tpu.parallel`); this package is the *between-process* layer —
multiple game processes, gates terminating client sockets, and a sharded
dispatcher router — kept host-side exactly like the reference, but with the
hot sync-record path batched into numpy arrays the device can consume
directly (and a C++ codec for the byte-level encode/decode).
"""
