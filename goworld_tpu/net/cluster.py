"""Dispatcher-cluster client: every game/gate holds one connection per
dispatcher and routes by EntityID hash.

Reference being rebuilt: ``engine/dispatchercluster`` (``Initialize``,
``SelectByEntityID/ByGateID``, send wrappers — ``dispatchercluster.go:18-135``)
and ``engine/dispatchercluster/dispatcherclient`` (connect-forever loop,
re-handshake with entity census on reconnect — ``DispatcherConnMgr.go:63-131``).

Routing (reference ``hash.go:7-12``): hash the last two bytes of the
16-char EntityID modulo dispatcher count; gates route themselves by
``(gate_id - 1) % n``. Identical hashing on every process is what makes the
sharded star consistent.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Awaitable, Callable

from goworld_tpu.net.packet import Packet, PacketConnection, wire_payload
from goworld_tpu.utils import consts, log, metrics

logger = log.get("cluster")


def entity_shard(eid: str, n: int) -> int:
    """Dispatcher index for an EntityID (reference ``hash.go:7-12``)."""
    if n == 1:
        return 0
    b = eid.encode("ascii", "replace")
    return (b[-2] << 8 | b[-1]) % n


def srv_shard(srv_id: str, n: int) -> int:
    """Dispatcher index for a service/registry key (string hash)."""
    if n == 1:
        return 0
    h = 0
    for ch in srv_id.encode():
        h = (h * 31 + ch) & 0xFFFFFFFF
    return h % n


class DispatcherConn:
    """Connect-forever manager for ONE dispatcher (reference
    ``DispatcherConnMgr``). ``handshake`` is awaited after every (re)connect;
    received packets go to ``on_packet``; sends while disconnected queue."""

    def __init__(
        self,
        index: int,
        addr: tuple[str, int],
        on_packet: Callable[[int, int, Packet], None],
        handshake: Callable[["DispatcherConn"], Awaitable[None]],
        reconnect_delay: float = 1.0,
        edge: str = "",
        pend_max_packets: int = consts.MAX_RECONNECT_PEND_PACKETS,
        pend_max_bytes: int = consts.MAX_RECONNECT_PEND_BYTES,
    ):
        self.index = index
        self.addr = addr
        self.on_packet = on_packet
        self.handshake = handshake
        self.reconnect_delay = reconnect_delay
        self.edge = edge  # fault-injection label (utils/faults.py)
        self.conn: PacketConnection | None = None
        # reconnect pend queue, BOUNDED by a packet + byte budget with a
        # drop-oldest policy: a long dispatcher outage must degrade to
        # bounded message loss (counted below), never to unbounded
        # process growth. Oldest-first because queued cluster messages
        # age badly — the census re-handshake on reconnect re-asserts
        # current state anyway.
        self._pending: deque[bytes] = deque()
        self._pending_bytes = 0
        self.pend_max_packets = pend_max_packets
        self.pend_max_bytes = pend_max_bytes
        self._m_pend_dropped = metrics.counter(
            "cluster_pend_dropped_total",
            help="queued-while-disconnected packets dropped on overflow",
            dispatcher=str(index),
        )
        self._pend_warned = False
        self.connected = asyncio.Event()
        self._stopped = False
        # fired on every connection loss (before the reconnect sleep);
        # the gate uses this to terminate instead of reconnecting
        # (reference gate.go:137-143)
        self.on_disconnect = None

    async def run(self) -> None:
        """The assureConnected/serve loop; returns only when stopped."""
        while not self._stopped:
            try:
                reader, writer = await asyncio.open_connection(*self.addr)
            except OSError:
                await asyncio.sleep(self.reconnect_delay)
                continue
            self.conn = PacketConnection(reader, writer, edge=self.edge)
            try:
                await self.handshake(self)
                while self._pending:
                    self.conn.send(Packet(self._pending.popleft()),
                                   release=False)
                self._pending_bytes = 0
                self._pend_warned = False
                self.connected.set()
                while True:
                    msgtype, pkt = await self.conn.recv()
                    self.on_packet(self.index, msgtype, pkt)
            except (EOFError, ConnectionError, OSError):
                # EOFError also covers a malformed/truncated packet
                # whose decode underran (IncompleteReadError is an
                # EOFError subclass): sever + reconnect, never wedge
                pass
            finally:
                self.connected.clear()
                await self.conn.close()
                self.conn = None
            if not self._stopped:
                logger.warning(
                    "lost dispatcher%d at %s; reconnecting",
                    self.index, self.addr,
                )
                if self.on_disconnect is not None:
                    self.on_disconnect(self.index)
                await asyncio.sleep(self.reconnect_delay)

    def send(self, p: Packet, release: bool = True) -> None:
        if self.conn is not None and not self.conn.closed:
            self.conn.send(p, release=release)
        else:
            # wire_payload keeps a trace trailer through the reconnect
            # queue (byte-identical to p.buf when untraced); the flush
            # sends the stored bytes verbatim
            raw = wire_payload(p)
            self._pending.append(raw)
            self._pending_bytes += len(raw)
            while self._pending and (
                len(self._pending) > self.pend_max_packets
                or self._pending_bytes > self.pend_max_bytes
            ):
                self._pending_bytes -= len(self._pending.popleft())
                self._m_pend_dropped.inc()
                if not self._pend_warned:
                    self._pend_warned = True
                    logger.warning(
                        "dispatcher%d reconnect queue over budget "
                        "(%d pkts / %d B): dropping oldest (counted in "
                        "cluster_pend_dropped_total)", self.index,
                        self.pend_max_packets, self.pend_max_bytes,
                    )
            if release:
                p.release()

    def stop(self) -> None:
        self._stopped = True


class DispatcherCluster:
    """All dispatcher connections of one game/gate process."""

    def __init__(
        self,
        addrs: list[tuple[str, int]],
        on_packet: Callable[[int, int, Packet], None],
        handshake: Callable[[DispatcherConn], Awaitable[None]],
        edge: str = "",
        pend_max_packets: int = consts.MAX_RECONNECT_PEND_PACKETS,
        pend_max_bytes: int = consts.MAX_RECONNECT_PEND_BYTES,
    ):
        self.conns = [
            DispatcherConn(i, a, on_packet, handshake, edge=edge,
                           pend_max_packets=pend_max_packets,
                           pend_max_bytes=pend_max_bytes)
            for i, a in enumerate(addrs)
        ]
        self._tasks: list[asyncio.Task] = []

    def start(self) -> None:
        self._tasks = [
            asyncio.ensure_future(c.run()) for c in self.conns
        ]

    async def wait_connected(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(
            asyncio.gather(*(c.connected.wait() for c in self.conns)),
            timeout,
        )

    def stop(self) -> None:
        for c in self.conns:
            c.stop()
        for t in self._tasks:
            t.cancel()

    # -- selection (reference dispatchercluster.go:115-135) -------------
    def select_by_entity_id(self, eid: str) -> DispatcherConn:
        return self.conns[entity_shard(eid, len(self.conns))]

    def select_by_gate_id(self, gate_id: int) -> DispatcherConn:
        return self.conns[(gate_id - 1) % len(self.conns)]

    def select_by_srv_id(self, srv_id: str) -> DispatcherConn:
        return self.conns[srv_shard(srv_id, len(self.conns))]

    def broadcast(self, p: Packet) -> None:
        for c in self.conns:
            c.send(Packet(bytes(p.buf)), release=False)
        p.release()
