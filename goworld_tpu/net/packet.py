"""Binary packet framing: pooled packets + asyncio stream codec.

Reference being rebuilt: ``engine/netutil/Packet.go`` (pooled little-endian
buffer with Append/Read for u16/u32/float32/EntityID/VarStr/VarBytes/Data)
and ``engine/netutil/PacketConnection.go`` (length-prefixed framing over
TCP). Wire format kept in the same spirit:

    [u32 payload_size][u16 msgtype][payload ...]        (little-endian)

EntityIDs are fixed 16 ASCII bytes (:mod:`goworld_tpu.utils.ids`);
structured args are msgpack (reference ``MsgPacker.go``); hot-path position
sync records are fixed 32-byte binary records — 16B entity id + 4×f32
x,y,z,yaw (reference ``proto.go:122`` SYNC_INFO_SIZE_PER_ENTITY plus the id
prefix) — batch-encoded by :mod:`goworld_tpu.net.codec`.
"""

from __future__ import annotations

import asyncio
import struct
import zlib
from typing import Any

import msgpack

from goworld_tpu.utils import faults, tracing
from goworld_tpu.utils.ids import ENTITYID_LENGTH

MAX_PAYLOAD_LENGTH = 32 * 1024 * 1024  # defensive cap (reference 16M-ish)
_SIZE_FMT = struct.Struct("<I")
_TYPE_FMT = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U16 = struct.Struct("<H")
_F32 = struct.Struct("<f")
HEADER_SIZE = 4  # the u32 size prefix; msgtype counts into payload_size

# bit 15 of the u16 msgtype field marks a trace-context trailer: the
# last CTX_WIRE_SIZE bytes of the payload are a packed
# tracing.TraceContext, stripped before the handler sees the packet.
# Every real msgtype lives in the documented 0..2047 routing ranges
# (net/proto.py; guarded by tests/test_proto_invariants.py), so the bit
# can never collide — and untraced packets pay zero bytes (the framed
# stream is byte-identical to the pre-tracing wire).
TRACE_FLAG = 0x8000
# bit 14 marks a sync-age stamp trailer (utils/syncage.py): the 45-byte
# per-batch provenance record the sync fan-out legs carry from game to
# gate. Same contract as TRACE_FLAG — the routing ranges stop at 2047,
# so the bit never collides, unstamped packets are byte-identical to
# the pre-stamp wire, and the trailer is stripped into ``packet.age``
# before any handler sees the payload. When both trailers ride one
# packet the trace context is OUTERMOST (appended last, stripped
# first) — tracing wraps every other plane.
AGE_FLAG = 0x4000
MSGTYPE_MASK = 0x7FFF

_pool: list["Packet"] = []
_POOL_MAX = 256


class Packet:
    """A reusable binary message buffer (reference ``Packet.go``).

    Append-side builds `[u16 msgtype][payload]`; read-side walks the same
    bytes with a cursor. Use :func:`alloc` / :meth:`release` for pooling on
    hot paths; plain construction also works.
    """

    __slots__ = ("buf", "rpos", "trace", "age")

    def __init__(self, data: bytes | bytearray | None = None):
        self.buf = bytearray(data) if data is not None else bytearray()
        self.rpos = 0
        # attached tracing.TraceContext (or None): set by decode_wire on
        # traced inbound packets and by hops/new_packet on outbound ones;
        # applied to the wire as a flagged trailer by wire_payload
        self.trace = None
        # attached syncage.SyncAgeStamp (or None): set by decode_wire on
        # stamped inbound sync batches and by the game's fan-out flush
        # on outbound ones; the dispatcher patches its forward instant
        # into it before relaying (utils/syncage.py)
        self.age = None

    # -- lifecycle -------------------------------------------------------
    @staticmethod
    def alloc() -> "Packet":
        try:
            # list.pop is GIL-atomic; EAFP keeps this safe across the
            # logic + network threads without a lock
            return _pool.pop()
        except IndexError:
            return Packet()

    def release(self) -> None:
        self.trace = None  # never leak a context into a pooled reuse
        self.age = None
        if len(_pool) < _POOL_MAX:
            self.buf.clear()
            self.rpos = 0
            _pool.append(self)

    # -- append side -----------------------------------------------------
    def append_u8(self, v: int) -> None:
        self.buf.append(v & 0xFF)

    def append_bool(self, v: bool) -> None:
        self.buf.append(1 if v else 0)

    def append_u16(self, v: int) -> None:
        self.buf += _U16.pack(v & 0xFFFF)

    def append_u32(self, v: int) -> None:
        self.buf += _U32.pack(v & 0xFFFFFFFF)

    def append_f32(self, v: float) -> None:
        self.buf += _F32.pack(v)

    def append_bytes(self, b: bytes) -> None:
        self.buf += b

    def append_entity_id(self, eid: str) -> None:
        b = eid.encode("ascii")
        if len(b) != ENTITYID_LENGTH:
            raise ValueError(f"bad entity id {eid!r}")
        self.buf += b

    def append_var_str(self, s: str) -> None:
        self.append_var_bytes(s.encode("utf-8"))

    def append_var_bytes(self, b: bytes) -> None:
        self.append_u32(len(b))
        self.buf += b

    def append_data(self, obj: Any) -> None:
        """msgpack-encode an arbitrary structure (reference ``AppendData``)."""
        self.append_var_bytes(
            msgpack.packb(obj, use_bin_type=True)
        )

    def append_args(self, args: tuple | list) -> None:
        """Argument list: u16 count + one msgpack blob per arg (reference
        ``AppendArgs`` packs each arg separately so the receiver can lazily
        decode)."""
        self.append_u16(len(args))
        for a in args:
            self.append_data(a)

    # -- read side -------------------------------------------------------
    def _take(self, n: int) -> memoryview:
        if self.rpos + n > len(self.buf):
            raise EOFError("packet underrun")
        mv = memoryview(self.buf)[self.rpos:self.rpos + n]
        self.rpos += n
        return mv

    def read_u8(self) -> int:
        return self._take(1)[0]

    def read_bool(self) -> bool:
        return self._take(1)[0] != 0

    def read_u16(self) -> int:
        return _U16.unpack(self._take(2))[0]

    def read_u32(self) -> int:
        return _U32.unpack(self._take(4))[0]

    def read_f32(self) -> float:
        return _F32.unpack(self._take(4))[0]

    def read_bytes(self, n: int) -> bytes:
        return bytes(self._take(n))

    def read_entity_id(self) -> str:
        return bytes(self._take(ENTITYID_LENGTH)).decode("ascii")

    def read_var_bytes(self) -> bytes:
        n = self.read_u32()
        return bytes(self._take(n))

    def read_var_str(self) -> str:
        return self.read_var_bytes().decode("utf-8")

    def read_data(self) -> Any:
        return msgpack.unpackb(self.read_var_bytes(), raw=False)

    def read_args(self) -> list:
        n = self.read_u16()
        return [self.read_data() for _ in range(n)]

    def remaining(self) -> int:
        return len(self.buf) - self.rpos

    def payload(self) -> bytes:
        return bytes(self.buf)


def new_packet(msgtype: int) -> Packet:
    p = Packet.alloc()
    p.append_u16(msgtype)
    if tracing.active:
        # inside a traced hop (tracing.use/hop): outbound packets carry
        # the emitting span's context so the next hop parents to it
        ctx = tracing.current()
        if ctx is not None:
            p.trace = ctx
    return p


def wire_payload(p: Packet) -> bytes:
    """Payload bytes as they go on the wire: verbatim when untraced and
    unstamped; with TRACE_FLAG / AGE_FLAG set on the msgtype and the
    packed trailer(s) appended when attached. The age stamp goes on
    FIRST so the trace context stays outermost (decode strips in
    reverse)."""
    if p.trace is None and p.age is None:
        return bytes(p.buf)
    buf = bytearray(p.buf)
    if p.age is not None:
        buf[1] |= 0x40  # little-endian u16 msgtype: bit 14 in byte 1
        buf += p.age.pack()
    if p.trace is not None:
        buf[1] |= 0x80  # bit 15 lives in byte 1
        buf += p.trace.pack()
    return bytes(buf)


def decode_wire(body: bytes | bytearray) -> tuple[int, Packet]:
    """Inverse of :func:`wire_payload` + the msgtype read: returns the
    masked msgtype and a Packet positioned after it, with any trace
    trailer stripped into ``packet.trace`` (handlers see byte-identical
    payloads either way)."""
    p = Packet(body)
    msgtype = p.read_u16()
    if msgtype & TRACE_FLAG:
        msgtype &= MSGTYPE_MASK
        if len(p.buf) < 2 + tracing.CTX_WIRE_SIZE:
            raise ConnectionError("traced packet too short for trailer")
        p.trace = tracing.TraceContext.unpack(
            bytes(p.buf[-tracing.CTX_WIRE_SIZE:])
        )
        del p.buf[-tracing.CTX_WIRE_SIZE:]
        # clear the flag in the stored bytes too: handlers that forward
        # or copy the raw buffer (queue-while-blocked, broadcasts) must
        # see payload bytes identical to an untraced packet's — the
        # flag is re-applied by wire_payload iff a context is attached
        p.buf[1] &= 0x7F
    if msgtype & AGE_FLAG:
        from goworld_tpu.utils import syncage

        msgtype &= ~AGE_FLAG
        if len(p.buf) < 2 + syncage.STAMP_WIRE_SIZE:
            raise ConnectionError("stamped packet too short for trailer")
        try:
            p.age = syncage.SyncAgeStamp.unpack(
                bytes(p.buf[-syncage.STAMP_WIRE_SIZE:])
            )
        except ValueError as exc:
            raise ConnectionError(f"bad sync-age stamp: {exc}") from exc
        del p.buf[-syncage.STAMP_WIRE_SIZE:]
        p.buf[1] &= 0xBF  # same re-apply contract as the trace flag
    return msgtype, p


def frame(p: Packet) -> bytes:
    """Wrap a packet's payload with the u32 size prefix for the wire."""
    payload = wire_payload(p)
    return _SIZE_FMT.pack(len(payload)) + payload


class PacketConnection:
    """Framed packet IO over an asyncio stream (reference
    ``PacketConnection.go``). Writes are buffered by the transport; reads
    return (msgtype, Packet-positioned-after-msgtype).

    ``compress=True`` runs one compression stream per direction over
    the connection — the cheap-stream-compression role snappy plays in
    the reference's client edge (``ClientProxy.go:38-53``).
    ``compress_codec`` picks the stream codec:

    * ``"snappy"`` (default) — the reference's codec, via the
      from-scratch framing-format implementation in
      :mod:`goworld_tpu.net.snappy` (each packet is one or more framed
      chunks; the stream identifier leads the first send).
    * ``"zlib"`` — one zlib-1 stream with ``Z_SYNC_FLUSH`` at packet
      boundaries; its shared per-connection dictionary compresses the
      dominant small packets (heartbeats, 34-byte sync records)
      better than snappy's per-chunk framing, at more CPU per byte.

    Both ends must agree on flag AND codec, exactly like the
    reference's ini flag; a codec the environment cannot provide
    raises at construction (silent fallback would desync the peer)."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        *,
        compress: bool = False,
        compress_codec: str = "snappy",
        edge: str = "",
    ):
        self.reader = reader
        self.writer = writer
        # fault-injection edge label ("game->dispatcher", ...): owners
        # set it so the seeded fault plane (utils/faults.py) can match
        # wire rules against this connection; "" = never injected
        self.edge = edge
        self.compress = compress
        if compress:
            if compress_codec == "snappy":
                from goworld_tpu.net import snappy as _snappy

                if not _snappy.available():
                    raise RuntimeError(
                        "snappy codec unavailable (native build failed);"
                        " set compress_codec = zlib on BOTH ends"
                    )
                self._comp = _snappy.StreamCompressor()
                self._decomp = _snappy.StreamDecompressor()
                self._snappy = True
            elif compress_codec == "zlib":
                self._comp = zlib.compressobj(1)
                self._decomp = zlib.decompressobj()
                self._snappy = False
            else:
                raise ValueError(
                    f"compress_codec must be snappy|zlib, "
                    f"got {compress_codec!r}"
                )
        self._closed = False

    def send(self, p: Packet, release: bool = True) -> None:
        if self._closed:
            return
        try:
            if self.compress:
                raw = wire_payload(p)
                if self._snappy:
                    payload = self._comp.compress(raw)
                else:
                    payload = self._comp.compress(raw) \
                        + self._comp.flush(zlib.Z_SYNC_FLUSH)
                self.writer.write(_SIZE_FMT.pack(len(payload)) + payload)
            elif faults.active and self.edge \
                    and self._faulted_send(p):
                pass  # the fault consumed (or rewrote) the packet
            else:
                self.writer.write(frame(p))
        except (ConnectionError, RuntimeError):
            self._closed = True
        if release:
            p.release()

    def _faulted_send(self, p: Packet) -> bool:
        """Apply a seeded wire fault to this send, if one fires.
        Returns True when the fault handled the packet (the normal
        write must be skipped). Only the uncompressed path is injected:
        stream compression shares codec state with the peer, so
        byte-level tampering there models a codec bug, not a network
        fault."""
        mt = ((p.buf[0] | (p.buf[1] << 8)) & MSGTYPE_MASK
              if len(p.buf) >= 2 else 0)
        rule = faults.plane.wire_fault(self.edge, mt, trace_ctx=p.trace)
        if rule is None:
            return False
        if rule.kind == "drop":
            return True
        payload = wire_payload(p)
        data = _SIZE_FMT.pack(len(payload)) + payload
        if rule.kind == "dup":
            self.writer.write(data)
            self.writer.write(data)
            return True
        if rule.kind == "truncate":
            # a consistently-framed but cut-short payload: the peer's
            # decoder sees a malformed packet (size < 2 or a handler
            # underrun) and severs the connection — the corruption
            # recovery path, not a stream desync
            cut = payload[: len(payload) // 2]
            self.writer.write(_SIZE_FMT.pack(len(cut)) + cut)
            return True
        if rule.kind == "disconnect":
            self._closed = True
            try:
                self.writer.transport.abort()
            except (AttributeError, RuntimeError):
                self.writer.close()
            return True
        if rule.kind == "delay":
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return False  # no loop (unit context): send normally

            def _late_write(w=self.writer, d=data):
                try:
                    w.write(d)
                except (ConnectionError, RuntimeError):
                    pass

            loop.call_later(rule.delay_s, _late_write)
            return True
        return False

    async def drain(self) -> None:
        if not self._closed:
            try:
                await self.writer.drain()
            except ConnectionError:
                self._closed = True

    async def recv(self) -> tuple[int, Packet]:
        hdr = await self.reader.readexactly(HEADER_SIZE)
        (size,) = _SIZE_FMT.unpack(hdr)
        if size < 2 or size > MAX_PAYLOAD_LENGTH:
            raise ConnectionError(f"bad packet size {size}")
        body: bytes | bytearray = await self.reader.readexactly(size)
        if self.compress:
            if self._snappy:
                try:
                    # the bound is checked chunk-by-chunk during
                    # decode, so a bomb stream fails before allocation
                    body = self._decomp.decompress(
                        bytes(body), max_out=MAX_PAYLOAD_LENGTH
                    )
                except ValueError as exc:
                    raise ConnectionError(
                        f"bad compressed packet: {exc}")
            else:
                try:
                    # max_length caps output BEFORE allocation: a
                    # crafted high-ratio stream (decompression bomb)
                    # hits the limit and leaves unconsumed input
                    # instead of eating RAM
                    body = self._decomp.decompress(
                        bytes(body), MAX_PAYLOAD_LENGTH + 1
                    )
                except zlib.error as exc:
                    raise ConnectionError(f"bad compressed packet: {exc}")
                if self._decomp.unconsumed_tail \
                        or len(body) > MAX_PAYLOAD_LENGTH:
                    raise ConnectionError("decompressed packet too large")
            if len(body) < 2:
                raise ConnectionError("short decompressed packet")
        return decode_wire(body)

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except (ConnectionError, RuntimeError):
            pass

    @property
    def closed(self) -> bool:
        return self._closed

    def peername(self):
        try:
            return self.writer.get_extra_info("peername")
        except Exception:
            return None
