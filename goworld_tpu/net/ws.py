"""Minimal RFC 6455 WebSocket transport (stdlib-only).

The gate's websocket edge and the bot client's ``-ws`` mode were
written against the third-party ``websockets`` package, which is not
part of this runtime — the import failed at connection-serve time and
(because it sat ABOVE the ``try:``) left the gate's ``ws_started``
event unset, wedging every harness boot with ``with_ws=True`` (the
pre-existing tier-1 ``tests/test_ws`` error). This module is the
from-scratch replacement: the exact API subset those two call sites
use (``serve``/``connect``, ``send``/``recv``/``close``/``open``,
``async for`` message iteration), implemented on asyncio streams.

Scope (all the engine needs — one framed engine packet per BINARY
message, matching the reference's websocket edge,
``GateService.go:121-168``):

* HTTP/1.1 upgrade handshake (Sec-WebSocket-Key -> SHA1/base64 accept);
* frame codec: FIN + opcode, 7/16/64-bit lengths, client->server
  masking (required by the RFC; servers send unmasked);
* text and binary data frames with continuation reassembly, ping ->
  pong, close -> echoed close;
* no extensions, no subprotocols, no TLS (the gate terminates TLS on
  its TCP listener; the ws edge is plaintext like the reference).

When the real ``websockets`` package IS installed, the call sites
still prefer it (``import websockets`` first, this module as the
fallback) — the shim exists so a bare container serves websocket
clients out of the box.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import os
import struct

__all__ = ["WebSocket", "ConnectionClosed", "serve", "connect"]

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

# bound on a single message (continuations included): the engine's
# client-edge packets are far smaller; a hostile length header must
# not balloon the reassembly buffer
MAX_MESSAGE_BYTES = 64 * 1024 * 1024


class ConnectionClosed(ConnectionError):
    """The peer closed (or the transport died) mid-conversation.
    Subclasses ConnectionError so every existing recv-loop handler
    (botclient, gate) catches it without naming this module."""


def _accept_key(key: str) -> str:
    digest = hashlib.sha1((key + _GUID).encode("ascii")).digest()
    return base64.b64encode(digest).decode("ascii")


def _xor_mask(payload: bytes, key: bytes) -> bytes:
    """``payload[i] ^ key[i % 4]`` for the whole buffer as ONE big-int
    XOR (a per-byte Python loop on the gate's per-packet ingress path
    would cost seconds for a large frame and stall the event loop)."""
    n = len(payload)
    if not n:
        return payload
    stream = (key * ((n + 3) // 4))[:n]
    return (int.from_bytes(payload, "little")
            ^ int.from_bytes(stream, "little")).to_bytes(n, "little")


class WebSocket:
    """One established websocket; the object handed to server handlers
    and returned by :func:`connect`.

    ``await send(data)`` ships one message (bytes -> binary frame, str
    -> text frame); ``await recv()`` returns the next DATA message
    payload (control frames are handled internally); ``async for msg
    in ws`` iterates messages until close. ``open`` mirrors the
    legacy ``websockets`` attribute the call sites probe."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, *, mask_outgoing: bool):
        self._reader = reader
        self._writer = writer
        self._mask = mask_outgoing  # clients mask, servers don't
        self._send_lock = asyncio.Lock()
        self._closed = False

    @property
    def open(self) -> bool:
        return not self._closed

    # -- frame codec ----------------------------------------------------
    async def _send_frame(self, opcode: int, payload: bytes) -> None:
        if self._closed and opcode != OP_CLOSE:
            raise ConnectionClosed("websocket is closed")
        head = bytearray([0x80 | opcode])
        mask_bit = 0x80 if self._mask else 0
        n = len(payload)
        if n < 126:
            head.append(mask_bit | n)
        elif n < (1 << 16):
            head.append(mask_bit | 126)
            head += struct.pack(">H", n)
        else:
            head.append(mask_bit | 127)
            head += struct.pack(">Q", n)
        if self._mask:
            key = os.urandom(4)
            head += key
            payload = _xor_mask(payload, key)
        async with self._send_lock:
            try:
                self._writer.write(bytes(head) + payload)
                await self._writer.drain()
            except (ConnectionError, OSError) as exc:
                self._closed = True
                raise ConnectionClosed(str(exc)) from exc

    async def _read_frame(self) -> tuple[int, bool, bytes]:
        """(opcode, fin, unmasked payload); raises ConnectionClosed on
        EOF/transport death."""
        try:
            b0, b1 = await self._reader.readexactly(2)
            fin = bool(b0 & 0x80)
            opcode = b0 & 0x0F
            masked = bool(b1 & 0x80)
            n = b1 & 0x7F
            if n == 126:
                (n,) = struct.unpack(
                    ">H", await self._reader.readexactly(2))
            elif n == 127:
                (n,) = struct.unpack(
                    ">Q", await self._reader.readexactly(8))
            if n > MAX_MESSAGE_BYTES:
                raise ConnectionClosed(f"frame too large ({n} bytes)")
            key = await self._reader.readexactly(4) if masked else b""
            payload = await self._reader.readexactly(n) if n else b""
        except (asyncio.IncompleteReadError, ConnectionError,
                OSError) as exc:
            self._closed = True
            raise ConnectionClosed(str(exc)) from exc
        if masked:
            payload = _xor_mask(payload, key)
        return opcode, fin, payload

    # -- public API (the websockets-package subset) ---------------------
    async def send(self, data) -> None:
        if isinstance(data, str):
            await self._send_frame(OP_TEXT, data.encode("utf-8"))
        else:
            await self._send_frame(OP_BINARY, bytes(data))

    async def recv(self):
        """Next data message: bytes for binary, str for text."""
        buf = bytearray()
        first_op: int | None = None
        while True:
            if self._closed:
                raise ConnectionClosed("websocket is closed")
            opcode, fin, payload = await self._read_frame()
            if opcode == OP_PING:
                await self._send_frame(OP_PONG, payload)
                continue
            if opcode == OP_PONG:
                continue
            if opcode == OP_CLOSE:
                self._closed = True
                try:
                    await self._send_frame(OP_CLOSE, payload[:2])
                except ConnectionClosed:
                    pass
                self._shut_transport()
                raise ConnectionClosed("peer sent close")
            if opcode in (OP_TEXT, OP_BINARY):
                first_op = opcode
                buf += payload
            elif opcode == OP_CONT and first_op is not None:
                buf += payload
            else:
                raise ConnectionClosed(f"bad opcode {opcode:#x}")
            if len(buf) > MAX_MESSAGE_BYTES:
                raise ConnectionClosed("message too large")
            if fin:
                data = bytes(buf)
                return data.decode("utf-8") if first_op == OP_TEXT \
                    else data

    def __aiter__(self) -> "WebSocket":
        return self

    async def __anext__(self):
        try:
            return await self.recv()
        except ConnectionClosed:
            raise StopAsyncIteration from None

    def _shut_transport(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass

    async def close(self, code: int = 1000) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            await self._send_frame(OP_CLOSE, struct.pack(">H", code))
        except ConnectionClosed:
            pass
        self._shut_transport()


# =======================================================================
# server side
# =======================================================================
async def _server_handshake(reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> bool:
    """Read the HTTP upgrade request and answer 101 (or 400)."""
    try:
        head = await asyncio.wait_for(
            reader.readuntil(b"\r\n\r\n"), timeout=10.0)
    except (asyncio.IncompleteReadError, asyncio.LimitOverrunError,
            asyncio.TimeoutError, ConnectionError, OSError):
        return False
    headers: dict[str, str] = {}
    for line in head.split(b"\r\n")[1:]:
        if b":" in line:
            k, _, v = line.partition(b":")
            headers[k.strip().lower().decode("latin-1")] = \
                v.strip().decode("latin-1")
    key = headers.get("sec-websocket-key")
    if key is None or "websocket" not in \
            headers.get("upgrade", "").lower():
        writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                     b"Content-Length: 0\r\n\r\n")
        try:
            await writer.drain()
        except (ConnectionError, OSError):
            pass
        return False
    writer.write(
        b"HTTP/1.1 101 Switching Protocols\r\n"
        b"Upgrade: websocket\r\n"
        b"Connection: Upgrade\r\n"
        b"Sec-WebSocket-Accept: "
        + _accept_key(key).encode("ascii") + b"\r\n\r\n"
    )
    try:
        await writer.drain()
    except (ConnectionError, OSError):
        return False
    return True


async def serve(handler, host: str, port: int) -> asyncio.AbstractServer:
    """``websockets.serve`` twin: start a TCP listener; each upgraded
    connection runs ``await handler(ws)``. Returns the asyncio server
    (``.close()`` to stop listening)."""

    async def _on_conn(reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        ws = None
        try:
            if not await _server_handshake(reader, writer):
                writer.close()
                return
            ws = WebSocket(reader, writer, mask_outgoing=False)
            await handler(ws)
        except (ConnectionClosed, ConnectionError, OSError):
            pass
        finally:
            if ws is not None:
                await ws.close()
            else:
                try:
                    writer.close()
                except Exception:
                    pass

    return await asyncio.start_server(_on_conn, host, port)


# =======================================================================
# client side
# =======================================================================
async def connect(uri: str) -> WebSocket:
    """``websockets.connect`` twin for ``ws://host:port[/path]``."""
    if not uri.startswith("ws://"):
        raise ValueError(f"only ws:// URIs are supported (got {uri!r})")
    rest = uri[len("ws://"):]
    hostport, _, path = rest.partition("/")
    host, _, port_s = hostport.partition(":")
    port = int(port_s or 80)
    reader, writer = await asyncio.open_connection(host, port)
    key = base64.b64encode(os.urandom(16)).decode("ascii")
    writer.write(
        (f"GET /{path} HTTP/1.1\r\n"
         f"Host: {hostport}\r\n"
         "Upgrade: websocket\r\n"
         "Connection: Upgrade\r\n"
         f"Sec-WebSocket-Key: {key}\r\n"
         "Sec-WebSocket-Version: 13\r\n\r\n").encode("latin-1")
    )
    await writer.drain()
    try:
        head = await asyncio.wait_for(
            reader.readuntil(b"\r\n\r\n"), timeout=10.0)
    except (asyncio.IncompleteReadError, asyncio.TimeoutError) as exc:
        writer.close()
        raise ConnectionError(f"websocket handshake failed: {exc}") \
            from exc
    status = head.split(b"\r\n", 1)[0]
    if b"101" not in status:
        writer.close()
        raise ConnectionError(
            f"websocket handshake rejected: {status.decode('latin-1')}")
    expect = _accept_key(key).encode("ascii")
    if expect not in head:
        writer.close()
        raise ConnectionError("websocket accept-key mismatch")
    return WebSocket(reader, writer, mask_outgoing=True)
