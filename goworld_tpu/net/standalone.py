"""Standalone cluster harness: dispatcher(s) + gate(s) in one process.

The reference always deploys dispatcher/game/gate as separate OS processes
(``cmd/goworld`` start). For tests, examples and single-machine runs we also
support hosting the dispatcher and gate services on a background asyncio
thread inside the game process — real sockets, real wire protocol, one
process. This is the "single-host multi-process integration driven by a bot
swarm" fixture of the reference's test strategy (``SURVEY.md#4``) without
process management.
"""

from __future__ import annotations

import asyncio
import threading
from concurrent.futures import Future
from typing import Coroutine

from goworld_tpu.net.dispatcher import DispatcherService
from goworld_tpu.net.gate import GateService
from goworld_tpu.utils import log

logger = log.get("standalone")


class ClusterHarness:
    """Runs N dispatchers + M gates on ephemeral ports in a daemon thread."""

    def __init__(self, n_dispatchers: int = 1, n_gates: int = 1,
                 desired_games: int = 1, host: str = "127.0.0.1",
                 heartbeat_timeout: float = 0.0,
                 position_sync_interval_ms: int = 20,
                 with_ws: bool = False, with_kcp: bool = False,
                 compress: bool = False,
                 tls_dir: str | None = None,
                 gate_exit_on_dispatcher_loss: bool = False,
                 gate_kwargs: dict | None = None):
        self.host = host
        self.n_dispatchers = n_dispatchers
        self.n_gates = n_gates
        self.desired_games = desired_games
        self.heartbeat_timeout = heartbeat_timeout
        self.position_sync_interval_ms = position_sync_interval_ms
        self.with_ws = with_ws
        self.with_kcp = with_kcp
        self.gate_kcp_addrs: list[tuple[str, int]] = []
        # client-edge transport (reference goworld_actions.ini runs CI
        # with compression+encryption ON)
        self.compress = compress
        self.tls_dir = tls_dir  # directory for the self-signed pair
        # default False: the harness tears processes down in arbitrary
        # order; real deployments keep the gate default (True)
        self.gate_exit_on_dispatcher_loss = gate_exit_on_dispatcher_loss
        # extra GateService kwargs (admission-control knobs in the
        # overload tests: max_clients, rate_limit_pps, ...)
        self.gate_kwargs = gate_kwargs or {}
        self.dispatchers: list[DispatcherService] = []
        self.gates: list[GateService] = []
        self.dispatcher_addrs: list[tuple[str, int]] = []
        self.gate_addrs: list[tuple[str, int]] = []
        self.gate_ws_addrs: list[tuple[str, int]] = []
        self.loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._tasks: list = []

    # ------------------------------------------------------------------
    def start(self, timeout: float = 30.0) -> None:
        ready = threading.Event()

        def run() -> None:
            self.loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self.loop)
            self.loop.run_until_complete(self._boot())
            ready.set()
            self.loop.run_forever()

        self._thread = threading.Thread(
            target=run, name="cluster-harness", daemon=True
        )
        self._thread.start()
        if not ready.wait(timeout):
            raise TimeoutError("cluster harness failed to start")

    async def _boot(self) -> None:
        for i in range(self.n_dispatchers):
            d = DispatcherService(
                i + 1, self.host, 0,
                desired_games=self.desired_games,
                desired_gates=self.n_gates,
            )
            self.dispatchers.append(d)
            self._tasks.append(asyncio.ensure_future(d.serve()))
            await d.started.wait()
            self.dispatcher_addrs.append((self.host, d.bound_port))
        for i in range(self.n_gates):
            ws_port = 0
            if self.with_ws:
                import socket

                with socket.socket() as s:
                    s.bind((self.host, 0))
                    ws_port = s.getsockname()[1]
            ssl_ctx = None
            if self.tls_dir is not None:
                import os

                from goworld_tpu.net import transport

                cert = os.path.join(self.tls_dir, "gate_tls.crt")
                key = os.path.join(self.tls_dir, "gate_tls.key")
                transport.ensure_self_signed_cert(cert, key)
                ssl_ctx = transport.server_ssl_context(cert, key)
            g = GateService(
                i + 1, self.host, 0, list(self.dispatcher_addrs),
                ws_port=ws_port,
                kcp_port=-1 if self.with_kcp else 0,
                heartbeat_timeout=self.heartbeat_timeout,
                position_sync_interval_ms=self.position_sync_interval_ms,
                compress=self.compress,
                ssl_context=ssl_ctx,
                exit_on_dispatcher_loss=self.gate_exit_on_dispatcher_loss,
                **self.gate_kwargs,
            )
            self.gates.append(g)
            self._tasks.append(asyncio.ensure_future(g.serve()))
            await g.started.wait()
            self.gate_addrs.append((self.host, g.bound_port))
            if self.with_kcp:
                self.gate_kcp_addrs.append((self.host, g.bound_kcp_port))
            if ws_port:
                self.gate_ws_addrs.append((self.host, ws_port))

    def submit(self, coro: Coroutine) -> Future:
        """Run a coroutine (e.g. a bot) on the harness loop."""
        assert self.loop is not None
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self) -> None:
        from goworld_tpu.net.loops import drain_and_close

        drain_and_close(self.loop, self._thread)
