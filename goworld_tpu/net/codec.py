"""Batch codec for the hot sync-record path — C++ via ctypes, numpy fallback.

Reference being rebuilt: the per-record encode/decode loops of the position
sync pipeline (``GateService.go:402-429``, ``DispatcherService.go:770-808``,
``GameService.go:395-407``). The reference touches each 16-byte record in Go
per packet hop; here whole batches are (de)serialised in one native call (or
one numpy structured-array view), because the game host feeds the records
straight into device input buffers.

Public API (all batch-level):
  encode_sync_batch(ids, vals) -> bytes           # N x 32B records
  decode_sync_batch(buf) -> (ids S16[N], vals f32[N,4])
  encode_client_sync_batch(cids, ids, vals) -> bytes   # N x 48B
  decode_client_sync_batch(buf) -> (cids, ids, vals)
  bucket_by_shard(shard_of, n_shards, capacity) -> (idx i32[S,cap], counts)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from goworld_tpu.utils import log

logger = log.get("codec")

SYNC_DTYPE = np.dtype([("eid", "S16"), ("v", "<f4", (4,))])
CLIENT_SYNC_DTYPE = np.dtype(
    [("cid", "S16"), ("eid", "S16"), ("v", "<f4", (4,))]
)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "_packet_codec.so"))
_build_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_tried = False


def _build_native() -> bool:
    src = os.path.join(_NATIVE_DIR, "packet_codec.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-Wall", "-std=c++17", "-fPIC", "-shared",
             "-o", _SO_PATH, src],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native codec build failed (%s); using numpy path", e)
        return False


def _load() -> ctypes.CDLL | None:
    """Load (building if needed) the native codec; None -> numpy fallback."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if not os.path.exists(_SO_PATH) and not _build_native():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            logger.warning("native codec load failed (%s)", e)
            return None
        c_char_p = ctypes.POINTER(ctypes.c_char)
        f32_p = ctypes.POINTER(ctypes.c_float)
        i32_p = ctypes.POINTER(ctypes.c_int32)
        i64_p = ctypes.POINTER(ctypes.c_int64)
        lib.encode_sync_records.argtypes = [
            c_char_p, f32_p, ctypes.c_int32, c_char_p]
        lib.decode_sync_records.argtypes = [
            c_char_p, ctypes.c_int32, c_char_p, f32_p]
        lib.encode_client_sync_records.argtypes = [
            c_char_p, c_char_p, f32_p, ctypes.c_int32, c_char_p]
        lib.decode_client_sync_records.argtypes = [
            c_char_p, ctypes.c_int32, c_char_p, c_char_p, f32_p]
        lib.scan_frames.argtypes = [
            c_char_p, ctypes.c_int64, ctypes.c_int64, i64_p, i64_p,
            ctypes.c_int32, i64_p]
        lib.scan_frames.restype = ctypes.c_int32
        lib.bucket_by_shard.argtypes = [
            i32_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            i32_p, i32_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _as_id_array(ids) -> np.ndarray:
    a = np.asarray(ids, dtype="S16")
    return np.ascontiguousarray(a)


def encode_sync_batch(ids, vals) -> bytes:
    """ids: N 16-char ids (list[str] or S16 array); vals: f32[N,4]."""
    ida = _as_id_array(ids)
    va = np.ascontiguousarray(np.asarray(vals, np.float32).reshape(-1, 4))
    n = ida.shape[0]
    assert va.shape[0] == n
    lib = _load()
    out = np.empty(n * 32, np.uint8)
    if lib is not None and n:
        lib.encode_sync_records(
            ida.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            va.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
        )
        return out.tobytes()
    rec = np.empty(n, SYNC_DTYPE)
    rec["eid"] = ida
    rec["v"] = va
    return rec.tobytes()


def decode_sync_batch(buf: bytes | memoryview) -> tuple[np.ndarray, np.ndarray]:
    """-> (ids S16[N], vals f32[N,4])."""
    n, rem = divmod(len(buf), 32)
    if rem:
        raise ValueError(f"sync batch length {len(buf)} not a multiple of 32")
    lib = _load()
    if lib is not None and n:
        raw = np.frombuffer(buf, np.uint8)
        ids = np.empty(n, "S16")
        vals = np.empty((n, 4), np.float32)
        lib.decode_sync_records(
            np.ascontiguousarray(raw).ctypes.data_as(
                ctypes.POINTER(ctypes.c_char)),
            n,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return ids, vals
    rec = np.frombuffer(buf, SYNC_DTYPE)
    return rec["eid"].copy(), rec["v"].copy()


def encode_client_sync_batch(cids, ids, vals) -> bytes:
    ca = _as_id_array(cids)
    ida = _as_id_array(ids)
    va = np.ascontiguousarray(np.asarray(vals, np.float32).reshape(-1, 4))
    n = ca.shape[0]
    if ida.shape[0] != n or va.shape[0] != n:
        raise ValueError(
            f"length mismatch: {n} cids, {ida.shape[0]} ids, "
            f"{va.shape[0]} vals"
        )
    lib = _load()
    if lib is not None and n:
        out = np.empty(n * 48, np.uint8)
        lib.encode_client_sync_records(
            ca.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            ida.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            va.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
        )
        return out.tobytes()
    rec = np.empty(n, CLIENT_SYNC_DTYPE)
    rec["cid"] = ca
    rec["eid"] = ida
    rec["v"] = va
    return rec.tobytes()


def decode_client_sync_batch(
    buf: bytes | memoryview,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    n, rem = divmod(len(buf), 48)
    if rem:
        raise ValueError(
            f"client sync batch length {len(buf)} not a multiple of 48"
        )
    lib = _load()
    if lib is not None and n:
        raw = np.ascontiguousarray(np.frombuffer(buf, np.uint8))
        cids = np.empty(n, "S16")
        ids = np.empty(n, "S16")
        vals = np.empty((n, 4), np.float32)
        lib.decode_client_sync_records(
            raw.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            n,
            cids.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return cids, ids, vals
    rec = np.frombuffer(buf, CLIENT_SYNC_DTYPE)
    return rec["cid"].copy(), rec["eid"].copy(), rec["v"].copy()


def bucket_by_shard(
    shard_of: np.ndarray, n_shards: int, capacity: int
) -> tuple[np.ndarray, np.ndarray]:
    """Group record indices by shard (dispatcher re-batching analog).

    shard_of: i32[N] with -1 meaning drop. Returns (idx i32[S,capacity],
    counts i32[S]); overflow beyond capacity is dropped (callers size
    capacity to the device input cap and warn on counts == capacity).
    """
    so = np.ascontiguousarray(np.asarray(shard_of, np.int32))
    n = so.shape[0]
    idx = np.zeros((n_shards, capacity), np.int32)
    counts = np.zeros(n_shards, np.int32)
    lib = _load()
    if lib is not None and n:
        lib.bucket_by_shard(
            so.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, n_shards, capacity,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return idx, counts
    for i in range(n):
        s = so[i]
        if 0 <= s < n_shards and counts[s] < capacity:
            idx[s, counts[s]] = i
            counts[s] += 1
    return idx, counts


def scan_frames(
    buf: bytes | bytearray, max_payload: int = 32 * 1024 * 1024,
    max_frames: int = 4096,
) -> tuple[list[tuple[int, int]], int]:
    """Find complete length-prefixed frames in a receive buffer.

    Returns ([(payload_offset, payload_size), ...], consumed_bytes).
    Raises ConnectionError on a malformed size prefix. (Used by sync-mode
    receivers; asyncio paths use readexactly framing in packet.py.)
    """
    lib = _load()
    if lib is not None:
        raw = np.frombuffer(bytes(buf), np.uint8)
        offs = np.empty(max_frames, np.int64)
        sizes = np.empty(max_frames, np.int64)
        consumed = np.zeros(1, np.int64)
        cnt = lib.scan_frames(
            np.ascontiguousarray(raw).ctypes.data_as(
                ctypes.POINTER(ctypes.c_char)),
            len(buf), max_payload,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            max_frames,
            consumed.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if cnt < 0:
            raise ConnectionError("malformed frame size")
        return (
            [(int(offs[i]), int(sizes[i])) for i in range(cnt)],
            int(consumed[0]),
        )
    frames = []
    pos = 0
    n = len(buf)
    while len(frames) < max_frames and pos + 4 <= n:
        size = int.from_bytes(buf[pos:pos + 4], "little")
        if size < 2 or size > max_payload:
            raise ConnectionError("malformed frame size")
        if pos + 4 + size > n:
            break
        frames.append((pos + 4, size))
        pos += 4 + size
    return frames, pos
