"""Batch codec for the hot sync-record path — C++ via ctypes, numpy fallback.

Reference being rebuilt: the per-record encode/decode loops of the position
sync pipeline (``GateService.go:402-429``, ``DispatcherService.go:770-808``,
``GameService.go:395-407``). The reference touches each 16-byte record in Go
per packet hop; here whole batches are (de)serialised in one native call (or
one numpy structured-array view), because the game host feeds the records
straight into device input buffers.

Public API (all batch-level):
  encode_sync_batch(ids, vals) -> bytes           # N x 32B records
  decode_sync_batch(buf) -> (ids S16[N], vals f32[N,4])
  encode_client_sync_batch(cids, ids, vals) -> bytes   # N x 48B
  decode_client_sync_batch(buf) -> (cids, ids, vals)
  bucket_by_shard(shard_of, n_shards, capacity) -> (idx i32[S,cap], counts)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from goworld_tpu.utils import log

logger = log.get("codec")

SYNC_DTYPE = np.dtype([("eid", "S16"), ("v", "<f4", (4,))])
CLIENT_SYNC_DTYPE = np.dtype(
    [("cid", "S16"), ("eid", "S16"), ("v", "<f4", (4,))]
)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "_packet_codec.so"))
_build_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_tried = False


def _build_native() -> bool:
    src = os.path.join(_NATIVE_DIR, "packet_codec.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-Wall", "-std=c++17", "-fPIC", "-shared",
             "-o", _SO_PATH, src],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native codec build failed (%s); using numpy path", e)
        return False


def _load() -> ctypes.CDLL | None:
    """Load (building if needed) the native codec; None -> numpy fallback."""
    global _lib, _lib_tried
    if _lib is not None or _lib_tried:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if not os.path.exists(_SO_PATH) and not _build_native():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            logger.warning("native codec load failed (%s)", e)
            return None
        c_char_p = ctypes.POINTER(ctypes.c_char)
        f32_p = ctypes.POINTER(ctypes.c_float)
        i32_p = ctypes.POINTER(ctypes.c_int32)
        i64_p = ctypes.POINTER(ctypes.c_int64)
        lib.encode_sync_records.argtypes = [
            c_char_p, f32_p, ctypes.c_int32, c_char_p]
        lib.decode_sync_records.argtypes = [
            c_char_p, ctypes.c_int32, c_char_p, f32_p]
        lib.encode_client_sync_records.argtypes = [
            c_char_p, c_char_p, f32_p, ctypes.c_int32, c_char_p]
        lib.decode_client_sync_records.argtypes = [
            c_char_p, ctypes.c_int32, c_char_p, c_char_p, f32_p]
        lib.scan_frames.argtypes = [
            c_char_p, ctypes.c_int64, ctypes.c_int64, i64_p, i64_p,
            ctypes.c_int32, i64_p]
        lib.scan_frames.restype = ctypes.c_int32
        lib.bucket_by_shard.argtypes = [
            i32_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            i32_p, i32_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _as_id_array(ids) -> np.ndarray:
    a = np.asarray(ids, dtype="S16")
    return np.ascontiguousarray(a)


def encode_sync_batch(ids, vals) -> bytes:
    """ids: N 16-char ids (list[str] or S16 array); vals: f32[N,4]."""
    ida = _as_id_array(ids)
    va = np.ascontiguousarray(np.asarray(vals, np.float32).reshape(-1, 4))
    n = ida.shape[0]
    assert va.shape[0] == n
    lib = _load()
    out = np.empty(n * 32, np.uint8)
    if lib is not None and n:
        lib.encode_sync_records(
            ida.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            va.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
        )
        return out.tobytes()
    rec = np.empty(n, SYNC_DTYPE)
    rec["eid"] = ida
    rec["v"] = va
    return rec.tobytes()


def decode_sync_batch(buf: bytes | memoryview) -> tuple[np.ndarray, np.ndarray]:
    """-> (ids S16[N], vals f32[N,4])."""
    n, rem = divmod(len(buf), 32)
    if rem:
        raise ValueError(f"sync batch length {len(buf)} not a multiple of 32")
    lib = _load()
    if lib is not None and n:
        raw = np.frombuffer(buf, np.uint8)
        ids = np.empty(n, "S16")
        vals = np.empty((n, 4), np.float32)
        lib.decode_sync_records(
            np.ascontiguousarray(raw).ctypes.data_as(
                ctypes.POINTER(ctypes.c_char)),
            n,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return ids, vals
    rec = np.frombuffer(buf, SYNC_DTYPE)
    return rec["eid"].copy(), rec["v"].copy()


def encode_client_sync_batch(cids, ids, vals) -> bytes:
    ca = _as_id_array(cids)
    ida = _as_id_array(ids)
    va = np.ascontiguousarray(np.asarray(vals, np.float32).reshape(-1, 4))
    n = ca.shape[0]
    if ida.shape[0] != n or va.shape[0] != n:
        raise ValueError(
            f"length mismatch: {n} cids, {ida.shape[0]} ids, "
            f"{va.shape[0]} vals"
        )
    lib = _load()
    if lib is not None and n:
        out = np.empty(n * 48, np.uint8)
        lib.encode_client_sync_records(
            ca.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            ida.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            va.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
        )
        return out.tobytes()
    rec = np.empty(n, CLIENT_SYNC_DTYPE)
    rec["cid"] = ca
    rec["eid"] = ida
    rec["v"] = va
    return rec.tobytes()


def decode_client_sync_batch(
    buf: bytes | memoryview,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    n, rem = divmod(len(buf), 48)
    if rem:
        raise ValueError(
            f"client sync batch length {len(buf)} not a multiple of 48"
        )
    lib = _load()
    if lib is not None and n:
        raw = np.ascontiguousarray(np.frombuffer(buf, np.uint8))
        cids = np.empty(n, "S16")
        ids = np.empty(n, "S16")
        vals = np.empty((n, 4), np.float32)
        lib.decode_client_sync_records(
            raw.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            n,
            cids.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_char)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return cids, ids, vals
    rec = np.frombuffer(buf, CLIENT_SYNC_DTYPE)
    return rec["cid"].copy(), rec["eid"].copy(), rec["v"].copy()


def bucket_by_shard(
    shard_of: np.ndarray, n_shards: int, capacity: int
) -> tuple[np.ndarray, np.ndarray]:
    """Group record indices by shard (dispatcher re-batching analog).

    shard_of: i32[N] with -1 meaning drop. Returns (idx i32[S,capacity],
    counts i32[S]); overflow beyond capacity is dropped (callers size
    capacity to the device input cap and warn on counts == capacity).
    """
    so = np.ascontiguousarray(np.asarray(shard_of, np.int32))
    n = so.shape[0]
    idx = np.zeros((n_shards, capacity), np.int32)
    counts = np.zeros(n_shards, np.int32)
    lib = _load()
    if lib is not None and n:
        lib.bucket_by_shard(
            so.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, n_shards, capacity,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return idx, counts
    for i in range(n):
        s = so[i]
        if 0 <= s < n_shards and counts[s] < capacity:
            idx[s, counts[s]] = i
            counts[s] += 1
    return idx, counts


def scan_frames(
    buf: bytes | bytearray, max_payload: int = 32 * 1024 * 1024,
    max_frames: int = 4096,
) -> tuple[list[tuple[int, int]], int]:
    """Find complete length-prefixed frames in a receive buffer.

    Returns ([(payload_offset, payload_size), ...], consumed_bytes).
    Raises ConnectionError on a malformed size prefix. (Used by sync-mode
    receivers; asyncio paths use readexactly framing in packet.py.)
    """
    lib = _load()
    if lib is not None:
        raw = np.frombuffer(bytes(buf), np.uint8)
        offs = np.empty(max_frames, np.int64)
        sizes = np.empty(max_frames, np.int64)
        consumed = np.zeros(1, np.int64)
        cnt = lib.scan_frames(
            np.ascontiguousarray(raw).ctypes.data_as(
                ctypes.POINTER(ctypes.c_char)),
            len(buf), max_payload,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            max_frames,
            consumed.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if cnt < 0:
            raise ConnectionError("malformed frame size")
        return (
            [(int(offs[i]), int(sizes[i])) for i in range(cnt)],
            int(consumed[0]),
        )
    frames = []
    pos = 0
    n = len(buf)
    while len(frames) < max_frames and pos + 4 <= n:
        size = int.from_bytes(buf[pos:pos + 4], "little")
        if size < 2 or size > max_payload:
            raise ConnectionError("malformed frame size")
        if pos + 4 + size > n:
            break
        frames.append((pos + 4, size))
        pos += 4 + size
    return frames, pos


# =======================================================================
# Delta-compressed client sync (the precision plane's wire half,
# ISSUE 12): steady-state sync fan-out bytes scale with
# dirty_frac * 13 B/record instead of every record's 48 B.
#
# The encoder (game side, per gate) keeps a per-(client, entity)
# BASELINE and ships int16 fixed-point deltas against it; a KEYFRAME
# record (full f32 values + the 32 B of addressing) is shipped when no
# baseline exists, every `keyframe_every` ticks per pair, or when a
# delta overflows int16 — after the first keyframe the pair is
# addressed by a u32 HANDLE assigned in-band, so a delta record is
# [u8 kind][u32 handle][4 x i16] = 13 B vs the full record's 48 B.
#
# DETERMINISM CONTRACT: the decoder's state is a pure function of the
# byte stream — every handle assignment, baseline value and reset
# rides in-band, and both sides advance baselines with the identical
# `base + dq * step` arithmetic, so decode is bit-deterministic. With
# the lattice quantizer active (GridSpec.precision=q16) x/z deltas are
# EXACT (both endpoints are lattice points, the step is a power of
# two); y/yaw reconstruct within step/2 until the next keyframe
# refresh (errors never chain: each delta is computed against the
# decoder-visible baseline). A decoder that missed a handle (gate
# restart) drops the record and self-heals at the pair's next
# keyframe — the same self-healing contract sync records already have.
# =======================================================================
DELTA_SYNC_VERSION = 1


def _i16(x: float) -> bool:
    return -32768.0 <= x <= 32767.0


class DeltaSyncEncoder:
    """Per-gate encoder state (game process). See module note above."""

    def __init__(self, step: float, yaw_step: float = 0.0,
                 keyframe_every: int = 16,
                 max_entries: int = 1 << 20):
        if not step > 0.0:
            raise ValueError(f"delta-sync step must be > 0, got {step!r}")
        if keyframe_every < 1:
            raise ValueError(
                f"sync_keyframe_every must be >= 1, got {keyframe_every!r}")
        # both steps round through f32 HERE: the wire header packs them
        # as "<f", so the decoder advances baselines with the f32
        # value — the encoder must chain with the IDENTICAL arithmetic
        # or its model of the decoder drifts between keyframes
        self.step = float(np.float32(step))
        # yaw is radians-scale; default step keeps headings visually
        # smooth (2*pi / 2^16) while fitting a full turn in i16
        self.yaw_step = float(np.float32(
            yaw_step if yaw_step > 0.0
            else (2.0 * 3.141592653589793) / 65536.0))
        self.keyframe_every = int(keyframe_every)
        self.max_entries = int(max_entries)
        # key (32B cid+eid) -> [handle, base_tick, bx, by, bz, byaw]
        self._base: dict[bytes, list] = {}
        self._next_handle = 0
        # keyframe_bytes/delta_bytes split the wire bytes BY RECORD
        # KIND (wire_bytes additionally counts the 16 B batch headers):
        # the sync-age plane correlates delivery staleness against
        # wire mode through sync_bytes_out{kind} (net/game.py)
        self.stats = {"keyframes": 0, "deltas": 0, "wire_bytes": 0,
                      "full_bytes": 0, "resets": 0,
                      "keyframe_bytes": 0, "delta_bytes": 0}

    def encode_batch(self, cids, eids, vals, tick: int) -> bytes:
        """(S16 cids, S16 eids, f32[N,4] vals) -> delta wire payload."""
        import struct

        cids = np.asarray(cids, "S16")
        eids = np.asarray(eids, "S16")
        vals = np.asarray(vals, np.float32).reshape(-1, 4)
        flags = 0
        if len(self._base) > self.max_entries:
            # bounded state: clear BOTH sides in-band (decoder resets
            # on the flag) — everything re-keyframes, nothing desyncs
            self._base.clear()
            self._next_handle = 0
            self.stats["resets"] += 1
            flags |= 1
        out = bytearray(struct.pack(
            "<BBHffI", DELTA_SYNC_VERSION, flags, self.keyframe_every,
            self.step, self.yaw_step, len(cids)))
        steps = (self.step, self.step, self.step, self.yaw_step)
        # S16 scalars strip trailing NULs; the wire needs fixed 16B
        craw = np.ascontiguousarray(cids).tobytes()
        eraw = np.ascontiguousarray(eids).tobytes()
        for i in range(len(cids)):
            key = craw[16 * i:16 * i + 16] + eraw[16 * i:16 * i + 16]
            v = vals[i]
            e = self._base.get(key)
            dq = None
            if e is not None and tick - e[1] < self.keyframe_every:
                dq = [round((float(v[j]) - e[2 + j]) / steps[j])
                      for j in range(4)]
                if not all(_i16(d) for d in dq):
                    dq = None          # i16 overflow -> keyframe
            if dq is None:
                if e is None:
                    e = self._base[key] = [self._next_handle, tick,
                                           0.0, 0.0, 0.0, 0.0]
                    self._next_handle += 1
                e[1] = tick
                e[2:6] = [float(v[0]), float(v[1]), float(v[2]),
                          float(v[3])]
                out += struct.pack("<B", 0) + key \
                    + struct.pack("<Iffff", e[0], *e[2:6])
                self.stats["keyframes"] += 1
                self.stats["keyframe_bytes"] += 53
            else:
                for j in range(4):     # decoder-identical chaining
                    e[2 + j] += dq[j] * steps[j]
                out += struct.pack("<BIhhhh", 1, e[0], *dq)
                self.stats["deltas"] += 1
                self.stats["delta_bytes"] += 13
        self.stats["wire_bytes"] += len(out)
        self.stats["full_bytes"] += 48 * len(cids)
        return bytes(out)

    def drop_client(self, cid) -> None:
        """Forget a disconnected client's baselines (its pairs simply
        re-keyframe if it ever reappears; handles are never reused)."""
        cid = np.ascontiguousarray(np.asarray([cid], "S16")).tobytes()
        for key in [k for k in self._base if k[:16] == cid]:
            del self._base[key]


class DeltaSyncDecoder:
    """Per-gate decoder state (gate process); pure function of the
    byte stream — see the determinism contract above."""

    def __init__(self, max_entries: int = 1 << 20):
        # handle -> [cid, eid, bx, by, bz, byaw]. Bounded: handles are
        # never reused on the wire, so under client churn the table
        # would otherwise grow one entry per pair EVER seen (the
        # encoder's reset only fires when ITS live table overflows,
        # which drop_client keeps small) — evict oldest-inserted past
        # the cap; an evicted-but-live pair just drops deltas until
        # its next keyframe (the stream's normal self-healing).
        self._base: dict[int, list] = {}
        self.max_entries = int(max_entries)
        self.stats = {"records": 0, "dropped_unknown": 0, "resets": 0,
                      "evicted": 0}

    def decode_batch(self, payload) -> tuple[np.ndarray, np.ndarray,
                                             np.ndarray]:
        """payload -> (S16 cids[M], S16 eids[M], f32[M,4] vals);
        unknown-handle deltas are dropped (self-heal at keyframe)."""
        import struct

        buf = bytes(payload)
        try:
            ver, flags, _kfe, step, yaw_step, count = \
                struct.unpack_from("<BBHffI", buf, 0)
        except struct.error as exc:
            raise ConnectionError(
                f"delta-sync header truncated: {exc}") from exc
        if ver != DELTA_SYNC_VERSION:
            raise ConnectionError(
                f"delta-sync version {ver} unsupported")
        if flags & 1:
            self._base.clear()
            self.stats["resets"] += 1
        off = 16
        steps = (step, step, step, yaw_step)
        cids, eids, vals = [], [], []
        try:
            for _ in range(count):
                kind = buf[off]
                off += 1
                if kind == 0:
                    cid, eid = buf[off:off + 16], buf[off + 16:off + 32]
                    off += 32
                    handle, x, y, z, yw = struct.unpack_from("<Iffff",
                                                             buf, off)
                    off += 20
                    self._base[handle] = [cid, eid, x, y, z, yw]
                    while len(self._base) > self.max_entries:
                        self._base.pop(next(iter(self._base)))
                        self.stats["evicted"] += 1
                    cids.append(cid)
                    eids.append(eid)
                    vals.append((x, y, z, yw))
                elif kind == 1:
                    handle, dx, dy, dz, dyw = struct.unpack_from(
                        "<Ihhhh", buf, off)
                    off += 12
                    e = self._base.get(handle)
                    if e is None:
                        self.stats["dropped_unknown"] += 1
                        continue
                    for j, d in enumerate((dx, dy, dz, dyw)):
                        e[2 + j] += d * steps[j]
                    cids.append(e[0])
                    eids.append(e[1])
                    vals.append(tuple(e[2:6]))
                else:
                    raise ConnectionError(
                        f"delta-sync record kind {kind} unknown")
        except (struct.error, IndexError) as exc:
            # truncated mid-record: the caller drops the batch (sync
            # records self-heal); a raw struct.error must never escape
            # into the dispatcher read loop
            raise ConnectionError(
                f"delta-sync batch truncated at {off}: {exc}") from exc
        self.stats["records"] += count
        return (np.asarray(cids, "S16"), np.asarray(eids, "S16"),
                np.asarray(vals, np.float32).reshape(-1, 4))
