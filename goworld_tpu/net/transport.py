"""Client-edge transport hardening: TLS contexts + self-signed certs.

Reference parity: the gate optionally wraps every client connection in TLS
(``components/gate/ClientProxy.go:38-53``; cert/key shipped as ``rsa.crt``
/ ``rsa.key`` at the repo root, ini flags ``encrypt_connection``) and
snappy compression. Here TLS rides stdlib ``ssl`` over asyncio; stream
compression defaults to SNAPPY — the reference's codec, implemented from
scratch (block + framing formats, :mod:`goworld_tpu.net.snappy`, C++
core in ``native/snappy_core.cpp``) — with zlib-1 selectable per ini
(``compress_codec``) for deployments that prefer its shared-dictionary
ratio on tiny packets.

The third client transport, KCP (reliable-UDP tuned for latency,
``GateService.go:129-161``), is implemented from scratch in
:mod:`goworld_tpu.net.kcp` — same wire protocol as the reference's
kcp-go dependency, adapted to the (reader, writer) seam so
PacketConnection runs unchanged over it.

Fault injection (:mod:`goworld_tpu.utils.faults`, docs/ROBUSTNESS.md)
wraps these boundaries one layer up: wire faults apply at
``PacketConnection.send`` above the TLS/compression stream (tampering
inside a negotiated stream would model a codec bug, not a network
fault), and the KCP edge drops whole datagrams through
``KcpServer``'s ``loss_hook`` so the ARQ path is what gets exercised.
"""

from __future__ import annotations

import os
import ssl
import subprocess

from goworld_tpu.utils import log

logger = log.get("transport")


def ensure_self_signed_cert(cert_path: str, key_path: str,
                            cn: str = "goworld-tpu-gate") -> None:
    """Generate a self-signed cert/key pair if absent (the reference
    ships one in-repo; generating on first use avoids committing private
    keys)."""
    if os.path.exists(cert_path) and os.path.exists(key_path):
        return
    os.makedirs(os.path.dirname(os.path.abspath(cert_path)), exist_ok=True)
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", key_path, "-out", cert_path,
            "-days", "3650", "-nodes", "-subj", f"/CN={cn}",
        ],
        check=True, capture_output=True,
    )
    logger.info("generated self-signed TLS cert %s", cert_path)


def server_ssl_context(cert_path: str, key_path: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx


def client_ssl_context(verify: bool = False) -> ssl.SSLContext:
    """Client side; ``verify=False`` accepts the gate's self-signed cert
    (the reference's test client dials TLS without a CA bundle too)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if not verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    return ctx
