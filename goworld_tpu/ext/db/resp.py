"""Minimal RESP2 (REdis Serialization Protocol) client.

The reference talks to redis through the redigo driver
(``engine/kvdb/backend/kvdbredis``, ``ext/db/gwredis.go``); this
environment has neither a redis driver package nor a redis server baked
in, so the wire protocol is implemented directly (it is small: five type
sigils over a TCP stream) and a compatible in-process server lives in
:mod:`goworld_tpu.ext.db.miniredis` for tests and single-host deployments.
Any real redis endpoint speaks the same bytes.

Blocking, single-connection, thread-safe via an internal lock — matching
how the engine uses it: every storage/kvdb op already serializes on one
dedicated worker (``storage.py``/``kvdb.py``), so connection pooling would
buy nothing.
"""

from __future__ import annotations

import socket
import threading


class RespError(Exception):
    """Server-reported error reply (the ``-ERR ...`` line)."""


class RespConnectionError(ConnectionError):
    pass


def parse_addr(addr: str) -> tuple[str, int, int]:
    """``host:port`` or ``host:port/db`` -> (host, port, db)."""
    db = 0
    if "/" in addr:
        addr, db_s = addr.rsplit("/", 1)
        db = int(db_s or 0)
    host, _, port_s = addr.rpartition(":")
    return host or "127.0.0.1", int(port_s or 6379), db


# --- cluster-mode key hashing (shared by the kvdb cluster client and
# --- miniredis's cluster mode) ------------------------------------------

NUM_SLOTS = 16384


def crc16(data: bytes) -> int:
    """CRC16-CCITT (XModem) — redis cluster's key-slot hash."""
    crc = 0
    for b in data:
        crc ^= b << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021) if crc & 0x8000 else (crc << 1)
            crc &= 0xFFFF
    return crc


def key_slot(key: bytes) -> int:
    """Redis cluster slot of a key, honoring ``{hashtag}`` semantics:
    if the key contains ``{...}`` with a NON-EMPTY tag, only the tag
    bytes hash (so ``{user1}.a`` and ``{user1}.b`` co-locate)."""
    lb = key.find(b"{")
    if lb != -1:
        rb = key.find(b"}", lb + 1)
        if rb != -1 and rb > lb + 1:
            key = key[lb + 1:rb]
    return crc16(key) % NUM_SLOTS


class RespClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 db: int = 0, timeout: float = 10.0):
        self.host, self.port, self.db = host, port, db
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._rfile = None
        self._lock = threading.Lock()

    @classmethod
    def from_addr(cls, addr: str, **kw) -> "RespClient":
        host, port, db = parse_addr(addr)
        return cls(host, port, db, **kw)

    # -- connection -----------------------------------------------------
    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout
        )
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rfile = self._sock.makefile("rb")
        if self.db:
            self._command_locked(b"SELECT", str(self.db).encode())

    def close(self) -> None:
        with self._lock:
            self._teardown()

    def _teardown(self) -> None:
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._rfile = None

    # -- protocol -------------------------------------------------------
    @staticmethod
    def _encode(args: tuple[bytes, ...]) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            out.append(b"$%d\r\n%s\r\n" % (len(a), a))
        return b"".join(out)

    def _read_reply(self):
        line = self._rfile.readline()
        if not line:
            raise RespConnectionError("connection closed by server")
        sigil, body = line[:1], line[1:-2]
        if sigil == b"+":
            return body.decode()
        if sigil == b"-":
            raise RespError(body.decode())
        if sigil == b":":
            return int(body)
        if sigil == b"$":
            n = int(body)
            if n == -1:
                return None
            data = self._rfile.read(n + 2)
            if len(data) != n + 2:
                raise RespConnectionError("short bulk read")
            return data[:-2]
        if sigil == b"*":
            n = int(body)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RespConnectionError(f"bad RESP sigil {sigil!r}")

    def _command_locked(self, *args: bytes):
        self._sock.sendall(self._encode(args))
        return self._read_reply()

    def command(self, *args):
        """Run one command; args are str/bytes/int. One transparent
        reconnect+retry on connection failure (reference ``storageRoutine``
        reconnects on EOF, ``storage.go:141-262``)."""
        enc = tuple(
            a if isinstance(a, bytes) else str(a).encode() for a in args
        )
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._connect()
                    return self._command_locked(*enc)
                except (OSError, RespConnectionError):
                    self._teardown()
                    if attempt:
                        raise

    # -- convenience ----------------------------------------------------
    def ping(self) -> bool:
        return self.command("PING") == "PONG"

    def get(self, key) -> bytes | None:
        return self.command("GET", key)

    def set(self, key, val) -> None:
        self.command("SET", key, val)

    def setnx(self, key, val) -> bool:
        return bool(self.command("SETNX", key, val))

    def delete(self, *keys) -> int:
        return self.command("DEL", *keys)

    def exists(self, key) -> bool:
        return bool(self.command("EXISTS", key))

    def mget(self, keys: list) -> list[bytes | None]:
        if not keys:
            return []
        return self.command("MGET", *keys)

    def scan_keys(self, match: str) -> list[bytes]:
        """Full SCAN sweep (cursor loop) for keys matching ``match``.
        Deduplicated: redis's SCAN contract allows the same key to appear
        in multiple cursor iterations."""
        cursor = b"0"
        seen: dict[bytes, None] = {}
        while True:
            reply = self.command("SCAN", cursor, "MATCH", match,
                                 "COUNT", "512")
            cursor, chunk = reply[0], reply[1]
            for k in chunk:
                seen[k] = None
            if cursor in (b"0", "0", 0):
                return list(seen)
