"""Async document-store wrapper for user game code.

Reference being rebuilt: ``ext/db/gwmongo.go:31-355`` — an mgo session
owned by one async group exposing ``InsertOne/FindOne/UpdateId/Count/...``
per (db, collection), every reply posted back to the logic thread.

DEVIATION NOTE: this environment bakes in neither a MongoDB server nor a
driver, so the document API is implemented over a pluggable
:class:`DocStore`. The default store keeps msgpack documents in any
redis-compatible endpoint (including the in-process miniredis) under
``doc:<db>:<collection>:<id>`` keys; a MongoDB-driver store can slot in
behind the same two-method interface where one exists. The ASYNC API —
what user code actually programs against — matches the reference's shape.
"""

from __future__ import annotations

from typing import Callable

import msgpack

from goworld_tpu.ext.db.resp import RespClient
from goworld_tpu.utils.asyncwork import AsyncWorkers
from goworld_tpu.utils import ids

_GROUP = "_gwmongo"  # dedicated worker group (reference gwmongo.go:31)


class DocStore:
    """Minimal KV the document layer needs (swap for a real driver)."""

    def put(self, key: str, blob: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def keys(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def close(self) -> None: ...


class RedisDocStore(DocStore):
    def __init__(self, addr: str):
        self._c = RespClient.from_addr(addr)

    def put(self, key, blob):
        self._c.set(key, blob)

    def get(self, key):
        return self._c.get(key)

    def delete(self, key):
        return bool(self._c.delete(key))

    def keys(self, prefix):
        return sorted(k.decode() for k in self._c.scan_keys(prefix + "*"))

    def close(self):
        self._c.close()


def _matches(doc: dict, query: dict) -> bool:
    """Flat equality filter (the subset the reference's examples use)."""
    return all(doc.get(k) == v for k, v in query.items())


class GWMongo:
    """``m = GWMongo(store, workers)``; all callbacks get ``(res, err)``
    on the logic thread."""

    def __init__(self, store: DocStore, workers: AsyncWorkers):
        self._store = store
        self._workers = workers

    @classmethod
    def connect_redis(cls, addr: str, workers: AsyncWorkers) -> "GWMongo":
        return cls(RedisDocStore(addr), workers)

    @staticmethod
    def _key(db: str, col: str, doc_id: str) -> str:
        return f"doc:{db}:{col}:{doc_id}"

    def _submit(self, job: Callable, cb: Callable | None) -> None:
        self._workers.submit(_GROUP, job, cb)

    # -- document ops (reference gwmongo.go Insert/Find/Update/Remove) ---
    def insert_one(self, db: str, col: str, doc: dict,
                   cb: Callable | None = None) -> str:
        """Returns the document id immediately; the write lands async."""
        doc_id = str(doc.get("_id") or ids.gen_entity_id())
        doc = dict(doc, _id=doc_id)

        def job():
            self._store.put(
                self._key(db, col, doc_id),
                msgpack.packb(doc, use_bin_type=True),
            )
            return doc_id

        self._submit(job, cb)
        return doc_id

    def find_id(self, db: str, col: str, doc_id: str,
                cb: Callable) -> None:
        def job():
            raw = self._store.get(self._key(db, col, doc_id))
            return None if raw is None else msgpack.unpackb(raw, raw=False)

        self._submit(job, cb)

    def find_one(self, db: str, col: str, query: dict,
                 cb: Callable) -> None:
        def job():
            for key in self._store.keys(f"doc:{db}:{col}:"):
                raw = self._store.get(key)
                if raw is None:
                    continue
                doc = msgpack.unpackb(raw, raw=False)
                if _matches(doc, query):
                    return doc
            return None

        self._submit(job, cb)

    def find_all(self, db: str, col: str, query: dict,
                 cb: Callable) -> None:
        def job():
            out = []
            for key in self._store.keys(f"doc:{db}:{col}:"):
                raw = self._store.get(key)
                if raw is None:
                    continue
                doc = msgpack.unpackb(raw, raw=False)
                if _matches(doc, query):
                    out.append(doc)
            return out

        self._submit(job, cb)

    def update_id(self, db: str, col: str, doc_id: str, fields: dict,
                  cb: Callable | None = None) -> None:
        """Merge ``fields`` into the document (reference ``UpdateId`` with
        a ``$set`` document)."""

        def job():
            key = self._key(db, col, doc_id)
            raw = self._store.get(key)
            doc = {} if raw is None else msgpack.unpackb(raw, raw=False)
            doc.update(fields)
            doc["_id"] = doc_id
            self._store.put(key, msgpack.packb(doc, use_bin_type=True))

        self._submit(job, cb)

    def remove_id(self, db: str, col: str, doc_id: str,
                  cb: Callable | None = None) -> None:
        self._submit(
            lambda: self._store.delete(self._key(db, col, doc_id)), cb
        )

    def count(self, db: str, col: str, cb: Callable) -> None:
        self._submit(
            lambda: len(self._store.keys(f"doc:{db}:{col}:")), cb
        )

    def close(self) -> None:
        self._store.close()
