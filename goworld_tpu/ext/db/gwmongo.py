"""Async document-store wrapper for user game code.

Reference being rebuilt: ``ext/db/gwmongo.go:31-355`` — an mgo session
owned by one async group exposing ``InsertOne/FindOne/UpdateId/Count/...``
per (db, collection), every reply posted back to the logic thread.

The document API rides a pluggable :class:`DocStore`.
:class:`MongoDocStore` (``connect_mongodb``) is the reference shape:
native BSON documents per (db, collection) over the from-scratch
OP_MSG wire client (:mod:`goworld_tpu.ext.db.mongowire`) — a real
mongod or the in-process :mod:`goworld_tpu.ext.db.minimongo` both
speak it. :class:`RedisDocStore` (``connect_redis``) keeps msgpack
documents in any redis-compatible endpoint under
``doc:<db>:<collection>:<id>`` keys. The ASYNC API — what user code
actually programs against — matches the reference's shape either way.
"""

from __future__ import annotations

from typing import Callable

import msgpack

from goworld_tpu.ext.db.resp import RespClient
from goworld_tpu.utils.asyncwork import AsyncWorkers
from goworld_tpu.utils import ids

_GROUP = "_gwmongo"  # dedicated worker group (reference gwmongo.go:31)


class DocStore:
    """Minimal KV the document layer needs (swap for a real driver)."""

    def put(self, key: str, blob: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def keys(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def query(self, db: str, col: str, flt: dict,
              limit: int = 0) -> "list[dict] | None":
        """OPTIONAL server-side filtered find. None = unsupported (the
        caller falls back to a keys()+get() scan); stores with a real
        query engine (MongoDocStore) answer in ONE round trip instead
        of 1 + N."""
        return None

    def close(self) -> None: ...


class RedisDocStore(DocStore):
    def __init__(self, addr: str):
        self._c = RespClient.from_addr(addr)

    def put(self, key, blob):
        self._c.set(key, blob)

    def get(self, key):
        return self._c.get(key)

    def delete(self, key):
        return bool(self._c.delete(key))

    def keys(self, prefix):
        return sorted(k.decode() for k in self._c.scan_keys(prefix + "*"))

    def close(self):
        self._c.close()


class MongoDocStore(DocStore):
    """The REAL thing: documents live as native BSON in their
    ``(db, collection)`` with ``_id``, via the from-scratch OP_MSG wire
    client — readable by any mongo tooling, no msgpack envelope. The
    DocStore key convention (``doc:<db>:<col>:<id>``) is parsed back
    into its parts; blobs are msgpack only at the interface seam (the
    GWMongo layer packs them) and are unpacked to store natively."""

    def __init__(self, addr: str):
        from goworld_tpu.ext.db.mongowire import MongoClient

        self._c = MongoClient.from_addr(addr)

    @staticmethod
    def _parse(key: str) -> tuple[str, str, str]:
        _, db, col, doc_id = key.split(":", 3)
        return db, col, doc_id

    def _coll(self, db: str, col: str) -> str:
        # one client bound to one wire-level $db; namespace by prefixing
        # the db part into the collection when it differs
        return col if db == self._c.db else f"{db}.{col}"

    def put(self, key, blob):
        db, col, doc_id = self._parse(key)
        doc = msgpack.unpackb(blob, raw=False)
        self._c.upsert_id(self._coll(db, col), doc_id, doc)

    def get(self, key):
        db, col, doc_id = self._parse(key)
        doc = self._c.find_id(self._coll(db, col), doc_id)
        if doc is None:
            return None
        return msgpack.packb(doc, use_bin_type=True)

    def delete(self, key):
        db, col, doc_id = self._parse(key)
        return self._c.delete(self._coll(db, col), {"_id": doc_id}) > 0

    def keys(self, prefix):
        # prefix is always "doc:<db>:<col>:" (the GWMongo key scheme)
        db, col, _ = self._parse(prefix + "\x00")
        docs = self._c.find(self._coll(db, col), {},
                            projection={"_id": 1})
        return sorted(f"doc:{db}:{col}:{d['_id']}" for d in docs)

    def query(self, db, col, flt, limit=0):
        # server-side filter: one round trip instead of a 1 + N
        # key-scan (the flat-equality filters GWMongo supports are
        # valid mongo filters verbatim)
        return self._c.find(self._coll(db, col), flt, limit=limit)

    def close(self):
        self._c.close()


def _matches(doc: dict, query: dict) -> bool:
    """Flat equality filter (the subset the reference's examples use)."""
    return all(doc.get(k) == v for k, v in query.items())


class GWMongo:
    """``m = GWMongo(store, workers)``; all callbacks get ``(res, err)``
    on the logic thread."""

    def __init__(self, store: DocStore, workers: AsyncWorkers):
        self._store = store
        self._workers = workers

    @classmethod
    def connect_redis(cls, addr: str, workers: AsyncWorkers) -> "GWMongo":
        return cls(RedisDocStore(addr), workers)

    @classmethod
    def connect_mongodb(cls, addr: str,
                        workers: AsyncWorkers) -> "GWMongo":
        """The reference shape: a real MongoDB endpoint (or the
        in-process minimongo) over the from-scratch wire client."""
        return cls(MongoDocStore(addr), workers)

    @staticmethod
    def _key(db: str, col: str, doc_id: str) -> str:
        return f"doc:{db}:{col}:{doc_id}"

    def _submit(self, job: Callable, cb: Callable | None) -> None:
        self._workers.submit(_GROUP, job, cb)

    # -- document ops (reference gwmongo.go Insert/Find/Update/Remove) ---
    def insert_one(self, db: str, col: str, doc: dict,
                   cb: Callable | None = None) -> str:
        """Returns the document id immediately; the write lands async."""
        doc_id = str(doc.get("_id") or ids.gen_entity_id())
        doc = dict(doc, _id=doc_id)

        def job():
            self._store.put(
                self._key(db, col, doc_id),
                msgpack.packb(doc, use_bin_type=True),
            )
            return doc_id

        self._submit(job, cb)
        return doc_id

    def find_id(self, db: str, col: str, doc_id: str,
                cb: Callable) -> None:
        def job():
            raw = self._store.get(self._key(db, col, doc_id))
            return None if raw is None else msgpack.unpackb(raw, raw=False)

        self._submit(job, cb)

    def find_one(self, db: str, col: str, query: dict,
                 cb: Callable) -> None:
        def job():
            native = self._store.query(db, col, query, limit=1)
            if native is not None:
                return native[0] if native else None
            for key in self._store.keys(f"doc:{db}:{col}:"):
                raw = self._store.get(key)
                if raw is None:
                    continue
                doc = msgpack.unpackb(raw, raw=False)
                if _matches(doc, query):
                    return doc
            return None

        self._submit(job, cb)

    def find_all(self, db: str, col: str, query: dict,
                 cb: Callable) -> None:
        def job():
            native = self._store.query(db, col, query)
            if native is not None:
                return native
            out = []
            for key in self._store.keys(f"doc:{db}:{col}:"):
                raw = self._store.get(key)
                if raw is None:
                    continue
                doc = msgpack.unpackb(raw, raw=False)
                if _matches(doc, query):
                    out.append(doc)
            return out

        self._submit(job, cb)

    def update_id(self, db: str, col: str, doc_id: str, fields: dict,
                  cb: Callable | None = None) -> None:
        """Merge ``fields`` into the document (reference ``UpdateId`` with
        a ``$set`` document)."""

        def job():
            key = self._key(db, col, doc_id)
            raw = self._store.get(key)
            doc = {} if raw is None else msgpack.unpackb(raw, raw=False)
            doc.update(fields)
            doc["_id"] = doc_id
            self._store.put(key, msgpack.packb(doc, use_bin_type=True))

        self._submit(job, cb)

    def remove_id(self, db: str, col: str, doc_id: str,
                  cb: Callable | None = None) -> None:
        self._submit(
            lambda: self._store.delete(self._key(db, col, doc_id)), cb
        )

    def count(self, db: str, col: str, cb: Callable) -> None:
        self._submit(
            lambda: len(self._store.keys(f"doc:{db}:{col}:")), cb
        )

    def close(self) -> None:
        self._store.close()
