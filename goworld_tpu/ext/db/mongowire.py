"""MongoDB wire-protocol client (OP_MSG), from scratch.

The reference talks to MongoDB through the mgo driver; no driver or
server exists in this environment, so the modern wire protocol is
implemented directly: every command rides an OP_MSG (opcode 2013)
message — a 16-byte standard header, uint32 flagBits (0), and one
kind-0 body section holding a single BSON command document. Replies
come back the same shape. This is the full protocol surface MongoDB
3.6+ requires for an auth-less deployment; the in-process test/dev
server lives in :mod:`goworld_tpu.ext.db.minimongo` and any real
mongod speaks the same bytes.

Blocking, single-connection, thread-safe via an internal lock —
mirroring :mod:`goworld_tpu.ext.db.resp`: storage/kvdb ops already
serialize on a dedicated worker.
"""

from __future__ import annotations

import socket
import struct
import threading

from goworld_tpu.ext.db import bson

_HDR = struct.Struct("<iiii")  # messageLength, requestID, responseTo, opCode
OP_MSG = 2013


class MongoError(Exception):
    """Server-reported command failure ({ok: 0, errmsg, code})."""


class MongoConnectionError(ConnectionError):
    pass


def parse_mongo_addr(addr: str) -> tuple[str, int, str]:
    """``host:port`` or ``host:port/dbname`` -> (host, port, db);
    db defaults to "goworld" like the reference's _DEFAULT_DB_NAME."""
    db = "goworld"
    if "/" in addr:
        addr, db_s = addr.rsplit("/", 1)
        db = db_s or db
    host, _, port_s = addr.rpartition(":")
    return host or "127.0.0.1", int(port_s or 27017), db


class MongoClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 27017,
                 db: str = "goworld", timeout: float = 10.0):
        self.host, self.port, self.db = host, port, db
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._rid = 0
        self._lock = threading.Lock()

    @classmethod
    def from_addr(cls, addr: str, **kw) -> "MongoClient":
        host, port, db = parse_mongo_addr(addr)
        return cls(host, port, db, **kw)

    # -- wire ----------------------------------------------------------
    def _connect(self) -> None:
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _recv_exact(self, n: int) -> bytes:
        assert self._sock is not None
        chunks = []
        while n:
            b = self._sock.recv(n)
            if not b:
                raise MongoConnectionError("connection closed by server")
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def _roundtrip_locked(self, cmd_doc: dict) -> dict:
        if self._sock is None:
            self._connect()
        self._rid += 1
        body = bson.encode(cmd_doc)
        payload = struct.pack("<I", 0) + b"\x00" + body  # flags, kind 0
        msg = _HDR.pack(16 + len(payload), self._rid, 0, OP_MSG) + payload
        assert self._sock is not None
        self._sock.sendall(msg)
        hdr = self._recv_exact(16)
        length, _rid, _resp_to, opcode = _HDR.unpack(hdr)
        rest = self._recv_exact(length - 16)
        if opcode != OP_MSG:
            raise MongoConnectionError(f"unexpected opcode {opcode}")
        # flagBits(4) + kind byte(1) + body document
        if rest[4] != 0:
            raise MongoConnectionError("expected kind-0 reply section")
        return bson.decode(rest, 5)

    def command(self, cmd_doc: dict) -> dict:
        """Run one command against ``self.db``; raises MongoError on
        {ok: 0} AND on per-document ``writeErrors`` (mongod reports
        those with ok:1 — swallowing them would let the storage
        retry-forever queue count a failed entity save as done). One
        transparent reconnect+retry on connection failure (the
        reference's mgo session refreshes the same way)."""
        cmd_doc = dict(cmd_doc)
        cmd_doc.setdefault("$db", self.db)
        with self._lock:
            for attempt in (0, 1):
                try:
                    reply = self._roundtrip_locked(cmd_doc)
                    break
                except (OSError, MongoConnectionError):
                    self.close()
                    if attempt:
                        raise
            else:  # pragma: no cover
                raise MongoConnectionError("unreachable")
        if not reply.get("ok"):
            raise MongoError(
                f"{reply.get('codeName', '')} "
                f"{reply.get('errmsg', 'command failed')}".strip())
        werrs = reply.get("writeErrors")
        if werrs:
            first = werrs[0] if isinstance(werrs, list) and werrs else {}
            raise MongoError(
                f"write error (code {first.get('code')}): "
                f"{first.get('errmsg', 'write failed')}")
        return reply

    # -- commands ------------------------------------------------------
    def ping(self) -> bool:
        try:
            return bool(self.command({"ping": 1}).get("ok"))
        except (MongoError, ConnectionError):
            return False

    def insert(self, coll: str, docs: list[dict]) -> int:
        r = self.command({"insert": coll, "documents": docs})
        return int(r.get("n", 0))

    def upsert_id(self, coll: str, _id, doc: dict) -> None:
        """Reference ``UpsertId``: replace-or-insert the whole doc."""
        self.command({
            "update": coll,
            "updates": [{"q": {"_id": _id},
                         "u": dict(doc, _id=_id),
                         "upsert": True, "multi": False}],
        })

    def find(self, coll: str, filter: dict | None = None, *,
             projection: dict | None = None, sort: dict | None = None,
             limit: int = 0) -> list[dict]:
        """Full-result find: follows multi-batch cursors with getMore
        (a real mongod caps an unlimited find's firstBatch at 101
        documents — entity listings and KV range scans must not stop
        there)."""
        cmd: dict = {"find": coll, "filter": filter or {}}
        if projection:
            cmd["projection"] = projection
        if sort:
            cmd["sort"] = sort
        if limit:
            cmd["limit"] = limit
        r = self.command(cmd)
        cur = r.get("cursor", {})
        out = list(cur.get("firstBatch", []))
        cid = cur.get("id", 0)
        while cid:
            r = self.command({"getMore": cid, "collection": coll})
            cur = r.get("cursor", {})
            out.extend(cur.get("nextBatch", []))
            cid = cur.get("id", 0)
        return out

    def find_id(self, coll: str, _id) -> dict | None:
        got = self.find(coll, {"_id": _id}, limit=1)
        return got[0] if got else None

    def delete(self, coll: str, filter: dict, *, many: bool = True) -> int:
        r = self.command({
            "delete": coll,
            "deletes": [{"q": filter, "limit": 0 if many else 1}],
        })
        return int(r.get("n", 0))
