"""BSON (Binary JSON) encoder/decoder, from scratch.

The reference's MongoDB backends (``engine/storage/backend/mongodb/
mongodb.go:27-136``, ``engine/kvdb/backend/kvdb_mongodb/mongodb.go``)
ride the mgo driver; this environment has no MongoDB driver, so the
public BSON spec (bsonspec.org) is implemented directly — the subset a
game-state store needs:

  0x01 double   0x02 string   0x03 document   0x04 array
  0x05 binary   0x08 bool     0x0A null       0x10 int32   0x12 int64

Python mapping: float <-> double, str <-> string, dict <-> document,
list <-> array, bytes <-> binary (subtype 0), bool <-> bool,
None <-> null, int -> int32 when it fits else int64 (both decode to
int). Attr trees are exactly this shape (entity attrs are
plain-JSON-like after ``to_plain``).
"""

from __future__ import annotations

import struct
from typing import Any

_D = struct.Struct("<d")
_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")

I32_MIN, I32_MAX = -(1 << 31), (1 << 31) - 1


def _encode_value(out: bytearray, name: bytes, v: Any) -> None:
    # bool BEFORE int: bool is an int subclass
    if isinstance(v, bool):
        out += b"\x08" + name + b"\x00" + (b"\x01" if v else b"\x00")
    elif isinstance(v, float):
        out += b"\x01" + name + b"\x00" + _D.pack(v)
    elif isinstance(v, int):
        if I32_MIN <= v <= I32_MAX:
            out += b"\x10" + name + b"\x00" + _I32.pack(v)
        else:
            out += b"\x12" + name + b"\x00" + _I64.pack(v)
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out += b"\x02" + name + b"\x00" + _I32.pack(len(b) + 1) + b \
            + b"\x00"
    elif isinstance(v, dict):
        out += b"\x03" + name + b"\x00" + encode(v)
    elif isinstance(v, (list, tuple)):
        out += b"\x04" + name + b"\x00" + encode(
            {str(i): x for i, x in enumerate(v)})
    elif isinstance(v, (bytes, bytearray)):
        out += b"\x05" + name + b"\x00" + _I32.pack(len(v)) + b"\x00" \
            + bytes(v)
    elif v is None:
        out += b"\x0a" + name + b"\x00"
    else:
        raise TypeError(f"BSON cannot encode {type(v).__name__}")


def encode(doc: dict) -> bytes:
    """Encode a dict into one BSON document."""
    body = bytearray()
    for k, v in doc.items():
        if not isinstance(k, str):
            k = str(k)
        kb = k.encode("utf-8")
        if b"\x00" in kb:
            raise ValueError("BSON keys cannot contain NUL")
        _encode_value(body, kb, v)
    return _I32.pack(len(body) + 5) + bytes(body) + b"\x00"


def _read_cstring(buf: memoryview, at: int) -> tuple[str, int]:
    end = at
    while buf[end] != 0:
        end += 1
    return bytes(buf[at:end]).decode("utf-8"), end + 1


def _decode_doc(buf: memoryview, at: int) -> tuple[dict, int]:
    # wire int32 lengths are attacker-controlled (minimongo feeds raw
    # socket bytes here): validate every one BEFORE advancing, or a
    # negative length walks the cursor backwards and loops the handler
    # thread forever (ADVICE.md)
    if len(buf) - at < 5:
        raise ValueError("BSON document truncated")
    (total,) = _I32.unpack_from(buf, at)
    if total < 5 or total > len(buf) - at:
        raise ValueError(f"BSON document length {total} out of range")
    end = at + total
    if buf[end - 1] != 0:
        raise ValueError("BSON document missing terminator")
    p = at + 4
    doc: dict = {}
    while p < end - 1:
        t = buf[p]
        p += 1
        name, p = _read_cstring(buf, p)
        if t == 0x01:
            (doc[name],) = _D.unpack_from(buf, p)
            p += 8
        elif t == 0x02:
            (n,) = _I32.unpack_from(buf, p)
            p += 4
            if n < 1 or p + n > end:
                raise ValueError(f"BSON string length {n} out of range")
            doc[name] = bytes(buf[p:p + n - 1]).decode("utf-8")
            p += n
        elif t == 0x03:
            doc[name], p = _decode_doc(buf, p)
        elif t == 0x04:
            sub, p = _decode_doc(buf, p)
            doc[name] = [sub[k] for k in sub]  # keys are "0","1",...
        elif t == 0x05:
            (n,) = _I32.unpack_from(buf, p)
            p += 5  # length + subtype byte
            if n < 0 or p + n > end:
                raise ValueError(f"BSON binary length {n} out of range")
            doc[name] = bytes(buf[p:p + n])
            p += n
        elif t == 0x08:
            doc[name] = buf[p] != 0
            p += 1
        elif t == 0x0A:
            doc[name] = None
        elif t == 0x10:
            (doc[name],) = _I32.unpack_from(buf, p)
            p += 4
        elif t == 0x12:
            (doc[name],) = _I64.unpack_from(buf, p)
            p += 8
        else:
            raise ValueError(f"BSON type 0x{t:02x} not supported")
    return doc, end


def decode(data: bytes | memoryview, at: int = 0) -> dict:
    """Decode one BSON document starting at ``at``."""
    doc, _ = _decode_doc(memoryview(data), at)
    return doc


def decode_with_end(data: bytes | memoryview,
                    at: int = 0) -> tuple[dict, int]:
    """Decode one document and return (doc, offset past it) — for
    walking OP_MSG sequences of concatenated documents."""
    return _decode_doc(memoryview(data), at)
