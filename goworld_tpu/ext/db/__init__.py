"""User-level async DB wrappers (reference ``ext/db``: ``gwredis.go``,
``gwmongo.go:31-355`` — async groups wrapping redigo/mgo with callbacks
posted back to the logic thread)."""
