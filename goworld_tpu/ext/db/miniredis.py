"""In-process redis-compatible server (RESP2 over TCP).

The reference's CI provisions a real Redis service for its kvdb/storage
tests (``.github/workflows/test.yml``); this container bakes in neither a
redis server nor a driver, so tests (and single-host deployments that want
a networked store without external dependencies) get this instead — the
same role miniredis plays in the Go ecosystem. It is a real socket server
speaking the real protocol: the client stack above it
(:mod:`goworld_tpu.ext.db.resp`, the storage/kvdb redis backends, gwredis)
is byte-for-byte the code that talks to an actual redis.

Supported commands: PING SELECT SET GET MGET SETNX DEL EXISTS KEYS SCAN
FLUSHDB DBSIZE HSET HGET HGETALL HDEL EXPIRE (expiry is accepted and
ignored — entity data must not vanish under the engine). Keyspace is
per-db (SELECT), values are bytes.
"""

from __future__ import annotations

import fnmatch
import socket
import socketserver
import threading


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.rfile = self.request.makefile("rb")
        self.db = 0

    def handle(self):
        try:
            while True:
                args = self._read_command()
                if args is None:
                    return
                self._dispatch(args)
        except (ConnectionError, OSError):
            return

    def finish(self):
        try:
            self.rfile.close()
        except OSError:
            pass

    # -- protocol -------------------------------------------------------
    def _read_command(self) -> list[bytes] | None:
        line = self.rfile.readline()
        if not line:
            return None
        if not line.startswith(b"*"):
            # inline command (telnet-style) — enough for PING
            return line.strip().split()
        n = int(line[1:])
        args = []
        for _ in range(n):
            hdr = self.rfile.readline()
            ln = int(hdr[1:])
            data = self.rfile.read(ln + 2)
            args.append(data[:-2])
        return args

    def _send(self, data: bytes) -> None:
        self.request.sendall(data)

    def _ok(self, s: str = "OK") -> None:
        self._send(f"+{s}\r\n".encode())

    def _int(self, n: int) -> None:
        self._send(f":{n}\r\n".encode())

    def _bulk(self, b: bytes | None) -> None:
        if b is None:
            self._send(b"$-1\r\n")
        else:
            self._send(b"$%d\r\n%s\r\n" % (len(b), b))

    def _array(self, items) -> None:
        self._send(b"*%d\r\n" % len(items))
        for it in items:
            if isinstance(it, (list, tuple)):
                self._array(it)
            elif isinstance(it, int):
                self._int(it)  # CLUSTER SLOTS carries slot numbers/ports
            else:
                self._bulk(it)

    def _err(self, msg: str) -> None:
        self._send(f"-ERR {msg}\r\n".encode())

    def _redirect(self, kind: str, slot: int, addr: str) -> None:
        # cluster redirect replies are errors WITHOUT the ERR prefix:
        # "-MOVED 3999 127.0.0.1:6381" / "-ASK 3999 127.0.0.1:6381"
        self._send(f"-{kind} {slot} {addr}\r\n".encode())

    def _cluster_check(self, srv: "MiniRedis", keys: list[bytes]) -> bool:
        """Cluster-mode ownership check; True = an error/redirect was
        sent and the command must not execute. Validates EVERY key like
        real cluster redis: a multi-key command spanning slots gets
        -CROSSSLOT even when all slots are locally owned. The ASKING
        flag (set by the previous command on THIS connection) admits
        one command for a slot being imported, per the cluster spec."""
        if srv.cluster_slots is None or not keys:
            return False
        asking, self._asking = getattr(self, "_asking", False), False
        from goworld_tpu.ext.db.resp import key_slot

        slots = {key_slot(k) for k in keys}
        if len(slots) > 1:
            self._send(b"-CROSSSLOT Keys in request don't hash to the "
                       b"same slot\r\n")
            return True
        slot = slots.pop()
        ask_to = srv.ask.get(slot)
        if ask_to is not None and not asking:
            self._redirect("ASK", slot, ask_to)
            return True
        lo, hi = srv.cluster_slots
        if lo <= slot <= hi or asking:
            return False
        for addr, (plo, phi) in srv.peers.items():
            if plo <= slot <= phi:
                self._redirect("MOVED", slot, addr)
                return True
        self._redirect("MOVED", slot, srv.addr)  # stale map fallback
        return True

    # -- commands -------------------------------------------------------
    def _dispatch(self, args: list[bytes]) -> None:
        srv: MiniRedis = self.server.owner  # type: ignore[attr-defined]
        cmd = args[0].upper().decode()
        a = args[1:]
        with srv.lock:
            d = srv.dbs.setdefault(self.db, {})
            if srv.cluster_slots is not None:
                if cmd in ("MGET", "DEL", "EXISTS"):
                    ck = a                      # every arg is a key
                elif cmd in ("SET", "SETNX", "GET", "HSET", "HGET",
                             "HGETALL", "HDEL", "EXPIRE"):
                    ck = a[:1]                  # first arg is THE key
                else:
                    ck = []
                if ck and self._cluster_check(srv, ck):
                    return
            if cmd == "PING":
                self._ok("PONG")
            elif cmd == "SELECT":
                self.db = int(a[0])
                self._ok()
            elif cmd == "SET":
                d[a[0]] = a[1]
                self._ok()
            elif cmd == "SETNX":
                if a[0] in d:
                    self._int(0)
                else:
                    d[a[0]] = a[1]
                    self._int(1)
            elif cmd == "GET":
                v = d.get(a[0])
                if isinstance(v, dict):
                    self._err("wrong type")
                else:
                    self._bulk(v)
            elif cmd == "MGET":
                vals = [d.get(k) for k in a]
                self._array([
                    None if isinstance(v, dict) else v for v in vals
                ])
            elif cmd == "DEL":
                n = sum(1 for k in a if d.pop(k, None) is not None)
                self._int(n)
            elif cmd == "EXISTS":
                self._int(sum(1 for k in a if k in d))
            elif cmd == "KEYS":
                pat = a[0].decode()
                self._array(
                    [k for k in d if fnmatch.fnmatchcase(k.decode(), pat)]
                )
            elif cmd == "SCAN":
                # single-pass cursor: return everything, cursor 0
                pat = b"*"
                for i, w in enumerate(a):
                    if w.upper() == b"MATCH":
                        pat = a[i + 1]
                keys = [
                    k for k in d
                    if fnmatch.fnmatchcase(k.decode(), pat.decode())
                ]
                self._array([b"0", keys])
            elif cmd == "FLUSHDB":
                d.clear()
                self._ok()
            elif cmd == "DBSIZE":
                self._int(len(d))
            elif cmd == "HSET":
                h = d.setdefault(a[0], {})
                if not isinstance(h, dict):
                    self._err("wrong type")
                    return
                added = 0
                for i in range(1, len(a) - 1, 2):
                    added += a[i] not in h
                    h[a[i]] = a[i + 1]
                self._int(added)
            elif cmd == "HGET":
                h = d.get(a[0])
                self._bulk(h.get(a[1]) if isinstance(h, dict) else None)
            elif cmd == "HGETALL":
                h = d.get(a[0])
                flat: list[bytes] = []
                if isinstance(h, dict):
                    for k, v in h.items():
                        flat += [k, v]
                self._array(flat)
            elif cmd == "HDEL":
                h = d.get(a[0])
                n = 0
                if isinstance(h, dict):
                    n = sum(1 for k in a[1:] if h.pop(k, None) is not None)
                self._int(n)
            elif cmd == "EXPIRE":
                self._int(1 if a[0] in d else 0)
            elif cmd == "ASKING":
                # admit the NEXT command on this connection for a slot
                # this node is importing (cluster spec)
                self._asking = True
                self._ok()
            elif cmd == "CLUSTER":
                sub = a[0].upper() if a else b""
                if srv.cluster_slots is None:
                    self._err("This instance has cluster support disabled")
                elif sub == b"SLOTS":
                    def node(addr: str):
                        h, _, p = addr.rpartition(":")
                        return [h.encode(), int(p)]

                    entries = [[srv.cluster_slots[0],
                                srv.cluster_slots[1], node(srv.addr)]]
                    for addr, (lo, hi) in srv.peers.items():
                        entries.append([lo, hi, node(addr)])
                    self._array(entries)
                else:
                    self._err(f"unknown CLUSTER subcommand {sub!r}")
            else:
                self._err(f"unknown command '{cmd}'")


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class MiniRedis:
    """``srv = MiniRedis(); srv.start()`` -> ``srv.port``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 cluster_slots: tuple[int, int] | None = None):
        self.host = host
        self.port = port
        self.dbs: dict[int, dict[bytes, object]] = {}
        self.lock = threading.Lock()
        self._server: _Server | None = None
        self._thread: threading.Thread | None = None
        # cluster mode (None = plain redis): this node owns the
        # inclusive slot range; `peers` maps other nodes' addr -> range
        # (drives CLUSTER SLOTS and -MOVED); `ask` maps slot -> addr
        # for migration-in-progress -ASK redirects. Tests mutate these
        # live to simulate resharding.
        self.cluster_slots = cluster_slots
        self.peers: dict[str, tuple[int, int]] = {}
        self.ask: dict[int, str] = {}

    def start(self) -> "MiniRedis":
        self._server = _Server((self.host, self.port), _Handler)
        self._server.owner = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="miniredis", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def __enter__(self) -> "MiniRedis":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
