"""Async redis wrapper for user game code.

Reference being rebuilt: ``ext/db/gwredis.go`` — a redigo connection owned
by one async group, exposing a generic command call whose reply is posted
back to the logic thread. Usage mirrors the reference::

    r = GWRedis("127.0.0.1:6379", workers)
    r.command(lambda reply, err: ..., "SET", "k", "v")
    r.get("k", lambda val, err: ...)

All ops serialize on the ``_gwredis`` worker group; callbacks run on the
logic thread via the world's post queue (the same contract as
:mod:`goworld_tpu.kvdb`).
"""

from __future__ import annotations

from typing import Callable

from goworld_tpu.ext.db.resp import RespClient
from goworld_tpu.utils.asyncwork import AsyncWorkers

_GROUP = "_gwredis"  # dedicated worker group (reference gwredis.go)


class GWRedis:
    def __init__(self, addr: str, workers: AsyncWorkers):
        self._c = RespClient.from_addr(addr)
        self._workers = workers

    def command(self, cb: Callable | None, *args) -> None:
        """Generic command (reference's ``redis.Do`` pass-through)."""
        self._workers.submit(_GROUP, lambda: self._c.command(*args), cb)

    # convenience wrappers over the generic call
    def get(self, key, cb: Callable) -> None:
        self.command(cb, "GET", key)

    def set(self, key, val, cb: Callable | None = None) -> None:
        self.command(cb, "SET", key, val)

    def delete(self, key, cb: Callable | None = None) -> None:
        self.command(cb, "DEL", key)

    def close(self) -> None:
        self._c.close()
