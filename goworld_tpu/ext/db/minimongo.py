"""In-process MongoDB server speaking real OP_MSG wire bytes.

The role miniredis plays for the redis backends: no MongoDB server is
baked into this environment, so a compatible one is implemented over
the same from-scratch BSON/OP_MSG codecs the client uses — tests and
single-host deployments run the REAL wire protocol end to end, and
the storage/kvdb backends work unchanged against an actual mongod.

Supported commands (the surface the reference backends use):
``hello``/``isMaster``, ``ping``, ``insert``, ``update`` (upsert-by-q,
whole-doc replace), ``find`` (empty filter, by ``_id``, ``_id`` range
``$gte``/``$lt``/``$gt``/``$lte``, projection, sort on ``_id``,
limit), ``delete``, ``drop``, ``listCollections``. Single-batch
cursors (id 0) — no getMore, matching the client.
"""

from __future__ import annotations

import socketserver
import struct
import threading

from goworld_tpu.ext.db import bson

_HDR = struct.Struct("<iiii")
OP_MSG = 2013
# real mongod caps messages at 48 MB (maxMessageSizeBytes); anything
# outside [16, cap] means the framing cannot be trusted — drop the
# connection instead of letting _recv_exact chew on garbage (ADVICE.md)
MAX_MESSAGE_BYTES = 48 * 1024 * 1024


def _match(doc: dict, q: dict) -> bool:
    for k, cond in q.items():
        v = doc.get(k)
        if isinstance(cond, dict) and any(
                key.startswith("$") for key in cond):
            for op, rhs in cond.items():
                if op == "$gte":
                    if not (v is not None and v >= rhs):
                        return False
                elif op == "$gt":
                    if not (v is not None and v > rhs):
                        return False
                elif op == "$lte":
                    if not (v is not None and v <= rhs):
                        return False
                elif op == "$lt":
                    if not (v is not None and v < rhs):
                        return False
                elif op == "$eq":
                    if v != rhs:
                        return False
                else:
                    raise ValueError(f"minimongo: operator {op!r}")
        elif v != cond:
            return False
    return True


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            while True:
                hdr = self._recv_exact(16)
                if hdr is None:
                    return
                length, rid, _resp, opcode = _HDR.unpack(hdr)
                if length < 16 or length > MAX_MESSAGE_BYTES:
                    return  # untrustworthy framing: drop connection
                body = self._recv_exact(length - 16)
                if body is None:
                    return
                if opcode != OP_MSG or len(body) < 5 or body[4] != 0:
                    return  # unsupported legacy opcode: drop connection
                try:
                    cmd = bson.decode(body, 5)
                except ValueError:
                    return  # malformed BSON: drop connection
                reply = self._dispatch(cmd)
                rb = bson.encode(reply)
                payload = struct.pack("<I", 0) + b"\x00" + rb
                self.request.sendall(
                    _HDR.pack(16 + len(payload), 0, rid, OP_MSG)
                    + payload)
        except (ConnectionError, OSError):
            return

    def _recv_exact(self, n: int) -> bytes | None:
        chunks = []
        while n:
            b = self.request.recv(n)
            if not b:
                return None
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    # -- commands -------------------------------------------------------
    def _dispatch(self, cmd: dict) -> dict:
        srv: MiniMongo = self.server.owner  # type: ignore[attr-defined]
        if not cmd:
            # next(iter({})) would raise StopIteration and kill the
            # handler thread; answer like mongod answers nonsense
            return {"ok": 0.0, "errmsg": "empty command", "code": 59}
        name = next(iter(cmd))
        db = cmd.get("$db", "goworld")
        with srv.lock:
            try:
                if name in ("hello", "isMaster", "ismaster"):
                    return {"ok": 1.0, "isWritablePrimary": True,
                            "maxWireVersion": 17, "minWireVersion": 6}
                if name == "ping":
                    return {"ok": 1.0}
                if name == "insert":
                    coll = srv.colls.setdefault((db, cmd["insert"]), {})
                    n = 0
                    for d in cmd.get("documents", []):
                        if "_id" not in d:
                            d = dict(d, _id=f"auto{srv.next_id()}")
                        if d["_id"] in coll:
                            return {"ok": 1.0, "n": n, "writeErrors": [
                                {"index": n, "code": 11000,
                                 "errmsg": "duplicate key"}]}
                        coll[d["_id"]] = d
                        n += 1
                    return {"ok": 1.0, "n": n}
                if name == "update":
                    coll = srv.colls.setdefault((db, cmd["update"]), {})
                    n = mod = ups = 0
                    upserted = []
                    for u in cmd.get("updates", []):
                        q, repl = u.get("q", {}), u.get("u", {})
                        if any(k.startswith("$") for k in repl):
                            raise ValueError(
                                "minimongo: update operators not "
                                "supported (whole-doc replace only)")
                        hits = [d for d in coll.values()
                                if _match(d, q)]
                        if hits:
                            for d in hits if u.get("multi") else hits[:1]:
                                nd = dict(repl)
                                nd.setdefault("_id", d["_id"])
                                del coll[d["_id"]]
                                coll[nd["_id"]] = nd
                                n += 1
                                mod += 1
                        elif u.get("upsert"):
                            nd = dict(repl)
                            if "_id" not in nd:
                                nd["_id"] = q.get(
                                    "_id", f"auto{srv.next_id()}")
                            coll[nd["_id"]] = nd
                            n += 1
                            ups += 1
                            upserted.append(
                                {"index": len(upserted),
                                 "_id": nd["_id"]})
                    r = {"ok": 1.0, "n": n, "nModified": mod}
                    if upserted:
                        r["upserted"] = upserted
                    return r
                if name == "find":
                    coll = srv.colls.get((db, cmd["find"]), {})
                    out = [d for d in coll.values()
                           if _match(d, cmd.get("filter", {}))]
                    sort = cmd.get("sort")
                    if sort:
                        if list(sort) != ["_id"]:
                            raise ValueError(
                                "minimongo: sort on _id only")
                        out.sort(key=lambda d: d["_id"],
                                 reverse=int(sort["_id"]) < 0)
                    lim = int(cmd.get("limit", 0))
                    if lim:
                        out = out[:lim]
                    proj = cmd.get("projection")
                    if proj:
                        # real mongod also supports EXCLUSION
                        # projections; reject rather than silently
                        # answering like an empty inclusion (tests
                        # must not certify behavior mongod differs on)
                        if any(not v for k, v in proj.items()
                               if k != "_id"):
                            raise ValueError(
                                "minimongo: exclusion projections "
                                "not supported")
                        keep = {k for k, v in proj.items() if v}
                        keep.add("_id")
                        if proj.get("_id", 1) in (0, False):
                            keep.discard("_id")
                        out = [{k: d[k] for k in d if k in keep}
                               for d in out]
                    ns = f"{db}.{cmd['find']}"
                    # real mongod batches: firstBatch caps at 101 for
                    # an unlimited find, the rest rides getMore — so
                    # the client's cursor loop is actually exercised
                    batch = int(cmd.get("batchSize", 0)) or 101
                    first, rest = out[:batch], out[batch:]
                    cid = 0
                    if rest:
                        cid = srv.next_cursor()
                        srv.cursors[cid] = (ns, rest)
                    return {"ok": 1.0, "cursor": {
                        "id": cid, "ns": ns, "firstBatch": first}}
                if name == "getMore":
                    cid = cmd["getMore"]
                    ns, rest = srv.cursors.pop(
                        cid, (f"{db}.{cmd.get('collection', '')}", []))
                    batch = int(cmd.get("batchSize", 0)) or 101
                    nxt, rest = rest[:batch], rest[batch:]
                    new_id = 0
                    if rest:
                        new_id = srv.next_cursor()
                        srv.cursors[new_id] = (ns, rest)
                    return {"ok": 1.0, "cursor": {
                        "id": new_id, "ns": ns, "nextBatch": nxt}}
                if name == "delete":
                    coll = srv.colls.get((db, cmd["delete"]), {})
                    n = 0
                    for dl in cmd.get("deletes", []):
                        q = dl.get("q", {})
                        lim = int(dl.get("limit", 0))
                        hits = [d["_id"] for d in coll.values()
                                if _match(d, q)]
                        if lim:
                            hits = hits[:lim]
                        for _id in hits:
                            del coll[_id]
                            n += 1
                    return {"ok": 1.0, "n": n}
                if name == "drop":
                    srv.colls.pop((db, cmd["drop"]), None)
                    return {"ok": 1.0}
                if name == "listCollections":
                    names = sorted(c for d, c in srv.colls if d == db)
                    return {"ok": 1.0, "cursor": {
                        "id": 0, "ns": f"{db}.$cmd.listCollections",
                        "firstBatch": [
                            {"name": n, "type": "collection"}
                            for n in names]}}
                return {"ok": 0.0, "errmsg": f"no such command: "
                                             f"'{name}'", "code": 59}
            except ValueError as e:
                return {"ok": 0.0, "errmsg": str(e), "code": 2}


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class MiniMongo:
    """``srv = MiniMongo(); srv.start()`` -> ``srv.port`` / ``srv.addr``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        # (db, collection) -> {_id: document}
        self.colls: dict[tuple[str, str], dict] = {}
        self.lock = threading.Lock()
        self._ctr = 0
        # open multi-batch cursors: id -> (ns, remaining docs)
        self.cursors: dict[int, tuple[str, list]] = {}
        self._server: _Server | None = None
        self._thread: threading.Thread | None = None

    def next_id(self) -> int:
        self._ctr += 1
        return self._ctr

    def next_cursor(self) -> int:
        self._ctr += 1
        return self._ctr

    def start(self) -> "MiniMongo":
        self._server = _Server((self.host, self.port), _Handler)
        self._server.owner = self  # type: ignore[attr-defined]
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="minimongo",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def __enter__(self) -> "MiniMongo":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
