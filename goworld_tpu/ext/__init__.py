"""Extensions built on the core framework (reference ``ext/``): pubsub
service, async DB wrappers."""
