"""Publish/subscribe service — subject tree with trailing-``*`` wildcards.

Reference being rebuilt: ``ext/pubsub/PublishSubscribeService.go:34-130``:
a (shardable) service entity maintaining a subject trie; subscribers
register exact subjects or prefix wildcards (``"price.*"``); publishing
walks the trie and RPCs ``OnPublish`` on every subscriber entity. Shard by
subject (the reference example uses shard key = subject,
``examples/test_game/Avatar.go:53-55``) so one subject's fan-out stays on
one shard.

Usage::

    services.register("PublishSubscribeService", PublishSubscribeService,
                      shard_count=3)
    # from any entity:
    e.call_service("PublishSubscribeService", "Subscribe",
                   e.id, "chat.room1", shard_key="chat.room1")
    e.call_service("PublishSubscribeService", "Publish",
                   "chat.room1", "hello", shard_key="chat.room1")
    # subscriber entities implement OnPublish(subject, *args)
"""

from __future__ import annotations

from goworld_tpu.entity.entity import Entity
from goworld_tpu.utils import log

logger = log.get("pubsub")

_SEP = "."
_WILDCARD = "*"


class _Node:
    __slots__ = ("children", "exact", "wildcard")

    def __init__(self):
        self.children: dict[str, _Node] = {}
        self.exact: set[str] = set()      # subscriber eids on this subject
        self.wildcard: set[str] = set()   # subscribers of "<prefix>.*"


class PublishSubscribeService(Entity):
    """The service entity (register via ``ServiceManager.register``)."""

    def OnInit(self):
        self._root = _Node()

    # -- helpers ---------------------------------------------------------
    def _walk(self, parts: list[str], create: bool) -> _Node | None:
        node = self._root
        for p in parts:
            nxt = node.children.get(p)
            if nxt is None:
                if not create:
                    return None
                nxt = node.children[p] = _Node()
            node = nxt
        return node

    @staticmethod
    def _split(subject: str) -> tuple[list[str], bool]:
        """-> (path parts, is_wildcard). ``"a.b.*"`` -> ([a, b], True)."""
        parts = subject.split(_SEP)
        if parts and parts[-1] == _WILDCARD:
            return parts[:-1], True
        return parts, False

    # -- service RPCs (called via call_service) --------------------------
    def Subscribe(self, subscriber: str, subject: str):
        parts, wild = self._split(subject)
        node = self._walk(parts, create=True)
        (node.wildcard if wild else node.exact).add(subscriber)

    def Unsubscribe(self, subscriber: str, subject: str):
        parts, wild = self._split(subject)
        node = self._walk(parts, create=False)
        if node is not None:
            (node.wildcard if wild else node.exact).discard(subscriber)

    def UnsubscribeAll(self, subscriber: str):
        def rec(node: _Node) -> None:
            node.exact.discard(subscriber)
            node.wildcard.discard(subscriber)
            for c in node.children.values():
                rec(c)

        rec(self._root)

    def Publish(self, subject: str, *args):
        parts, wild = self._split(subject)
        if wild:
            logger.warning("cannot publish to wildcard subject %r", subject)
            return
        targets: set[str] = set()
        node = self._root
        for p in parts:
            # wildcard subscribers at every prefix level match
            targets |= node.wildcard
            node = node.children.get(p)
            if node is None:
                break
        else:
            # wildcard subs match strictly-longer subjects only: "a.*"
            # gets "a.b" (prefix loop above) but not "a" itself
            targets |= node.exact
        for eid in targets:
            self.call(eid, "OnPublish", subject, *args)
