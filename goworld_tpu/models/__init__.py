"""Entity behavior models — the batched replacement for per-entity AI code.

In the reference, NPC behavior is interpreted per entity per timer tick
(``examples/unity_demo/Monster.go:32-100``: 100 ms AI timer + 30 ms move
tick over ``InterestedIn``). Here behaviors are vectorized functions over the
whole SoA population, selected per entity type, so the MXU does the work:

* :mod:`goworld_tpu.models.random_walk` — the bot-swarm movement model used
  by the reference's CI workload (``examples/test_client/ClientBot.go:214``).
* :mod:`goworld_tpu.models.npc_policy` — a bf16 MLP policy over local
  observations (the "fused NPC behavior kernel", BASELINE config 5).
"""

from goworld_tpu.models.npc_policy import MLPPolicy, init_policy, policy_accel
from goworld_tpu.models.random_walk import random_walk_step

__all__ = ["MLPPolicy", "init_policy", "policy_accel", "random_walk_step"]
