"""Fused NPC behavior-tree kernel (BASELINE config 5).

The reference runs arbitrary Go per NPC per AI tick: ``examples/unity_demo/
Monster.go:32-100`` is a 100 ms timer that picks the nearest player from the
monster's ``InterestedIn`` set, chases it, else idles/wanders. That per-
entity control flow is the opposite of what a TPU wants, so here the same
decision structure is a **static behavior tree compiled to masked vector
ops**: the tree shape is Python data fixed at trace time, every condition
is a bool[N] vector, every action produces a candidate velocity field, and
selector/sequence semantics become mask algebra — one fused XLA program
evaluates the whole population's AI per tick, no branches, no gathers
beyond the per-neighbor feature build.

Tree semantics (success/failure, no 'running' state — the reference's
Monster AI is also memoryless between ticks):

- ``Cond(name)``   succeeds where the named condition vector is True.
- ``Act(name)``    always succeeds; where reached, emits the named action.
- ``Seq(*kids)``   runs children in order; an entity continues only while
  every child succeeded (short-circuit via mask intersection).
- ``Sel(*kids)``   first succeeding child claims the entity; later
  children only see entities every earlier child failed.

Where several actions end up active for one entity (multi-action
sequences), the FIRST action emitted in traversal order wins — matching
the depth-first execution order a scalar BT interpreter would have.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

from goworld_tpu.models.random_walk import random_walk_step


# ------------------------------------------------------------- tree spec --

@dataclasses.dataclass(frozen=True)
class Cond:
    name: str


@dataclasses.dataclass(frozen=True)
class Act:
    name: str


@dataclasses.dataclass(frozen=True)
class Seq:
    children: tuple
    def __init__(self, *children):
        object.__setattr__(self, "children", tuple(children))


@dataclasses.dataclass(frozen=True)
class Sel:
    children: tuple
    def __init__(self, *children):
        object.__setattr__(self, "children", tuple(children))


def eval_tree(node, active, conds: dict) -> tuple[jax.Array, list]:
    """Unrolled-at-trace-time evaluation. Returns (success bool[N],
    [(action_name, mask bool[N]), ...] in traversal order)."""
    if isinstance(node, Cond):
        return active & conds[node.name], []
    if isinstance(node, Act):
        return active, [(node.name, active)]
    if isinstance(node, Seq):
        cur, acts = active, []
        for child in node.children:
            cur, a = eval_tree(child, cur, conds)
            acts.extend(a)
        # an action emitted mid-sequence only counts where the WHOLE
        # sequence later succeeded? No — the reference's scalar execution
        # performs each action as it reaches it; mask as reached.
        return cur, acts
    if isinstance(node, Sel):
        remaining, succeeded, acts = active, jnp.zeros_like(active), []
        for child in node.children:
            s, a = eval_tree(child, remaining, conds)
            acts.extend(a)
            succeeded = succeeded | s
            remaining = remaining & ~s
        return succeeded, acts
    raise TypeError(f"unknown BT node {node!r}")


def combine_actions(acts, actions: dict, shape) -> jax.Array:
    """First-emitted-wins combination of masked action velocities."""
    vel = jnp.zeros(shape, jnp.float32)
    claimed = jnp.zeros(shape[:1], bool)
    for name, mask in acts:
        take = mask & ~claimed
        vel = jnp.where(take[:, None], actions[name], vel)
        claimed = claimed | take
    return vel


def monster_tree() -> Sel:
    """The unity_demo Monster AI as a tree (Monster.go:32-100): chase the
    nearest player in AOI; avoid crowds; otherwise wander."""
    return Sel(
        Seq(Cond("player_in_aoi"), Act("chase")),
        Seq(Cond("crowded"), Act("separate")),
        Act("wander"),
    )


# ------------------------------------------------------- feature builders --

@struct.dataclass
class BTFeatures:
    nbr_cnt: jax.Array      # i32[N] AOI neighbor count
    client_cnt: jax.Array   # i32[N] client-owning neighbors
    client_off: jax.Array   # f32[N, 3] offset to nearest client neighbor
    mean_off: jax.Array     # f32[N, 3] mean neighbor offset


def features_from_neighbors(
    pos: jax.Array,
    has_client: jax.Array,
    nbr: jax.Array,
    nbr_cnt: jax.Array,
) -> BTFeatures:
    """Single-space feature build from the previous tick's neighbor lists
    (one [N, k] row gather — the same budget the MLP observation pays).
    ``pos``/``has_client`` index the candidate population the lists point
    into."""
    n = pos.shape[0]
    sentinel = n
    valid = nbr != sentinel
    nbr_c = jnp.minimum(nbr, n - 1)
    npos = pos[nbr_c]                                    # [N, k, 3]
    offs = jnp.where(
        valid[:, :, None], npos - pos[: nbr.shape[0], None, :], 0.0
    )
    is_client = valid & has_client[nbr_c]
    cheb = jnp.maximum(jnp.abs(offs[:, :, 0]), jnp.abs(offs[:, :, 2]))
    key = jnp.where(is_client, cheb, jnp.inf)
    lane = jnp.argmin(key, axis=1)                       # nearest player
    client_off = jnp.take_along_axis(
        offs, lane[:, None, None], axis=1
    )[:, 0, :]
    client_cnt = is_client.sum(axis=1).astype(jnp.int32)
    client_off = jnp.where(client_cnt[:, None] > 0, client_off, 0.0)
    denom = jnp.maximum(nbr_cnt, 1).astype(jnp.float32)
    return BTFeatures(
        nbr_cnt=nbr_cnt,
        client_cnt=client_cnt,
        client_off=client_off,
        mean_off=offs.sum(axis=1) / denom[:, None],
    )


def features_from_summary(
    nbr_cnt: jax.Array,
    nbr_client_cnt: jax.Array,
    nbr_mean_off: jax.Array,
) -> BTFeatures:
    """Megaspace variant: gid neighbor lists cannot gather positions
    locally, so the previous sweep's summary features stand in — chase
    heads along the mean neighbor offset when players are present (the
    nearest-player refinement needs per-neighbor positions; documented
    approximation)."""
    return BTFeatures(
        nbr_cnt=nbr_cnt,
        client_cnt=nbr_client_cnt,
        client_off=nbr_mean_off,
        mean_off=nbr_mean_off,
    )


# ------------------------------------------------------------- evaluation --

def btree_velocity(
    key: jax.Array,
    feats: BTFeatures,
    vel: jax.Array,
    npc_moving: jax.Array,
    speed: float,
    turn_prob: float,
    crowd_threshold: int = 12,
) -> jax.Array:
    """Evaluate the monster tree over the population -> f32[N, 3]."""
    conds = {
        "player_in_aoi": feats.client_cnt > 0,
        "crowded": feats.nbr_cnt >= crowd_threshold,
    }

    def toward(off, sign):
        norm = jnp.sqrt(off[:, 0] ** 2 + off[:, 2] ** 2 + 1e-6)
        d = off / norm[:, None]
        return sign * speed * d * jnp.asarray(
            [1.0, 0.0, 1.0], jnp.float32
        )

    actions = {
        "chase": toward(feats.client_off, 1.0),
        "separate": toward(feats.mean_off, -1.0),
        "wander": random_walk_step(
            key, vel, npc_moving, speed, turn_prob
        ),
    }
    active = npc_moving
    _, acts = eval_tree(monster_tree(), active, conds)
    out = combine_actions(acts, actions, vel.shape)
    return jnp.where(npc_moving[:, None], out, 0.0)
