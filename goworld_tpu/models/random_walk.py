"""Random-walk movement model (the reference CI workload's motion).

Reference: bots move with 50% probability every 100 ms by a random step
(``examples/test_client/ClientBot.go:214-227``); unity_demo Monsters pick a
random nearby target. Here: every tick each moving entity keeps its heading,
and with ``turn_prob`` picks a fresh uniform heading; speed is constant.
Vectorized over the whole population in one fused elementwise block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def random_walk_step(
    key: jax.Array,
    vel: jax.Array,
    moving: jax.Array,
    speed: float,
    turn_prob: float,
) -> jax.Array:
    """Return updated velocities f32[N,3] (y velocity stays 0)."""
    n = vel.shape[0]
    k_turn, k_head = jax.random.split(key)
    turn = jax.random.uniform(k_turn, (n,)) < turn_prob
    heading = jax.random.uniform(k_head, (n,), minval=0.0, maxval=2.0 * jnp.pi)
    new_vel = jnp.stack(
        [jnp.cos(heading) * speed, jnp.zeros(n), jnp.sin(heading) * speed],
        axis=1,
    )
    pick_new = (turn | (jnp.sum(jnp.abs(vel), axis=1) < 1e-6)) & moving
    return jnp.where(pick_new[:, None], new_vel, vel)
