"""bf16 MLP behavior policy over local observations (MXU path).

The reference runs arbitrary Go per NPC per AI tick
(``examples/unity_demo/Monster.go:32-100``); a TPU framework instead wants
"kernelizable" behaviors expressed as one batched network evaluation
(BASELINE config 5, the fused NPC behavior kernel). The observation builder
summarises AOI context (neighbor count, mean neighbor offset from the
neighbor lists) so the policy can chase/flee — a batched analog of the
Monster's "pick a target in InterestedIn" loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

OBS_DIM = 10


@struct.dataclass
class MLPPolicy:
    w1: jax.Array  # bf16[OBS_DIM, H]
    b1: jax.Array  # bf16[H]
    w2: jax.Array  # bf16[H, H]
    b2: jax.Array  # bf16[H]
    w3: jax.Array  # bf16[H, 3]
    b3: jax.Array  # bf16[3]


def init_policy(key: jax.Array, hidden: int = 128) -> MLPPolicy:
    k1, k2, k3 = jax.random.split(key, 3)
    dt = jnp.bfloat16

    def dense(k, i, o):
        return (jax.random.normal(k, (i, o), dt) * (1.0 / jnp.sqrt(i))).astype(dt)

    return MLPPolicy(
        w1=dense(k1, OBS_DIM, hidden),
        b1=jnp.zeros((hidden,), dt),
        w2=dense(k2, hidden, hidden),
        b2=jnp.zeros((hidden,), dt),
        w3=dense(k3, hidden, 3),
        b3=jnp.zeros((3,), dt),
    )


def neighbor_mean_offset(
    pos_src: jax.Array,
    self_pos: jax.Array,
    nbr: jax.Array,
    nbr_cnt: jax.Array,
    sentinel: int,
) -> jax.Array:
    """f32[N, 3] mean offset to valid neighbors. ``pos_src`` is the
    candidate position table ``nbr`` indexes into (the full population for
    a single space; local+ghost rows for a megaspace tile)."""
    valid = nbr != sentinel
    nbr_c = jnp.minimum(nbr, pos_src.shape[0] - 1)
    npos = pos_src[nbr_c]                               # [N, k, 3]
    offs = jnp.where(valid[:, :, None], npos - self_pos[:, None, :], 0.0)
    cnt = jnp.maximum(nbr_cnt, 1).astype(jnp.float32)
    return offs.sum(axis=1) / cnt[:, None]


def build_obs_from_features(
    pos: jax.Array,
    vel: jax.Array,
    yaw: jax.Array,
    nbr_cnt: jax.Array,
    mean_off: jax.Array,
    k: int,
    world_extent: tuple[float, float],
) -> jax.Array:
    """f32[N, OBS_DIM] from precomputed neighbor features — the megaspace
    path, whose gid neighbor lists cannot gather positions locally
    (features come from the previous tick's AOI sweep)."""
    ex, ez = world_extent
    return jnp.concatenate(
        [
            pos[:, :1] / ex,
            pos[:, 2:3] / ez,
            vel / 10.0,
            jnp.sin(yaw)[:, None],
            jnp.cos(yaw)[:, None],
            (nbr_cnt.astype(jnp.float32) / k)[:, None],
            mean_off[:, ::2] / 100.0,                    # x, z mean offset
        ],
        axis=1,
    )


def build_obs(
    pos: jax.Array,
    vel: jax.Array,
    yaw: jax.Array,
    nbr: jax.Array,
    nbr_cnt: jax.Array,
    world_extent: tuple[float, float],
) -> jax.Array:
    """f32[N, OBS_DIM]: normalized pos, vel, yaw sin/cos, neighbor summary."""
    n, k = nbr.shape
    mean_off = neighbor_mean_offset(pos, pos, nbr, nbr_cnt, n)
    return build_obs_from_features(
        pos, vel, yaw, nbr_cnt, mean_off, k, world_extent
    )


def policy_accel(params: MLPPolicy, obs: jax.Array) -> jax.Array:
    """Batched MLP forward; returns f32[N, 3] acceleration."""
    x = obs.astype(jnp.bfloat16)
    x = jnp.tanh(x @ params.w1 + params.b1)
    x = jnp.tanh(x @ params.w2 + params.b2)
    out = x @ params.w3 + params.b3
    return out.astype(jnp.float32)
