"""goworld_tpu — a TPU-native distributed game-server framework.

A from-scratch rebuild of the capabilities of GoWorld (the reference at
/root/reference: spaces & entities, AOI interest management, reactive
attribute sync, location-transparent entity RPC, entity migration, sharded
services, persistence, hot reload, gate/dispatcher/game deployment), with an
execution model designed for TPUs:

* Each Space's entity population lives as a structure-of-arrays (SoA) pytree
  of JAX arrays on device (``goworld_tpu.core.state``).
* The per-tick hot loop of the reference — AOI sweep + position/attr sync
  (``engine/entity/Entity.go:1208-1267`` ``CollectEntitySyncInfos``) — is a
  single jitted step function over those arrays (``goworld_tpu.core.step``).
* Spaces are pinned to TPU cores; cross-space RPC, AOI halos and entity
  migration ride XLA collectives over ICI (``goworld_tpu.parallel``) instead
  of the reference's dispatcher TCP hop.
* The host side keeps GoWorld's programming model — entity classes with
  lifecycle hooks, reactive attrs, timers, services
  (``goworld_tpu.entity``) — staging events into fixed-capacity per-tick
  batches.

The public facade mirrors the reference's root package ``goworld.go:34-256``.
"""

__version__ = "0.1.0"


def __getattr__(name: str):
    """Lazy facade: ``goworld_tpu.api`` pulls in jax (via the entity
    runtime); dispatcher/gate processes import this package for config and
    wire code only and must NOT initialize a TPU client (under the axon
    tunnel, every jax-using process contends for the single chip).

    Submodules resolve first (so ``from goworld_tpu import config`` does
    not recurse through the api import); everything else proxies to the
    facade in :mod:`goworld_tpu.api`."""
    import importlib

    try:
        return importlib.import_module(f"goworld_tpu.{name}")
    except ModuleNotFoundError as e:
        # only swallow "no such submodule"; a submodule's own failing
        # import (e.g. a missing third-party dep) must surface as-is
        if e.name != f"goworld_tpu.{name}":
            raise
    from goworld_tpu import api

    try:
        return getattr(api, name)
    except AttributeError:
        raise AttributeError(
            f"module 'goworld_tpu' has no attribute {name!r}"
        ) from None
