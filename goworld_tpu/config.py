"""Cluster configuration — one INI file shared by every process.

Reference being rebuilt: ``engine/config`` (``read_config.go:40-118,238-330``
and ``goworld.ini.sample``): a single ``goworld.ini`` read by dispatcher,
game and gate processes alike, with ``[deployment]`` desired process counts
(the readiness barrier), numbered sections ``[dispatcherN]``/``[gameN]``/
``[gateN]``, and ``*_common`` sections providing inherited defaults.

TPU additions live in the game sections: per-space device capacity, AOI
radius, number of space shards, mesh axis size.
"""

from __future__ import annotations

import configparser
import dataclasses
import os

from goworld_tpu.utils import consts
from goworld_tpu.utils.consts import (
    MAX_RECONNECT_PEND_BYTES,
    MAX_RECONNECT_PEND_PACKETS,
)

DEFAULT_CONFIG_PATHS = ("goworld_tpu.ini", "goworld.ini")


@dataclasses.dataclass
class DispatcherConfig:
    host: str = "127.0.0.1"
    port: int = 14000
    http_port: int = 0


@dataclasses.dataclass
class GameConfig:
    boot_entity: str = "Account"
    save_interval: float = 300.0
    position_sync_interval_ms: int = 100
    ban_boot_entity: bool = False
    http_port: int = 0
    # distributed tracing: sampling rate for traces the GAME roots
    # itself (outbound migrations); inbound traced packets are always
    # recorded regardless (the gate made the sampling decision)
    trace_sample_rate: float = 0.0
    log_file: str = ""
    log_level: str = "info"
    # TPU execution knobs
    capacity: int = 16384
    n_spaces: int = 1
    aoi_radius: float = 50.0
    # AOI kernel tuning (ops/aoi.py GridSpec): sweep candidate fetch
    # ("table" | "ranges" | "cellrow" — table with premerged windows +
    # one row-gather per query, bit-identical to table | "shift" —
    # cell-major/gather-free but drops cap-overflowed entities as
    # watchers | "fused" — the ranges front half with the whole back
    # half (window gather -> key pack -> top-k) as one VMEM-resident
    # Pallas kernel, bit-identical to ranges; interpret-mode emulation
    # off-TPU, so never a CPU default) and top-k select
    # ("exact" | "sort" | "f32" — all three exact; sort/f32 lower to
    # faster TPU kernels — or "approx", which may miss a true neighbor
    # with ~2% probability on TPU). Unknown values are rejected at
    # GridSpec construction. Defaults come from
    # consts.DEFAULT_SWEEP_IMPL / DEFAULT_TOPK_IMPL — the one source
    # of truth shared with GridSpec and bench.py.
    aoi_sweep_impl: str = consts.DEFAULT_SWEEP_IMPL
    aoi_topk_impl: str = consts.DEFAULT_TOPK_IMPL
    # front-half cell-sort lowering ("argsort" | "counting" — two-pass
    # counting sort, bit-identical to argsort, deletes the bitonic
    # network | "pallas" — its kernel form). consts.DEFAULT_SORT_IMPL
    # is the shared default literal.
    aoi_sort_impl: str = consts.DEFAULT_SORT_IMPL
    # Verlet skin width (world units; 0 = off): build the AOI grid for
    # radius + skin and skip the whole front half on ticks where no
    # entity moved more than skin/2 since the last rebuild — exact by
    # the standard Verlet bound (ops/aoi.py GridSpec.skin). Size it
    # from movement speed: rebuild cadence ~ skin / (2*speed*dt).
    # Ignored for megaspace games (ghost query rows keep the stateless
    # sweep) and for n_spaces > 1 (the vmapped multi-space step runs
    # both cond branches). Memory: capacity x aoi_verlet_cap i32.
    aoi_skin: float = consts.DEFAULT_AOI_SKIN
    # cached candidate lanes per entity for the skin (0 = auto k+k//2);
    # exactness holds while rebuild-time candidate demand fits — the
    # aoi_over_k_rows gauge fires otherwise, like aoi_k
    aoi_verlet_cap: int = 0
    # force an AOI rebuild at least every N ticks regardless of
    # displacement (staleness backstop; 0 = displacement-driven only)
    aoi_rebuild_every_max: int = 0
    # AOI capacity bounds (ops/aoi.py GridSpec k / cell_cap): exactness
    # holds while true neighbor demand <= aoi_k and cell occupancy <=
    # aoi_cell_cap; overflow degrades to nearest-k and fires the
    # aoi_over_* opmon gauges. Re-provision from the gauges: aoi_k >
    # aoi_demand_max, aoi_cell_cap > aoi_cell_max. 0 = library default.
    aoi_k: int = 0
    aoi_cell_cap: int = 0
    # churn-adaptive extraction small-tier row budget (ops/extract.py
    # SMALL_TIER_ROWS; also env GOWORLD_SMALL_TIER_ROWS). 0 = library
    # default (16384, sized from the 1M bench's client-row churn;
    # TPU-profile re-derivation pending — docs/TODO_R5.md)
    small_tier_rows: int = 0
    # periodic crash-recovery checkpoint cadence in seconds (0 = off):
    # the game snapshots the running world on this interval so a
    # watchdog restart (`ctl watchdog`) can -restore from it. Async
    # off-thread on single-controller games; synchronous at a
    # tick-count cadence on multihost groups (leader writes the file).
    checkpoint_interval: float = 0.0
    # freeze boot-time objects out of the cyclic GC when the logic loop
    # starts (gen-2 collections otherwise walk the whole entity
    # population — ~100 ms at a 131K-entity shard vs the 16 ms frame);
    # post-boot churn stays tracked and collectable. CAVEAT: frozen
    # objects are reclaimed by refcounting only. The engine severs the
    # cycles it owns on destroy (attr trees, timer callbacks —
    # manager.destroy_entity / attrs.sever_tree), but USER-held cycles
    # among boot entities (e.g. two NPCs storing references to each
    # other) will leak after destroy — break such references in
    # OnDestroy, or set gc_freeze = false
    gc_freeze: bool = True
    # serve-loop tick rate (Hz): the deadline the overload governor
    # measures against. The 60 Hz default is the device-tick target;
    # hosts that cannot hold it should lower this rather than run
    # permanently DEGRADED (the ladder compares wall time per tick
    # against 1/tick_hz)
    tick_hz: float = float(consts.TICK_HZ)
    # overload-protection ladder (utils/overload.py; docs/ROBUSTNESS.md
    # "Overload & degradation"): NORMAL -> DEGRADED -> SHEDDING ->
    # REJECTING driven by tick latency / backlog / queue depths with
    # hysteresis. overload = false keeps the prioritized ingress queues
    # but never escalates past NORMAL.
    overload: bool = True
    overload_up_ticks: int = consts.OVERLOAD_UP_TICKS
    overload_down_ticks: int = consts.OVERLOAD_DOWN_TICKS
    overload_latency_ratio: float = consts.OVERLOAD_LATENCY_RATIO
    # DEGRADED fan-out degradation: position/attr sync serves each
    # entity cohort every Nth tick; client event/sync bundles flush
    # every Nth tick (bigger batches, fewer packets)
    degraded_sync_stride: int = consts.DEGRADED_SYNC_STRIDE
    degraded_event_coalesce: int = consts.DEGRADED_EVENT_COALESCE_TICKS
    # pipeline the host decode one tick behind the device step
    # (single-controller non-mesh games only; silently ignored
    # elsewhere): tick N's device execution overlaps tick N-1's host
    # event decode, so the frame pays max(device, host) instead of
    # their sum. Cost: client-visible events lag one tick (~one
    # position-sync interval).
    pipeline_decode: bool = False
    # resident-world runtime (ISSUE 20): donate the SpaceState carry
    # into the tick so XLA aliases it in place — zero steady-state
    # HBM allocation on the serve loop. Bit-identical to off (donation
    # is an aliasing hint, not a numerics change); snapshot/freeze
    # paths fall back LOUDLY to an explicit device copy of the planes
    # they read across ticks. Default on.
    resident: bool = True
    extent_x: float = 1000.0
    extent_z: float = 1000.0
    mesh_devices: int = 0  # 0 = single-device vmap path (GLOBAL count
                           # when mesh_processes > 1)
    mesh_processes: int = 1  # SPMD controller OS processes for this
                           # game: the CLI spawns one per rank with a
                           # shared jax.distributed coordinator; ONE
                           # logical game spans them (multihost)
    # reconnect pend queue budget (net/cluster.py): packets queued while
    # a dispatcher link is down; beyond either bound the OLDEST drop
    # (cluster_pend_dropped_total counts them)
    pend_max_packets: int = MAX_RECONNECT_PEND_PACKETS
    pend_max_bytes: int = MAX_RECONNECT_PEND_BYTES
    npc_speed: float = 5.0
    behavior: str = "random_walk"  # random_walk | mlp | btree (the fused
                                   # NPC kernels, BASELINE config 5)
    # adversarial workload scenario (goworld_tpu/scenarios registry:
    # hotspot | shrink | flock | teleport | mixed_radius | mixed —
    # docs/SCENARIOS.md). When set, NPC motion dispatches the spec's
    # heterogeneous behavior mix through one vmapped lax.switch and
    # `behavior` above is ignored for velocity. "" = off. Ignored for
    # megaspace games (the tile step keeps the homogeneous path).
    scenario: str = ""
    # ONE logical space spanning the whole mesh as spatial tiles
    # (parallel/megaspace.py; BASELINE config 4). extent_x/extent_z are
    # the WORLD extents; tiles are derived from mega_shape ("8" = 1D
    # x-strips, "4x2" = 2D XZ tiles; device count must match
    # mesh_devices). capacity is PER TILE.
    megaspace: bool = False
    mega_shape: str = ""           # "" = 1D strips over mesh_devices
    halo_cap: int = 1024
    migrate_cap: int = 256
    # halo ghost shipping impl (parallel/halo.py): "ppermute" (default,
    # barriered collective) | "async" (Pallas make_async_remote_copy
    # per edge, dirty-only packed payload — overlap-capable; off-TPU it
    # runs interpret mode with a one-time warning, never a CPU default)
    halo_impl: str = "ppermute"
    # live device-telemetry lanes (ops/telemetry.py; ISSUE 11): the
    # production tick accumulates tick signals (rebuild rate, skin
    # slack, over_k/over_cap, event volumes, per-tile occupancy) on
    # device with zero added host syncs and serves the reduced
    # workload signature at debug-http /workload. false = off (the
    # flight recorder then records frames without signature marks).
    telemetry_live: bool = True
    # incident flight recorder (utils/flightrec.py): per-tick frame
    # ring size (0 = off) and the per-trigger-kind dedup cooldown for
    # frozen snapshot bundles served at /incidents
    flightrec_ring: int = 512
    flightrec_cooldown_secs: float = 30.0
    # quantized state planes (ops/aoi.py GridSpec.precision; ISSUE 12):
    # "off" (default — bit-identical to pre-r12 behavior) | "q16" —
    # AOI-visible positions snap to a power-of-two int16 lattice and
    # the byte-heavy paths run on narrow planes (packed sorted view,
    # packed Verlet cache, bf16 velocity). Exact vs the oracle over the
    # snapped world BY CONSTRUCTION (docs/ROOFLINE.md "Quantized state
    # planes"). Rejected loudly at GridSpec build when the lattice
    # would be coarser than radius/4 or the origin is nonzero. Ignored
    # (warned) for megaspace games this round — the tile grids keep
    # f32 while the halo packing is staged (the audit stamps its
    # projected ICI win as ici_halo_mb_by_impl *_q16 rows).
    precision: str = consts.DEFAULT_PRECISION
    # delta-compressed client sync fan-out (net/codec.py
    # DeltaSyncEncoder; ISSUE 12): steady-state sync bytes scale with
    # dirty_frac * 13 B/record instead of 48 B/record. Decode at the
    # gate is bit-deterministic (baselines/keyframes ride in-band).
    sync_delta: bool = False
    # full-precision keyframe cadence per (client, entity) pair for
    # the delta sync stream (ticks)
    sync_keyframe_every: int = 16
    # end-to-end sync-age stamping (utils/syncage.py; docs/
    # OBSERVABILITY.md "End-to-end sync age"): every sync fan-out
    # batch carries the device-tick epoch that produced it as a 45 B
    # flagged trailer; the gate ages records at delivery into
    # sync_age_ms histograms and the deployment aggregator prints one
    # SLO verdict against the paper's 16 ms target. false = the legacy
    # byte-identical wire.
    sync_age: bool = True
    # delta-compressed snapshot chain (freeze.py SnapshotChain): every
    # Nth periodic checkpoint is a full quantized keyframe, the writes
    # between ship sparse int16 plane deltas with per-plane CRCs.
    # 0 = the monolithic checkpoint format, unchanged.
    snapshot_keyframe_every: int = 0
    # serve-loop residency plane (utils/residency.py; docs/
    # OBSERVABILITY.md "Serve-loop residency"): host-bubble/phase
    # timing from perf_counter marks on the tick's existing structure
    # (zero added device syncs), the sampled alloc-churn probes and
    # the donation-readiness buffer census, served at /residency and
    # merged into the deployment verdict. false = off.
    residency: bool = True
    # cadence (ticks) of the sampled probes — the buffer census and
    # device.memory_stats() deltas; the timing lanes are always-on.
    # Must be >= 1 (validated loudly at World build).
    residency_sample_every: int = 16
    # correctness audit plane (utils/audit.py; docs/OBSERVABILITY.md
    # "Correctness audit"): an independent entity-ownership ledger
    # (census digests + migrate ownership seqs -> deployment
    # conservation verdicts), a sampled live AOI oracle judging one
    # cohort's interest sets brute-force off the hot path, and mirror
    # consistency probes — served at /audit, violations feed
    # audit_violations_total{kind} + the audit_violation flight-
    # recorder trigger. false = off.
    audit: bool = True
    # oracle/probe sample cadence (ticks) and cohort size (entities
    # judged per sample). Must be >= 1 (validated loudly at World
    # build).
    audit_sample_every: int = 64
    audit_cohort: int = 64
    # SnapshotChain CRC-scrub cadence (ticks; 0 = off): the audit
    # worker re-reads this game's chain files on this cadence so
    # latent on-disk corruption is a named violation, not a surprise
    # at the next -restore boot
    audit_scrub_every: int = 0
    # online kernel governor (goworld_tpu/autotune; docs/AUTOTUNE.md):
    # the live workload signature hot-swaps the resolved tick config
    # (aoi_skin on/off, sort/sweep impl) between ticks with AOT-warmed
    # executables (zero mid-serving compile stalls), a deterministic
    # decision log (/governor endpoint) and a post-swap regret guard.
    # Single-shard non-mesh games only; requires telemetry_live.
    governor: bool = False
    # signature-window length in ticks (one governor decision per
    # window; also sets the live signature rotation cadence)
    governor_window_ticks: int = 64
    # hysteresis: consecutive windows a target config must win before
    # a swap is decided (down = returning to the table default), plus
    # the per-swap cooldown in windows
    governor_up_windows: int = 2
    governor_down_windows: int = 2
    governor_cooldown_windows: int = 4
    # regret guard: revert + pin when the post-swap tick-ms p90
    # worsens past this fraction vs the pre-swap window
    governor_regret_pct: float = 0.25
    # mapping-table override, "class:label;class:label" over the
    # candidate pool (classes: flock_like/teleport_like/density/
    # default; labels: the SCENARIO_KERNEL_CANDIDATES keys). Default:
    # seeded from the checked-in per-scenario best_kernel stamps.
    governor_table: str = ""
    # hot-standby replication (goworld_tpu/replication/; docs/
    # ROBUSTNESS.md "Hot-standby worlds"): nonzero makes THIS game a
    # warm standby of game N — it boots empty (no boot entities, never
    # chosen for clients), subscribes to game N's frame stream through
    # the dispatcher, mirrors its world live, and is promoted by the
    # supervisor when game N dies (kvreg-arbitrated, split-brain-safe).
    # 0 = a normal primary.
    standby_of: int = 0
    # primary-side stream cadence: every Nth streamed frame is a full
    # keyframe (deltas between). Also the disk-chain cadence when a
    # standby is attached; defaults to snapshot_keyframe_every when 0.
    replication_keyframe_every: int = 0
    # bounded replication-worker queue (captures). Full queue = the
    # capture is DROPPED (loud counter) and the next accepted one is
    # forced to a keyframe — backlog degrades cadence, never the tick.
    replication_queue: int = 4
    # standby staleness budget: /standby's verdict fails when the time
    # since the last applied frame exceeds this many primary ticks
    replication_lag_budget_ticks: int = 16


@dataclasses.dataclass
class GateConfig:
    host: str = "127.0.0.1"
    port: int = 15000
    ws_port: int = 0          # 0 = no websocket listener
    kcp_port: int = 0         # 0 = no KCP (reliable-UDP) listener
                              # (reference GateService.go:129-161)
    kcp_idle_timeout: float = 60.0  # reap KCP sessions with no inbound
                              # datagram for this long (UDP has no
                              # connection_lost; 0 disables)
    # client-edge transport (reference goworld.ini.sample compress/encrypt
    # flags; ClientProxy.go:38-53). encrypt=TLS on the TCP listener; the
    # cert/key are generated self-signed on first use when paths are empty.
    compress: bool = False
    # stream codec for compressed client connections: "snappy" (the
    # reference's codec — from-scratch framing-format implementation,
    # net/snappy.py) or "zlib" (one zlib-1 stream per direction; its
    # shared dictionary wins on tiny packets at more CPU per byte).
    # Both ends must agree, like the compress flag itself.
    compress_codec: str = "snappy"
    encrypt: bool = False
    tls_cert: str = ""
    tls_key: str = ""
    # default ON (a vanished TCP peer — cable pull, NAT expiry — is
    # reaped without opt-in; the reference ships 60 in its sample ini);
    # 0 stays the explicit off switch
    heartbeat_timeout: float = 30.0
    # admission control (utils/overload.py): connection cap (0 =
    # unlimited; new handshakes past the cap — or while the gate's
    # overload ladder is REJECTING — are refused), per-client
    # token-bucket rate limits on inbound packets/s and bytes/s (0 =
    # off), and the per-client downstream buffer budget with the
    # stalled-consumer kick window
    max_clients: int = 0
    rate_limit_pps: float = 0.0
    rate_limit_bps: float = 0.0
    downstream_max_bytes: int = consts.GATE_DOWNSTREAM_MAX_BYTES
    downstream_kick_secs: float = consts.GATE_DOWNSTREAM_KICK_SECS
    position_sync_interval_ms: int = 100
    # delivery target for the end-to-end sync-age verdict (ms): the
    # paper's 16 ms AOI-sync SLO by default. Ages are measured at this
    # gate's per-client flush (utils/syncage.py); a flush window whose
    # e2e p99 blows the target freezes a sync_age_breach incident.
    sync_age_target_ms: float = 16.0
    # reconnect pend queue budget (net/cluster.py; drop-oldest beyond)
    pend_max_packets: int = MAX_RECONNECT_PEND_PACKETS
    pend_max_bytes: int = MAX_RECONNECT_PEND_BYTES
    http_port: int = 0        # debug/metrics endpoint (0 = off); every
                              # process kind serves the same /metrics +
                              # /trace map (docs/OBSERVABILITY.md)
    # distributed tracing: probability that a client packet entering
    # this gate roots a sampled trace (0 = off; also settable live via
    # debug-http /tracing?rate= and `goworld_tpu trace`)
    trace_sample_rate: float = 0.0
    log_file: str = ""
    log_level: str = "info"


@dataclasses.dataclass
class StorageConfig:
    kind: str = "filesystem"   # filesystem | memory | redis | mongodb
    directory: str = "entity_storage"  # path, or host:port[/db] for
                                       # the networked kinds


@dataclasses.dataclass
class KVDBConfig:
    kind: str = "filesystem"   # filesystem | memory | redis |
                               # redis_cluster | mongodb
    path: str = "kvdb_data"    # path, addr[,addr...] or host:port[/db]


@dataclasses.dataclass
class ClusterConfig:
    entry: str = "server.py"   # game script ([deployment] entry = ...)
    # deterministic fault injection ([deployment] faults / faults_seed;
    # grammar in docs/ROBUSTNESS.md; env GOWORLD_FAULTS[_SEED] override)
    faults: str = ""
    faults_seed: int = 0
    # self-healing rebalance plane ([deployment] rebalance*;
    # goworld_tpu/rebalance/, docs/ROBUSTNESS.md "Elastic
    # rebalancing"): a game holding DEGRADED-or-worse for
    # rebalance_hold_windows observation windows while a peer has
    # headroom hands a bounded cohort (rebalance_batch entities per
    # window) to the underloaded game; committed (donor, target)
    # pairs then cool down for rebalance_cooldown_secs before the
    # pair can move again (ping-pong suppression)
    rebalance: bool = False
    rebalance_hold_windows: int = 3
    rebalance_batch: int = 64
    rebalance_cooldown_secs: float = 30.0
    dispatchers: dict[int, DispatcherConfig] = dataclasses.field(
        default_factory=dict)
    games: dict[int, GameConfig] = dataclasses.field(default_factory=dict)
    gates: dict[int, GateConfig] = dataclasses.field(default_factory=dict)
    storage: StorageConfig = dataclasses.field(default_factory=StorageConfig)
    kvdb: KVDBConfig = dataclasses.field(default_factory=KVDBConfig)

    @property
    def desired_games(self) -> int:
        return len(self.games)

    @property
    def desired_gates(self) -> int:
        return len(self.gates)

    def dispatcher_addrs(self) -> list[tuple[str, int]]:
        return [
            (d.host, d.port)
            for _, d in sorted(self.dispatchers.items())
        ]


def _fill(dc, section) -> None:
    """Assign section keys onto a dataclass, coercing by field type."""
    types = {f.name: f.type for f in dataclasses.fields(dc)}
    for key, raw in section.items():
        if key not in types:
            continue
        t = types[key]
        cur = getattr(dc, key)
        if isinstance(cur, bool) or t == "bool":
            val: object = raw.strip().lower() in ("1", "true", "yes", "on")
        elif isinstance(cur, int):
            val = int(raw)
        elif isinstance(cur, float):
            val = float(raw)
        else:
            val = raw
        setattr(dc, key, val)


def load(path: str | None = None) -> ClusterConfig:
    """Load the cluster config (reference ``config.Get()``); falls back to
    a 1-dispatcher/1-game/1-gate localhost layout when no file exists."""
    cp = configparser.ConfigParser()
    found = None
    if path is not None:
        found = path
    else:
        for cand in DEFAULT_CONFIG_PATHS:
            if os.path.exists(cand):
                found = cand
                break
    if found is not None:
        with open(found) as f:
            cp.read_file(f)

    cfg = ClusterConfig()

    def common_of(prefix: str):
        name = f"{prefix}_common"
        return cp[name] if cp.has_section(name) else {}

    def build(prefix: str, cls, store: dict) -> None:
        common = common_of(prefix)
        for name in cp.sections():
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                idx = int(name[len(prefix):])
                dc = cls()
                _fill(dc, common)
                _fill(dc, cp[name])
                store[idx] = dc

    build("dispatcher", DispatcherConfig, cfg.dispatchers)
    build("game", GameConfig, cfg.games)
    build("gate", GateConfig, cfg.gates)
    if cp.has_section("deployment"):
        dep = cp["deployment"]
        if "entry" in dep:
            cfg.entry = dep["entry"]
        cfg.faults = dep.get("faults", cfg.faults)
        if "faults_seed" in dep:
            cfg.faults_seed = int(dep["faults_seed"])
        if "rebalance" in dep:
            cfg.rebalance = dep["rebalance"].strip().lower() in (
                "1", "true", "yes", "on")
        if "rebalance_hold_windows" in dep:
            cfg.rebalance_hold_windows = int(
                dep["rebalance_hold_windows"])
        if "rebalance_batch" in dep:
            cfg.rebalance_batch = int(dep["rebalance_batch"])
        if "rebalance_cooldown_secs" in dep:
            cfg.rebalance_cooldown_secs = float(
                dep["rebalance_cooldown_secs"])
        # reference semantics: [deployment] declares DESIRED COUNTS
        # (read_config.go:40-118): counts beyond the explicit numbered
        # sections auto-create defaults from the *_common section, and
        # sections beyond the count are dropped (the count IS the
        # deployment). Auto-created listeners get a per-index port
        # offset — inheriting one host:port N times would EADDRINUSE at
        # start. (These keys share names with ClusterConfig's dicts —
        # never _fill them, or `games = 3` would clobber the dict.)
        for key, cls, store, prefix in (
            ("dispatchers", DispatcherConfig, cfg.dispatchers,
             "dispatcher"),
            ("games", GameConfig, cfg.games, "game"),
            ("gates", GateConfig, cfg.gates, "gate"),
        ):
            if key not in dep:
                continue
            want = int(dep[key])
            common = common_of(prefix)
            for idx in range(1, want + 1):
                if idx not in store:
                    dc = cls()
                    _fill(dc, common)
                    for pf in ("port", "ws_port", "kcp_port",
                               "http_port"):
                        base = getattr(dc, pf, 0)
                        if base:
                            setattr(dc, pf, base + idx - 1)
                    lf = getattr(dc, "log_file", "")
                    if lf:  # shared log files interleave unattributably
                        stem, dot, ext = lf.rpartition(".")
                        setattr(dc, "log_file",
                                f"{stem}{idx}{dot}{ext}" if dot
                                else f"{lf}{idx}")
                    store[idx] = dc
            for idx in [i for i in store if i > want]:
                del store[idx]
        # explicit sections inheriting a *_common port can still collide
        # with an auto-created sibling's offset scheme: detect instead of
        # guessing intent
        for role, store in (("dispatcher", cfg.dispatchers),
                            ("gate", cfg.gates)):
            seen: dict[tuple, int] = {}
            for idx, dc in sorted(store.items()):
                for pf in ("port", "ws_port", "kcp_port", "http_port"):
                    p = getattr(dc, pf, 0)
                    if not p or p < 0:
                        continue
                    key = (getattr(dc, "host", ""), p)
                    if key in seen:
                        raise ValueError(
                            f"{role}{idx} {pf} {p} collides with "
                            f"{role}{seen[key]} — give each listener a "
                            "distinct port"
                        )
                    seen[key] = idx
    # debug-http collisions including GAME rank spans: a multihost game
    # binds http_port .. http_port + mesh_processes - 1 (one endpoint
    # per controller, api.run), which the dispatcher/gate-only check
    # above cannot see — and a wrong-port scrape silently attributes
    # one process's health to another
    seen_http: dict[tuple, str] = {}
    for role, store in (("dispatcher", cfg.dispatchers),
                        ("gate", cfg.gates)):
        for idx, dc in sorted(store.items()):
            p = getattr(dc, "http_port", 0)
            if p > 0:
                seen_http[(dc.host, p)] = f"{role}{idx}"
    for idx, gdc in sorted(cfg.games.items()):
        if gdc.http_port <= 0:
            continue
        span = max(1, getattr(gdc, "mesh_processes", 1))
        for rank in range(span):
            key = ("127.0.0.1", gdc.http_port + rank)  # games bind lo
            if key in seen_http:
                raise ValueError(
                    f"game{idx} http_port {key[1]}"
                    + (f" (rank {rank})" if span > 1 else "")
                    + f" collides with {seen_http[key]} — give each "
                    "debug endpoint a distinct port"
                )
            seen_http[key] = f"game{idx}" + (f"c{rank}" if span > 1
                                             else "")

    if cp.has_section("storage"):
        _fill(cfg.storage, cp["storage"])
    if cp.has_section("kvdb"):
        _fill(cfg.kvdb, cp["kvdb"])

    if not cfg.dispatchers:
        cfg.dispatchers[1] = DispatcherConfig()
    if not cfg.games:
        cfg.games[1] = GameConfig()
    if not cfg.gates:
        cfg.gates[1] = GateConfig()
    return cfg


def dumps_sample() -> str:
    """A commented sample config (reference ``goworld.ini.sample``)."""
    return """\
# goworld_tpu cluster configuration (reference: goworld.ini.sample)
# Every process reads this same file; numbered sections declare the
# deployment (their count is the readiness barrier).

# [deployment]
# faults = drop:gate->dispatcher:0.05,kill:game1@t+10s
#                    # seeded fault-injection schedule (chaos testing;
# faults_seed = 42   # grammar in docs/ROBUSTNESS.md; env
#                    # GOWORLD_FAULTS / GOWORLD_FAULTS_SEED override)
# rebalance = true   # self-healing entity rebalancing: a game holding
#                    # DEGRADED-or-worse hands a bounded cohort to an
#                    # underloaded peer (docs/ROBUSTNESS.md "Elastic
#                    # rebalancing"; served live at /rebalance)
# rebalance_hold_windows = 3    # sustained windows before a move plans
# rebalance_batch = 64          # entities per handoff send window
# rebalance_cooldown_secs = 30  # per-(donor,target) pair cooldown

[dispatcher1]
host = 127.0.0.1
port = 14000
# http_port = 14100  # debug/metrics endpoint: /metrics (Prometheus),
#                    # /trace (Chrome JSON), /vars, /ops, /healthz

[game_common]
boot_entity = Account
position_sync_interval_ms = 100
save_interval = 300
# TPU execution
capacity = 16384
n_spaces = 1
aoi_radius = 50.0
extent_x = 1000.0
extent_z = 1000.0
# behavior = btree   # fused NPC kernel: random_walk | mlp | btree
# scenario = hotspot # adversarial workload mix (goworld_tpu/scenarios
#                    # registry; docs/SCENARIOS.md): hotspot | shrink |
#                    # flock | teleport | mixed_radius | mixed
#                    # (megaspace games honor it too — border churn)
# halo_impl = ppermute # megaspace ghost shipping: ppermute (barriered
#                    # collective) | async (Pallas per-edge remote DMA,
#                    # dirty-only packed payload; interpret + warning
#                    # off-TPU — never a CPU default)
# pipeline_decode = true   # overlap host event decode with the device
#                          # step (single-controller non-mesh games;
#                          # client events lag one tick)
# resident = true          # carry donation: XLA aliases the SpaceState
#                          # in place, zero steady-state HBM allocation
#                          # (default ON; bit-identical either way —
#                          # snapshot capture falls back loudly to a
#                          # device copy of the planes it pins)
# http_port = 16000        # debug/metrics endpoint (multihost ranks
#                          # bind http_port + rank)
# gc_freeze = false        # keep boot objects in the cyclic GC (the
#                          # default freezes them out: gen-2 passes
#                          # cost ~100 ms at a 131K-entity shard)
# overload = true          # overload ladder NORMAL->DEGRADED->SHEDDING
#                          # ->REJECTING (docs/ROBUSTNESS.md); knobs:
# overload_up_ticks = 8    # pressured ticks to climb one rung
# overload_down_ticks = 120  # calm ticks to descend one rung
# overload_latency_ratio = 1.5  # tick wall / interval that = pressure
# degraded_sync_stride = 4 # DEGRADED: sync each entity cohort every Nth
# degraded_event_coalesce = 2  # DEGRADED: flush bundles every Nth tick
# precision = q16          # quantized state planes (ISSUE 12): snap
#                          # AOI-visible positions to an int16 lattice,
#                          # bf16 velocity, packed sweep/Verlet planes —
#                          # halves modeled bytes/tick; off = bit-
#                          # identical to pre-r12 (docs/ROOFLINE.md)
# sync_delta = true        # delta-compressed sync fan-out: int16 deltas
#                          # vs per-(client,entity) baselines, 13 B vs
#                          # 48 B/record steady state
# sync_keyframe_every = 16 # full-precision keyframe cadence (ticks)
# sync_age = false         # drop the 45 B per-batch sync-age stamp
#                          # (default ON: gates age every record at
#                          # delivery vs the paper's 16 ms target —
#                          # docs/OBSERVABILITY.md "End-to-end sync
#                          # age"; off = legacy byte-identical wire)
# snapshot_keyframe_every = 8  # delta-compressed checkpoint chain:
#                          # every Nth checkpoint is a full quantized
#                          # keyframe (0 = monolithic checkpoints)
# residency = false        # drop the serve-loop residency plane
#                          # (default ON: host-bubble/alloc-churn/
#                          # serve-gap verdicts at /residency —
#                          # docs/OBSERVABILITY.md "Serve-loop
#                          # residency"; timing only, no device syncs)
# residency_sample_every = 16  # cadence (ticks) of the buffer census
#                          # + memory_stats probes; must be >= 1
# audit = false            # drop the correctness audit plane
#                          # (default ON: entity-ownership ledger +
#                          # sampled AOI oracle + mirror probes at
#                          # /audit — docs/OBSERVABILITY.md
#                          # "Correctness audit"; zero device syncs)
# audit_sample_every = 64  # oracle/probe sample cadence (ticks)
# audit_cohort = 64        # entities judged per sample
# audit_scrub_every = 1024 # SnapshotChain CRC-scrub cadence (ticks;
#                          # 0 = off)
# governor = true          # online kernel governor (docs/AUTOTUNE.md):
#                          # the live workload signature hot-swaps the
#                          # tick config (skin on/off, counting sort)
#                          # between ticks — warm-gated, regret-guarded
# governor_window_ticks = 64   # one decision per signature window
# governor_up_windows = 2  # windows a target must win before a swap
# governor_down_windows = 2    # same, returning to the default config
# governor_cooldown_windows = 4  # refractory windows after a swap
# governor_regret_pct = 0.25   # post-swap p90 worsening that reverts
# governor_table = teleport_like:skin=0;density:sort=counting,skin=0
#                          # mapping override (class:label;...)
# standby_of = 1           # make THIS game a hot standby of game 1:
#                          # boots empty, mirrors game 1's frame stream
#                          # live, promoted by the supervisor on game 1
#                          # death (docs/ROBUSTNESS.md "Hot-standby
#                          # worlds"); 0 = a normal primary
# replication_keyframe_every = 8  # stream keyframe cadence (frames);
#                          # 0 = inherit snapshot_keyframe_every
# replication_queue = 4    # bounded replication-worker queue; full =
#                          # drop capture + force next keyframe
# replication_lag_budget_ticks = 16  # /standby verdict fails past this
#                          # staleness (primary ticks)

[game1]

[gate_common]
host = 127.0.0.1
compress = false
# heartbeat reaping defaults to 30 when omitted; 0 = explicit off
heartbeat_timeout = 60

[gate1]
port = 15000
# ws_port = 15100    # websocket listener
# kcp_port = 15200   # KCP (reliable-UDP) listener
# compress = true    # stream compression (both ends must agree)
# compress_codec = snappy   # snappy (default, the reference codec) | zlib
# encrypt = true     # TLS on the TCP listener (self-signed on first use)
# max_clients = 10000       # connection cap (0 = unlimited); REJECTING
#                           # state refuses new handshakes regardless
# rate_limit_pps = 200      # per-client inbound packets/s (0 = off)
# rate_limit_bps = 262144   # per-client inbound bytes/s (0 = off)
# downstream_max_bytes = 4194304  # per-client downstream buffer budget
# downstream_kick_secs = 10 # disconnect a client whose buffer stays full

[storage]
kind = filesystem
directory = entity_storage
# kind = mongodb           # the reference's primary backend (BSON +
# directory = 127.0.0.1:27017/goworld   # OP_MSG wire; mongod or the
#                          # in-process minimongo)
# kind = redis
# directory = 127.0.0.1:6379

[kvdb]
kind = filesystem
path = kvdb_data
"""
