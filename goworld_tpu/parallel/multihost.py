"""Multi-host (multi-controller) deployment of the device mesh over DCN.

Reference behavior being rebuilt: GoWorld scales past one machine by
running more game processes connected through the dispatcher star over TCP
(``engine/dispatchercluster/dispatchercluster.go:18-37``; SURVEY.md §5.8).
The TPU-native equivalent keeps that host-side wire protocol for gates and
cross-cluster RPC, but the ENTITY data plane — AOI halos, tile migration,
global counters — rides XLA collectives. Within one host those collectives
use ICI; across hosts, ``jax.distributed`` forms one global device mesh
and the very same ``shard_map`` programs (:mod:`goworld_tpu.parallel.step`,
:mod:`goworld_tpu.parallel.megaspace`) run unchanged, with XLA routing the
``all_to_all`` / ``ppermute`` / ``psum`` legs that cross process
boundaries over DCN (gRPC/Gloo on CPU test rigs, ICI+DCN on real pods).

One process per host, SPMD: every process traces the same tick over the
global mesh and owns the shards on its local devices. Host-side output
decoding must therefore read only addressable shards —
:func:`local_shard_outputs` — because a non-addressable shard's data never
exists in this process.

Tested end-to-end in ``tests/test_multihost.py``: two OS processes, four
virtual CPU devices each, one 8-tile megaspace; an NPC walks across the
process boundary and arrives on the other host's shard via the collective
migration path, and ghost-zone interest enters fire across the boundary.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from goworld_tpu.parallel.mesh import SPACE_AXIS


def init_distributed(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
) -> None:
    """Join (or form) the multi-controller cluster.

    Call BEFORE any other jax API touches a backend. Equivalent of the
    reference game's dispatcher handshake (``DispatcherConnMgr.go:63-85``)
    at the data-plane level: process 0 is the coordinator, everyone blocks
    until all ``num_processes`` have joined. Device-count env knobs
    (``xla_force_host_platform_device_count`` for CPU rigs) must already
    be set in the environment — XLA reads them at backend init.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(axis_name: str = SPACE_AXIS) -> Mesh:
    """One mesh axis over EVERY device of EVERY process, in process order
    (jax.devices() is globally consistent, so all processes build the
    identical mesh and the shard_map programs agree)."""
    return Mesh(np.array(jax.devices()), (axis_name,))


def local_shard_indices(mesh: Mesh) -> list[int]:
    """Mesh positions owned by THIS process (= the World shard indices
    whose outputs this host may decode)."""
    me = jax.process_index()
    return [
        i for i, d in enumerate(mesh.devices.ravel())
        if d.process_index == me
    ]


def local_shard_outputs(out_tree, mesh: Mesh):
    """Per-local-shard host copies of a sharded output pytree.

    Returns ``(indices, [tree_of_np_arrays per local shard])`` where each
    tree leaf has the leading [n_dev] axis stripped. Only addressable
    shards are touched — never the cross-host ones.
    """
    idxs = local_shard_indices(mesh)
    pos_of = {i: k for k, i in enumerate(idxs)}

    def per_leaf(x):
        rows = [None] * len(idxs)
        for s in x.addressable_shards:
            row = s.index[0] if s.index else slice(None)
            if isinstance(row, slice):
                if row.start is None and row.stop is None:
                    # replicated on the mesh axis: every device holds the
                    # full array — slice out this process's rows once
                    data = np.asarray(s.data)
                    for i in idxs:
                        rows[pos_of[i]] = data[i]
                    break
                start = row.start or 0
                stop = row.stop if row.stop is not None else start + 1
                for off in range(stop - start):
                    if start + off in pos_of:
                        rows[pos_of[start + off]] = np.asarray(s.data)[off]
                continue
            if row in pos_of:
                rows[pos_of[row]] = np.asarray(s.data)[0]
        return rows

    leaves, treedef = jax.tree_util.tree_flatten(out_tree)
    per_shard_leaves = [per_leaf(x) for x in leaves]
    trees = [
        jax.tree_util.tree_unflatten(
            treedef, [pl[k] for pl in per_shard_leaves]
        )
        for k in range(len(idxs))
    ]
    return idxs, trees
