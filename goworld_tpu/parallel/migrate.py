"""Entity migration as an all_to_all row exchange at tick boundaries.

Reference protocol being replaced: ``EnterSpace`` on a remote space runs a
3-phase dance — query the space's game, block the entity's packet queue at
the dispatcher (60 s timeout), msgpack all attrs + timers, destroy, recreate
on the target game, unblock (``Entity.go:956-1115``,
``DispatcherService.go:834-891``). The blocking router exists because the
processes are asynchronous.

A synchronous mesh needs none of that: each shard packs up to ``cap``
emigrant SoA rows per destination into a fixed ``[n_dev, cap, F]`` buffer,
one ``lax.all_to_all`` moves every buffer simultaneously over ICI, and each
shard scatters arrivals into free slots — all inside the compiled step.
In-flight RPCs re-route host-side using the (tag -> new slot) arrival records
the step emits; there is no window where the entity is addressable in two
places because the move is atomic within the tick.

Cold host-side entity state (nested attrs, timers) travels on the host lane
keyed by the same migration tag (:mod:`goworld_tpu.entity` stages it), so
the device path moves only hot SoA rows.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from goworld_tpu.core.state import SpaceState
from goworld_tpu.ops.extract import bounded_extract

# int-lane fields per migrating row
I_TYPE, I_HAS_CLIENT, I_CLIENT_GATE, I_TAG, I_NPC_MOVING, I_VALID = range(6)
I_FIELDS = 6


def pack_emigrants(
    state: SpaceState,
    target: jax.Array,   # i32[N]: destination shard, -1 = stay
    tag: jax.Array,      # i32[N]: host-assigned migration tag
    n_dev: int,
    cap: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Build per-destination send buffers and the departed mask.

    Returns:
      fbuf: f32[n_dev, cap, 8+A] (pos, yaw, vel, aoi_radius, hot_attrs)
      ibuf: i32[n_dev, cap, I_FIELDS]
      departed: bool[N] rows actually packed (despawn them locally)
      demand: i32[n_dev] true per-destination emigrant counts (may exceed cap;
        surplus entities stay put this tick and retry next tick — bounded
        buffers are the backpressure, like the reference's pending queue caps)
    """
    n = state.pos.shape[0]
    emigrate = (target >= 0) & (target < n_dev) & state.alive
    dst_mask = (
        target[None, :] == jnp.arange(n_dev, dtype=jnp.int32)[:, None]
    ) & emigrate[None, :]                                       # [D, N]

    flat, valid, demand = jax.vmap(
        partial(bounded_extract, cap=cap)
    )(dst_mask)                                                 # [D, cap]
    slots = jnp.where(valid, flat, n - 1)                       # safe gather

    fbuf = jnp.concatenate(
        [
            state.pos[slots],                                   # [D, cap, 3]
            state.yaw[slots][..., None],
            state.vel[slots],
            state.aoi_radius[slots][..., None],
            state.hot_attrs[slots],
        ],
        axis=-1,
    )
    fbuf = jnp.where(valid[..., None], fbuf, 0.0)
    ibuf = jnp.stack(
        [
            state.type_id[slots],
            state.has_client[slots].astype(jnp.int32),
            state.client_gate[slots],
            tag[slots],
            state.npc_moving[slots].astype(jnp.int32),
            valid.astype(jnp.int32),
        ],
        axis=-1,
    )
    ibuf = jnp.where(valid[..., None], ibuf, 0)

    drop_slots = jnp.where(valid, flat, n)                      # n = no-op row
    departed = (
        jnp.zeros(n, bool).at[drop_slots.ravel()].set(True, mode="drop")
    )
    return fbuf, ibuf, departed, demand


def despawn_departed(state: SpaceState, departed: jax.Array) -> SpaceState:
    keep = ~departed
    return state.replace(
        alive=state.alive & keep,
        has_client=state.has_client & keep,
        npc_moving=state.npc_moving & keep,
        dirty=state.dirty & keep,
        client_gate=jnp.where(departed, -1, state.client_gate),
        attr_dirty=jnp.where(departed, jnp.uint32(0), state.attr_dirty),
    )


def insert_arrivals(
    state: SpaceState,
    fbuf: jax.Array,     # f32[n_dev, cap, 8+A] (post-all_to_all: from each src)
    ibuf: jax.Array,     # i32[n_dev, cap, I_FIELDS]
    nbr_sentinel: int,
    quarantine: jax.Array | None = None,
) -> tuple[SpaceState, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Scatter arriving rows into free slots.

    ``quarantine`` (bool[N]) marks slots freed THIS tick (departed
    emigrants): they are excluded from reuse for one tick so their stale
    interest lists still produce the previous occupant's leave events on the
    next diff — otherwise those leaves would be dropped or kept depending on
    free-slot pressure (the reference always fires OnLeaveAOI on destroy,
    ``Entity.go:631-651``).

    Returns (state, arr_tag i32[D*cap], arr_slot i32[D*cap], arr_n i32,
    dropped i32). arr_slot is -1 past arr_n. ``dropped`` counts arrivals
    that found no free slot (host must treat as fatal capacity misconfig).
    """
    n = state.pos.shape[0]
    a = state.hot_attrs.shape[1]
    d, cap, _ = fbuf.shape
    total = d * cap

    f = fbuf.reshape(total, 8 + a)
    i = ibuf.reshape(total, I_FIELDS)
    arr_valid = i[:, I_VALID] > 0

    free_mask = ~state.alive
    if quarantine is not None:
        free_mask &= ~quarantine
    free_flat, free_valid, free_cnt = bounded_extract(free_mask, total)
    rank = jnp.cumsum(arr_valid.astype(jnp.int32)) - 1         # [total]
    can = arr_valid & (rank < jnp.minimum(free_cnt, total)) & (rank >= 0)
    slot = jnp.where(can, free_flat[jnp.clip(rank, 0, total - 1)], n)

    st = state.replace(
        pos=state.pos.at[slot].set(f[:, 0:3], mode="drop"),
        yaw=state.yaw.at[slot].set(f[:, 3], mode="drop"),
        vel=state.vel.at[slot].set(f[:, 4:7], mode="drop"),
        aoi_radius=state.aoi_radius.at[slot].set(f[:, 7], mode="drop"),
        hot_attrs=state.hot_attrs.at[slot].set(f[:, 8:], mode="drop"),
        type_id=state.type_id.at[slot].set(i[:, I_TYPE], mode="drop"),
        has_client=state.has_client.at[slot].set(
            i[:, I_HAS_CLIENT] > 0, mode="drop"
        ),
        client_gate=state.client_gate.at[slot].set(
            i[:, I_CLIENT_GATE], mode="drop"
        ),
        npc_moving=state.npc_moving.at[slot].set(
            i[:, I_NPC_MOVING] > 0, mode="drop"
        ),
        alive=state.alive.at[slot].set(True, mode="drop"),
        dirty=state.dirty.at[slot].set(True, mode="drop"),
        gen=state.gen.at[slot].add(1, mode="drop"),
        attr_dirty=state.attr_dirty.at[slot].set(jnp.uint32(0), mode="drop"),
        # stale interest of the slot's previous occupant must not produce
        # phantom enter/leave diffs for the newcomer
        nbr=state.nbr.at[slot].set(nbr_sentinel, mode="drop"),
        nbr_cnt=state.nbr_cnt.at[slot].set(0, mode="drop"),
    )
    arr_n = can.sum().astype(jnp.int32)
    dropped = (arr_valid & ~can).sum().astype(jnp.int32)
    order = jnp.argsort(~can)                  # compact accepted to front
    arr_tag = jnp.where(can, i[:, I_TAG], -1)[order]
    arr_slot = jnp.where(can, slot, -1)[order]
    return st, arr_tag, arr_slot, arr_n, dropped
