"""The multi-space tick: shard_map over the "space" mesh axis.

Each device runs the single-space :func:`goworld_tpu.core.step.tick_body` on
its own shard, then all shards exchange migrating entities with one
``all_to_all`` and reduce global stats with ``psum`` — the compiled
equivalent of the reference's game-process loops plus the dispatcher hop
between them (``SURVEY.md#2.3``: "dispatcher/star-TCP is replaced within a
mesh by compiled collectives").

Host contract per tick:
  inputs: per-shard TickInputs (client pos syncs routed by the host to the
  owning shard) + per-slot migration requests (target shard, host tag) —
  the staged form of ``EnterSpace`` (``Entity.go:956-973``).
  outputs: per-shard TickOutputs + arrival records (tag -> new slot) the
  host uses to re-point EntityID -> (space, slot), exactly where the
  reference's dispatcher rewrites its entityDispatchInfos table
  (``DispatcherService.go:877-891``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from goworld_tpu.core.state import SpaceState, WorldConfig
from goworld_tpu.core.step import TickInputs, TickOutputs, tick_body
from goworld_tpu.parallel import migrate as mig
from goworld_tpu.parallel.mesh import SPACE_AXIS, shard_map


@struct.dataclass
class MultiTickInputs:
    base: TickInputs          # leaves [n_dev, ...]
    migrate_target: jax.Array  # i32[n_dev, N]: dest shard or -1
    migrate_tag: jax.Array     # i32[n_dev, N]: host tag for remapping

    @staticmethod
    def empty(cfg: WorldConfig, n_dev: int) -> "MultiTickInputs":
        base = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n_dev,) + x.shape),
            TickInputs.empty(cfg),
        )
        return MultiTickInputs(
            base=base,
            migrate_target=jnp.full((n_dev, cfg.capacity), -1, jnp.int32),
            migrate_tag=jnp.full((n_dev, cfg.capacity), -1, jnp.int32),
        )


@struct.dataclass
class MultiTickOutputs:
    base: TickOutputs          # leaves [n_dev, ...]
    arr_tag: jax.Array         # i32[n_dev, n_dev*cap]
    arr_slot: jax.Array        # i32[n_dev, n_dev*cap]
    arr_n: jax.Array           # i32[n_dev]
    migrate_dropped: jax.Array  # i32[n_dev] arrivals lost to full shards
    migrate_demand: jax.Array  # i32[n_dev, n_dev] true per-dest emigrants
    global_alive: jax.Array    # i32[n_dev] (identical on every shard; psum)


def make_multi_tick(cfg: WorldConfig, mesh: Mesh, migrate_cap: int = 256,
                    donate: bool = False):
    """Build the jitted multi-space step over ``mesh``.

    Returns ``step(states, inputs, policy) -> (states, outputs)`` where
    every array carries a leading [n_dev] axis sharded over "space".
    donate=True donates the state carry (arg 0) so XLA aliases the
    output shards in place — the caller's old carry is deleted after
    dispatch (resident-world contract, see entity/manager.py).
    """
    n_dev = mesh.devices.size

    def shard_fn(
        state: SpaceState, inputs: MultiTickInputs, policy
    ) -> tuple[SpaceState, MultiTickOutputs]:
        state = jax.tree.map(lambda x: x[0], state)
        inputs = jax.tree.map(lambda x: x[0], inputs)

        state, outs = tick_body(cfg, state, inputs.base, policy)

        # --- migration: pack -> all_to_all over ICI -> insert ------------
        fbuf, ibuf, departed, demand = mig.pack_emigrants(
            state, inputs.migrate_target, inputs.migrate_tag,
            n_dev, migrate_cap,
        )
        state = mig.despawn_departed(state, departed)
        fbuf = jax.lax.all_to_all(
            fbuf, SPACE_AXIS, split_axis=0, concat_axis=0, tiled=True
        )
        ibuf = jax.lax.all_to_all(
            ibuf, SPACE_AXIS, split_axis=0, concat_axis=0, tiled=True
        )
        state, arr_tag, arr_slot, arr_n, dropped = mig.insert_arrivals(
            state, fbuf, ibuf, nbr_sentinel=cfg.capacity,
            quarantine=departed,
        )

        # --- global stats over the mesh (one psum) -----------------------
        global_alive = jax.lax.psum(
            state.alive.sum().astype(jnp.int32), SPACE_AXIS
        )

        outputs = MultiTickOutputs(
            base=outs,
            arr_tag=arr_tag,
            arr_slot=arr_slot,
            arr_n=arr_n,
            migrate_dropped=dropped,
            migrate_demand=demand,
            global_alive=global_alive,
        )
        state = jax.tree.map(lambda x: x[None], state)
        outputs = jax.tree.map(lambda x: x[None], outputs)
        return state, outputs

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(SPACE_AXIS), P(SPACE_AXIS), P()),
        out_specs=(P(SPACE_AXIS), P(SPACE_AXIS)),
    )
    # keep_unused: behavior-dead carry lanes must stay parameters or
    # they lose their donation source (see _make_local_tick)
    return jax.jit(mapped, donate_argnums=(0,) if donate else (),
                   keep_unused=donate)
