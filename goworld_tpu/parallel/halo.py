"""Ring/halo AOI ghost exchange for Spaces sharded across devices.

The reference cannot shard one Space: a space lives wholly on one game
process and user code caps its population (``doc.go:12-14``,
``SpaceService.go:14`` <=100 avatars/space). The rebuild's flagship upgrade
(``SURVEY.md#5.7``) is a Space whose entity SoA spans the mesh as spatial
tiles along x; AOI then needs each tile to see the ``radius``-wide strips of
its left/right neighbor tiles. Structurally identical to ring attention's
block rotation: bounded ghost buffers rotate over ICI with ``ppermute``
while each shard computes locally.

Ghost buffers are fixed capacity ``halo_cap``; entities in a boundary strip
beyond the cap are dropped from the neighbor's view that tick (the AOI-limit
tradeoff again — size halo_cap for the worst expected strip density).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from goworld_tpu.ops.extract import bounded_extract


def exchange_halo(
    axis: str,
    n_dev: int,
    pos: jax.Array,        # f32[N, 3] (global coords)
    yaw: jax.Array,
    dirty: jax.Array,      # bool[N]
    alive: jax.Array,
    tile_w: float,
    radius: float,
    halo_cap: int,
):
    """Ship boundary strips to lateral neighbor tiles.

    Returns a ghost block of size 2*halo_cap (left-neighbor ghosts then
    right-neighbor ghosts): (gpos f32[2H,3], gyaw f32[2H], gdirty bool[2H],
    gvalid bool[2H], ggid i32[2H] global entity ids = owner_dev * N + slot),
    plus ``strip_demand`` i32: the true occupancy of this shard's fuller
    boundary strip (host alarm when it exceeds halo_cap — ghosts beyond the
    cap were invisible to the neighbor tile this tick).
    """
    n = pos.shape[0]
    d = lax.axis_index(axis)
    tile_min = d.astype(jnp.float32) * tile_w
    x = pos[:, 0]

    def pack(mask):
        flat, valid, demand = bounded_extract(mask, halo_cap)
        slots = jnp.where(valid, flat, n - 1)
        return (
            jnp.where(valid[:, None], pos[slots], 0.0),
            jnp.where(valid, yaw[slots], 0.0),
            dirty[slots] & valid,
            valid,
            jnp.where(valid, d * n + slots, -1),
        ), demand

    left_pack, left_demand = pack(alive & (x < tile_min + radius))
    right_pack, right_demand = pack(alive & (x >= tile_min + tile_w - radius))
    # edge tiles don't ship their outward strip — exclude it from the
    # capacity alarm so a crowd at the world border can't trigger a false
    # "widen halo_cap" recompile
    strip_demand = jnp.maximum(
        jnp.where(d > 0, left_demand, 0),
        jnp.where(d < n_dev - 1, right_demand, 0),
    )

    # my left strip is a ghost for tile d-1; my right strip for tile d+1.
    # Non-periodic: edge tiles receive zeros (gvalid False).
    to_left = [(i, i - 1) for i in range(1, n_dev)]
    to_right = [(i, i + 1) for i in range(n_dev - 1)]
    from_right = jax.tree.map(
        lambda t: lax.ppermute(t, axis, to_left), left_pack
    )
    from_left = jax.tree.map(
        lambda t: lax.ppermute(t, axis, to_right), right_pack
    )

    gpos = jnp.concatenate([from_left[0], from_right[0]])
    gyaw = jnp.concatenate([from_left[1], from_right[1]])
    gdirty = jnp.concatenate([from_left[2], from_right[2]])
    gvalid = jnp.concatenate([from_left[3], from_right[3]])
    ggid = jnp.concatenate([from_left[4], from_right[4]])
    return gpos, gyaw, gdirty, gvalid, ggid, strip_demand
