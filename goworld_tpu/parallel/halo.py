"""Ring/halo AOI ghost exchange for Spaces sharded across devices.

The reference cannot shard one Space: a space lives wholly on one game
process and user code caps its population (``doc.go:12-14``,
``SpaceService.go:14`` <=100 avatars/space). The rebuild's flagship upgrade
(``SURVEY.md#5.7``) is a Space whose entity SoA spans the mesh as spatial
tiles along x; AOI then needs each tile to see the ``radius``-wide strips of
its left/right neighbor tiles. Structurally identical to ring attention's
block rotation: bounded ghost buffers rotate over ICI with ``ppermute``
while each shard computes locally.

Ghost buffers are fixed capacity ``halo_cap``; entities in a boundary strip
beyond the cap are dropped from the neighbor's view that tick (the AOI-limit
tradeoff again — size halo_cap for the worst expected strip density).

Two shipping impls (``halo_impl`` knob on :class:`MegaConfig`):

* ``"ppermute"`` (default): one ``lax.ppermute`` per payload lane per
  direction. Collectives are barriered — every device enters the
  exchange together, so the halo serializes against the whole tick.
* ``"async"``: the Pallas ``make_async_remote_copy`` pattern
  (SNIPPETS.md [2] / the jax distributed-Pallas guide). Each device
  DMAs ONE packed i32 strip buffer straight into its neighbor's
  receive buffer — no mesh-wide barrier, only a sender/receiver
  semaphore pair per edge, so the copy can overlap every part of the
  tick that does not consume ghosts (behavior, integrate, the migrate
  pack: the ghost block's only consumer is the AOI window gather).
  The packed payload is dirty-only: pos (12 B) + one meta word
  (gid/dirty/valid bits, 4 B) always ship, and the yaw lane (4 B) is
  zero unless the row is dirty — 16 B + 4 B·dirty versus the 22 B/row
  of the 5-lane ppermute path in the modeled ICI budget
  (``devprof.roofline_model_bytes_multichip``). Off-TPU the kernel
  runs in interpret mode behind
  :func:`goworld_tpu.ops.pallas_compat.interpret_default` (loud
  one-time warning, never a CPU default).

Both impls are bit-identical: same ghost blocks, same demand gauges
(tests/test_halo_async.py holds them exact across dirty/visible
permutations and halo_cap overflow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from goworld_tpu.ops.extract import bounded_extract

HALO_IMPLS = ("ppermute", "async")

# packed meta word: (gid + 1) << 2 | dirty << 1 | valid. gid ∈ [-1,
# gid_sentinel], so the +1 shift keeps it non-negative and the pack is
# exact while gid_sentinel + 1 < 2^29 (1M/chip × 64 chips = 2^26 —
# plenty; megaspace.py guards the bound at build time).
_META_GID_BITS = 29


def meta_gid_bound() -> int:
    """Largest gid the async meta word can carry exactly."""
    return (1 << _META_GID_BITS) - 2


def _pack_strip(gpos, gyaw, gdirty, gvalid, ggid) -> jax.Array:
    """One i32[H, 5] buffer per strip: cols 0-2 pos bits, col 3 yaw
    bits, col 4 meta. f32 -> i32 is a bitcast (exact roundtrip); the
    meta word packs gid/dirty/valid."""
    meta = ((ggid + 1) << 2) \
        | (gdirty.astype(jnp.int32) << 1) \
        | gvalid.astype(jnp.int32)
    return jnp.concatenate([
        lax.bitcast_convert_type(gpos, jnp.int32),
        lax.bitcast_convert_type(gyaw, jnp.int32)[:, None],
        meta[:, None],
    ], axis=1)


def _unpack_strip(buf: jax.Array):
    pos = lax.bitcast_convert_type(buf[:, 0:3], jnp.float32)
    yaw = lax.bitcast_convert_type(buf[:, 3], jnp.float32)
    meta = buf[:, 4]
    return (
        pos,
        yaw,
        ((meta >> 1) & 1).astype(bool),
        (meta & 1).astype(bool),
        (meta >> 2) - 1,
    )


def _async_ship(axis: str, n_dev: int, shift: int, buf: jax.Array,
                recv_ok) -> jax.Array:
    """DMA ``buf`` to device ``(d + shift) % n_dev`` with one Pallas
    ``make_async_remote_copy`` per device — the SNIPPETS.md [2] ring
    pattern. The ring wraps so no device conditionally skips its send
    (conditional DMAs deadlock interpret mode); non-participating
    receivers (``recv_ok`` False — world-edge tiles) zero their block
    instead, reproducing ``ppermute``'s fill exactly."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from goworld_tpu.ops.pallas_compat import interpret_default

    def kernel(in_ref, out_ref, send_sem, recv_sem):
        my_id = lax.axis_index(axis)
        dst = lax.rem(my_id + shift + n_dev, n_dev)
        op = pltpu.make_async_remote_copy(
            src_ref=in_ref, dst_ref=out_ref,
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        op.start()
        op.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(buf.shape, buf.dtype),
        grid_spec=grid_spec,
        interpret=interpret_default("halo_async"),
    )(buf)
    return jnp.where(recv_ok, out, 0)


def _ship(axis: str, n_dev: int, shift: int, perm, pack, recv_ok,
          impl: str):
    """Ship one packed strip tuple ``(pos, yaw, dirty, valid, gid)``
    ``shift`` devices along the flat axis. ``perm`` is the explicit
    non-periodic (src, dst) list the ppermute impl uses; the async impl
    rides the periodic ring (every device sends — conditional DMAs
    would deadlock interpret mode) and non-participating receivers
    (``recv_ok`` False — world-edge tiles) zero their block instead,
    reproducing ``ppermute``'s fill exactly."""
    if impl == "async":
        buf = _pack_strip(*pack)
        return _unpack_strip(_async_ship(axis, n_dev, shift, buf,
                                         recv_ok))
    if impl != "ppermute":
        raise ValueError(
            f"halo_impl {impl!r} not in {HALO_IMPLS}"
        )
    return jax.tree.map(lambda t: lax.ppermute(t, axis, perm), pack)


def exchange_halo(
    axis: str,
    n_dev: int,
    pos: jax.Array,        # f32[N, 3] (global coords)
    yaw: jax.Array,
    dirty: jax.Array,      # bool[N]
    alive: jax.Array,
    tile_w: float,
    radius: float,
    halo_cap: int,
    impl: str = "ppermute",
):
    """Ship boundary strips to lateral neighbor tiles.

    Returns a ghost block of size 2*halo_cap (left-neighbor ghosts then
    right-neighbor ghosts): (gpos f32[2H,3], gyaw f32[2H], gdirty bool[2H],
    gvalid bool[2H], ggid i32[2H] global entity ids = owner_dev * N + slot),
    plus ``strip_demand`` i32: the true occupancy of this shard's fuller
    boundary strip (host alarm when it exceeds halo_cap — ghosts beyond the
    cap were invisible to the neighbor tile this tick).

    The yaw lane ships dirty-gated (zero for clean rows) under BOTH
    impls: sync collection only ever reads the yaw of dirty subjects,
    so the ghost outputs are consumer-invariant and the two impls stay
    bit-identical.
    """
    n = pos.shape[0]
    d = lax.axis_index(axis)
    tile_min = d.astype(jnp.float32) * tile_w
    x = pos[:, 0]

    def pack(mask):
        flat, valid, demand = bounded_extract(mask, halo_cap)
        slots = jnp.where(valid, flat, n - 1)
        sel_dirty = dirty[slots] & valid
        return (
            jnp.where(valid[:, None], pos[slots], 0.0),
            jnp.where(sel_dirty, yaw[slots], 0.0),
            sel_dirty,
            valid,
            jnp.where(valid, d * n + slots, -1),
        ), demand

    left_pack, left_demand = pack(alive & (x < tile_min + radius))
    right_pack, right_demand = pack(alive & (x >= tile_min + tile_w - radius))
    # edge tiles don't ship their outward strip — exclude it from the
    # capacity alarm so a crowd at the world border can't trigger a false
    # "widen halo_cap" recompile
    strip_demand = jnp.maximum(
        jnp.where(d > 0, left_demand, 0),
        jnp.where(d < n_dev - 1, right_demand, 0),
    )

    # my left strip is a ghost for tile d-1; my right strip for tile d+1.
    # Non-periodic: edge tiles receive zeros (gvalid False).
    to_left = [(i, i - 1) for i in range(1, n_dev)]
    to_right = [(i, i + 1) for i in range(n_dev - 1)]
    from_right = _ship(axis, n_dev, -1, to_left, left_pack,
                       d < n_dev - 1, impl)
    from_left = _ship(axis, n_dev, +1, to_right, right_pack, d > 0,
                      impl)

    gpos = jnp.concatenate([from_left[0], from_right[0]])
    gyaw = jnp.concatenate([from_left[1], from_right[1]])
    gdirty = jnp.concatenate([from_left[2], from_right[2]])
    gvalid = jnp.concatenate([from_left[3], from_right[3]])
    ggid = jnp.concatenate([from_left[4], from_right[4]])
    # normalize invalid rows' gid to 0 (ppermute edge fill / async
    # zero block / packed -1 all collapse): consumers gate on gvalid,
    # and one canonical fill keeps the impls bit-identical
    ggid = jnp.where(gvalid, ggid, 0)
    return gpos, gyaw, gdirty, gvalid, ggid, strip_demand


def exchange_halo_2d(
    axis: str,
    shape: tuple[int, int],   # (tx, tz) device grid over the flat axis
    n_per_dev: int,
    pos: jax.Array,           # f32[N, 3] (global coords)
    yaw: jax.Array,
    dirty: jax.Array,
    alive: jax.Array,
    tile_w: float,            # x tile width
    tile_d: float,            # z tile depth
    radius: float,
    halo_cap: int,
    impl: str = "ppermute",
):
    """Two-phase 8-neighbor halo for 2D (XZ) tiling.

    Device ``d`` owns tile ``(ix, iz) = (d // tz, d % tz)``. Phase 1
    ships the west/east boundary strips laterally; phase 2 ships the
    north/south strips of the COMBINED region (local + phase-1 ghosts),
    so corner neighbors arrive transitively — the classic 2-phase halo
    that avoids 4 extra diagonal transfers. Ghost block = 4 * halo_cap
    rows (west, east, north, south — the z-phase buffers carry the
    corners). Per-strip capacity overflow drops entities beyond the cap
    in slot order (not by distance) from the neighbor's view that tick —
    same contract as the 1D exchange; size halo_cap for the worst
    expected strip density.

    Returns (gpos[4H,3], gyaw[4H], gdirty[4H], gvalid[4H], ggid[4H],
    strip_demand) — strip_demand is the max true occupancy over this
    shard's inward-facing strips (alarm when > halo_cap). The yaw lane
    ships dirty-gated like the 1D exchange.
    """
    tx, tz = shape
    n = pos.shape[0]
    d = lax.axis_index(axis)
    ix = d // tz
    iz = d % tz
    tmin_x = ix.astype(jnp.float32) * tile_w
    tmin_z = iz.astype(jnp.float32) * tile_d
    x = pos[:, 0]
    z = pos[:, 2]
    local_gid = d * n_per_dev + jnp.arange(n, dtype=jnp.int32)

    def pack(mask, src_pos, src_yaw, src_dirty, src_gid):
        m = src_pos.shape[0]
        flat, valid, demand = bounded_extract(mask, halo_cap)
        slots = jnp.where(valid, flat, m - 1)
        sel_dirty = src_dirty[slots] & valid
        return (
            jnp.where(valid[:, None], src_pos[slots], 0.0),
            jnp.where(sel_dirty, src_yaw[slots], 0.0),
            sel_dirty,
            valid,
            jnp.where(valid, src_gid[slots], -1),
        ), demand

    # ---- phase 1: x strips over the flat axis (stride tz) -------------
    west_pack, west_dem = pack(
        alive & (x < tmin_x + radius), pos, yaw, dirty, local_gid
    )
    east_pack, east_dem = pack(
        alive & (x >= tmin_x + tile_w - radius), pos, yaw, dirty,
        local_gid,
    )
    n_dev = tx * tz
    to_west = [(i, i - tz) for i in range(n_dev) if i // tz > 0]
    to_east = [(i, i + tz) for i in range(n_dev) if i // tz < tx - 1]
    from_east = _ship(axis, n_dev, -tz, to_west, west_pack,
                      ix < tx - 1, impl)
    from_west = _ship(axis, n_dev, +tz, to_east, east_pack, ix > 0,
                      impl)

    # ---- phase 2: z strips of local + phase-1 ghosts ------------------
    cpos = jnp.concatenate([pos, from_west[0], from_east[0]])
    cyaw = jnp.concatenate([yaw, from_west[1], from_east[1]])
    cdirty = jnp.concatenate([dirty, from_west[2], from_east[2]])
    cvalid = jnp.concatenate([alive, from_west[3], from_east[3]])
    cgid = jnp.concatenate([local_gid, from_west[4], from_east[4]])
    cz = cpos[:, 2]
    north_pack, north_dem = pack(
        cvalid & (cz < tmin_z + radius), cpos, cyaw, cdirty, cgid
    )
    south_pack, south_dem = pack(
        cvalid & (cz >= tmin_z + tile_d - radius), cpos, cyaw, cdirty,
        cgid,
    )
    to_north = [(i, i - 1) for i in range(n_dev) if i % tz > 0]
    to_south = [(i, i + 1) for i in range(n_dev) if i % tz < tz - 1]
    from_south = _ship(axis, n_dev, -1, to_north, north_pack,
                       iz < tz - 1, impl)
    from_north = _ship(axis, n_dev, +1, to_south, south_pack, iz > 0,
                       impl)

    gpos = jnp.concatenate(
        [from_west[0], from_east[0], from_north[0], from_south[0]]
    )
    gyaw = jnp.concatenate(
        [from_west[1], from_east[1], from_north[1], from_south[1]]
    )
    gdirty = jnp.concatenate(
        [from_west[2], from_east[2], from_north[2], from_south[2]]
    )
    gvalid = jnp.concatenate(
        [from_west[3], from_east[3], from_north[3], from_south[3]]
    )
    ggid = jnp.concatenate(
        [from_west[4], from_east[4], from_north[4], from_south[4]]
    )
    ggid = jnp.where(gvalid, ggid, 0)
    # inward-facing strips only: world-edge outward strips never ship
    strip_demand = jnp.max(jnp.stack([
        jnp.where(ix > 0, west_dem, 0),
        jnp.where(ix < tx - 1, east_dem, 0),
        jnp.where(iz > 0, north_dem, 0),
        jnp.where(iz < tz - 1, south_dem, 0),
    ]))
    return gpos, gyaw, gdirty, gvalid, ggid, strip_demand
