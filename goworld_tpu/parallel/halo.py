"""Ring/halo AOI ghost exchange for Spaces sharded across devices.

The reference cannot shard one Space: a space lives wholly on one game
process and user code caps its population (``doc.go:12-14``,
``SpaceService.go:14`` <=100 avatars/space). The rebuild's flagship upgrade
(``SURVEY.md#5.7``) is a Space whose entity SoA spans the mesh as spatial
tiles along x; AOI then needs each tile to see the ``radius``-wide strips of
its left/right neighbor tiles. Structurally identical to ring attention's
block rotation: bounded ghost buffers rotate over ICI with ``ppermute``
while each shard computes locally.

Ghost buffers are fixed capacity ``halo_cap``; entities in a boundary strip
beyond the cap are dropped from the neighbor's view that tick (the AOI-limit
tradeoff again — size halo_cap for the worst expected strip density).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from goworld_tpu.ops.extract import bounded_extract


def exchange_halo(
    axis: str,
    n_dev: int,
    pos: jax.Array,        # f32[N, 3] (global coords)
    yaw: jax.Array,
    dirty: jax.Array,      # bool[N]
    alive: jax.Array,
    tile_w: float,
    radius: float,
    halo_cap: int,
):
    """Ship boundary strips to lateral neighbor tiles.

    Returns a ghost block of size 2*halo_cap (left-neighbor ghosts then
    right-neighbor ghosts): (gpos f32[2H,3], gyaw f32[2H], gdirty bool[2H],
    gvalid bool[2H], ggid i32[2H] global entity ids = owner_dev * N + slot),
    plus ``strip_demand`` i32: the true occupancy of this shard's fuller
    boundary strip (host alarm when it exceeds halo_cap — ghosts beyond the
    cap were invisible to the neighbor tile this tick).
    """
    n = pos.shape[0]
    d = lax.axis_index(axis)
    tile_min = d.astype(jnp.float32) * tile_w
    x = pos[:, 0]

    def pack(mask):
        flat, valid, demand = bounded_extract(mask, halo_cap)
        slots = jnp.where(valid, flat, n - 1)
        return (
            jnp.where(valid[:, None], pos[slots], 0.0),
            jnp.where(valid, yaw[slots], 0.0),
            dirty[slots] & valid,
            valid,
            jnp.where(valid, d * n + slots, -1),
        ), demand

    left_pack, left_demand = pack(alive & (x < tile_min + radius))
    right_pack, right_demand = pack(alive & (x >= tile_min + tile_w - radius))
    # edge tiles don't ship their outward strip — exclude it from the
    # capacity alarm so a crowd at the world border can't trigger a false
    # "widen halo_cap" recompile
    strip_demand = jnp.maximum(
        jnp.where(d > 0, left_demand, 0),
        jnp.where(d < n_dev - 1, right_demand, 0),
    )

    # my left strip is a ghost for tile d-1; my right strip for tile d+1.
    # Non-periodic: edge tiles receive zeros (gvalid False).
    to_left = [(i, i - 1) for i in range(1, n_dev)]
    to_right = [(i, i + 1) for i in range(n_dev - 1)]
    from_right = jax.tree.map(
        lambda t: lax.ppermute(t, axis, to_left), left_pack
    )
    from_left = jax.tree.map(
        lambda t: lax.ppermute(t, axis, to_right), right_pack
    )

    gpos = jnp.concatenate([from_left[0], from_right[0]])
    gyaw = jnp.concatenate([from_left[1], from_right[1]])
    gdirty = jnp.concatenate([from_left[2], from_right[2]])
    gvalid = jnp.concatenate([from_left[3], from_right[3]])
    ggid = jnp.concatenate([from_left[4], from_right[4]])
    return gpos, gyaw, gdirty, gvalid, ggid, strip_demand


def exchange_halo_2d(
    axis: str,
    shape: tuple[int, int],   # (tx, tz) device grid over the flat axis
    n_per_dev: int,
    pos: jax.Array,           # f32[N, 3] (global coords)
    yaw: jax.Array,
    dirty: jax.Array,
    alive: jax.Array,
    tile_w: float,            # x tile width
    tile_d: float,            # z tile depth
    radius: float,
    halo_cap: int,
):
    """Two-phase 8-neighbor halo for 2D (XZ) tiling.

    Device ``d`` owns tile ``(ix, iz) = (d // tz, d % tz)``. Phase 1
    ships the west/east boundary strips laterally; phase 2 ships the
    north/south strips of the COMBINED region (local + phase-1 ghosts),
    so corner neighbors arrive transitively — the classic 2-phase halo
    that avoids 4 extra diagonal transfers. Ghost block = 4 * halo_cap
    rows (west, east, north, south — the z-phase buffers carry the
    corners). Per-strip capacity overflow drops entities beyond the cap
    in slot order (not by distance) from the neighbor's view that tick —
    same contract as the 1D exchange; size halo_cap for the worst
    expected strip density.

    Returns (gpos[4H,3], gyaw[4H], gdirty[4H], gvalid[4H], ggid[4H],
    strip_demand) — strip_demand is the max true occupancy over this
    shard's inward-facing strips (alarm when > halo_cap).
    """
    tx, tz = shape
    n = pos.shape[0]
    d = lax.axis_index(axis)
    ix = d // tz
    iz = d % tz
    tmin_x = ix.astype(jnp.float32) * tile_w
    tmin_z = iz.astype(jnp.float32) * tile_d
    x = pos[:, 0]
    z = pos[:, 2]
    local_gid = d * n_per_dev + jnp.arange(n, dtype=jnp.int32)

    def pack(mask, src_pos, src_yaw, src_dirty, src_gid):
        m = src_pos.shape[0]
        flat, valid, demand = bounded_extract(mask, halo_cap)
        slots = jnp.where(valid, flat, m - 1)
        return (
            jnp.where(valid[:, None], src_pos[slots], 0.0),
            jnp.where(valid, src_yaw[slots], 0.0),
            src_dirty[slots] & valid,
            valid,
            jnp.where(valid, src_gid[slots], -1),
        ), demand

    # ---- phase 1: x strips over the flat axis (stride tz) -------------
    west_pack, west_dem = pack(
        alive & (x < tmin_x + radius), pos, yaw, dirty, local_gid
    )
    east_pack, east_dem = pack(
        alive & (x >= tmin_x + tile_w - radius), pos, yaw, dirty,
        local_gid,
    )
    n_dev = tx * tz
    to_west = [(i, i - tz) for i in range(n_dev) if i // tz > 0]
    to_east = [(i, i + tz) for i in range(n_dev) if i // tz < tx - 1]
    from_east = jax.tree.map(
        lambda t: lax.ppermute(t, axis, to_west), west_pack
    )
    from_west = jax.tree.map(
        lambda t: lax.ppermute(t, axis, to_east), east_pack
    )

    # ---- phase 2: z strips of local + phase-1 ghosts ------------------
    cpos = jnp.concatenate([pos, from_west[0], from_east[0]])
    cyaw = jnp.concatenate([yaw, from_west[1], from_east[1]])
    cdirty = jnp.concatenate([dirty, from_west[2], from_east[2]])
    cvalid = jnp.concatenate([alive, from_west[3], from_east[3]])
    cgid = jnp.concatenate([local_gid, from_west[4], from_east[4]])
    cz = cpos[:, 2]
    north_pack, north_dem = pack(
        cvalid & (cz < tmin_z + radius), cpos, cyaw, cdirty, cgid
    )
    south_pack, south_dem = pack(
        cvalid & (cz >= tmin_z + tile_d - radius), cpos, cyaw, cdirty,
        cgid,
    )
    to_north = [(i, i - 1) for i in range(n_dev) if i % tz > 0]
    to_south = [(i, i + 1) for i in range(n_dev) if i % tz < tz - 1]
    from_south = jax.tree.map(
        lambda t: lax.ppermute(t, axis, to_north), north_pack
    )
    from_north = jax.tree.map(
        lambda t: lax.ppermute(t, axis, to_south), south_pack
    )

    gpos = jnp.concatenate(
        [from_west[0], from_east[0], from_north[0], from_south[0]]
    )
    gyaw = jnp.concatenate(
        [from_west[1], from_east[1], from_north[1], from_south[1]]
    )
    gdirty = jnp.concatenate(
        [from_west[2], from_east[2], from_north[2], from_south[2]]
    )
    gvalid = jnp.concatenate(
        [from_west[3], from_east[3], from_north[3], from_south[3]]
    )
    ggid = jnp.concatenate(
        [from_west[4], from_east[4], from_north[4], from_south[4]]
    )
    # inward-facing strips only: world-edge outward strips never ship
    strip_demand = jnp.max(jnp.stack([
        jnp.where(ix > 0, west_dem, 0),
        jnp.where(ix < tx - 1, east_dem, 0),
        jnp.where(iz > 0, north_dem, 0),
        jnp.where(iz < tz - 1, south_dem, 0),
    ]))
    return gpos, gyaw, gdirty, gvalid, ggid, strip_demand
