"""One giant Space sharded across the mesh as spatial tiles (megaspace).

The reference's scaling unit is the Space pinned to one process; population
per space is capped in user code (``SpaceService.go:14``). A megaspace
removes that ceiling: entities live in x-interval tiles (device d owns
``x in [d*tile_w, (d+1)*tile_w)``), AOI sees across tile borders via the
ring/halo ghost exchange (:mod:`goworld_tpu.parallel.halo`), and entities
crossing a border migrate automatically through the all_to_all row exchange
(:mod:`goworld_tpu.parallel.migrate`) — no EnterSpace call, no dispatcher.

Identity across the megaspace is the global id ``gid = shard * N + slot``.
Neighbor lists in state hold gids (sentinel ``n_dev * N``), so interest
deltas stay stable while ghost buffer order changes tick to tick, and
enter/leave/sync records emitted to the host reference gids directly.

BASELINE config 4 (64 spaces / 1M entities over ICI) is this module at
n_dev=64; config 2 is :mod:`goworld_tpu.core.step` at n_dev=1.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from goworld_tpu.core.state import SpaceState, WorldConfig
from goworld_tpu.core.step import TickOutputs, compute_velocity
from goworld_tpu.models.npc_policy import neighbor_mean_offset
from goworld_tpu.ops.aoi import grid_neighbors_flags
from goworld_tpu.ops.delta import interest_pairs
from goworld_tpu.ops.integrate import apply_pos_inputs, integrate
from goworld_tpu.ops.sync import collect_attr_deltas, collect_sync
from goworld_tpu.parallel import migrate as mig
from goworld_tpu.parallel.halo import (
    HALO_IMPLS,
    exchange_halo,
    exchange_halo_2d,
    meta_gid_bound,
)
from goworld_tpu.parallel.mesh import SPACE_AXIS, shard_map_norep
from goworld_tpu.parallel.step import MultiTickInputs
from goworld_tpu.scenarios.behaviors import scenario_velocity


@dataclasses.dataclass(frozen=True)
class MegaConfig:
    """Static megaspace configuration.

    ``cfg.grid`` describes the TILE-LOCAL grid in shifted coordinates:
    origin 0, ``extent_x = tile_w + 2 * radius`` (one halo margin on each
    side). 1D mode (``mesh_shape=None``): devices tile the x axis as
    strips and ``extent_z`` is the world's z extent. 2D mode
    (``mesh_shape=(tx, tz)``): devices tile the XZ plane, device ``d``
    owns tile ``(d // tz, d % tz)`` of size ``tile_w x tile_d``, and
    ``extent_z = tile_d + 2 * radius`` — the realistic layout for square
    worlds at high device counts, where 1D strips become thinner than
    the AOI radius (BASELINE config 4 at 64 devices).
    """

    cfg: WorldConfig
    n_dev: int
    tile_w: float
    halo_cap: int = 1024
    migrate_cap: int = 256
    mesh_shape: tuple[int, int] | None = None  # (tx, tz); None = (n_dev, 1)
    tile_d: float = 0.0                        # z tile depth (2D only)
    # halo shipping impl (parallel/halo.py): "ppermute" (barriered
    # collective, the default) or "async" (Pallas make_async_remote_copy
    # per edge with a dirty-only packed payload — overlap-capable;
    # interpret mode + one-time warning off-TPU, never a CPU default)
    halo_impl: str = "ppermute"

    def __post_init__(self):
        g = self.cfg.grid
        if self.cfg.scenario is not None \
                and "btree" in self.cfg.scenario.behavior_names:
            # the tile step feeds the switch from summary feature lanes
            # (mean offset / client count); the btree chase branch also
            # needs the NEAREST-CLIENT offset, which those lanes don't
            # carry — monsters would silently freeze instead of chasing.
            # Refuse at build time rather than diverge from the
            # single-chip semantics.
            raise ValueError(
                "megaspace scenarios cannot include the 'btree' mix "
                "member: the tile step's summary features carry no "
                "nearest-client offset (pick a non-btree mix, or run "
                "cfg.behavior='btree' homogeneous)"
            )
        if self.halo_impl not in HALO_IMPLS:
            raise ValueError(
                f"halo_impl {self.halo_impl!r} not in {HALO_IMPLS}"
            )
        if self.halo_impl == "async" \
                and self.n_dev * self.cfg.capacity > meta_gid_bound():
            raise ValueError(
                "halo_impl='async' packs gids into a 29-bit meta lane; "
                f"n_dev * capacity = {self.n_dev * self.cfg.capacity} "
                f"exceeds {meta_gid_bound()} — use halo_impl='ppermute'"
            )
        expected = self.tile_w + 2.0 * g.radius
        if abs(g.extent_x - expected) > 1e-6:
            raise ValueError(
                f"grid.extent_x must be tile_w + 2*radius = {expected}, "
                f"got {g.extent_x}"
            )
        if g.origin_x != 0.0 or g.origin_z != 0.0:
            raise ValueError(
                "megaspace grids use tile-shifted coordinates; "
                "grid.origin_x/origin_z must be 0"
            )
        if g.radius > self.tile_w:
            # The halo exchange is one hop each way: an AOI radius wider
            # than a tile would need neighbors-of-neighbors, which never
            # arrive — interest events silently missing.
            raise ValueError(
                f"grid.radius ({g.radius}) must be <= tile_w "
                f"({self.tile_w}) for adjacent-tile halo exchange"
            )
        if self.mesh_shape is not None:
            tx, tz = self.mesh_shape
            if tx * tz != self.n_dev:
                raise ValueError(
                    f"mesh_shape {self.mesh_shape} != n_dev {self.n_dev}"
                )
            if tz > 1:
                if self.tile_d <= 0:
                    raise ValueError("2D megaspace requires tile_d > 0")
                if g.radius > self.tile_d:
                    raise ValueError(
                        f"grid.radius ({g.radius}) must be <= tile_d "
                        f"({self.tile_d})"
                    )
                expected_z = self.tile_d + 2.0 * g.radius
                if abs(g.extent_z - expected_z) > 1e-6:
                    raise ValueError(
                        "2D megaspace: grid.extent_z must be "
                        f"tile_d + 2*radius = {expected_z}, got "
                        f"{g.extent_z}"
                    )

    @property
    def shape(self) -> tuple[int, int]:
        return self.mesh_shape or (self.n_dev, 1)

    @property
    def is_2d(self) -> bool:
        return self.shape[1] > 1

    @property
    def world_x(self) -> float:
        return self.tile_w * self.shape[0]

    @property
    def world_z(self) -> float:
        if self.is_2d:
            return self.tile_d * self.shape[1]
        return self.cfg.grid.extent_z

    @property
    def ghost_rows(self) -> int:
        return (4 if self.is_2d else 2) * self.halo_cap

    @property
    def gid_sentinel(self) -> int:
        return self.n_dev * self.cfg.capacity

    def tile_of(self, x: float, z: float) -> int:
        """Owning device of a world coordinate (host-side placement)."""
        tx, tz = self.shape
        ix = max(0, min(tx - 1, int(x // self.tile_w)))
        if not self.is_2d:
            return ix
        iz = max(0, min(tz - 1, int(z // self.tile_d)))
        return ix * tz + iz


@struct.dataclass
class MegaTickOutputs:
    base: TickOutputs          # j ids are GLOBAL gids; w are local slots
    arr_tag: jax.Array         # i32[n_dev, n_dev*mcap]: old gid of arrival
    arr_slot: jax.Array        # i32[n_dev, n_dev*mcap]: new local slot
    arr_n: jax.Array           # i32[n_dev]
    migrate_dropped: jax.Array  # i32[n_dev]
    migrate_demand: jax.Array  # i32[n_dev, n_dev] true per-dest emigrants
                               # (alarm when > migrate_cap: surplus entities
                               # linger on the wrong tile with degraded AOI)
    halo_demand: jax.Array     # i32[n_dev] boundary strip occupancy (alarm
                               # when > halo_cap)
    global_alive: jax.Array    # i32[n_dev]


def create_mega_state(mc: MegaConfig, seed: int = 0) -> SpaceState:
    """Stacked per-tile state with GLOBAL-id neighbor lists."""
    from goworld_tpu.parallel.mesh import create_multi_state

    st = create_multi_state(mc.cfg, mc.n_dev, seed)
    return st.replace(
        nbr=jnp.full_like(st.nbr, mc.gid_sentinel),
        nbr_cnt=jnp.zeros_like(st.nbr_cnt),
    )


def make_mega_tick(mc: MegaConfig, mesh: Mesh, donate: bool = False):
    """Build the jitted megaspace step. Signature matches make_multi_tick:
    ``step(states, inputs, policy) -> (states, MegaTickOutputs)`` with
    leading [n_dev] axes; ``inputs.migrate_target`` is ignored (tile
    migration is automatic from position). donate=True donates the state
    carry (arg 0): XLA aliases output shards in place and deletes the
    caller's old carry (resident-world contract, entity/manager.py)."""
    cfg = mc.cfg
    n = cfg.capacity
    n_dev = mc.n_dev
    if mesh.devices.size != n_dev:
        raise ValueError(
            f"MegaConfig.n_dev={n_dev} but mesh has {mesh.devices.size} "
            "devices; tile ownership and ring neighbors would disagree"
        )
    radius = cfg.grid.radius
    gsent = mc.gid_sentinel
    tx, tz = mc.shape
    ghost_rows = mc.ghost_rows

    def shard_fn(state, inputs: MultiTickInputs, policy):
        state = jax.tree.map(lambda x: x[0], state)
        inputs = jax.tree.map(lambda x: x[0], inputs)
        d = jax.lax.axis_index(SPACE_AXIS)
        d_ix = d // tz
        d_iz = d % tz
        tile_min = d_ix.astype(jnp.float32) * mc.tile_w
        tile_min_z = d_iz.astype(jnp.float32) * mc.tile_d

        # 1. client inputs (global coords), behaviors, integrate over the
        #    WHOLE world extent (not the tile: movers cross borders freely).
        pos, yaw, touched = apply_pos_inputs(
            state.pos, state.yaw,
            inputs.base.pos_sync_idx, inputs.base.pos_sync_vals,
            inputs.base.pos_sync_n,
        )
        rng, k_behave = jax.random.split(state.rng)
        # state.nbr holds GLOBAL gids (not local gather indices); the MLP
        # observation instead reads state.nbr_cnt/nbr_mean_off — neighbor
        # features computed over local+ghost positions by the PREVIOUS
        # tick's AOI sweep (step 5 below)
        tele = None
        if cfg.scenario is not None:
            # heterogeneous scenario mix (goworld_tpu/scenarios): the
            # same vmapped lax.switch as tick_body, with the phase
            # schedule anchored to WORLD bounds (the tile grid's
            # extents are tile-local) and the neighbor features read
            # from the summary lanes the previous tick's sweep left
            # behind — gid neighbor lists can't feed the per-slot
            # feature gathers. This is how the multichip bench's
            # border_churn phase drives sustained tile crossings.
            vel, tele_pos, tele = scenario_velocity(
                cfg, k_behave, pos, yaw, state, policy,
                bounds=(0.0, 0.0, mc.world_x, mc.world_z),
                features=(
                    state.nbr_mean_off,
                    state.nbr_client_cnt.astype(jnp.float32),
                    jnp.zeros_like(state.nbr_mean_off),
                ),
            )
        else:
            vel = compute_velocity(
                cfg, k_behave, pos, yaw, state, policy,
                (mc.world_x, mc.world_z), nbr=None, nbr_cnt=None,
            )
        pos, moved = integrate(
            pos, vel, state.npc_moving, cfg.dt,
            (0.0, -1e9, 0.0), (mc.world_x, 1e9, mc.world_z),
        )
        if tele is not None:
            # teleports override the integrated position BEFORE tile
            # targeting, so a cross-tile jump migrates on this tick
            pos = jnp.where(tele[:, None], tele_pos, pos)
            moved = moved | tele
        state = state.replace(pos=pos, yaw=yaw, vel=vel, rng=rng)
        pre_dirty = (moved | touched | state.dirty) & state.alive

        # 2. automatic tile migration from position (x strip in 1D;
        #    (ix, iz) tile in 2D).
        tgt_ix = jnp.clip(
            jnp.floor(pos[:, 0] / mc.tile_w).astype(jnp.int32), 0, tx - 1
        )
        if mc.is_2d:
            tgt_iz = jnp.clip(
                jnp.floor(pos[:, 2] / mc.tile_d).astype(jnp.int32),
                0, tz - 1,
            )
            tgt = tgt_ix * tz + tgt_iz
        else:
            tgt = tgt_ix
        tgt = jnp.where(state.alive & (tgt != d), tgt, -1)
        tag = d * n + jnp.arange(n, dtype=jnp.int32)   # old gid as tag
        fbuf, ibuf, departed, mig_demand = mig.pack_emigrants(
            state, tgt, tag, n_dev, mc.migrate_cap
        )
        state = mig.despawn_departed(state, departed)
        pre_dirty &= ~departed
        fbuf = jax.lax.all_to_all(fbuf, SPACE_AXIS, 0, 0, tiled=True)
        ibuf = jax.lax.all_to_all(ibuf, SPACE_AXIS, 0, 0, tiled=True)
        state, arr_tag, arr_slot, arr_n, dropped = mig.insert_arrivals(
            state, fbuf, ibuf, nbr_sentinel=gsent, quarantine=departed
        )
        dirty = pre_dirty | state.dirty   # arrivals force-sync

        # 3. halo ghost exchange (ring ppermute). AOI-excluded entities
        #    (aoi_radius <= 0, e.g. service types) never ship as ghosts —
        #    they are invisible to every watcher, local or remote.
        visible = state.alive & (state.aoi_radius > 0.0)
        if mc.is_2d:
            gpos, gyaw, gdirty, gvalid, ggid, halo_demand = \
                exchange_halo_2d(
                    SPACE_AXIS, (tx, tz), n, state.pos, state.yaw, dirty,
                    visible, mc.tile_w, mc.tile_d, radius, mc.halo_cap,
                    impl=mc.halo_impl,
                )
        else:
            gpos, gyaw, gdirty, gvalid, ggid, halo_demand = exchange_halo(
                SPACE_AXIS, n_dev, state.pos, state.yaw, dirty, visible,
                mc.tile_w, radius, mc.halo_cap, impl=mc.halo_impl,
            )

        # 4. AOI over the extended local+ghost population, in tile-shifted
        #    coordinates so the static grid covers [0, tile_w + 2R)
        #    (x [0, tile_d + 2R) in z for 2D tiles).
        pos_ext = jnp.concatenate([state.pos, gpos])
        shift = jnp.array([0.0, 0.0, 0.0], jnp.float32) \
            .at[0].set(tile_min - radius)
        if mc.is_2d:
            shift = shift.at[2].set(tile_min_z - radius)
        alive_ext = jnp.concatenate([state.alive, gvalid])
        # ghosts already passed the source-side visibility filter: give
        # them +inf so only the local per-entity radii gate here
        wr_ext = jnp.concatenate([
            state.aoi_radius,
            jnp.full((ghost_rows,), jnp.inf, jnp.float32),
        ])
        # ghosts are candidates but never watchers: query only local rows.
        # Dirty and has_client bits (local + ghost) ride the sweep so sync
        # collection needs no [N, k] dirty gather and the behavior tree
        # gets its players-in-AOI count for free. Halo records don't carry
        # has_client, so remote-tile clients read as NPCs to the
        # behavior tree (boundary approximation; transport.py-level parity
        # is unaffected — sync/interest never consult bit 1 of ghosts).
        dirty_ext = jnp.concatenate([dirty, gdirty])
        hc_ext = jnp.concatenate([
            state.has_client,
            jnp.zeros((ghost_rows,), bool),
        ])
        nbr_ext, nbr_cnt, nbr_fl, aoi_stats = grid_neighbors_flags(
            cfg.grid, pos_ext - shift, alive_ext, query_rows=n,
            watch_radius=wr_ext,
            flag_bits=dirty_ext.astype(jnp.int32)
            | (hc_ext.astype(jnp.int32) << 1),
            with_stats=True,
        )

        # 5. neighbor features for next tick's MLP observation (computed
        #    HERE because nbr_ext still indexes pos_ext; after the gid
        #    translation below the positions are no longer addressable),
        #    then translate to stable GLOBAL ids and diff.
        p_ext = n + ghost_rows
        wants_features = (
            cfg.behavior in ("mlp", "btree")
            if cfg.scenario is None else cfg.scenario.needs_features
        )
        if wants_features:  # static at trace time
            mean_off = neighbor_mean_offset(
                pos_ext, state.pos, nbr_ext, nbr_cnt, p_ext
            )
        else:
            # nothing reads the features: skip the [N, k, 3] gather
            # (gathers are the scarce resource on TPU)
            mean_off = state.nbr_mean_off
        gid_ext = jnp.concatenate(
            [d * n + jnp.arange(n, dtype=jnp.int32), ggid]
        )
        nbr_gid = jnp.where(
            nbr_ext == p_ext, gsent,
            gid_ext[jnp.minimum(nbr_ext, p_ext - 1)],
        )
        nbr_gid = jnp.sort(nbr_gid, axis=1)
        (enter_w, enter_j, enter_n, leave_w, leave_j, leave_n,
         delta_rows_n) = interest_pairs(
            state.nbr, nbr_gid, gsent, cfg.enter_cap, cfg.leave_cap,
            min(cfg.delta_rows_cap_eff, n),
        )

        # 6. sync records over the extended population; subjects -> gids.
        yaw_ext = jnp.concatenate([state.yaw, gyaw])
        sync_w, sync_j, sync_vals, sync_n = collect_sync(
            nbr_ext, dirty_ext, state.has_client, pos_ext, yaw_ext,
            cfg.sync_cap, nbr_dirty=(nbr_fl & 1).astype(bool),
        )
        sync_j = jnp.where(
            sync_j >= 0, gid_ext[jnp.clip(sync_j, 0, p_ext - 1)], -1
        )

        # 7. attr deltas (local only; ghosts' attrs sync on their own shard).
        attr_e, attr_i, attr_v, attr_n = collect_attr_deltas(
            state.hot_attrs, state.attr_dirty, cfg.attr_sync_cap
        )

        global_alive = jax.lax.psum(
            state.alive.sum().astype(jnp.int32), SPACE_AXIS
        )
        state = state.replace(
            nbr=nbr_gid,
            nbr_cnt=nbr_cnt,
            nbr_client_cnt=(
                (nbr_fl >> 1) & 1
            ).sum(axis=1).astype(jnp.int32),
            nbr_mean_off=mean_off,
            dirty=jnp.zeros_like(state.dirty),
            attr_dirty=jnp.zeros_like(state.attr_dirty),
            tick=state.tick + 1,
        )
        outputs = MegaTickOutputs(
            base=TickOutputs(
                enter_w=enter_w, enter_j=enter_j, enter_n=enter_n,
                leave_w=leave_w, leave_j=leave_j, leave_n=leave_n,
                delta_rows_n=delta_rows_n,
                sync_w=sync_w, sync_j=sync_j, sync_vals=sync_vals,
                sync_n=sync_n,
                attr_e=attr_e, attr_i=attr_i, attr_v=attr_v, attr_n=attr_n,
                alive_count=state.alive.sum().astype(jnp.int32),
                aoi_demand_max=aoi_stats[0],
                aoi_over_k_rows=aoi_stats[1],
                aoi_cell_max=aoi_stats[2],
                aoi_over_cap_cells=aoi_stats[3],
            ),
            arr_tag=arr_tag, arr_slot=arr_slot, arr_n=arr_n,
            migrate_dropped=dropped,
            migrate_demand=mig_demand,
            halo_demand=halo_demand,
            global_alive=global_alive,
        )
        state = jax.tree.map(lambda x: x[None], state)
        outputs = jax.tree.map(lambda x: x[None], outputs)
        return state, outputs

    # norep: pallas_call (the async halo) has no replication rule; the
    # static rep check adds nothing here — every output is sharded
    mapped = shard_map_norep(
        shard_fn,
        mesh=mesh,
        in_specs=(P(SPACE_AXIS), P(SPACE_AXIS), P()),
        out_specs=(P(SPACE_AXIS), P(SPACE_AXIS)),
    )
    # keep_unused: behavior-dead carry lanes must stay parameters or
    # they lose their donation source (see _make_local_tick)
    return jax.jit(mapped, donate_argnums=(0,) if donate else (),
                   keep_unused=donate)
