"""Mesh construction and sharded state layout.

One mesh axis, ``"space"``: device d hosts space shard d. Entity placement
across shards is the host's job (the reference's dispatcher ``chooseGame``
min-CPU heap, ``DispatcherService.go:523-536``, becomes the host scheduler in
:mod:`goworld_tpu.entity`); the device layer only requires that every leaf of
the stacked state carries a leading ``[n_dev, ...]`` axis sharded over
``"space"``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from goworld_tpu.core.state import SpaceState, WorldConfig, create_state

SPACE_AXIS = "space"

# shard_map moved from jax.experimental to the jax namespace across
# the supported versions; resolve ONCE here so every mesh program
# (parallel/step.py, parallel/megaspace.py) builds on either
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map  # noqa: F401


def shard_map_norep(fn, **kw):
    """shard_map with the replication check OFF — required wherever the
    shard body contains a ``pallas_call`` (no replication rule, e.g.
    the async halo). The knob name changed across jax versions
    (check_rep -> check_vma); keep that dance HERE, next to the
    shard_map resolver, so callers never hand-roll it."""
    try:
        return shard_map(fn, check_rep=False, **kw)
    except TypeError:
        return shard_map(fn, check_vma=False, **kw)


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            # A short mesh would make shard_map hand each device a
            # [k>1, ...] block whose shard_fn only ticks row 0 — spaces
            # silently dropped. Fail loudly instead.
            raise ValueError(
                f"make_mesh({n_devices}) but only {len(devs)} device(s) "
                "available; set XLA_FLAGS=--xla_force_host_platform_device_"
                "count=N JAX_PLATFORMS=cpu for simulated meshes"
            )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (SPACE_AXIS,))


def create_multi_state(cfg: WorldConfig, n_dev: int, seed: int = 0) -> SpaceState:
    """Stacked state: every leaf gains a leading [n_dev] axis."""
    shards = [create_state(cfg, seed=seed * n_dev + d) for d in range(n_dev)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *shards)


def shard_state(state: SpaceState, mesh: Mesh) -> SpaceState:
    """Place a stacked state on the mesh (leading axis over "space")."""
    sharding = NamedSharding(mesh, P(SPACE_AXIS))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), state)
