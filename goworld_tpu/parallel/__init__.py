"""Mesh sharding: spaces pinned to TPU cores, collectives instead of sockets.

This layer replaces the reference's cluster-communication backend — the
N-dispatcher sharded star over TCP (``engine/dispatchercluster``,
``components/dispatcher``) — *within* a TPU mesh:

* space-per-core sharding via ``jax.shard_map`` (:mod:`.step`) — the analog
  of P1/P2 horizontal scaling (``SURVEY.md#2.4``),
* entity migration as an ``all_to_all`` row exchange at tick boundaries
  (:mod:`.migrate`) — replacing the dispatcher's block-and-queue migration
  protocol (``DispatcherService.go:850-891``),
* giant sharded Spaces with ring/halo AOI ghost exchange over ``ppermute``
  (:mod:`.halo`) — the long-context analog (``SURVEY.md#5.7``),
* global barriers/counters via ``psum``.

Because the mesh is synchronous, migration needs no per-entity blocking
router: emigrant rows leave and arrive inside one compiled step, and the
host re-points EntityID -> (space, slot) from the arrival records.
"""

from goworld_tpu.parallel.mesh import make_mesh, create_multi_state, shard_state
from goworld_tpu.parallel.step import (
    MultiTickInputs,
    MultiTickOutputs,
    make_multi_tick,
)
from goworld_tpu.parallel.megaspace import MegaConfig, make_mega_tick

__all__ = [
    "make_mesh",
    "create_multi_state",
    "shard_state",
    "MultiTickInputs",
    "MultiTickOutputs",
    "make_multi_tick",
    "MegaConfig",
    "make_mega_tick",
]
