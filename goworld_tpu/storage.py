"""Async entity persistence with pluggable backends.

Reference being rebuilt: ``engine/storage`` (``storage.go``): a background
worker consumes a queue of save/load/exists/list requests against an
``EntityStorage`` backend (``storage_common.go:5-13``); saves retry forever
(entity data must not be lost), callbacks are posted back to the logic
thread, and a queue-length monitor warns on backlog (``:102-110``).

Backends here: ``mongodb`` (the reference's primary backend,
``backend/mongodb/mongodb.go:27-136`` — re-implemented over a
from-scratch BSON + OP_MSG wire client, one collection per entity type
with ``_id`` = EntityID and the attrs under ``data``; works against a
real mongod or the in-process :mod:`goworld_tpu.ext.db.minimongo`),
``redis`` (networked, RESP wire protocol; key scheme ``gw:<type>:<eid>``),
``filesystem`` (one directory per entity type, one msgpack file per
entity), and ``memory`` (tests).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

import msgpack

from goworld_tpu.utils import consts, faults, log, metrics, opmon, \
    overload

logger = log.get("storage")

SAVE_RETRY_DELAY = 1.0    # backoff base: saves retry FOREVER (entity
SAVE_RETRY_MAX = 30.0     # data must not be lost), but with capped
                          # exponential backoff so a dead backend is not
                          # hammered once per second for hours
READ_RETRY_ATTEMPTS = 3   # loads/exists/lists retry transient errors a
READ_RETRY_DELAY = 0.05   # bounded number of times before reporting None
WARN_QUEUE_LEN = 100  # reference storage.go:102-110
_TRANSIENT = (ConnectionError, TimeoutError, OSError)


class EntityStorageBackend:
    """Backend interface (reference ``EntityStorage``)."""

    def write(self, type_name: str, eid: str, data: dict) -> None:
        raise NotImplementedError

    def read(self, type_name: str, eid: str) -> dict | None:
        raise NotImplementedError

    def exists(self, type_name: str, eid: str) -> bool:
        raise NotImplementedError

    def list_entity_ids(self, type_name: str) -> list[str]:
        raise NotImplementedError

    def close(self) -> None: ...


class MemoryStorage(EntityStorageBackend):
    def __init__(self):
        self._data: dict[tuple[str, str], dict] = {}

    def write(self, type_name, eid, data):
        self._data[(type_name, eid)] = data

    def read(self, type_name, eid):
        return self._data.get((type_name, eid))

    def exists(self, type_name, eid):
        return (type_name, eid) in self._data

    def list_entity_ids(self, type_name):
        return [e for t, e in self._data if t == type_name]


class FilesystemStorage(EntityStorageBackend):
    """``<root>/<type>/<eid>.mp`` — atomic replace via temp file."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, type_name: str, eid: str) -> str:
        return os.path.join(self.root, type_name, f"{eid}.mp")

    def write(self, type_name, eid, data):
        d = os.path.join(self.root, type_name)
        os.makedirs(d, exist_ok=True)
        path = self._path(type_name, eid)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(data, use_bin_type=True))
        os.replace(tmp, path)

    def read(self, type_name, eid):
        try:
            with open(self._path(type_name, eid), "rb") as f:
                return msgpack.unpackb(f.read(), raw=False)
        except FileNotFoundError:
            return None

    def exists(self, type_name, eid):
        return os.path.exists(self._path(type_name, eid))

    def list_entity_ids(self, type_name):
        d = os.path.join(self.root, type_name)
        if not os.path.isdir(d):
            return []
        return [f[:-3] for f in os.listdir(d) if f.endswith(".mp")]


class RedisStorage(EntityStorageBackend):
    """Networked backend over the RESP wire protocol (reference persists
    to MongoDB, one collection per type with ``_id`` = EntityID,
    ``backend/mongodb/mongodb.go:27-136``; the key scheme here is the
    redis equivalent: ``gw:<type>:<eid>`` -> msgpack attr blob). Works
    against any redis-compatible endpoint, including the in-process
    :mod:`goworld_tpu.ext.db.miniredis`."""

    PREFIX = "gw"

    def __init__(self, addr: str):
        from goworld_tpu.ext.db.resp import RespClient

        self._c = RespClient.from_addr(addr)

    def _key(self, type_name: str, eid: str) -> str:
        return f"{self.PREFIX}:{type_name}:{eid}"

    def write(self, type_name, eid, data):
        self._c.set(self._key(type_name, eid),
                    msgpack.packb(data, use_bin_type=True))

    def read(self, type_name, eid):
        raw = self._c.get(self._key(type_name, eid))
        return None if raw is None else msgpack.unpackb(raw, raw=False)

    def exists(self, type_name, eid):
        return self._c.exists(self._key(type_name, eid))

    def list_entity_ids(self, type_name):
        pre = f"{self.PREFIX}:{type_name}:"
        return sorted(
            k.decode()[len(pre):]
            for k in self._c.scan_keys(pre + "*")
        )

    def close(self):
        self._c.close()


class MongoDBStorage(EntityStorageBackend):
    """The reference's primary backend
    (``backend/mongodb/mongodb.go:27-136``), byte-compatible layout:
    one collection per entity type, ``_id`` = EntityID, attrs under
    ``data`` (``col.UpsertId(entityID, bson.M{"data": data})``). Rides
    the from-scratch BSON/OP_MSG client
    (:mod:`goworld_tpu.ext.db.mongowire`) — no driver needed; any
    mongod or the in-process minimongo speaks the wire."""

    def __init__(self, addr: str):
        from goworld_tpu.ext.db.mongowire import MongoClient

        self._c = MongoClient.from_addr(addr)

    def write(self, type_name, eid, data):
        self._c.upsert_id(type_name, eid, {"data": data})

    def read(self, type_name, eid):
        doc = self._c.find_id(type_name, eid)
        return None if doc is None else doc.get("data")

    def exists(self, type_name, eid):
        return bool(self._c.find(type_name, {"_id": eid},
                                 projection={"_id": 1}, limit=1))

    def list_entity_ids(self, type_name):
        return sorted(
            d["_id"] for d in self._c.find(
                type_name, {}, projection={"_id": 1})
        )

    def close(self):
        self._c.close()


def open_backend(kind: str, location: str = "") -> EntityStorageBackend:
    if kind == "memory":
        return MemoryStorage()
    if kind == "filesystem":
        return FilesystemStorage(location or "entity_storage")
    if kind == "redis":
        return RedisStorage(location or "127.0.0.1:6379")
    if kind == "mongodb":
        return MongoDBStorage(location or "127.0.0.1:27017/goworld")
    raise ValueError(f"unknown storage backend {kind!r}")


class Storage:
    """The async storage front-end attached to a World
    (``world.storage = Storage(backend, world.post_q.post)``)."""

    def __init__(self, backend: EntityStorageBackend,
                 post: Callable[[Callable], None]):
        self.backend = backend
        self._post = post
        self._q: list[tuple] = []
        self._cv = threading.Condition()
        self._closed = False
        self.op_count = 0
        # /metrics shim beside the opmon rows: latency histogram per op
        # kind + a queue-depth gauge a scraper can alarm on
        self._hists = {
            op: metrics.histogram("storage_op_ms", op=op,
                                  help="storage backend op latency")
            for op in ("save", "load", "exists", "list")
        }
        self._m_queue = metrics.gauge(
            "storage_queue_depth", help="pending storage ops")
        self._m_retry = metrics.counter(
            "storage_retry_total",
            help="storage ops retried after a backend error")
        self._m_err = metrics.counter(
            "storage_op_errors_total",
            help="non-save storage ops that exhausted retries")
        # circuit breaker around the backend: reads fail FAST while
        # open (a dead backend must not stack 3-attempt retry sleeps
        # per op); saves never give up — they wait out the open window
        # and ride the half-open probe when it comes
        self.breaker = overload.register_breaker(overload.CircuitBreaker(
            "storage",
            failure_threshold=consts.CIRCUIT_FAILURE_THRESHOLD,
            reset_timeout=consts.CIRCUIT_RESET_TIMEOUT,
        ))
        self._m_circuit_rejected = metrics.counter(
            "storage_circuit_rejected_total",
            help="storage ops failed fast while the circuit was open")
        self._thread = threading.Thread(
            target=self._run, name="storage", daemon=True
        )
        self._thread.start()

    # -- public API (reference storage.go:60-100) -----------------------
    def save(self, type_name: str, eid: str, data: dict,
             cb: Callable[[], None] | None = None) -> None:
        self._enqueue(("save", type_name, eid, data, cb))

    def load(self, type_name: str, eid: str,
             cb: Callable[[dict | None], None]) -> None:
        self._enqueue(("load", type_name, eid, None, cb))

    def exists(self, type_name: str, eid: str,
               cb: Callable[[bool], None]) -> None:
        self._enqueue(("exists", type_name, eid, None, cb))

    def list_entity_ids(self, type_name: str,
                        cb: Callable[[list[str]], None]) -> None:
        self._enqueue(("list", type_name, "", None, cb))

    def queue_len(self) -> int:
        with self._cv:
            return len(self._q)

    def shutdown(self, timeout: float = 30.0) -> None:
        """Drain then stop (reference ``Shutdown`` waits for queue empty).
        Idempotent: freeze and process teardown may both call it."""
        with self._cv:
            if self._closed:
                return
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._q and time.monotonic() < deadline:
                self._cv.wait(0.1)
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
        self.backend.close()

    # -- worker ----------------------------------------------------------
    def _enqueue(self, op: tuple) -> None:
        with self._cv:
            if self._closed:
                logger.error("storage closed; dropping %s", op[0])
                return
            self._q.append(op)
            self._m_queue.set(len(self._q))
            if len(self._q) > WARN_QUEUE_LEN:
                logger.warning("storage queue backlog: %d", len(self._q))
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q and self._closed:
                    return
                op = self._q.pop(0)
                self._cv.notify_all()
            self._execute(op)

    def _execute(self, op: tuple) -> None:
        kind, type_name, eid, data, cb = op
        attempt = 0
        t0 = time.perf_counter()
        while True:
            if not self.breaker.allow():
                self._m_circuit_rejected.inc()
                if kind == "save":
                    # saves never give up: wait out the open window,
                    # then the half-open probe (one real attempt)
                    # decides whether the backend is back
                    time.sleep(min(self.breaker.reset_timeout,
                                   SAVE_RETRY_MAX))
                    continue
                logger.error(
                    "storage %s %s.%s rejected fast (circuit open)",
                    kind, type_name, eid,
                )
                res = None
                break
            # per-ATTEMPT timing (like the kvdb shim): folding the
            # retry backoff sleeps into storage_op_ms would report
            # injected wait, not backend latency
            t0 = time.perf_counter()
            try:
                faults.maybe_op_fault("storage", kind)
                if kind == "save":
                    self.backend.write(type_name, eid, data)
                    res: Any = None
                elif kind == "load":
                    res = self.backend.read(type_name, eid)
                elif kind == "exists":
                    res = self.backend.exists(type_name, eid)
                else:
                    res = self.backend.list_entity_ids(type_name)
                self.breaker.record_success()
                break
            except Exception as exc:
                self.breaker.record_failure()
                attempt += 1
                if kind == "save":
                    # saves retry forever: losing entity data is worse
                    # than blocking the queue (reference storageRoutine)
                    # — but back off exponentially (capped) so a dead
                    # backend isn't hammered at a fixed cadence
                    self._m_retry.inc()
                    delay = min(SAVE_RETRY_MAX,
                                SAVE_RETRY_DELAY * 2 ** (attempt - 1))
                    logger.exception(
                        "save %s.%s failed (attempt %d); retrying in "
                        "%.1fs", type_name, eid, attempt, delay,
                    )
                    time.sleep(delay)
                    continue
                # reads: a TRANSIENT blip gets a bounded number of
                # quick retries before the op reports failure — a load
                # that fails on one dropped TCP segment would otherwise
                # boot the player with a fresh entity
                if isinstance(exc, _TRANSIENT) \
                        and attempt < READ_RETRY_ATTEMPTS:
                    self._m_retry.inc()
                    logger.warning(
                        "storage %s %s.%s transient error (%s); "
                        "retry %d", kind, type_name, eid, exc, attempt,
                    )
                    time.sleep(READ_RETRY_DELAY * 2 ** (attempt - 1))
                    continue
                self._m_err.inc()
                logger.exception("storage %s %s.%s failed",
                                 kind, type_name, eid)
                res = None
                break
        self.op_count += 1
        dt = time.perf_counter() - t0
        opmon.monitor.record(f"storage.{kind}", dt)
        self._hists[kind].observe(dt * 1e3)
        self._m_queue.set(self.queue_len())
        if cb is not None:
            if kind == "save":
                self._post(cb)
            else:
                self._post(lambda cb=cb, res=res: cb(res))
