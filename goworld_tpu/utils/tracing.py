"""Cross-process distributed tracing: context propagation + span recorder.

The reference engine has no tracing layer; its opmon/pprof surface stops
at process boundaries. This module is the Dapper/OpenTelemetry-shaped
missing piece for a location-transparent RPC fabric: a client call hops
gate -> dispatcher -> game (and a second game during migration) before
anything runs, and per-process telemetry (:mod:`metrics`) cannot say
*which hop* a packet spent its 16 ms budget in.

Three parts, all stdlib:

* :class:`TraceContext` — 16B trace_id + 8B span_id + 1B flags, packed
  as a 25-byte wire trailer by :mod:`goworld_tpu.net.packet` (keyed off
  ``TRACE_FLAG``, bit 15 of the msgtype field — untraced packets pay
  zero bytes, see the wire-compat test).
* sampling + thread-local *current context*: the gate roots a context
  on sampled client packets (:func:`maybe_sample`); every hop installs
  its own child as current (:func:`use` / :func:`hop`), and
  ``packet.new_packet`` auto-stamps outbound packets with it, so
  multi-hop chains (entity RPC fan-out, migration acks) stay linked
  without per-call-site plumbing.
* :class:`SpanRecorder` — a ring buffer of completed spans with
  parent/child linkage, exported next to the :class:`TickTimeline`
  ring in ``debug_http /trace`` as Chrome/Perfetto ``X`` events (one
  named track per service), merged cluster-wide by
  ``tools/merge_traces.py`` which synthesizes the flow arrows.

Overhead discipline: with sampling off, the wire is byte-identical and
the per-packet cost is one ``is None`` check (plus one module-bool load
in ``new_packet``); spans cost two ``perf_counter`` calls + one deque
append, same budget as the tick timeline.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from random import random as _rand
from typing import Any

__all__ = [
    "TraceContext", "SpanRecorder", "recorder",
    "CTX_WIRE_SIZE", "FLAG_SAMPLED",
    "new_trace", "maybe_sample", "set_sample_rate", "sample_rate",
    "current", "use", "hop", "root",
]

CTX_WIRE_SIZE = 25      # 16B trace_id + 8B span_id + 1B flags
FLAG_SAMPLED = 0x01

# fast-path gate: False until the first set_sample_rate(>0) or use();
# packet.new_packet checks this single module bool before touching the
# thread-local, so fully-untraced processes pay one global load
active = False

_rate = 0.0
_tls = threading.local()


def _new_id(n: int) -> bytes:
    return os.urandom(n)


class TraceContext:
    """One position in a trace: (trace_id, span_id, flags). A packet
    carries the context of the span that *emitted* it; the receiving
    hop records its own span with ``parent = carried span_id``."""

    __slots__ = ("trace_id", "span_id", "flags")

    def __init__(self, trace_id: bytes, span_id: bytes, flags: int = FLAG_SAMPLED):
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = flags

    # -- wire form (the 25B packet trailer) -----------------------------
    def pack(self) -> bytes:
        return self.trace_id + self.span_id + bytes((self.flags & 0xFF,))

    @classmethod
    def unpack(cls, b: bytes) -> "TraceContext":
        if len(b) != CTX_WIRE_SIZE:
            raise ValueError(f"bad trace context length {len(b)}")
        return cls(bytes(b[:16]), bytes(b[16:24]), b[24])

    # -- lineage --------------------------------------------------------
    def child(self) -> "TraceContext":
        """Same trace, fresh span id (the receiving hop's own span)."""
        return TraceContext(self.trace_id, _new_id(8), self.flags)

    @property
    def sampled(self) -> bool:
        return bool(self.flags & FLAG_SAMPLED)

    @property
    def trace_hex(self) -> str:
        return self.trace_id.hex()

    @property
    def span_hex(self) -> str:
        return self.span_id.hex()

    def __repr__(self) -> str:  # log-friendly
        return f"TraceContext({self.trace_hex[:8]}../{self.span_hex})"


def new_trace(flags: int = FLAG_SAMPLED) -> TraceContext:
    """Root a brand-new trace (the gate-ingress stamp)."""
    return TraceContext(_new_id(16), _new_id(8), flags)


def set_sample_rate(rate: float) -> None:
    """Probability that :func:`maybe_sample` roots a trace (0 = off).
    Set per process: via ``trace_sample_rate`` in the cluster ini, the
    debug-http ``/tracing?rate=`` endpoint, or ``goworld_tpu trace``."""
    global _rate, active
    _rate = min(1.0, max(0.0, float(rate)))
    # disarming also drops the fast-path flag, restoring the documented
    # one-global-load overhead; an inbound traced packet re-raises it
    # (use.__enter__), so cross-process propagation keeps working
    active = _rate > 0.0


def sample_rate() -> float:
    return _rate


def maybe_sample() -> TraceContext | None:
    """Roll the sampling dice; a new root context or None."""
    if _rate <= 0.0:
        return None
    if _rate < 1.0 and _rand() >= _rate:
        return None
    return new_trace()


# =======================================================================
# thread-local current context (one logic/IO thread per process kind)
# =======================================================================
def current() -> TraceContext | None:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


class use:
    """``with use(ctx): ...`` — install ``ctx`` as the thread's current
    context; ``new_packet`` stamps outbound packets with it."""

    __slots__ = ("_ctx",)

    def __init__(self, ctx: TraceContext):
        self._ctx = ctx

    def __enter__(self) -> TraceContext:
        global active
        active = True
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> None:
        _tls.stack.pop()


# =======================================================================
# span recorder
# =======================================================================
class _Span:
    """Timing scope for one span; records on exit."""

    __slots__ = ("_rec", "_name", "_track", "_ctx", "_parent", "_args",
                 "_wall_us", "_t0")

    def __init__(self, rec: "SpanRecorder", name: str, track: str,
                 ctx: TraceContext, parent: str | None, args):
        self._rec = rec
        self._name = name
        self._track = track
        self._ctx = ctx
        self._parent = parent
        self._args = args

    def __enter__(self) -> "_Span":
        self._wall_us = time.time() * 1e6
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._rec.record(
            self._name, self._track, self._ctx, self._parent,
            self._wall_us, (time.perf_counter() - self._t0) * 1e6,
            self._args,
        )


class SpanRecorder:
    """Ring buffer of completed spans. Unlike :class:`TickTimeline`
    (one open tick, logic thread only) any thread records here — gate
    and dispatcher services have no tick loop. Exported beside the
    timeline in the same ``/trace`` JSON."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._recs: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, name: str, track: str, ctx: TraceContext,
               parent: str | None, wall_us: float, dur_us: float,
               args: dict | None = None) -> None:
        with self._lock:
            self._recs.append(
                (name, track, ctx.trace_hex, ctx.span_hex, parent,
                 wall_us, dur_us, args or None)
            )

    def span(self, name: str, track: str, ctx: TraceContext,
             parent: str | None, **args: Any) -> _Span:
        """``with recorder.span("route", "dispatcher1", ctx, parent):``"""
        return _Span(self, name, track, ctx, parent, args or None)

    def records(self) -> list:
        """(name, track, trace_hex, span_hex, parent_hex, wall_us,
        dur_us, args) tuples, oldest first."""
        with self._lock:
            return list(self._recs)

    def tail(self, n: int) -> list:
        """The newest ``n`` records (oldest first) WITHOUT copying the
        whole ring — the flight recorder correlates incident bundles
        with the last sampled trace ids at freeze time."""
        with self._lock:
            k = min(n, len(self._recs))
            return [self._recs[len(self._recs) - k + i]
                    for i in range(k)]

    def clear(self) -> None:
        with self._lock:
            self._recs.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._recs)

    def chrome_events(self, pid: int, base_tid: int = 8) -> list[dict]:
        """Chrome-trace ``X`` events, one named thread track per
        service track (tids from ``base_tid`` up, clear of the tick
        timeline's ``logic`` tid 0). Span linkage rides in ``args``
        (``trace_id``/``span_id``/``parent_id``) for
        ``tools/merge_traces.py`` to turn into flow arrows."""
        events: list[dict] = []
        tids: dict[str, int] = {}
        for name, track, trace_hex, span_hex, parent, wall_us, dur_us, \
                args in self.records():
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = base_tid + len(tids)
                events.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": track},
                })
            ev_args = {"trace_id": trace_hex, "span_id": span_hex}
            if parent:
                ev_args["parent_id"] = parent
            if args:
                ev_args.update(args)
            events.append({
                "name": name, "ph": "X", "ts": wall_us, "dur": dur_us,
                "pid": pid, "tid": tid, "args": ev_args,
            })
        return events


recorder = SpanRecorder()


class root:
    """The ROOT twin of :class:`hop`: record a parentless span for a
    freshly-rooted context (gate ingress, game-initiated migration) and
    install it as current so outbound packets are auto-stamped.

    ``with root("gate_ingress", "gate1", maybe_sample(), msgtype=13):``
    """

    __slots__ = ("_span", "_use", "ctx")

    def __init__(self, name: str, track: str, ctx: TraceContext,
                 **args: Any):
        self.ctx = ctx
        self._span = recorder.span(name, track, ctx, None, **args)
        self._use = use(ctx)

    def __enter__(self) -> TraceContext:
        self._span.__enter__()
        self._use.__enter__()
        return self.ctx

    def __exit__(self, *exc) -> None:
        self._use.__exit__(*exc)
        self._span.__exit__(*exc)


class hop:
    """One traced hop: derive a child context from the inbound one,
    record a span for the handler's duration, and install the child as
    current so every outbound packet created inside is auto-stamped.

    ``with hop("route", "dispatcher1", inbound, msgtype=8) as my:
        pkt.trace = my        # the forwarded packet carries MY span
        ...handle...``
    """

    __slots__ = ("_span", "_use", "ctx")

    def __init__(self, name: str, track: str, inbound: TraceContext,
                 **args: Any):
        self.ctx = inbound.child()
        self._span = recorder.span(name, track, self.ctx,
                                   inbound.span_hex, **args)
        self._use = use(self.ctx)

    def __enter__(self) -> TraceContext:
        self._span.__enter__()
        self._use.__enter__()
        return self.ctx

    def __exit__(self, *exc) -> None:
        self._use.__exit__(*exc)
        self._span.__exit__(*exc)
