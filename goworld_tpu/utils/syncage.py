"""End-to-end sync-age plane: how stale is a position update when it
leaves a gate toward a client?

Every SLO verdict before this module measured only the DEVICE tick
(``tick_latency_ms`` / the bench scan-marginal). But the paper's target
— "AOI-sync p99 < 16 ms" — is about what a *client* observes, and
between the device tick that computed a position and the gate flushing
it to a socket sit four host-side hops: output fetch + decode, the
game's per-gate encode, the dispatcher forward, and the gate's
per-client regroup/flush. This module makes that whole path legible:

* :class:`SyncAgeStamp` — a fixed 45-byte per-BATCH stamp (one per
  sync fan-out packet, never per record) carrying the device-tick
  epoch that produced the batch (a per-tick monotonic ``seq`` plus a
  host wall anchor captured at the tick's EXISTING fetch-outputs
  transfer — zero extra device syncs) and one wall instant per hop
  boundary. It rides the wire as a flagged trailer exactly like the
  tracing context (``net/packet.py`` ``AGE_FLAG``): packets without a
  stamp are byte-identical to the pre-stamp wire.
* :class:`AgeTracker` — the gate-side accumulator: at flush time it
  turns a stamp + delivery instant into AGE-AT-DELIVERY observations
  in fixed-bucket histograms — ``sync_age_ms`` (end-to-end) plus one
  ``sync_age_hop_ms{hop=...}`` lane per hop — weighted by the number
  of records delivered (a 10K-record batch arriving late is 10K stale
  updates, not one).

Hop lanes (each pair of adjacent instants; they sum EXACTLY to the
end-to-end age by construction):

====================  ==================================================
``device_tick``       tick start -> outputs host-visible (device step +
                      the blocking fetch; under ``pipeline_decode`` the
                      anchors follow the outputs one tick back, so the
                      lane honestly includes the pipeline skew)
``drain_decode``      outputs host-visible -> sync flush begins (host
                      decode + AOI fan-out staging)
``encode``            flush begins -> packet handed to the socket
                      (per-gate concat + batch/delta encode)
``dispatcher``        game send -> dispatcher forward (wire leg + any
                      dispatcher pend-queue residence)
``gate_flush``        dispatcher forward -> gate per-client send (wire
                      leg + delta decode + per-client regroup)
====================  ==================================================

Clock honesty: instants are ``time.time()`` microseconds from three
processes. On one host (every test/bench deployment) they share a
clock; across hosts the deployment aggregator
(``tools/obs_aggregate.py``) measures pairwise wall offsets through
the existing ``/clock`` anchors and stamps the worst skew next to its
verdict, so cross-process ages are never silently trusted. A lane that
comes out negative (clock warp) clamps to zero and is counted in
``sync_age_clock_warp_total`` instead of poisoning a histogram.

Jax-free; shared by net/game, net/dispatcher, net/gate, debug_http
(``/syncage``), bench.py and the aggregator.
"""

from __future__ import annotations

import struct
import threading
import time
import weakref
from typing import Any

from goworld_tpu.utils import metrics

__all__ = [
    "SyncAgeStamp", "AgeTracker", "HOPS", "STAMP_WIRE_SIZE",
    "DEFAULT_TARGET_MS", "now_us", "ptiles", "register",
    "unregister", "snapshot_all", "reset",
]

# the paper's headline target: AOI-sync p99 < 16 ms @ 60 Hz
DEFAULT_TARGET_MS = 16.0

HOPS = ("device_tick", "drain_decode", "encode", "dispatcher",
        "gate_flush")

_STAMP = struct.Struct("<BIQQQQQ")  # version, seq, 5 wall-us instants
STAMP_WIRE_SIZE = _STAMP.size       # 45 bytes per BATCH packet
STAMP_VERSION = 1


def now_us() -> int:
    return int(time.time() * 1e6)


def ptiles(edges, counts) -> dict[str, Any]:
    """Reduce a count vector to ``{samples, p50/p90/p99_ms}`` with the
    interpolated estimator (non-finite quantiles stringify as
    ``"inf"``). The ONE home for the percentile convention — shared by
    :class:`AgeTracker`, the deployment aggregator
    (``tools/obs_aggregate.py``) and the bench ``sync_age`` block."""
    from goworld_tpu.utils import devprof

    total = sum(counts)
    if total <= 0:
        return {"samples": 0}
    out: dict[str, Any] = {"samples": int(total)}
    for name, q in (("p50_ms", 0.50), ("p90_ms", 0.90),
                    ("p99_ms", 0.99)):
        v = devprof.hist_quantile_interp(edges, counts, q)
        out[name] = round(v, 3) if v == v and v != float("inf") \
            else "inf"
    return out


class SyncAgeStamp:
    """One sync fan-out batch's provenance: the device-tick epoch that
    produced it plus a wall instant per hop boundary. ``t_disp_us`` is
    zero until the dispatcher forwards the packet (it patches its own
    instant in); a zero dispatcher instant folds that hop into
    ``gate_flush`` so the lane sum stays exact."""

    __slots__ = ("seq", "t_tick_us", "t_fetch_us", "t_stage_us",
                 "t_send_us", "t_disp_us")

    def __init__(self, seq: int, t_tick_us: int, t_fetch_us: int,
                 t_stage_us: int = 0, t_send_us: int = 0,
                 t_disp_us: int = 0):
        self.seq = int(seq)
        self.t_tick_us = int(t_tick_us)
        self.t_fetch_us = int(t_fetch_us)
        self.t_stage_us = int(t_stage_us)
        self.t_send_us = int(t_send_us)
        self.t_disp_us = int(t_disp_us)

    def pack(self) -> bytes:
        return _STAMP.pack(STAMP_VERSION, self.seq & 0xFFFFFFFF,
                           self.t_tick_us, self.t_fetch_us,
                           self.t_stage_us, self.t_send_us,
                           self.t_disp_us)

    @classmethod
    def unpack(cls, b: bytes) -> "SyncAgeStamp":
        if len(b) != STAMP_WIRE_SIZE:
            raise ValueError(
                f"sync-age stamp must be {STAMP_WIRE_SIZE} bytes, "
                f"got {len(b)}")
        ver, seq, t_tick, t_fetch, t_stage, t_send, t_disp = \
            _STAMP.unpack(b)
        if ver != STAMP_VERSION:
            raise ValueError(f"sync-age stamp version {ver} unsupported")
        return cls(seq, t_tick, t_fetch, t_stage, t_send, t_disp)

    def lanes_us(self, t_deliver_us: int) -> tuple[dict[str, int], int]:
        """Per-hop residence times in microseconds at delivery instant
        ``t_deliver_us``. Returns ``(lanes, warped)`` where ``warped``
        counts boundary pairs that came out negative (cross-process
        clock skew) and were clamped to zero. The clamped lanes still
        sum to ``max(0, t_deliver - t_tick)`` exactly: each boundary is
        first made monotone, then adjacent differences are taken."""
        t_disp = self.t_disp_us or self.t_send_us
        raw = [self.t_tick_us, self.t_fetch_us, self.t_stage_us,
               self.t_send_us, t_disp, int(t_deliver_us)]
        warped = 0
        mono = [raw[0]]
        for v in raw[1:]:
            if v < mono[-1]:
                warped += 1
                v = mono[-1]
            mono.append(v)
        lanes = {hop: mono[i + 1] - mono[i]
                 for i, hop in enumerate(HOPS)}
        return lanes, warped


class AgeTracker:
    """Gate-side sync-age accumulator: fixed-bucket histograms for the
    end-to-end age and every hop lane, record-weighted, plus a
    windowed p99 reader for the flight-recorder breach trigger. All
    series live in the process metrics registry (scraped at
    ``/metrics``); :meth:`snapshot` serves the raw count vectors at
    ``/syncage`` so the deployment aggregator can merge histograms
    exactly (``Histogram.add_counts``) instead of re-parsing
    Prometheus text."""

    def __init__(self, target_ms: float = DEFAULT_TARGET_MS,
                 name: str = "gate"):
        # series are labeled by tracker name: registry families dedup
        # by (name, labels), so two trackers in one process (multi-gate
        # tests, embedded harnesses) must not silently share buckets
        self.target_ms = float(target_ms)
        self.name = name
        self._h_e2e = metrics.histogram(
            "sync_age_ms",
            help="age of sync records at gate delivery, device-tick "
                 "epoch to per-client flush (record-weighted)",
            gate=name)
        self._h_hop = {
            hop: metrics.histogram(
                "sync_age_hop_ms",
                help="per-hop share of the sync age at delivery",
                gate=name, hop=hop)
            for hop in HOPS
        }
        self._m_warp = metrics.counter(
            "sync_age_clock_warp_total",
            help="sync-age boundary pairs clamped for negative "
                 "(cross-process clock skew) residence",
            gate=name)
        self._m_batches = metrics.counter(
            "sync_age_batches_total",
            help="stamped sync batches aged at delivery",
            gate=name)
        # freshest observation, for tests and the /syncage payload —
        # exact microsecond lanes, before any bucketing
        self.last_lanes_ms: dict[str, float] | None = None
        self.last_e2e_ms: float | None = None
        self.last_seq: int | None = None
        # window mark for the flush-cadence breach trigger: e2e count
        # vector at the previous window_verdict() call
        self._win_mark: list[int] | None = None
        self._lock = threading.Lock()

    def observe(self, stamp: SyncAgeStamp, t_deliver_us: int,
                n_records: int) -> None:
        if n_records <= 0:
            return
        lanes, warped = stamp.lanes_us(t_deliver_us)
        e2e_us = sum(lanes.values())
        self._h_e2e.observe_n(e2e_us / 1e3, n_records)
        for hop, us in lanes.items():
            self._h_hop[hop].observe_n(us / 1e3, n_records)
        if warped:
            self._m_warp.inc(warped)
        self._m_batches.inc()
        self.last_lanes_ms = {h: v / 1e3 for h, v in lanes.items()}
        self.last_e2e_ms = e2e_us / 1e3
        self.last_seq = stamp.seq

    # -- reading ---------------------------------------------------------
    @staticmethod
    def _edges_counts(h: metrics.Histogram) -> tuple[list, list]:
        snap = h.snapshot()
        edges = [u for u, _c in snap["buckets"]]
        counts = [c for _u, c in snap["buckets"]] + [snap["inf"]]
        return edges, counts

    _ptiles = staticmethod(ptiles)

    def window_verdict(self) -> tuple[float | None, int]:
        """(e2e p99 over the observations since the previous call,
        sample count). ``None`` p99 on an empty window. Drives the
        gate's flight-recorder ``sync_age_breach`` frames."""
        edges, counts = self._edges_counts(self._h_e2e)
        with self._lock:
            mark, self._win_mark = self._win_mark, list(counts)
        if mark is None or len(mark) != len(counts):
            return None, 0
        delta = [max(0, a - b) for a, b in zip(counts, mark)]
        n = sum(delta)
        if n <= 0:
            return None, 0
        from goworld_tpu.utils import devprof

        p99 = devprof.hist_quantile_interp(edges, delta, 0.99)
        return (None if p99 != p99 else p99), n

    def snapshot(self) -> dict:
        """The ``/syncage`` payload: raw count vectors (mergeable via
        ``Histogram.add_counts``) plus derived percentiles and the
        e2e verdict against this tracker's target."""
        edges, e2e_counts = self._edges_counts(self._h_e2e)
        e2e = self._ptiles(edges, e2e_counts)
        hops: dict[str, Any] = {}
        hop_counts: dict[str, list] = {}
        for hop in HOPS:
            he, hc = self._edges_counts(self._h_hop[hop])
            hops[hop] = self._ptiles(he, hc)
            hop_counts[hop] = hc
        out = {
            "target_ms": self.target_ms,
            "edges_ms": edges,
            "e2e": e2e,
            "e2e_counts": e2e_counts,
            "hops": hops,
            "hop_counts": hop_counts,
            "clock_warp_total": int(self._m_warp.value),
            "batches": int(self._m_batches.value),
        }
        p99 = e2e.get("p99_ms")
        if isinstance(p99, (int, float)):
            out["pass"] = bool(p99 <= self.target_ms)
        return out


# =======================================================================
# process-local registry (served by debug_http /syncage). Weak values:
# the tracker belongs to its GateService and a discarded gate must not
# be pinned by the registry (the flightrec/devprof convention).
# =======================================================================
_reg_lock = threading.Lock()
_trackers: "weakref.WeakValueDictionary[str, AgeTracker]" = \
    weakref.WeakValueDictionary()


def register(name: str, tracker: AgeTracker) -> AgeTracker:
    with _reg_lock:
        _trackers[name] = tracker
    return tracker


def unregister(name: str) -> None:
    with _reg_lock:
        _trackers.pop(name, None)


def snapshot_all() -> dict:
    """``/syncage``: every registered tracker's snapshot, or an honest
    absence (a game/dispatcher process serves the endpoint but ages
    nothing — the aggregator skips it silently)."""
    with _reg_lock:
        trackers = dict(_trackers)
    if not trackers:
        return {"error": "no sync-age tracker in this process"}
    return {name: t.snapshot() for name, t in sorted(trackers.items())}


def reset() -> None:
    """Drop registered trackers (tests)."""
    with _reg_lock:
        _trackers.clear()
