"""Debug/observability HTTP server.

Reference being rebuilt: ``engine/binutil`` (``binutil.go:17-75``) — every
process serves ``net/http/pprof`` + expvar on its ``http_addr``. The
TPU-native analog exposes:

* ``/vars``   — gwvar-style exposed variables (:mod:`opmon` ``expose``)
* ``/ops``    — opmon op stats (count / avg / max per named op)
* ``/metrics``— Prometheus text exposition of the :mod:`metrics` registry
  (the expvar/opmon role, scrapeable: counters, gauges, histograms)
* ``/trace``  — Chrome ``chrome://tracing`` / Perfetto JSON: the per-tick
  phase timeline ring buffer (:data:`metrics.timeline`) merged with the
  distributed-tracing span ring (:data:`tracing.recorder`); gzipped when
  the client sends ``Accept-Encoding: gzip`` (merged cluster traces at
  1M entities are large)
* ``/tracing``— distributed-tracing control: ``?rate=R`` sets the
  process's sample rate, ``?clear=1`` drops recorded spans; always
  returns the current state (driven by ``goworld_tpu trace``)
* ``/clock``  — paired monotonic/wall anchors for cross-process clock
  alignment (``tools/merge_traces.py``)
* ``/healthz``— liveness probe
* ``/profile``— jax.profiler capture trigger: GET starts a device trace
  (``?logdir=`` overrides the output dir), ``?stop=1`` stops it,
  ``?seconds=N`` auto-stops the capture after N seconds (a started
  capture that is never stopped would otherwise hold the per-process
  profiler lock forever), ``?status=1`` reports without side effects;
  a clear JSON error when jax.profiler is unavailable
* ``/costs`` — device-plane cost observability (:mod:`goworld_tpu.
  utils.devprof`): registered :class:`CostReport`s of compiled tick
  executables, lazy analyze providers (run with ``?analyze=1`` —
  a lower+compile costs seconds, so it is operator-triggered), and
  the freshest SLO verdict (recorded, or derived live from the
  ``tick_latency_ms`` histogram)
* ``/workload`` — the live workload signature (:mod:`goworld_tpu.ops.
  telemetry` reducer over the in-graph telemetry lanes the serving
  tick accumulates on device): churn/density/event/skew classes +
  the ``[gameN]`` kernel-config recommendation
* ``/incidents`` — the incident flight recorder (:mod:`goworld_tpu.
  utils.flightrec`): frozen snapshot bundles (SLO breach, overload
  transition, oracle anomaly, signature change) with their per-tick
  frame tails; ``?frames=1`` includes the live ring too
* ``/faults`` — fault-injection plane state (:mod:`goworld_tpu.utils.
  faults`): seed, per-rule trial counts and the deterministic fired
  log; ``{"active": false}`` when no schedule is installed
* ``/overload`` — overload-protection plane state (:mod:`goworld_tpu.
  utils.overload`): every registered governor's ladder state and
  transition log, circuit breaker states, per-class shed counters
* ``/governor`` — online kernel-governor state (:mod:`goworld_tpu.
  autotune`): current/pending config key, the deterministic swap +
  decision logs, warm-set compile states, regret-guard status and the
  freshest signature the policy judged
* ``/syncage`` — the end-to-end sync-age plane (:mod:`goworld_tpu.
  utils.syncage`): per-gate age-at-delivery percentiles (e2e + per
  hop) AND the raw bucket count vectors so the deployment aggregator
  (``tools/obs_aggregate.py`` / ``cli.py watch``) can merge
  histograms exactly; an honest error on processes that age nothing
* ``/residency`` — the serve-loop residency plane (:mod:`goworld_tpu.
  utils.residency`): per-world host-bubble/phase percentiles with raw
  mergeable count vectors, alloc-churn samples, the donation-readiness
  buffer census and the serve_gap verdict; an honest error on
  processes that tick no world
* ``/audit`` — the correctness audit plane (:mod:`goworld_tpu.utils.
  audit`): per-game entity-ownership census digests (count + CRC fold
  per type) with the in-flight migration window, sampled AOI-oracle /
  mirror-probe / snapshot-scrub stats and the violation rings;
  ``?eids=1`` adds the (bounded) sorted EntityID lists for diffing a
  census divergence down to the first differing id; an honest error
  on processes that track no entities
* ``/standby`` — the hot-standby replication plane (:mod:`goworld_tpu.
  replication.standby`): per-standby applied seq/tick, stream bytes,
  reject counts by torn-stream reason, last-keyframe age and a
  sync-age-style staleness verdict (lag ticks vs budget);
  ``?promote=1`` (optionally ``&epoch=E``) is the supervisor's
  promotion poke; an honest error on processes that mirror nothing
* ``/rebalance`` — the self-healing rebalance plane (:mod:`goworld_tpu.
  rebalance`): per-game handoff agents (active job, queue/unacked
  depth, move/abort counters by cause) and the controller's policy
  state + decision-log tail where one runs; ``?handoff=GAMEID``
  (optionally ``&batch=N``) pokes a bounded manual cohort drain on
  this process's agent

Stdlib-only (http.server on a daemon thread), one call to :func:`start`.
"""

from __future__ import annotations

import gzip as _gzip
import json
import math
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from goworld_tpu.utils import log, metrics, opmon, tracing

logger = log.get("debug_http")

_ENDPOINTS = ["/healthz", "/vars", "/ops", "/metrics", "/trace",
              "/tracing", "/clock", "/profile", "/faults", "/overload",
              "/costs", "/workload", "/incidents", "/governor",
              "/syncage", "/residency", "/audit", "/standby",
              "/rebalance"]

# jax.profiler capture state (one capture at a time per process)
_profile_lock = threading.Lock()
_profile_dir: str | None = None
# monotonically bumped per start: the ?seconds auto-stop timer only
# fires for ITS capture (a manual stop + fresh start must not be
# killed by a stale timer)
_profile_gen = 0


def merged_trace(process_name: str) -> dict:
    """The tick timeline's Chrome trace with the span recorder's events
    (RPC/migration hop spans, one named track per service) appended —
    one JSON object per process, merged cluster-wide by
    ``tools/merge_traces.py``."""
    obj = metrics.timeline.chrome_trace(process_name)
    obj["traceEvents"].extend(
        tracing.recorder.chrome_events(os.getpid())
    )
    return obj


def _profile_auto_stop(gen: int) -> None:
    """Timer body for ``?seconds=N``: stop the capture IF it is still
    the one that armed this timer (generation check — a manual stop +
    restart must never be killed by a stale timer)."""
    global _profile_dir
    with _profile_lock:
        if _profile_dir is None or gen != _profile_gen:
            return
        try:
            from jax import profiler as jax_profiler

            jax_profiler.stop_trace()
        except Exception as exc:  # the capture is still torn down
            logger.warning("profile auto-stop failed: %s", exc)
        logger.info("profile auto-stopped (logdir %s)", _profile_dir)
        _profile_dir = None


def _profile_action(query: dict) -> tuple[dict, int]:
    """Start/stop a jax.profiler trace capture; (json body, status)."""
    global _profile_dir, _profile_gen
    try:
        from jax import profiler as jax_profiler
    except Exception:
        return ({"error": "jax.profiler unavailable in this process"},
                501)
    # presence of the key counts (`?stop` and `?stop=1` both stop)
    stop = "stop" in query and query["stop"][0] not in ("0", "false")
    status = "status" in query and query["status"][0] not in ("0",
                                                              "false")
    with _profile_lock:
        if status:
            return ({"active": _profile_dir is not None,
                     "logdir": _profile_dir}, 200)
        if stop:
            if _profile_dir is None:
                return ({"error": "no capture in progress"}, 409)
            try:
                jax_profiler.stop_trace()
            except Exception as exc:
                _profile_dir = None
                return ({"error": f"stop_trace failed: {exc}"}, 500)
            d, _profile_dir = _profile_dir, None
            return ({"ok": True, "stopped": True, "logdir": d}, 200)
        if _profile_dir is not None:
            return ({"error": "capture already in progress",
                     "logdir": _profile_dir}, 409)
        seconds = 0.0
        if "seconds" in query:
            # parse BEFORE starting: a bad value must not leave a
            # capture running with no auto-stop armed
            try:
                seconds = float(query["seconds"][0])
            except ValueError:
                return ({"error": "seconds must be a number"}, 400)
            # reject non-finite too: Timer(nan) fires immediately and
            # Timer(inf) never — both defeat the auto-stop guarantee
            # this parameter exists to provide
            if not math.isfinite(seconds) or seconds <= 0:
                return ({"error": "seconds must be a finite number "
                                  "> 0"}, 400)
        logdir = query.get("logdir", [""])[0] or os.path.join(
            os.getcwd(), "jax_profile"
        )
        try:
            jax_profiler.start_trace(logdir)
        except Exception as exc:
            return ({"error": f"start_trace failed: {exc}"}, 500)
        _profile_dir = logdir
        _profile_gen += 1
        body = {"ok": True, "started": True, "logdir": logdir}
        if seconds:
            t = threading.Timer(seconds, _profile_auto_stop,
                                args=(_profile_gen,))
            t.daemon = True
            t.start()
            body["auto_stop_s"] = seconds
        return (body, 200)


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # keep request noise out of server logs
        pass

    def _body(self, body: bytes, ctype: str, code: int = 200,
              gzip_ok: bool = False) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        if gzip_ok and "gzip" in \
                self.headers.get("Accept-Encoding", ""):
            body = _gzip.compress(body)
            self.send_header("Content-Encoding", "gzip")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200) -> None:
        self._body(json.dumps(obj, indent=2, default=str).encode(),
                   "application/json", code)

    def do_GET(self):  # noqa: N802 (stdlib api)
        path, _, qs = self.path.partition("?")
        # keep_blank_values: `?stop` / `?clear` (no value) must count
        query = urllib.parse.parse_qs(qs, keep_blank_values=True)
        if path == "/healthz":
            self._json({"ok": True})
        elif path == "/vars":
            self._json(opmon.vars())
        elif path == "/ops":
            self._json(opmon.monitor.snapshot())
        elif path == "/metrics":
            self._body(metrics.REGISTRY.expose_text().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/trace":
            self._body(
                json.dumps(merged_trace(
                    getattr(self.server, "process_name", "goworld_tpu")
                )).encode(),
                "application/json",
                gzip_ok=True,
            )
        elif path == "/tracing":
            if "rate" in query:
                try:
                    tracing.set_sample_rate(float(query["rate"][0]))
                except ValueError:
                    self._json({"error": "rate must be a float"}, 400)
                    return
            if "clear" in query \
                    and query["clear"][0] not in ("0", "false"):
                tracing.recorder.clear()
            self._json({"rate": tracing.sample_rate(),
                        "spans": len(tracing.recorder)})
        elif path == "/clock":
            # both clocks sampled back to back: the merge tool pairs
            # them with its own request midpoint to estimate this
            # process's wall-clock offset
            self._json({
                "wall_us": time.time() * 1e6,
                "mono_us": time.monotonic() * 1e6,
                "pid": os.getpid(),
                "process_name": getattr(self.server, "process_name",
                                        "goworld_tpu"),
            })
        elif path == "/profile":
            body, code = _profile_action(query)
            self._json(body, code)
        elif path == "/faults":
            # fault-injection plane state: per-rule trial counts + the
            # deterministic fired-trial log (utils/faults.py; chaos
            # runs scrape this to verify seeded replay)
            from goworld_tpu.utils import faults

            self._json(faults.snapshot())
        elif path == "/overload":
            # overload ladder state, per-class shed counters and
            # circuit breakers (utils/overload.py)
            from goworld_tpu.utils import overload

            self._json(overload.snapshot())
        elif path == "/costs":
            # device-plane cost reports + SLO verdict (utils/devprof):
            # ?analyze=1 runs the registered lazy providers (a
            # lower+compile of the live tick — seconds, so opt-in)
            from goworld_tpu.utils import devprof

            analyze = "analyze" in query \
                and query["analyze"][0] not in ("0", "false")
            self._json(devprof.snapshot(analyze=analyze))
        elif path == "/workload":
            # live workload signature (ops/telemetry reducer over the
            # serving tick's device lanes; utils/flightrec registry)
            from goworld_tpu.utils import flightrec

            self._json(flightrec.workload_snapshot())
        elif path == "/governor":
            # online kernel-governor state (goworld_tpu/autotune):
            # swap/decision logs, warm-set states, regret guard
            from goworld_tpu.autotune import governor as autotune_gov

            self._json(autotune_gov.snapshot())
        elif path == "/syncage":
            # end-to-end sync-age plane (utils/syncage registry):
            # percentiles + mergeable raw count vectors per tracker
            from goworld_tpu.utils import syncage

            self._json(syncage.snapshot_all())
        elif path == "/residency":
            # serve-loop residency plane (utils/residency registry):
            # bubble/phase percentiles + mergeable count vectors,
            # alloc churn, buffer census and serve_gap per world
            from goworld_tpu.utils import residency

            self._json(residency.snapshot_all())
        elif path == "/audit":
            # correctness audit plane (utils/audit registry): ledger
            # census digests + in-flight migration window, sampled
            # oracle/probe/scrub stats, violations; ?eids=1 adds the
            # (bounded) sorted EntityID list so a census divergence
            # can be diffed down to the first differing id
            from goworld_tpu.utils import audit

            want_eids = "eids" in query \
                and query["eids"][0] not in ("0", "false")
            self._json(audit.snapshot_all(eids=want_eids))
        elif path == "/standby":
            # hot-standby replication plane (goworld_tpu/replication/
            # standby registry): per-standby lag/bytes/reject stats
            # with a sync-age-style staleness verdict; ?promote=1
            # (optionally &epoch=E) drives the supervisor's promotion
            # poke — the claim itself runs on the game's logic thread
            from goworld_tpu.replication import standby

            if "promote" in query \
                    and query["promote"][0] not in ("0", "false"):
                ep = query.get("epoch", [None])[0]
                self._json(standby.request_promotion(
                    int(ep) if ep not in (None, "") else None))
            else:
                self._json(standby.snapshot_all())
        elif path == "/rebalance":
            # self-healing rebalance plane (goworld_tpu/rebalance
            # registry): per-game handoff agents (active job, move/
            # abort counters) and, on the controller's host, the
            # policy state + decision log tail; ?handoff=GAMEID
            # (optionally &batch=N) pokes a bounded cohort handoff on
            # this process's agent — the operator's manual drain knob,
            # same bookkeeping as an automated move
            from goworld_tpu import rebalance

            if "handoff" in query and query["handoff"][0]:
                try:
                    target = int(query["handoff"][0])
                except ValueError:
                    self._json({"error": "handoff wants a game id"},
                               400)
                    return
                batch_q = query.get("batch", [None])[0]
                self._json(rebalance.request_handoff(
                    target,
                    int(batch_q) if batch_q not in (None, "") else None))
            else:
                self._json(rebalance.snapshot())
        elif path == "/incidents":
            # flight-recorder incident bundles (utils/flightrec);
            # ?frames=1 adds the live per-tick frame ring
            from goworld_tpu.utils import flightrec

            frames = "frames" in query \
                and query["frames"][0] not in ("0", "false")
            self._json(flightrec.snapshot_all(frames=frames))
        else:
            self._json({"error": "not found",
                        "endpoints": _ENDPOINTS}, 404)


def start(port: int, host: str = "127.0.0.1",
          process_name: str = "goworld_tpu") -> ThreadingHTTPServer:
    """Serve debug endpoints on a daemon thread; returns the server (its
    bound port is ``server.server_address[1]`` when ``port=0``).
    ``process_name`` labels the ``/trace`` export (e.g. ``game1``).

    For on-device profiling beyond the ``/profile`` start/stop trigger,
    pair with ``jax.profiler.start_server(profiler_port)`` and capture
    traces via TensorBoard — the reference's pprof role
    (``binutil.go:26-47``)."""
    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.process_name = process_name  # type: ignore[attr-defined]
    t = threading.Thread(target=srv.serve_forever,
                         name=f"debug-http-{port}", daemon=True)
    t.start()
    logger.info("debug http on %s:%d", host, srv.server_address[1])
    return srv
