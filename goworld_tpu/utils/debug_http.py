"""Debug/observability HTTP server.

Reference being rebuilt: ``engine/binutil`` (``binutil.go:17-75``) — every
process serves ``net/http/pprof`` + expvar on its ``http_addr``. The
TPU-native analog exposes:

* ``/vars``   — gwvar-style exposed variables (:mod:`opmon` ``expose``)
* ``/ops``    — opmon op stats (count / avg / max per named op)
* ``/metrics``— Prometheus text exposition of the :mod:`metrics` registry
  (the expvar/opmon role, scrapeable: counters, gauges, histograms)
* ``/trace``  — Chrome ``chrome://tracing`` / Perfetto JSON of the
  per-tick phase timeline ring buffer (:data:`metrics.timeline`)
* ``/healthz``— liveness probe
* ``/profile``— a jax.profiler trace capture hint (profiling is driven by
  ``jax.profiler.start_server`` when available; see ``start``'s docstring)

Stdlib-only (http.server on a daemon thread), one call to :func:`start`.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from goworld_tpu.utils import log, metrics, opmon

logger = log.get("debug_http")

_ENDPOINTS = ["/healthz", "/vars", "/ops", "/metrics", "/trace"]


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # keep request noise out of server logs
        pass

    def _body(self, body: bytes, ctype: str, code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200) -> None:
        self._body(json.dumps(obj, indent=2, default=str).encode(),
                   "application/json", code)

    def do_GET(self):  # noqa: N802 (stdlib api)
        if self.path == "/healthz":
            self._json({"ok": True})
        elif self.path == "/vars":
            self._json(opmon.vars())
        elif self.path == "/ops":
            self._json(opmon.monitor.snapshot())
        elif self.path == "/metrics":
            self._body(metrics.REGISTRY.expose_text().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/trace":
            self._body(
                metrics.timeline.chrome_trace_json(
                    getattr(self.server, "process_name", "goworld_tpu")
                ).encode(),
                "application/json",
            )
        else:
            self._json({"error": "not found",
                        "endpoints": _ENDPOINTS}, 404)


def start(port: int, host: str = "127.0.0.1",
          process_name: str = "goworld_tpu") -> ThreadingHTTPServer:
    """Serve debug endpoints on a daemon thread; returns the server (its
    bound port is ``server.server_address[1]`` when ``port=0``).
    ``process_name`` labels the ``/trace`` export (e.g. ``game1``).

    For on-device profiling, pair with ``jax.profiler.start_server(
    profiler_port)`` and capture traces via TensorBoard — the reference's
    pprof role (``binutil.go:26-47``)."""
    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.process_name = process_name  # type: ignore[attr-defined]
    t = threading.Thread(target=srv.serve_forever,
                         name=f"debug-http-{port}", daemon=True)
    t.start()
    logger.info("debug http on %s:%d", host, srv.server_address[1])
    return srv
