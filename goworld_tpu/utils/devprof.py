"""Device-plane observability: XLA cost auditor, roofline audit, SLO.

Everything observable so far (metrics registry, tick timeline,
distributed tracing) lives on the HOST side of the ``jit`` boundary —
the compiled tick graph itself was a black box, and every TPU claim
rested on the hand-derived docs/ROOFLINE.md model. This module makes
the device plane legible with three pieces:

* :class:`CostReport` / :func:`cost_report` — for any jitted tick
  (single-space, vmapped, megaspace, scenario), run
  ``fn.lower(*args).compile()`` and fold ``cost_analysis()`` +
  ``memory_analysis()`` into one structured record: FLOPs, bytes
  accessed, peak HBM, output bytes, keyed by the resolved kernel
  config (sweep/topk/sort/skin stamps). XLA counts a ``while``-loop
  body ONCE, so a ``lax.scan`` probe's numbers are per-tick already.
* :func:`roofline_model_bytes` / :func:`roofline_audit` — the
  docs/ROOFLINE.md hand model, machine-readable: per-phase HBM bytes
  as a function of (n, grid knobs), diffed against the XLA-derived
  terms and the measured phase timings into the ``roofline_audit``
  block bench.py stamps into every BENCH_r*.json. The model is finally
  machine-checked on every platform, TPU relay or not.
* the SLO plane — :func:`hist_quantile` / :func:`slo_from_histogram`
  turn a fixed-bucket histogram (the in-graph telemetry lanes of
  :mod:`goworld_tpu.ops.telemetry`, or the live ``tick_latency_ms``
  metric) into a {target_ms, p50/p90/p99, pass} verdict, plus a
  process-local registry served by debug_http ``/costs`` (reports,
  lazy analyze providers, the last SLO verdict).

The module is import-safe without jax (the bench parent and the
jax-free tools import the model/quantile half); jax is imported inside
the functions that need it.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Any, Callable

__all__ = [
    "CostReport", "cost_report", "grid_config_key",
    "roofline_model_bytes", "roofline_audit", "V5E_HBM_GBPS",
    "roofline_model_bytes_multichip", "roofline_audit_multichip",
    "V5E_ICI_GBPS", "HALO_ROW_BYTES",
    "hist_quantile", "slo_from_histogram",
    "register_report", "register_provider", "record_slo", "snapshot",
    "set_slo_target", "reset",
]

# public v5e figure the ROOFLINE.md model is priced against
V5E_HBM_GBPS = 819.0

# public v5e ICI figure: ~400 GB/s aggregate inter-chip bandwidth per
# chip (4 links x ~100 GB/s each way) — the multichip halo/migrate
# terms are priced against it (docs/ROOFLINE.md "Multichip")
V5E_ICI_GBPS = 400.0

# modeled halo payload bytes per ghost row by halo_impl
# (parallel/halo.py): the 5-lane ppermute path ships pos f32[3] +
# yaw f32 + dirty/valid bools + gid i32 = 22 B; the async packed path
# ships pos + one meta word always (16 B) and its yaw lane is zero
# unless the row is dirty, so the model charges it at dirty duty
HALO_ROW_BYTES = {"ppermute": 22.0, "async": 16.0}
HALO_ASYNC_YAW_BYTES = 4.0
# ... and under the quantized planes (precision=q16, ISSUE 12): the
# xz pair ships as ONE packed i32 lane (4 B) + y f32 (4 B), yaw as
# int16 (2 B) — ppermute 4+4+2+2+4 = 16 B/row, async packed 4+4+4
# = 12 B/row + 2 B dirty-only yaw. The wire change itself is staged
# for a relay window (the model arbitrates first, the audit stamps
# both projections via ici_halo_mb_by_impl).
HALO_ROW_BYTES_Q = {"ppermute": 16.0, "async": 12.0}
HALO_ASYNC_YAW_BYTES_Q = 2.0

# the paper's AOI-sync latency target (BASELINE.md: p99 < 16 ms at the
# 1M/60 Hz headline shape) — the default SLO budget everywhere
DEFAULT_SLO_TARGET_MS = 16.0


# =======================================================================
# CostReport: compiled-artifact cost auditor
# =======================================================================
@dataclasses.dataclass
class CostReport:
    """Structured XLA cost/memory analysis of ONE compiled executable.

    ``flops``/``bytes_accessed``/``output_bytes`` come from
    ``compiled.cost_analysis()`` (None where the backend exposes no
    figure), the ``*_size`` fields from ``memory_analysis()``.
    ``peak_hbm_bytes`` is argument + output + temp — the executable's
    live-memory high-water mark. ``config`` carries the resolved
    kernel stamps (sweep/topk/sort/skin...) so a report is
    self-describing next to a BENCH headline."""

    name: str
    flops: float | None = None
    bytes_accessed: float | None = None
    output_bytes: float | None = None
    argument_size: int | None = None
    output_size: int | None = None
    temp_size: int | None = None
    peak_hbm_bytes: int | None = None
    generated_code_size: int | None = None
    # donation accounting (ISSUE 16; feeds ROADMAP item 5): bytes the
    # compiled executable ALREADY aliases input->output, and the upper
    # bound donate_argnums could still reclaim — the overlap of
    # argument and output footprints not yet aliased. temp vs arg split
    # is readable directly off temp_size/argument_size above.
    # donation_applied (ISSUE 20) is the "did reclaim" column next to
    # donation_reclaimable's "could reclaim": the actual aliased bytes
    # from the executable's input-output aliasing — 0 on a
    # non-resident world, ~= the carry footprint once donate_argnums
    # is threaded (alias_size under a different, operator-facing name
    # so /costs and the bench cost_report read as a pair).
    alias_size: int | None = None
    donation_applied: int | None = None
    donation_reclaimable: int | None = None
    n: int | None = None
    # multichip mode: device count of the mesh executable (cost figures
    # then cover the WHOLE mesh — divide by n_devices for per-chip)
    n_devices: int | None = None
    platform: str | None = None
    config: dict | None = None
    error: str | None = None

    @property
    def key(self) -> str:
        """Compact per-config key (autotune-log style)."""
        cfg = self.config or {}
        return ",".join(f"{k}={cfg[k]}" for k in sorted(cfg)) or "default"

    def as_dict(self) -> dict:
        d = {k: v for k, v in dataclasses.asdict(self).items()
             if v is not None}
        d["key"] = self.key
        return d


def grid_config_key(grid) -> dict:
    """Resolved kernel stamps for a GridSpec — the per-config key every
    CostReport and BENCH headline shares (one naming for both)."""
    return {
        "sweep_impl": grid.sweep_impl,
        "topk_impl": grid.topk_impl,
        "sort_impl": grid.sort_impl,
        "skin": grid.skin,
        "k": grid.k,
        "cell_cap": grid.cell_cap,
        "precision": getattr(grid, "precision", "off"),
    }


def cost_report(fn, *args, name: str = "tick", config: dict | None = None,
                n: int | None = None,
                n_devices: int | None = None) -> CostReport:
    """Lower + compile ``fn(*args)`` and emit its :class:`CostReport`.

    ``fn`` may be an ALREADY-COMPILED executable (has
    ``.cost_analysis`` — e.g. ``jitted.lower(x).compile()``, zero
    extra compiles), an already-jitted function (has ``.lower``), or a
    plain callable (wrapped in ``jax.jit`` here). Analysis failures
    are folded into ``report.error`` instead of raising — a cost audit
    must never kill a measurement run."""
    import jax

    rep = CostReport(name=name, config=config, n=n, n_devices=n_devices)
    try:
        rep.platform = jax.devices()[0].platform
        if hasattr(fn, "cost_analysis"):
            compiled = fn
        else:
            jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
            compiled = jfn.lower(*args).compile()
    except Exception as exc:
        rep.error = f"lower/compile: {str(exc)[:200]}"
        return rep
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        rep.flops = float(ca["flops"]) if "flops" in ca else None
        if "bytes accessed" in ca:
            rep.bytes_accessed = float(ca["bytes accessed"])
        if "bytes accessedout{}" in ca:
            rep.output_bytes = float(ca["bytes accessedout{}"])
    except Exception as exc:
        rep.error = f"cost_analysis: {str(exc)[:200]}"
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            rep.argument_size = int(ma.argument_size_in_bytes)
            rep.output_size = int(ma.output_size_in_bytes)
            rep.temp_size = int(ma.temp_size_in_bytes)
            rep.peak_hbm_bytes = (rep.argument_size + rep.output_size
                                  + rep.temp_size)
            rep.generated_code_size = int(ma.generated_code_size_in_bytes)
            # donation headroom: what input->output aliasing could
            # still reclaim. alias_size_in_bytes is what XLA already
            # aliases (0 without donate_argnums); the bound is the
            # smaller of the two footprints minus that.
            alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
            rep.alias_size = alias
            rep.donation_applied = alias
            rep.donation_reclaimable = max(
                0, min(rep.argument_size, rep.output_size) - alias)
    except Exception as exc:
        rep.error = (rep.error or "") + f" memory_analysis: {str(exc)[:200]}"
        rep.error = rep.error.strip()
    return rep


# =======================================================================
# roofline hand model (docs/ROOFLINE.md, machine-readable)
# =======================================================================
def _padded_cells(grid_kw: dict) -> int:
    """(cols+2) * (rows+2) padded grid rows, the table-build term."""
    radius = float(grid_kw.get("radius", 50.0))
    ex = float(grid_kw.get("extent_x", 1024.0))
    ez = float(grid_kw.get("extent_z", ex))
    cols = max(1, int(math.ceil(ex / radius)))
    rows = max(1, int(math.ceil(ez / radius)))
    return (cols + 2) * (rows + 2)


def roofline_model_bytes(n: int, grid_kw: dict) -> dict[str, float]:
    """Per-phase HBM bytes/tick of the hand model (docs/ROOFLINE.md
    table), keyed by the bench phase-probe names. ``grid_kw`` needs
    k, cell_cap, sort_impl, sweep_impl, skin (+ radius/extent for the
    table term); missing knobs take the documented bench defaults.

    These are the MODEL's coefficients — the whole point of the audit
    is that XLA's own accounting (cost_analysis) is diffed against
    them, so keep changes here in lockstep with docs/ROOFLINE.md."""
    k = int(grid_kw.get("k", 32))
    cc = int(grid_kw.get("cell_cap", 12))
    sort_impl = grid_kw.get("sort_impl", "argsort")
    sweep = grid_kw.get("sweep_impl", "ranges")
    skin = float(grid_kw.get("skin", 0.0))
    vcap = int(grid_kw.get("verlet_cap", 0)) or (k + k // 2)
    # quantized state planes (precision=q16, ISSUE 12): the per-term
    # narrowings below mirror exactly what ops/aoi.py ships — the
    # packed 2-lane "ranges" sorted view, the packed-qxz reuse gather,
    # the 21-bit-triplet cand cache, bf16 velocity, and the
    # deadbanded-dirty delta prefilter. Keep in lockstep with
    # docs/ROOFLINE.md "Quantized state planes".
    q16 = grid_kw.get("precision", "off") != "off"
    cells = _padded_cells(grid_kw)
    win = 9 * cc                      # candidate-window lanes per query

    out: dict[str, float] = {}
    out["cell_ids"] = 12.0 * n        # read pos x/z + write rows
    if sort_impl in ("counting", "pallas"):
        # two-pass counting sort: histogram + cumsum + stable scatter
        out["aoi_sort"] = 28.0 * n + 8.0 * cells
    else:
        # bitonic network: ~0.5 log^2(n) compare-exchange passes over
        # keys+payload (16 B/element/pass)
        out["aoi_sort"] = 0.5 * max(1.0, math.log2(max(n, 2))) ** 2 \
            * 16.0 * n
    if sweep in ("table", "cellrow", "shift"):
        # dense per-cell table init + 3x scatter in/out
        out["aoi_build"] = 4.0 * (3 * cc) * cells + 24.0 * n
    elif sweep == "ranges" and q16:
        # packed 2-lane sorted view ((qx,qz) pair + word = 8 B/row)
        out["aoi_build"] = 8.0 * n
    else:
        # tableless ranges/fused front half: sorted [n, 3] view write
        out["aoi_build"] = 12.0 * n
    if sweep == "fused":
        # the whole back half is ONE VMEM-resident kernel: sorted view
        # streamed once + query scalars in, ranked keys + demand out —
        # the [n, 108] window and packed keys never round-trip HBM
        # (under q16 the fused kernel keeps its f32 view — its window
        # already never touches HBM, so there is nothing left to
        # narrow)
        out["aoi_gather"] = 12.0 * n + 44.0 * n
        out["aoi_pack"] = 0.0
        out["aoi_rank"] = 4.0 * k * n + 4.0 * n
    elif sweep == "ranges" and q16:
        # 3 dynamic-slices of (2, 3*cell_cap) lanes per query — the
        # position pair rides ONE i32 lane instead of two f32 lanes
        out["aoi_gather"] = 3 * 2 * (3 * cc) * 4.0 * n
        out["aoi_pack"] = 2 * 4.0 * win * n
        out["aoi_rank"] = 4.0 * win * n + 4.0 * k * n
    else:
        # 3 dynamic-slices of (3, 3*cell_cap) f32 per query
        out["aoi_gather"] = 3 * 3 * (3 * cc) * 4.0 * n
        out["aoi_pack"] = 2 * 4.0 * win * n     # packed keys w + r
        out["aoi_rank"] = 4.0 * win * n + 4.0 * k * n
    if skin > 0:
        # Verlet reuse tick (the steady state the cache-carried probe
        # measures): candidate ids + positions + flags re-gathers plus
        # the shared ranking — front half + window fetch amortize to
        # ~1/cadence duty (cadence is workload speed, not modeled here)
        if q16:
            # 21-bit-packed cand rows (2*ceil(V/3) u32 words) + ONE
            # packed-qxz i32 gather per lane + ranked [n, k] out
            cand_words = 2 * ((vcap + 2) // 3)
            out["aoi_reuse"] = (4.0 * cand_words + 4.0 * vcap
                                + 4.0 * k) * n
        else:
            out["aoi_reuse"] = (3 * 4.0 * vcap + 4.0 * k) * n
        out["aoi_rebuild"] = (out["cell_ids"] + out["aoi_sort"]
                              + out["aoi_build"] + out["aoi_gather"]
                              + out["aoi_pack"] + out["aoi_rank"])
        out["aoi"] = out["aoi_reuse"]   # reuse-dominated steady state
    else:
        out["aoi"] = (out["cell_ids"] + out["aoi_sort"]
                      + out["aoi_build"] + out["aoi_gather"]
                      + out["aoi_pack"] + out["aoi_rank"])
    if q16:
        # pos r/w 24 + prev re-snap read 12 (the deadband compare) +
        # bf16 velocity streams 24 (half of f32's 48) + qxz mirror 4
        out["move"] = 64.0 * n
        # interest delta streams prev+new ONCE each (8k): the changed-
        # row prefilter rides the deadbanded quantized dirty lanes the
        # sweep already delivers, and the k^2 membership compare only
        # gathers the bounded changed-row set (ops/delta two_tier);
        # sync/attr masks + cap-scale value gathers ~= 24 B/row
        out["collect"] = 8.0 * k * n + 24.0 * n
    else:
        out["move"] = 96.0 * n        # pos/vel/yaw streams x ~4
        # interest delta (prev/new nbr reads x2) + sync/attr collection
        out["collect"] = 16.0 * k * n + (4.0 * k + 64.0) * n
    return out


def roofline_audit(phase_ms: dict, phase_costs: dict, n: int,
                   grid_kw: dict, platform: str | None = None) -> dict:
    """The ``roofline_audit`` block: per-phase modeled vs XLA-derived
    vs measured, with drift percentages.

    ``phase_ms`` is bench's measured per-phase ms; ``phase_costs`` maps
    phase name -> :class:`CostReport` (or its dict) for the SAME probe.
    ``drift_pct`` compares XLA's bytes-accessed accounting to the hand
    model (platform-lowering differences included — CPU numbers bound
    the traffic model, TPU numbers certify it); ``model_ms_v5e`` is
    the model's bandwidth-roofline projection at v5e HBM."""
    model = roofline_model_bytes(n, grid_kw)
    phases: dict[str, dict] = {}
    tot_model = tot_xla = 0.0
    xla_covered: list[str] = []
    for name, mbytes in model.items():
        row: dict[str, Any] = {"model_mb": round(mbytes / 1e6, 3)}
        row["model_ms_v5e"] = round(mbytes / (V5E_HBM_GBPS * 1e6), 4)
        cr = phase_costs.get(name)
        if cr is not None:
            crd = cr.as_dict() if isinstance(cr, CostReport) else cr
            xb = crd.get("bytes_accessed")
            if xb is not None:
                row["xla_mb"] = round(xb / 1e6, 3)
                if mbytes > 0:
                    row["drift_pct"] = round(
                        (xb - mbytes) / mbytes * 100.0, 1)
            if crd.get("flops") is not None:
                row["xla_gflops"] = round(crd["flops"] / 1e9, 4)
            if crd.get("donation_reclaimable") is not None:
                # bytes input->output aliasing could still reclaim for
                # this phase's executable (ROADMAP item 5's budget)
                row["donation_reclaimable_mb"] = round(
                    crd["donation_reclaimable"] / 1e6, 3)
            if crd.get("donation_applied") is not None:
                # ...and what donation ALREADY reclaimed (ISSUE 20):
                # could-vs-did as a pair
                row["donation_applied_mb"] = round(
                    crd["donation_applied"] / 1e6, 3)
            if crd.get("error"):
                row["cost_error"] = crd["error"]
        if name in phase_ms:
            row["measured_ms"] = phase_ms[name]
        phases[name] = row
        if name in ("aoi", "move", "collect"):  # non-overlapping total
            tot_model += mbytes
            if "xla_mb" in row:
                xla_covered.append(name)
                tot_xla += row["xla_mb"] * 1e6
    out = {
        "doc": "docs/ROOFLINE.md",
        "n": n,
        "bandwidth_gbps": V5E_HBM_GBPS,
        "platform": platform,
        "phases": phases,
        "total_model_mb": round(tot_model / 1e6, 3),
    }
    # the total drift compares LIKE FOR LIKE: only stamped when every
    # top-level phase carries XLA bytes — a partial sum against the
    # full model total would read as bogus "model overestimates" rot
    if len(xla_covered) == 3:
        out["total_xla_mb"] = round(tot_xla / 1e6, 3)
        out["total_drift_pct"] = round(
            (tot_xla - tot_model) / tot_model * 100.0, 1)
    elif xla_covered:
        out["xla_coverage_partial"] = sorted(xla_covered)
    return out


def roofline_model_bytes_multichip(n_per_chip: int, grid_kw: dict,
                                   mega_kw: dict) -> dict[str, float]:
    """The multichip hand model: PER-CHIP HBM bytes/tick of the tile
    step plus the ICI halo/migrate terms (docs/ROOFLINE.md
    "Multichip"). ``mega_kw`` needs n_dev, halo_cap, migrate_cap;
    optional mesh_shape (default 1D strips), halo_impl (default
    "ppermute"), dirty_frac (fraction of ghost rows shipping a live
    yaw word — the async packed payload's dirty-only lane; default
    1.0, the conservative all-dirty bound) and hot_attrs (default 8).

    Keys: the single-chip phase terms at the EXTENDED population
    (local + ghost rows all ride the sweep), plus ``ici_halo`` and
    ``ici_migrate`` — bytes SHIPPED per chip per tick over ICI."""
    n_dev = int(mega_kw["n_dev"])
    halo_cap = int(mega_kw["halo_cap"])
    migrate_cap = int(mega_kw["migrate_cap"])
    shape = mega_kw.get("mesh_shape") or (n_dev, 1)
    halo_impl = mega_kw.get("halo_impl", "ppermute")
    dirty_frac = float(mega_kw.get("dirty_frac", 1.0))
    attrs = int(mega_kw.get("hot_attrs", 8))
    if halo_impl not in HALO_ROW_BYTES:
        raise ValueError(f"unknown halo_impl {halo_impl!r}")

    # the AOI terms price the extended local+ghost population
    strips = 4 if shape[1] > 1 else 2
    ghost_rows = strips * halo_cap
    out = roofline_model_bytes(n_per_chip + ghost_rows, grid_kw)
    # ICI halo: every inward-facing strip ships halo_cap rows each
    # way. Under the quantized planes (grid_kw precision=q16) the row
    # narrows to the packed-xz/int16-yaw layout (HALO_ROW_BYTES_Q) —
    # the halo interplay term of ISSUE 12 (wire change staged; the
    # audit stamps both projections so the relay can arbitrate).
    q16 = grid_kw.get("precision", "off") != "off"
    row_b = (HALO_ROW_BYTES_Q if q16 else HALO_ROW_BYTES)[halo_impl]
    if halo_impl == "async":
        row_b = row_b + (HALO_ASYNC_YAW_BYTES_Q if q16
                         else HALO_ASYNC_YAW_BYTES) * dirty_frac
    out["ici_halo"] = float(strips * halo_cap) * row_b
    # ICI migrate: the all_to_all ships [n_dev, cap] rows of
    # (8 + attrs) f32 + 6 i32 each, both directions ~= one buffer out
    out["ici_migrate"] = float(n_dev * migrate_cap) \
        * ((8.0 + attrs) * 4.0 + 24.0)
    return out


def roofline_audit_multichip(tick_ms: float | None, cost, n_total: int,
                             grid_kw: dict, mega_kw: dict,
                             platform: str | None = None) -> dict:
    """The MULTICHIP artifact's ``roofline_audit`` block: per-chip
    modeled HBM phases + ICI halo/migrate terms (priced against the
    v5e ICI figure), diffed against XLA's accounting of the compiled
    mesh scan where available. Same shape contract as
    :func:`roofline_audit` (a ``phases`` dict of ``model_mb`` rows) so
    tools/bench_schema.py validates both with one rule. Also stamps
    the dirty-only packing delta: modeled ICI halo bytes under each
    halo_impl at the same dirty fraction, so the async win is visible
    in the artifact."""
    n_dev = int(mega_kw["n_dev"])
    n_per_chip = max(1, n_total // n_dev)
    model = roofline_model_bytes_multichip(n_per_chip, grid_kw, mega_kw)
    phases: dict[str, dict] = {}
    hbm_total = 0.0
    for name, mbytes in model.items():
        row: dict[str, Any] = {"model_mb": round(mbytes / 1e6, 3)}
        if name.startswith("ici_"):
            row["model_ms_v5e_ici"] = round(
                mbytes / (V5E_ICI_GBPS * 1e6), 4)
        else:
            row["model_ms_v5e"] = round(
                mbytes / (V5E_HBM_GBPS * 1e6), 4)
            if name in ("aoi", "move", "collect"):
                hbm_total += mbytes
        phases[name] = row
    out = {
        "doc": "docs/ROOFLINE.md#multichip",
        "mode": "multichip",
        "n": n_total,
        "n_devices": n_dev,
        "n_per_chip": n_per_chip,
        "bandwidth_gbps": V5E_HBM_GBPS,
        "ici_gbps": V5E_ICI_GBPS,
        "platform": platform,
        "phases": phases,
        "total_model_mb_per_chip": round(hbm_total / 1e6, 3),
    }
    if tick_ms is not None:
        out["measured_tick_ms"] = tick_ms
    if cost is not None:
        crd = cost.as_dict() if isinstance(cost, CostReport) else cost
        if crd.get("bytes_accessed") is not None:
            # whole-mesh bytes: divide by n_dev for the per-chip view
            out["xla_mb_mesh"] = round(crd["bytes_accessed"] / 1e6, 3)
            out["xla_mb_per_chip"] = round(
                crd["bytes_accessed"] / n_dev / 1e6, 3)
        if crd.get("error"):
            out["cost_error"] = crd["error"]
    # the dirty-only packing delta, made visible: ICI halo bytes under
    # both impls at this config's dirty fraction — and under both
    # precision domains (the "<impl>_q16" rows are the quantized-plane
    # projection, ISSUE 12's staged halo win)
    deltas = {}
    for impl in HALO_ROW_BYTES:
        mk = dict(mega_kw)
        mk["halo_impl"] = impl
        deltas[impl] = round(
            roofline_model_bytes_multichip(
                n_per_chip, grid_kw, mk)["ici_halo"] / 1e6, 3)
        gq = dict(grid_kw)
        gq["precision"] = "q16"
        deltas[impl + "_q16"] = round(
            roofline_model_bytes_multichip(
                n_per_chip, gq, mk)["ici_halo"] / 1e6, 3)
    out["ici_halo_mb_by_impl"] = deltas
    return out


# =======================================================================
# BENCH/MULTICHIP artifact conventions (jax-free; the ONE home for the
# round-number and wrapper parsing the trajectory tools share —
# bench_trend, bench_schema and roofline_audit must never disagree
# about which rounds have headlines)
# =======================================================================
def artifact_round(path: str) -> int:
    """Round number from a BENCH_r*/MULTICHIP_r* filename; -1 when the
    name carries none."""
    import os
    import re

    m = re.search(r"_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def artifact_headline(doc: dict) -> dict | None:
    """The stamped artifact record of one BENCH_r*.json (driver
    ``{"parsed": ...}`` wrapper or bare), or None when the round
    recorded no usable headline (failed rounds record ``parsed: null``
    honestly). Callers layer their own extra filters (e.g. the trend
    gate also drops ``timing_suspect`` headlines)."""
    rec = doc.get("parsed") if "parsed" in doc else doc
    if not isinstance(rec, dict) or not rec.get("value"):
        return None
    return rec


# =======================================================================
# histogram quantiles + SLO verdicts (jax-free; shared with the tools)
# =======================================================================
def hist_quantile(edges, counts, q: float) -> float:
    """Quantile from a fixed-bucket histogram: the UPPER edge of the
    bucket containing the q-th sample (conservative — the true value is
    <= the reported one). ``counts`` has len(edges)+1 entries (the last
    is the +Inf bucket, reported as ``inf``). NaN on an empty
    histogram."""
    total = sum(counts)
    if total <= 0:
        return float("nan")
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank:
            if i < len(edges):
                return float(edges[i])
            return float("inf")
    return float("inf")


def hist_quantile_interp(edges, counts, q: float) -> float:
    """Quantile with LINEAR INTERPOLATION inside the containing bucket
    (the Prometheus histogram_quantile estimator). The upper-edge form
    above is right for conservative SLO verdicts, but a COMPARISON of
    two quantiles (the autotune regret guard: post-swap p90 vs
    pre-swap p90) cannot live on 2x-spaced bucket edges — any
    detectable change would read as >= 2x while a within-bucket
    regression reads as 0. Interpolation keeps the estimate continuous
    as mass shifts between buckets. Still ``inf`` when the q-th sample
    sits in the +Inf bucket, NaN on an empty histogram."""
    total = sum(counts)
    if total <= 0:
        return float("nan")
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        prev_cum = cum
        cum += c
        if cum >= rank:
            if i >= len(edges):
                return float("inf")
            lo = float(edges[i - 1]) if i > 0 else 0.0
            hi = float(edges[i])
            if c <= 0:
                return hi
            return lo + (hi - lo) * (rank - prev_cum) / c
    return float("inf")


def slo_from_histogram(edges, counts, target_ms: float | None = None,
                       source: str = "histogram") -> dict:
    """{target_ms, p50/p90/p99_ms, samples, pass} from a fixed-bucket
    latency histogram. ``pass`` is conservative: percentiles are bucket
    upper bounds, so a pass means the true p99 is under target too.

    Non-finite percentiles (a sample past the last edge lands in the
    +Inf bucket; an empty histogram has none at all) are stamped as
    None with ``"overflow": true`` — ``json.dumps`` would otherwise
    emit the non-RFC ``Infinity``/``NaN`` tokens straight into the
    BENCH artifacts. Either way the verdict can only be a fail."""
    if target_ms is None:
        target_ms = DEFAULT_SLO_TARGET_MS
    total = int(sum(counts))
    p50 = hist_quantile(edges, counts, 0.50)
    p90 = hist_quantile(edges, counts, 0.90)
    p99 = hist_quantile(edges, counts, 0.99)
    ok = total > 0 and p99 <= target_ms
    out = {
        "target_ms": float(target_ms),
        "p50_ms": p50, "p90_ms": p90, "p99_ms": p99,
        "samples": total,
        "pass": bool(ok),
        "source": source,
    }
    if not all(math.isfinite(out[k])
               for k in ("p50_ms", "p90_ms", "p99_ms")):
        out["overflow"] = True
        for k in ("p50_ms", "p90_ms", "p99_ms"):
            if not math.isfinite(out[k]):
                out[k] = None
    return out


# =======================================================================
# process-local registry (served by debug_http /costs)
# =======================================================================
_lock = threading.Lock()
_reports: dict[str, dict] = {}
_providers: dict[str, Callable[[], "CostReport | dict"]] = {}
_slo: dict | None = None
_slo_target_ms: float = DEFAULT_SLO_TARGET_MS


def register_report(report: CostReport | dict,
                    name: str | None = None) -> None:
    """Record a cost report for this process's ``/costs`` endpoint."""
    d = report.as_dict() if isinstance(report, CostReport) else dict(report)
    with _lock:
        _reports[name or d.get("name", "tick")] = d


def register_provider(name: str,
                      fn: Callable[[], "CostReport | dict"]) -> None:
    """Register a LAZY cost-report provider (e.g. the World's live tick
    executable). Providers run only on ``/costs?analyze=1`` — a
    lower+compile in a live process costs seconds and must be
    operator-triggered, never scrape-triggered."""
    with _lock:
        _providers[name] = fn


def record_slo(verdict: dict) -> None:
    """Record the latest SLO verdict (bench child, or a live process)."""
    global _slo
    with _lock:
        _slo = dict(verdict)


def set_slo_target(target_ms: float) -> None:
    """Set this process's SLO budget (e.g. 1000/tick_hz in a game)."""
    global _slo_target_ms
    with _lock:
        _slo_target_ms = float(target_ms)


def _live_slo() -> dict | None:
    """SLO verdict from the live ``tick_latency_ms`` metric histogram,
    when this process serves one (game serve loop)."""
    from goworld_tpu.utils import metrics

    snap = metrics.REGISTRY.histogram_snapshot("tick_latency_ms")
    if not snap:
        return None
    # merge every labeled child into one distribution
    edges: list[float] | None = None
    counts: list[int] | None = None
    for _labels, s in snap:
        e = [u for u, _c in s["buckets"]]
        c = [cnt for _u, cnt in s["buckets"]] + [s["inf"]]
        if edges is None:
            edges, counts = e, c
        elif e == edges:
            counts = [a + b for a, b in zip(counts, c)]
    if edges is None or sum(counts) == 0:
        return None
    return slo_from_histogram(edges, counts, _slo_target_ms,
                              source="tick_latency_ms")


def snapshot(analyze: bool = False) -> dict:
    """The ``/costs`` payload: recorded reports, provider names (run
    when ``analyze``), and the freshest SLO verdict (explicitly
    recorded, else derived live from ``tick_latency_ms``)."""
    if analyze:
        with _lock:
            pending = list(_providers.items())
        for name, fn in pending:
            try:
                register_report(fn(), name=name)
            except Exception as exc:  # a provider must never 500 /costs
                register_report({"name": name,
                                 "error": str(exc)[:200]}, name=name)
    with _lock:
        out: dict = {
            "reports": dict(_reports),
            "providers": sorted(_providers),
            "slo": dict(_slo) if _slo is not None else None,
            "slo_target_ms": _slo_target_ms,
        }
    if out["slo"] is None:
        out["slo"] = _live_slo()
    return out


def reset() -> None:
    """Drop all registered state (tests)."""
    global _slo, _slo_target_ms
    with _lock:
        _reports.clear()
        _providers.clear()
        _slo = None
        _slo_target_ms = DEFAULT_SLO_TARGET_MS
