"""Unified telemetry: metrics registry + per-tick timeline recorder.

The reference engine ships opmon + expvar + pprof on every process
(``engine/binutil/binutil.go:17-75``); :mod:`opmon` rebuilds the op
table and gwvar map, but nothing gave the live serve loops the per-tick
phase attribution that ``bench.py`` produces offline. This module is
that attribution as an always-on subsystem:

* :class:`Registry` — process-wide counters, gauges and fixed-bucket
  histograms. Lock-protected, labels rendered as name suffixes
  (``name{k="v"}``), exported in Prometheus text exposition format
  (served by ``debug_http`` as ``/metrics``).
* :class:`TickTimeline` — a ring buffer of per-tick phase spans
  (drain-inputs / device-step / fetch-outputs / fan-out, with the
  jitted step's timing folded in as tick args), exportable as Chrome
  ``chrome://tracing`` / Perfetto JSON (served as ``/trace``).

Overhead budget: one span is two ``perf_counter`` calls and one tuple
append; a full game tick records ~6 spans — microseconds against the
16 ms frame (< 0.1%), so the recorder stays on unconditionally.

Metric naming scheme (see docs/OBSERVABILITY.md):
``<subsystem>_<what>_<unit|total>`` — e.g. ``tick_latency_ms``,
``aoi_overflow_total``, ``gate_packet_handle_ms``,
``dispatcher_route_total{msgtype="..."}``.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from collections import deque
from typing import Any

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "TickTimeline",
    "REGISTRY", "counter", "gauge", "histogram", "timeline",
    "DEFAULT_MS_BUCKETS", "DEFAULT_SIZE_BUCKETS",
    "parse_prometheus_text",
]

# latency buckets in milliseconds: sub-ms through the 16 ms roofline
# frame up to multi-second stalls
DEFAULT_MS_BUCKETS = (0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 33.0, 66.0,
                      133.0, 266.0, 533.0, 1066.0, 2133.0, 4266.0)
# size buckets (records per batch, queue depths, ...)
DEFAULT_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                        4096, 16384, 65536)


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats print as integers."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _render_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    """Monotonic counter (``_total`` naming convention)."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Gauge:
    """Instantaneous value (queue depths, backlog, flags)."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._v -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Fixed-bucket histogram: per-bucket counts + sum + count. Buckets
    are upper bounds; an implicit ``+Inf`` bucket catches the rest."""

    __slots__ = ("_lock", "_uppers", "_counts", "_sum", "_count")

    def __init__(self, buckets=DEFAULT_MS_BUCKETS):
        uppers = sorted(float(b) for b in buckets)
        if not uppers:
            raise ValueError("histogram needs at least one bucket")
        self._lock = threading.Lock()
        self._uppers = uppers
        self._counts = [0] * (len(uppers) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self._uppers, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def observe_n(self, v: float, n: int) -> None:
        """``n`` samples of the same value in one locked update — the
        record-weighted sync-age lanes observe one value per BATCH but
        must weight it by the records delivered (one bisect, not n)."""
        if n <= 0:
            return
        i = bisect.bisect_left(self._uppers, v)
        with self._lock:
            self._counts[i] += n
            self._sum += v * n
            self._count += n

    def add_counts(self, counts, sum_: float = 0.0) -> None:
        """Merge a pre-bucketed count vector (``len(uppers)+1``
        entries, last = +Inf) — the in-graph telemetry lanes drain
        into the live registry through this (the device accumulator
        shares the bisect_left-on-upper-edges semantics of
        ``observe``, so merged counts are bit-compatible). ``sum_``
        is optional: lanes carry no per-sample sum, so quantiles stay
        exact while the ``_sum`` series only covers host-observed
        samples."""
        if len(counts) != len(self._uppers) + 1:
            raise ValueError(
                f"count vector has {len(counts)} entries, histogram "
                f"has {len(self._uppers) + 1} buckets"
            )
        with self._lock:
            n = 0
            for i, c in enumerate(counts):
                c = int(c)
                self._counts[i] += c
                n += c
            self._count += n
            self._sum += float(sum_)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "buckets": list(zip(self._uppers, self._counts)),
                "inf": self._counts[-1],
                "sum": self._sum,
                "count": self._count,
            }

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


class _Family:
    __slots__ = ("kind", "help", "buckets", "children")

    def __init__(self, kind: str, help_: str, buckets):
        self.kind = kind
        self.help = help_
        self.buckets = buckets
        # label-key tuple -> (labels dict, metric)
        self.children: dict[tuple, tuple[dict, Any]] = {}


class Registry:
    """Process-wide metric registry. Metrics are created on first use
    and returned again on re-request (same name + labels), so call
    sites can hold direct references to the hot-path objects."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get(self, kind: str, name: str, help_: str, buckets,
             labels: dict[str, str]):
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(kind, help_, buckets)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            child = fam.children.get(key)
            if child is None:
                if kind == "counter":
                    m: Any = Counter()
                elif kind == "gauge":
                    m = Gauge()
                else:
                    m = Histogram(fam.buckets)
                child = fam.children[key] = (
                    {k: str(v) for k, v in labels.items()}, m,
                )
            return child[1]

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, None, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, None, labels)

    def histogram(self, name: str, buckets=DEFAULT_MS_BUCKETS,
                  help: str = "", **labels) -> Histogram:
        return self._get("histogram", name, help, tuple(buckets), labels)

    def expose_text(self) -> str:
        """Prometheus text exposition format, version 0.0.4."""
        out: list[str] = []
        with self._lock:
            # snapshot the children lists too: _get inserts new children
            # concurrently (e.g. the dispatcher's lazy per-msgtype route
            # counters) and dict iteration would die mid-scrape
            fams = [
                (name, fam.kind, fam.help, list(fam.children.values()))
                for name, fam in sorted(self._families.items())
            ]
        for name, kind, help_, children in fams:
            if help_:
                out.append(f"# HELP {name} {_escape(help_)}")
            out.append(f"# TYPE {name} {kind}")
            for labels, m in children:
                if kind in ("counter", "gauge"):
                    out.append(
                        f"{name}{_render_labels(labels)} {_fmt(m.value)}"
                    )
                    continue
                snap = m.snapshot()
                cum = 0
                for upper, cnt in snap["buckets"]:
                    cum += cnt
                    lb = dict(labels, le=_fmt(upper))
                    out.append(
                        f"{name}_bucket{_render_labels(lb)} {cum}"
                    )
                lb = dict(labels, le="+Inf")
                out.append(
                    f"{name}_bucket{_render_labels(lb)} {snap['count']}"
                )
                out.append(
                    f"{name}_sum{_render_labels(labels)} "
                    f"{_fmt(snap['sum'])}"
                )
                out.append(
                    f"{name}_count{_render_labels(labels)} "
                    f"{snap['count']}"
                )
        return "\n".join(out) + "\n" if out else ""

    def histogram_snapshot(self, name: str) -> list | None:
        """``[(labels, Histogram.snapshot()), ...]`` for a histogram
        family, or None when it doesn't exist (or isn't a histogram).
        The devprof ``/costs`` SLO verdict reads ``tick_latency_ms``
        through this instead of poking family internals."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam.kind != "histogram":
                return None
            children = list(fam.children.values())
        return [(dict(labels), m.snapshot()) for labels, m in children]

    def reset(self) -> None:
        """Drop every registered metric (tests)."""
        with self._lock:
            self._families.clear()


# =======================================================================
# per-tick phase timeline
# =======================================================================
class _Span:
    """``with timeline.span("device_step"): ...`` — records a phase span
    into the currently open tick. No-op when no tick is open."""

    __slots__ = ("_tl", "_name", "_args", "_t0")

    def __init__(self, tl: "TickTimeline | None", name: str, args):
        self._tl = tl
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        tl = self._tl
        if tl is None:
            return
        open_ = tl._open
        if open_ is None:
            return
        start = self._t0 - open_[1]
        open_[2].append(
            (self._name, start, time.perf_counter() - self._t0,
             self._args)
        )


_NULL_SPAN = _Span(None, "", None)


class TickTimeline:
    """Ring buffer of per-tick phase spans, exportable as Chrome trace
    JSON. One open tick at a time; the logic thread opens/closes ticks
    and records spans, any thread may snapshot (``/trace``)."""

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._recs: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # open tick: [wall_us, perf_t0, spans, args]
        self._open: list | None = None

    @property
    def is_open(self) -> bool:
        return self._open is not None

    def begin_tick(self) -> None:
        """Open a tick record; an unclosed previous tick is discarded."""
        self._open = [time.time() * 1e6, time.perf_counter(), [], {}]

    def span(self, name: str, **args) -> _Span:
        if self._open is None:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def set_tick_args(self, **kw) -> None:
        """Fold extra attribution (e.g. the jitted step's phase timing)
        into the open tick's args."""
        if self._open is not None:
            self._open[3].update(kw)

    def end_tick(self) -> float | None:
        """Close the open tick; returns its wall duration in seconds."""
        open_, self._open = self._open, None
        if open_ is None:
            return None
        dur = time.perf_counter() - open_[1]
        with self._lock:
            self._recs.append((open_[0], dur, open_[2], open_[3]))
        return dur

    def records(self) -> list:
        with self._lock:
            return list(self._recs)

    def clear(self) -> None:
        with self._lock:
            self._recs.clear()
        self._open = None

    def coverage(self) -> float:
        """Fraction of recorded tick wall time covered by phase spans
        (spans are sequential, never nested)."""
        recs = self.records()
        total = sum(r[1] for r in recs)
        if total <= 0:
            return 0.0
        covered = sum(s[2] for r in recs for s in r[2])
        return covered / total

    def chrome_trace(self, process_name: str = "goworld_tpu") -> dict:
        """Chrome ``chrome://tracing`` / Perfetto JSON object format:
        one ``tick`` umbrella event per tick (tick args attached) with
        its phase spans nested inside on the same track."""
        pid = os.getpid()
        events: list[dict] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": process_name},
        }, {
            "name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "logic"},
        }]
        for wall_us, dur, spans, args in self.records():
            events.append({
                "name": "tick", "ph": "X", "ts": wall_us,
                "dur": dur * 1e6, "pid": pid, "tid": 0,
                "args": args or {},
            })
            for name, start, sdur, sargs in spans:
                ev = {
                    "name": name, "ph": "X",
                    "ts": wall_us + start * 1e6, "dur": sdur * 1e6,
                    "pid": pid, "tid": 0,
                }
                if sargs:
                    ev["args"] = sargs
                events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def chrome_trace_json(self, process_name: str = "goworld_tpu") -> str:
        return json.dumps(self.chrome_trace(process_name))


# =======================================================================
# process-wide instances + scrape-side parsing
# =======================================================================
REGISTRY = Registry()
timeline = TickTimeline()


def counter(name: str, help: str = "", **labels) -> Counter:
    return REGISTRY.counter(name, help=help, **labels)


def gauge(name: str, help: str = "", **labels) -> Gauge:
    return REGISTRY.gauge(name, help=help, **labels)


def histogram(name: str, buckets=DEFAULT_MS_BUCKETS, help: str = "",
              **labels) -> Histogram:
    return REGISTRY.histogram(name, buckets=buckets, help=help, **labels)


def parse_prometheus_text(text: str) -> dict[str, float]:
    """Parse Prometheus text exposition into ``{series: value}`` where
    ``series`` is the name with its label suffix verbatim. Shared by
    ``tools/scrape_metrics.py``, ``cli.py status`` and the tests."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        series, _, val = line.rpartition(" ")
        try:
            out[series] = float(val)
        except ValueError:
            continue
    return out
