"""Deterministic, seeded fault-injection plane.

The reference engine's resilience story (connect-forever dispatcher
links, freeze/restore, disconnect census cleanup) is only trustworthy if
its failure branches can be *exercised on demand*. This module makes
failure a first-class, reproducible input the same way the metrics
registry made latency a first-class output: a seeded schedule of faults
injected at the transport and storage seams, every injection counted in
the metrics registry (``faults_injected_total{kind,edge}``), stamped
into the distributed-tracing span ring (``fault:<kind>`` instants on the
``faults`` track, parented to the victim packet's span when traced) and
recorded in a deterministic per-rule log served at debug-http
``/faults``.

Schedule grammar (full reference: ``docs/ROBUSTNESS.md``)::

    spec  := rule ("," rule)*
    wire  := kind ":" edge [":mt=" N] ":" prob [":" D "ms"]
             kind  = drop | dup | delay | truncate | disconnect
             edge  = src "->" dst      (role tokens or "*")
    kill  := "kill:" process "@t+" SECS "s"
    err   := "err:" subsys "." op ":" prob        subsys = kvdb | storage
    crash := "crash:" point (":" prob | "@n=" N)

Examples::

    drop:gate->dispatcher:0.05            5% of gate->dispatcher packets
    delay:game->dispatcher:mt=13:0.5:20ms delay half the client RPCs 20ms
    kill:game1@t+10s                      SIGKILL-equivalent 10s in
    err:kvdb.put:0.2                      20% of kvdb puts raise
    crash:game.tick@n=600                 die at the 600th game tick

Determinism contract: every rule owns a ``random.Random`` seeded from
``crc32(seed | rule-text)``; decisions are a pure function of the rule's
own trial counter, so two runs whose matching call sites see the same
number of trials produce **byte-identical** per-rule fault logs (the
trial indices at which each rule fired). Wall-clock enters only through
``kill:...@t+...`` timers, which log without a trial index.

Activation: :func:`install` is called at process boot (``api.run`` for
games, the CLI runners for dispatchers/gates) with the process label and
the ini ``[deployment] faults`` / ``faults_seed`` values; the
``GOWORLD_FAULTS`` / ``GOWORLD_FAULTS_SEED`` environment variables
override the ini. No spec -> the module stays inert and every hook is a
single module-bool load.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from random import Random

from goworld_tpu.utils import log, metrics

logger = log.get("faults")

# exit code used by injected kills/crashes: distinguishable from clean
# exit (0) and the freeze exit (consts.FREEZE_EXIT_CODE) in supervisor
# logs
KILL_EXIT_CODE = 86

WIRE_KINDS = ("drop", "dup", "delay", "truncate", "disconnect")

# module fast-path flag + active plane (the tracing.active idiom: hot
# call sites check one bool before touching anything else)
active = False
plane: "FaultPlane | None" = None


class InjectedFaultError(ConnectionError):
    """Raised by op-fault hooks (``err:...`` rules). Subclasses
    ConnectionError so the kvdb/storage retry wrappers treat it exactly
    like a real transient backend failure."""


class FaultRule:
    """One parsed rule; owns its RNG, trial counter and fired log."""

    __slots__ = ("text", "kind", "src", "dst", "msgtype", "prob",
                 "delay_s", "target", "at_s", "subsys", "op", "point",
                 "at_n", "_rng", "trials", "fired", "_counter")

    def __init__(self, text: str):
        self.text = text
        self.kind = ""
        self.src = self.dst = "*"
        self.msgtype: int | None = None
        self.prob = 0.0
        self.delay_s = 0.0
        self.target = ""          # kill: process label
        self.at_s: float | None = None
        self.subsys = self.op = ""  # err rules
        self.point = ""           # crash rules
        self.at_n: int | None = None
        self._rng: Random | None = None
        self.trials = 0
        self.fired: list[int] = []
        self._counter: metrics.Counter | None = None

    # -- deterministic decision ----------------------------------------
    def seed_with(self, seed: int) -> None:
        self._rng = Random(zlib.crc32(f"{seed}|{self.text}".encode()))

    def decide(self) -> int | None:
        """Count one trial; return the trial index if the rule fires."""
        n = self.trials
        self.trials += 1
        if self.at_n is not None:
            hit = (n + 1) == self.at_n
        else:
            hit = self._rng.random() < self.prob
        if not hit:
            return None
        self.fired.append(n)
        return n

    def matches_edge(self, edge: str, msgtype: int) -> bool:
        if self.msgtype is not None and msgtype != self.msgtype:
            return False
        sep = edge.find("->")
        if sep < 0:
            return False
        src, dst = edge[:sep], edge[sep + 2:]
        return (self.src in ("*", src)) and (self.dst in ("*", dst))


def _parse_rule(text: str) -> FaultRule:
    r = FaultRule(text)
    kind, _, rest = text.partition(":")
    r.kind = kind
    if kind == "kill":
        # kill:<process>@t+<secs>s
        target, at, ts = rest.partition("@t+")
        if not at or not ts.endswith("s"):
            raise ValueError(f"bad kill rule {text!r} "
                             "(want kill:<proc>@t+<secs>s)")
        r.target = target
        r.at_s = float(ts[:-1])
        return r
    if kind == "crash":
        # crash:<point>:<p>  |  crash:<point>@n=<N>
        point, at, n = rest.partition("@n=")
        if at:
            r.point = point
            r.at_n = int(n)
        else:
            point, _, p = rest.rpartition(":")
            if not point:
                raise ValueError(f"bad crash rule {text!r}")
            r.point = point
            r.prob = float(p)
        return r
    if kind == "err":
        # err:<subsys>.<op>:<p>
        target, _, p = rest.rpartition(":")
        subsys, dot, op = target.partition(".")
        if not dot or subsys not in ("kvdb", "storage"):
            raise ValueError(f"bad err rule {text!r} "
                             "(want err:kvdb|storage.<op>:<p>)")
        r.subsys, r.op = subsys, op
        r.prob = float(p)
        return r
    if kind not in WIRE_KINDS:
        raise ValueError(f"unknown fault kind {kind!r} in {text!r}")
    parts = rest.split(":")
    if len(parts) < 2:
        raise ValueError(f"bad {kind} rule {text!r} "
                         f"(want {kind}:<edge>[:mt=<N>]:<p>)")
    edge = parts.pop(0)
    src, sep, dst = edge.partition("->")
    if not sep:
        raise ValueError(f"bad edge {edge!r} in {text!r} (want src->dst)")
    r.src, r.dst = src or "*", dst or "*"
    if parts and parts[0].startswith("mt="):
        r.msgtype = int(parts.pop(0)[3:])
    if not parts:
        raise ValueError(f"missing probability in {text!r}")
    r.prob = float(parts.pop(0))
    if kind == "delay":
        ms = parts.pop(0) if parts else "10ms"
        if not ms.endswith("ms"):
            raise ValueError(f"bad delay {ms!r} in {text!r} (want <N>ms)")
        r.delay_s = float(ms[:-2]) / 1e3
    if parts:
        raise ValueError(f"trailing fields {parts} in {text!r}")
    return r


def parse_schedule(spec: str) -> list[FaultRule]:
    return [_parse_rule(t.strip())
            for t in spec.split(",") if t.strip()]


class FaultPlane:
    """The per-process injection engine: parsed rules + seed + log."""

    def __init__(self, rules: list[FaultRule], seed: int,
                 process: str = ""):
        self.rules = rules
        self.seed = seed
        self.process = process
        self.injected_total = 0
        self._lock = threading.Lock()
        self._timers: list[threading.Timer] = []
        # a test can intercept kills/crashes instead of dying
        self.exit_hook = None
        self._wire_rules = [r for r in rules if r.kind in WIRE_KINDS]
        for r in rules:
            r.seed_with(seed)
            if r.kind in WIRE_KINDS:
                r._counter = metrics.counter(
                    "faults_injected_total",
                    help="injected faults by kind and edge",
                    kind=r.kind, edge=f"{r.src}->{r.dst}",
                )
            elif r.kind == "err":
                r._counter = metrics.counter(
                    "faults_injected_total",
                    kind="err", edge=f"{r.subsys}.{r.op}",
                )
            else:
                r._counter = metrics.counter(
                    "faults_injected_total",
                    kind=r.kind, edge=r.target or r.point,
                )

    # -- lifecycle ------------------------------------------------------
    def start_timers(self) -> None:
        """Arm ``kill:<proc>@t+...`` rules matching this process."""
        for r in self.rules:
            if r.kind == "kill" and r.target == self.process:
                t = threading.Timer(r.at_s, self._timed_kill, (r,))
                t.daemon = True
                t.start()
                self._timers.append(t)

    def stop(self) -> None:
        for t in self._timers:
            t.cancel()

    def _timed_kill(self, rule: FaultRule) -> None:
        with self._lock:
            rule.fired.append(-1)  # wall-clock fault: no trial index
            self.injected_total += 1
        rule._counter.inc()
        logger.error("FAULT kill: %s dies now (%s)", self.process,
                     rule.text)
        self._die()

    def _die(self) -> None:
        if self.exit_hook is not None:
            self.exit_hook()
            return
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(KILL_EXIT_CODE)

    # -- wire faults ----------------------------------------------------
    def wire_fault(self, edge: str, msgtype: int, trace_ctx=None,
                   kinds: tuple | None = None) -> FaultRule | None:
        """Consult every wire rule matching (edge, msgtype) in spec
        order; each match consumes one trial. The first rule that fires
        wins (later rules get no trial for this packet, keeping the
        whole decision stream a pure function of the seed)."""
        with self._lock:
            for r in self._wire_rules:
                if kinds is not None and r.kind not in kinds:
                    continue
                if not r.matches_edge(edge, msgtype):
                    continue
                n = r.decide()
                if n is not None:
                    self.injected_total += 1
                    self._note(r, n, edge=edge, msgtype=msgtype,
                               trace_ctx=trace_ctx)
                    return r
        return None

    # -- op faults (kvdb/storage) ---------------------------------------
    def op_fault(self, subsys: str, op: str) -> bool:
        with self._lock:
            for r in self.rules:
                if r.kind != "err" or r.subsys != subsys \
                        or r.op not in ("*", op):
                    continue
                n = r.decide()
                if n is not None:
                    self.injected_total += 1
                    self._note(r, n, edge=f"{subsys}.{op}")
                    return True
        return False

    # -- crashpoints ----------------------------------------------------
    def crash(self, point: str) -> None:
        fired = None
        with self._lock:
            for r in self.rules:
                if r.kind != "crash" or r.point != point:
                    continue
                n = r.decide()
                if n is not None:
                    self.injected_total += 1
                    self._note(r, n, edge=point)
                    fired = r
                    break
        if fired is not None:
            logger.error("FAULT crash at %r (%s)", point, fired.text)
            self._die()

    # -- observability --------------------------------------------------
    def _note(self, rule: FaultRule, trial: int, edge: str = "",
              msgtype: int | None = None, trace_ctx=None) -> None:
        """Count + trace-stamp one injection (lock held by caller)."""
        rule._counter.inc()
        # stamp the span ring so /trace exports show the injection as a
        # zero-duration instant; parent it to the victim packet's span
        # when the packet was traced
        from goworld_tpu.utils import tracing

        ctx = (trace_ctx.child() if trace_ctx is not None
               else tracing.new_trace())
        args = {"rule": rule.text, "trial": trial}
        if msgtype is not None:
            args["msgtype"] = msgtype
        tracing.recorder.record(
            f"fault:{rule.kind}", f"faults:{self.process or edge}", ctx,
            trace_ctx.span_hex if trace_ctx is not None else None,
            time.time() * 1e6, 0.0, args,
        )

    def log_lines(self) -> list[str]:
        """Deterministic per-rule fault log: one line per rule in spec
        order listing the trial indices that fired (``-1`` marks a
        wall-clock kill). Byte-identical across runs with the same seed
        and per-rule trial counts."""
        with self._lock:
            return [
                f"{r.text} -> "
                + ",".join(str(n) for n in r.fired)
                for r in self.rules
            ]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "active": True,
                "process": self.process,
                "seed": self.seed,
                "injected_total": self.injected_total,
                "rules": [
                    {"rule": r.text, "trials": r.trials,
                     "fired": list(r.fired)}
                    for r in self.rules
                ],
            }


# =======================================================================
# module-level install + hooks (the call-site API)
# =======================================================================
def install(process: str, spec: str = "", seed: int = 0,
            ) -> FaultPlane | None:
    """Install the process-wide plane from an ini spec, overridable by
    ``GOWORLD_FAULTS`` / ``GOWORLD_FAULTS_SEED``. Returns None (and
    leaves the module inert) when no spec is configured anywhere."""
    global active, plane
    env_spec = os.environ.get("GOWORLD_FAULTS")
    if env_spec is not None:
        spec = env_spec
    env_seed = os.environ.get("GOWORLD_FAULTS_SEED")
    if env_seed:
        seed = int(env_seed)
    if not spec.strip():
        return None
    plane = FaultPlane(parse_schedule(spec), seed, process=process)
    active = True
    plane.start_timers()
    logger.warning(
        "fault injection ACTIVE in %s: seed=%d spec=%s", process, seed,
        spec,
    )
    return plane


def uninstall() -> None:
    """Deactivate (tests)."""
    global active, plane
    if plane is not None:
        plane.stop()
    plane = None
    active = False


def maybe_op_fault(subsys: str, op: str) -> None:
    """kvdb/storage op seam: raise a transient error when an ``err``
    rule fires. One module-bool load when inert."""
    if active and plane is not None and plane.op_fault(subsys, op):
        raise InjectedFaultError(
            f"injected {subsys}.{op} fault (seed {plane.seed})"
        )


def maybe_crash(point: str) -> None:
    """Named crashpoint (e.g. ``freeze.write``, ``game.tick``): the
    process dies here when a ``crash`` rule fires."""
    if active and plane is not None:
        plane.crash(point)


def kcp_loss_hook(edge: str):
    """Datagram-level injection for the KCP (reliable-UDP) edge: returns
    a ``loss_hook(datagram) -> bool`` for :mod:`goworld_tpu.net.kcp`
    (True = drop this datagram), or None when inert or no drop rule
    matches the edge. KCP retransmits, so drops here exercise the ARQ
    path rather than losing packets outright."""
    if not active or plane is None:
        return None
    if not any(r.kind == "drop" and r.matches_edge(edge, 0)
               for r in plane._wire_rules):
        return None

    def hook(_datagram: bytes) -> bool:
        # re-read the module global: the gate captures this hook once,
        # but uninstall() (tests) may clear the plane while KCP
        # sessions are still sending
        p = plane
        if not active or p is None:
            return False
        return p.wire_fault(edge, 0, kinds=("drop",)) is not None

    return hook


def snapshot() -> dict:
    """debug-http ``/faults`` payload."""
    if not active or plane is None:
        return {"active": False}
    s = plane.snapshot()
    s["log"] = plane.log_lines()
    return s
