"""Runtime utilities: ids, logging, constants, config.

TPU-native rebuild of the reference's ``engine/{common,uuid,gwlog,consts,
config}`` utility layer.
"""
