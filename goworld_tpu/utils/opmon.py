"""Operation monitor — in-process op latency/count accounting.

Reference being rebuilt: ``engine/opmon`` (``opmon.go:37-118``): named
operations record count / cumulative time / max time; a periodic dump logs
the table; ops exceeding a warn threshold log immediately. Used by the gate
around packet handling (``GateService.go:435-442``) and by storage ops
(``storage.go:165``). Also covers ``engine/gwvar`` (expvar flags): instead
of an HTTP expvar page, :func:`expose`/:func:`vars` give a process-wide
string->value map that the CLI ``status`` and tests can read.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from goworld_tpu.utils import log

logger = log.get("opmon")

_WARN_THRESHOLD = 0.120  # seconds (reference consts.OPMON_WARN 120ms-ish)


class _OpStat:
    __slots__ = ("count", "total", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0


class Monitor:
    """Process-wide op stats. One global instance (:data:`monitor`), plus
    per-subsystem instances where isolation helps tests."""

    def __init__(self, warn_threshold: float = _WARN_THRESHOLD):
        self._stats: dict[str, _OpStat] = {}
        self._lock = threading.Lock()
        self.warn_threshold = warn_threshold

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _OpStat()
            st.count += 1
            st.total += seconds
            if seconds > st.max:
                st.max = seconds
        if seconds > self.warn_threshold:
            logger.warning("op %s took %.1f ms", name, seconds * 1e3)

    def op(self, name: str) -> "_Op":
        """``with monitor.op("handle_packet"): ...``"""
        return _Op(self, name)

    def snapshot(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "count": st.count,
                    "avg_ms": (st.total / st.count * 1e3) if st.count else 0.0,
                    "max_ms": st.max * 1e3,
                }
                for name, st in self._stats.items()
            }

    def dump(self) -> None:
        """Reference's periodic dump (``opmon.go:92-118``)."""
        for name, row in sorted(self.snapshot().items()):
            logger.info(
                "op %-32s count=%-8d avg=%.2fms max=%.2fms",
                name, row["count"], row["avg_ms"], row["max_ms"],
            )

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


class _Op:
    __slots__ = ("_mon", "_name", "_t0")

    def __init__(self, mon: Monitor, name: str):
        self._mon = mon
        self._name = name

    def __enter__(self) -> "_Op":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._mon.record(self._name, time.perf_counter() - self._t0)


monitor = Monitor()


# -----------------------------------------------------------------------
# gwvar-style exposed variables (reference engine/gwvar/gwvar.go:1-29)
# -----------------------------------------------------------------------
_vars: dict[str, Any] = {}
_vars_lock = threading.Lock()


def expose(name: str, value: Any) -> None:
    with _vars_lock:
        _vars[name] = value


def vars() -> dict[str, Any]:
    with _vars_lock:
        return dict(_vars)
