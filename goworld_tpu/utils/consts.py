"""Framework-wide tunables.

Reference parity: ``engine/consts/consts.go:7-113`` centralises every
compile-time tunable (tick intervals, buffer sizes, queue caps, timeouts,
debug switches). We keep the same idea — one module, documented values —
with TPU-specific additions (kernel capacity caps).
"""

# --- tick / timing ------------------------------------------------------
TICK_HZ = 60                      # device tick rate target (reference games
                                  # tick timers every 5ms, position sync every
                                  # 100ms; our device tick subsumes both)
HOST_TICK_INTERVAL = 0.005        # host service loop resolution (consts.go:32)
POSITION_SYNC_INTERVAL_MS = 100   # client<->server sync cadence default
                                  # (goworld.ini.sample:50,75)

# --- kernel capacity defaults ------------------------------------------
DEFAULT_CAPACITY = 16384          # entity slots per space shard
DEFAULT_MAX_NEIGHBORS = 64        # K: AOI interest cap per entity
DEFAULT_CELL_CAP = 32             # max candidates considered per grid cell
DEFAULT_EVENT_CAP = 4096          # enter/leave events surfaced per tick
DEFAULT_SYNC_CAP = 16384          # sync records surfaced per tick
DEFAULT_INPUT_CAP = 4096          # client position-sync inputs per tick
DEFAULT_ROW_BLOCK = 32768         # AOI row-block size (memory ceiling knob)
# The ONE source of truth for the AOI sweep/top-k implementation
# defaults. GridSpec (kernel level), GameConfig.aoi_* (ini level) and
# bench.py all draw from here so a direct GridSpec user gets the same
# measured-winner config the production stack runs (r4 A/B: "ranges"
# beat "table" by ~18% on CPU and is fidelity-identical-or-better —
# its pooled 3*cell_cap triple cap only ever ADMITS candidates the
# per-cell cap dropped; "sort" ranking is exact under every workload
# and was ~2.5x the generic int32 lax.top_k on both platforms).
DEFAULT_SWEEP_IMPL = "ranges"
DEFAULT_TOPK_IMPL = "sort"
# Front-half cell-sort lowering (GridSpec.sort_impl): "argsort" is the
# XLA sort; "counting" is the two-pass counting sort (ops/sort.py) that
# deletes the bitonic network — the roofline's dominant HBM term at 1M
# (docs/ROOFLINE.md); "pallas" is its kernel form (interpret-validated,
# TPU lowering staged). Default stays "argsort" pending a CPU/TPU
# measurement; bench autotune A/Bs "counting" every run.
DEFAULT_SORT_IMPL = "argsort"
# Verlet skin width (GridSpec.skin): 0 disables front-half reuse. The
# library default is OFF — the skin trades cache memory (N x verlet_cap
# i32) and a knob for skipping the whole front half + window fetch on
# ticks where nothing moved more than skin/2; workloads opt in via
# [gameN] aoi_skin or BENCH_SKIN with a value matched to their movement
# speed (rebuild cadence ~ skin / (2 * speed * dt)).
DEFAULT_AOI_SKIN = 0.0
# Quantized state planes (GridSpec.precision, ISSUE 12 / ROADMAP 3):
# "off" keeps today's all-f32 streams bit-identically; "q16" snaps the
# AOI-visible positions to a POWER-OF-TWO lattice sized so one axis
# fits int16 (<= 2^PRECISION_POS_BITS lattice points) and threads
# int16/bf16 planes through the byte-heavy paths (packed sorted view,
# packed Verlet candidate cache, bf16 velocity, delta sync, delta
# snapshots). Exactness is by construction, not by tolerance: the
# lattice step is a power of two and the cell size a power-of-two
# multiple of it, so every quantized coordinate, difference and cell
# index is EXACT in both the int16 and f32 domains — the quantized
# sweep is bit-identical to the f32 sweep over the snapped positions,
# and the oracle over snapped positions gates exactness like every
# other parity suite (docs/ROOFLINE.md "Quantized state planes").
DEFAULT_PRECISION = "off"
PRECISION_POS_BITS = 15

# Packed-key id width (ops/aoi.py _ID_BITS draws from here): slot ids
# share an int32 with the quantized distance, so the packed fast paths
# (single-array front sort, shift sweep, Verlet reuse) require
# n < 2^AOI_ID_BITS. One source of truth for every n-bound guard —
# core/step.py's verlet dispatch and bench.py's (jax-free parent)
# phase probes mirror the same bound.
AOI_ID_BITS = 21

# --- queues / backpressure (reference consts.go:26-28) -----------------
MAX_PENDING_PACKETS_PER_GAME = 1_000_000
MAX_PENDING_PACKETS_PER_ENTITY = 1_000
# reconnect pend queue budget (net/cluster.py DispatcherConn._pending):
# packets queued while a dispatcher link is down, drop-OLDEST beyond
# either bound (counted in cluster_pend_dropped_total). Overridable per
# game/gate via the ini pend_max_packets / pend_max_bytes keys.
MAX_RECONNECT_PEND_PACKETS = 65_536
MAX_RECONNECT_PEND_BYTES = 32 << 20

# --- overload protection (utils/overload.py; docs/ROBUSTNESS.md) -------
# governor hysteresis: consecutive pressured observations to climb one
# ladder rung / consecutive calm observations to descend one
OVERLOAD_UP_TICKS = 8
OVERLOAD_DOWN_TICKS = 120
# tick wall time / tick_interval that counts as pressure (1.0 = the
# loop exactly misses its cadence; 1.5 leaves headroom for one-off GC
# or compile stalls)
OVERLOAD_LATENCY_RATIO = 1.5
OVERLOAD_BACKLOG_ENTER = 2.0
# per-class ingress queue caps for the bounded (sheddable) classes;
# critical/rpc use MAX_PENDING_PACKETS_PER_GAME as an OOM backstop
OVERLOAD_QUEUE_CAP_SYNC = 65_536
OVERLOAD_QUEUE_CAP_EVENTS = 65_536
OVERLOAD_QUEUE_CAP_NOISE = 4_096
# DEGRADED fan-out degradation: sync every Nth tick per entity cohort,
# flush client event/sync bundles every Nth tick (bigger batches)
DEGRADED_SYNC_STRIDE = 4
DEGRADED_EVENT_COALESCE_TICKS = 2
# gate admission: per-client downstream buffer budget; a client whose
# socket stays full past the kick window is disconnected
GATE_DOWNSTREAM_MAX_BYTES = 4 << 20
GATE_DOWNSTREAM_KICK_SECS = 10.0
# dispatcher per-game pend queue byte budget (packet budget is
# MAX_PENDING_PACKETS_PER_GAME)
MAX_PENDING_BYTES_PER_GAME = 64 << 20
# circuit breakers around kvdb/storage backends
CIRCUIT_FAILURE_THRESHOLD = 5
CIRCUIT_RESET_TIMEOUT = 5.0

# --- timeouts (reference consts.go:58-64) ------------------------------
MIGRATE_TIMEOUT = 60.0
LOAD_TIMEOUT = 60.0
FREEZE_BLOCK_TIMEOUT = 10.0

# --- persistence ---------------------------------------------------------
DEFAULT_SAVE_INTERVAL = 300.0     # reference read_config.go:28 (5 min)

# --- debug switches (reference consts.go:76-89) ------------------------
DEBUG_PACKETS = False
DEBUG_SPACES = False
OPTIMIZE_LOCAL_ENTITY_CALL = True  # set False in tests to force the full
                                   # routed path (reference consts.go:7)

# --- networking ----------------------------------------------------------
SUPERVISOR_STARTED_TAG = "GOWORLD_TPU_PROCESS_STARTED"  # consts.go:108-112
FREEZE_EXIT_CODE = 23  # game exited via freeze; CLI restarts with -restore

# Dispatcher game-ids for multihost FOLLOWER controllers: the logical
# game keeps its gid (leader), followers get base + gid*64 + rank so
# their connections don't collide with real game ids (u16 wire field)
MH_FOLLOWER_GAME_ID_BASE = 30000
