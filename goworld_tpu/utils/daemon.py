"""Self-daemonization (reference ``engine/binutil/unix.go:12-29`` wraps
sevlyar/go-daemon for the per-process ``-d`` flag).

The classic UNIX double-fork: detach from the controlling terminal, start
a new session, and redirect stdio to a logfile so the supervisor STARTED
tag (``consts.SUPERVISOR_STARTED_TAG``) still lands somewhere the ops CLI
can poll. The ``goworld_tpu start`` CLI already detaches its children via
``start_new_session``; ``-d`` is for running a single process by hand.
"""

from __future__ import annotations

import os
import sys


def daemonize(logfile: str | None = None) -> None:
    """Fork into the background. Returns only in the daemon process."""
    if os.fork() > 0:
        os._exit(0)  # first parent exits
    os.setsid()
    if os.fork() > 0:
        os._exit(0)  # first child exits; grandchild has no session tty
    sys.stdout.flush()
    sys.stderr.flush()
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)
    os.close(devnull)
    out = os.open(
        logfile, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
    ) if logfile else os.open(os.devnull, os.O_WRONLY)
    os.dup2(out, 1)
    os.dup2(out, 2)
    os.close(out)
