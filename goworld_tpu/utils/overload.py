"""Overload-protection plane: admission control, prioritized
backpressure and load shedding.

The fault plane (:mod:`goworld_tpu.utils.faults`) can *create* overload
— delay/dup storms, kill-restart thundering herds — but nothing in the
stack survived it gracefully: the game's backlog alarm literally
advised "shed load" with no mechanism behind it, the gate admitted
unlimited clients at unlimited rates, and a stalled downstream grew
queues without bound. This module makes degradation a **designed
ladder** instead of an OOM:

* :class:`OverloadGovernor` — a per-process state machine
  ``NORMAL → DEGRADED → SHEDDING → REJECTING`` driven by measured
  signals (tick latency vs ``tick_interval``, backlog ticks, queue
  depth fractions, reconnect-pend fractions) with hysteresis so it
  never flaps. The decision is a **pure function of the observation
  sequence**: two runs fed identical signal streams produce
  byte-identical transition logs (the seeded-replay property the fault
  plane already has).
* **Traffic classes** — every wire msgtype maps to one of five
  priority classes; shedding drops the cheapest class first and
  *never* touches correctness-critical classes (migration /
  persistence / control / RPC).
* :class:`ClassQueues` — bounded priority queues for the game ingress:
  the pump drains highest-priority first, overflow evicts only within
  the overflowing class, every drop counted.
* :class:`TokenBucket` — per-client packet/byte rate limiting at the
  gate edge (deterministic under an injected clock).
* :class:`CircuitBreaker` — wraps the kvdb/storage retry paths: after
  a failure budget the breaker opens and callers fail fast (degrading
  persistence) instead of stalling ticks on a dead backend; half-open
  probes close it again.

Observability: current state in the ``overload_state`` gauge,
transitions in ``overload_transitions_total{from,to}`` and as
zero-duration instants in the tracing span ring, per-class drops in
``shed_total{class,stage}``, all served at debug-http ``/overload``
(see docs/ROBUSTNESS.md "Overload & degradation").
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

from goworld_tpu.utils import consts, log, metrics

logger = log.get("overload")

__all__ = [
    "NORMAL", "DEGRADED", "SHEDDING", "REJECTING", "STATE_NAMES",
    "state_rank",
    "CLASS_CRITICAL", "CLASS_RPC", "CLASS_SYNC", "CLASS_EVENTS",
    "CLASS_NOISE", "CLASS_NAMES", "classify", "shed_counter",
    "OverloadGovernor", "ClassQueues", "TokenBucket", "CircuitBreaker",
    "register", "unregister", "snapshot",
]

# =======================================================================
# states
# =======================================================================
NORMAL = 0
DEGRADED = 1
SHEDDING = 2
REJECTING = 3
STATE_NAMES = ("NORMAL", "DEGRADED", "SHEDDING", "REJECTING")


def state_rank(name: str) -> int:
    """Severity rank of a governor state NAME (the scraped ``/overload``
    payload ships names, not ints). Unknown names rank as NORMAL — a
    scrape gap or version skew must never synthesize load, only miss
    it (the rebalance policy's donor test is ``rank >= DEGRADED``)."""
    try:
        return STATE_NAMES.index(str(name))
    except ValueError:
        return NORMAL

# =======================================================================
# traffic classes (priority order; LOWER number = more important)
# =======================================================================
CLASS_CRITICAL = 0   # migration / persistence / control / lifecycle
CLASS_RPC = 1        # entity RPC (server- and client-originated)
CLASS_SYNC = 2       # attr / position sync fan-out (server -> client)
CLASS_EVENTS = 3     # client-origin event streams (position spam; the
                     # client re-sends continuously, dropping self-heals)
CLASS_NOISE = 4      # heartbeats
CLASS_NAMES = ("critical", "rpc", "sync", "events", "noise")
N_CLASSES = len(CLASS_NAMES)

# the cheapest class a state sheds at ingress: packets with
# class >= floor are dropped (N_CLASSES = shed nothing). DEGRADED sheds
# nothing at ingress — it degrades by striding/coalescing fan-out.
_SHED_FLOOR = {
    NORMAL: N_CLASSES,
    DEGRADED: N_CLASSES,
    SHEDDING: CLASS_EVENTS,
    REJECTING: CLASS_SYNC,
}


def _build_class_map() -> dict[int, int]:
    from goworld_tpu.net import proto

    m: dict[int, int] = {}
    for mt in (
        # handshake / readiness / lifecycle / freeze / registry: the
        # control plane — dropping any of these wedges the cluster
        proto.MT_SET_GAME_ID, proto.MT_SET_GATE_ID,
        proto.MT_SET_GAME_ID_ACK,
        proto.MT_NOTIFY_CREATE_ENTITY, proto.MT_NOTIFY_DESTROY_ENTITY,
        proto.MT_DECLARE_SERVICE, proto.MT_UNDECLARE_SERVICE,
        proto.MT_CREATE_ENTITY_ANYWHERE, proto.MT_LOAD_ENTITY_ANYWHERE,
        proto.MT_NOTIFY_CLIENT_CONNECTED,
        proto.MT_NOTIFY_ALL_GAMES_CONNECTED,
        proto.MT_START_FREEZE_GAME, proto.MT_START_FREEZE_GAME_ACK,
        proto.MT_NOTIFY_GAME_CONNECTED, proto.MT_NOTIFY_GAME_DISCONNECTED,
        proto.MT_NOTIFY_DEPLOYMENT_READY, proto.MT_GAME_LBC_INFO,
        proto.MT_KVREG_REGISTER,
        proto.MT_GAME_READY,
    ):
        m[mt] = CLASS_CRITICAL
    for mt in (
        proto.MT_CALL_ENTITY_METHOD,
        proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT,
        # ENTITY-ADDRESSED, ORDER-SENSITIVE control shares the RPC
        # class ON PURPOSE: a higher class would let these OVERTAKE
        # the same entity's queued calls in the priority pump.
        # Migration acks jumping queued pings snapshot the migrate
        # data BEFORE those pings apply — in-flight RPCs silently
        # lost (tests/test_cross_game_migration.py caught it live);
        # a disconnect jumping the client's own queued calls fails
        # their own-client authorization (a deposit!). FIFO-with-RPCs
        # keeps the per-entity order the single-queue pump had; only
        # PROCESS-level control (handshakes, readiness, freeze,
        # kvreg) outranks RPCs.
        proto.MT_QUERY_SPACE_GAMEID_FOR_MIGRATE,
        proto.MT_QUERY_SPACE_GAMEID_FOR_MIGRATE_ACK,
        proto.MT_MIGRATE_REQUEST, proto.MT_MIGRATE_REQUEST_ACK,
        proto.MT_REAL_MIGRATE, proto.MT_CANCEL_MIGRATE,
        proto.MT_NOTIFY_CLIENT_DISCONNECTED,
        proto.MT_NOTIFY_GATE_DISCONNECTED,
        proto.MT_CALL_NIL_SPACES,
        proto.MT_CALL_FILTERED_CLIENTS,
        proto.MT_SET_CLIENT_FILTER_PROP,
        # the per-tick client event bundle carries create/destroy/attr
        # records — dropping one desyncs the client's world PERMANENTLY
        # (unlike position sync, nothing re-sends it)
        proto.MT_CLIENT_EVENTS_BATCH,
        proto.MT_CREATE_ENTITY_ON_CLIENT,
        proto.MT_DESTROY_ENTITY_ON_CLIENT,
        proto.MT_CALL_ENTITY_METHOD_ON_CLIENT,
    ):
        m[mt] = CLASS_RPC
    for mt in (
        proto.MT_SYNC_POSITION_YAW_ON_CLIENTS,
        proto.MT_NOTIFY_ATTR_CHANGE_ON_CLIENT,
        proto.MT_NOTIFY_ATTR_DEL_ON_CLIENT,
        proto.MT_UPDATE_POSITION_ON_CLIENT,
        proto.MT_UPDATE_YAW_ON_CLIENT,
    ):
        m[mt] = CLASS_SYNC
    for mt in (
        # client-origin position streams: the client re-sends at 10 Hz,
        # so a dropped batch self-heals within one sync interval
        proto.MT_SYNC_POSITION_YAW_FROM_CLIENT,
        proto.MT_CLIENT_SYNC_POSITION_YAW,
    ):
        m[mt] = CLASS_EVENTS
    m[proto.MT_HEARTBEAT] = CLASS_NOISE
    return m


_class_map: dict[int, int] | None = None


def classify(msgtype: int) -> int:
    """Traffic class for a wire msgtype. Unknown types classify as
    ``CLASS_RPC`` — never shed — so a future msgtype fails safe."""
    global _class_map
    m = _class_map
    if m is None:
        m = _class_map = _build_class_map()
    return m.get(msgtype, CLASS_RPC)


# shed counters, cached per (class, stage): the hot drop paths pay one
# dict hit + one locked increment (the dispatcher route-counter idiom)
_shed_counters: dict[tuple[int, str], metrics.Counter] = {}


def shed_counter(cls: int, stage: str) -> metrics.Counter:
    c = _shed_counters.get((cls, stage))
    if c is None:
        c = _shed_counters[(cls, stage)] = metrics.counter(
            "shed_total",
            help="packets shed by traffic class and pipeline stage",
            **{"class": CLASS_NAMES[cls], "stage": stage},
        )
    return c


def shed_snapshot() -> dict[str, float]:
    """Current ``shed_total`` readings keyed ``<class>/<stage>``."""
    return {
        f"{CLASS_NAMES[cls]}/{stage}": c.value
        for (cls, stage), c in sorted(_shed_counters.items())
    }


# =======================================================================
# governor
# =======================================================================
class OverloadGovernor:
    """The per-process overload state machine.

    ``observe()`` is called once per evaluation interval (the game's
    tick, the gate's flush loop) with *measured* signals. One
    observation scores 0 (calm), 1 (pressured) or ``severe_boost``
    (severely pressured) points; ``up_ticks`` consecutive pressured
    observations escalate one rung, ``down_ticks`` consecutive calm
    observations de-escalate one rung. A mixed observation (neither
    calm nor pressured — the hysteresis band) resets *both* runs, so
    the ladder holds its rung instead of flapping.

    Everything is a pure function of the observation sequence — no
    wall clock, no RNG — so equal signal streams replay identical
    transition logs (asserted by tests/test_overload.py).
    """

    def __init__(
        self,
        name: str,
        *,
        up_ticks: int = consts.OVERLOAD_UP_TICKS,
        down_ticks: int = consts.OVERLOAD_DOWN_TICKS,
        latency_ratio: float = consts.OVERLOAD_LATENCY_RATIO,
        backlog_enter: float = consts.OVERLOAD_BACKLOG_ENTER,
        queue_frac_enter: float = 0.5,
        severe_boost: int = 4,
        on_transition: Callable[[int, int, str], None] | None = None,
    ):
        self.name = name
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.latency_ratio = float(latency_ratio)
        self.backlog_enter = float(backlog_enter)
        self.queue_frac_enter = float(queue_frac_enter)
        self.severe_boost = max(1, int(severe_boost))
        self.on_transition = on_transition
        self.state = NORMAL
        self.obs_count = 0
        self._up_score = 0
        self._down_run = 0
        # (obs index, from, to, reason) — deterministic transition log
        self.transitions: list[tuple[int, int, int, str]] = []
        self._m_state = metrics.gauge(
            "overload_state",
            help="overload ladder rung: 0=NORMAL 1=DEGRADED "
                 "2=SHEDDING 3=REJECTING",
            process=name,
        )
        self._m_trans: dict[tuple[int, int], metrics.Counter] = {}
        self._m_state.set(NORMAL)

    # -- classification of one observation ------------------------------
    def _pressure(self, latency_ratio: float, backlog_ticks: float,
                  queue_frac: float, pend_frac: float) -> int:
        """0 = calm, 1 = pressured, severe_boost = severely pressured."""
        severe = (
            latency_ratio >= 2.0 * self.latency_ratio
            or backlog_ticks >= 4.0 * self.backlog_enter
            or queue_frac >= 0.9
            or pend_frac >= 0.9
        )
        if severe:
            return self.severe_boost
        pressured = (
            latency_ratio >= self.latency_ratio
            or backlog_ticks >= self.backlog_enter
            or queue_frac >= self.queue_frac_enter
            or pend_frac >= self.queue_frac_enter
        )
        if pressured:
            return 1
        # calm needs headroom BELOW the enter thresholds (hysteresis
        # band): between calm and pressured the ladder holds its rung
        calm = (
            latency_ratio < 0.6 * self.latency_ratio
            and backlog_ticks < 0.5 * self.backlog_enter
            and queue_frac < 0.5 * self.queue_frac_enter
            and pend_frac < 0.5 * self.queue_frac_enter
        )
        return 0 if calm else -1  # -1 = hysteresis band

    def observe(self, latency_ratio: float, backlog_ticks: float = 0.0,
                queue_frac: float = 0.0, pend_frac: float = 0.0) -> int:
        """Feed one evaluation's signals; returns the (possibly new)
        state."""
        n = self.obs_count
        self.obs_count = n + 1
        p = self._pressure(latency_ratio, backlog_ticks, queue_frac,
                           pend_frac)
        if p > 0:
            self._up_score += p
            self._down_run = 0
            if self._up_score >= self.up_ticks and self.state < REJECTING:
                self._transition(
                    n, self.state + 1,
                    f"pressure {self._up_score}/{self.up_ticks} "
                    f"(lat={latency_ratio:.2f}x backlog={backlog_ticks:.1f}"
                    f" q={queue_frac:.2f} pend={pend_frac:.2f})",
                )
                self._up_score = 0
        elif p == 0:
            self._down_run += 1
            self._up_score = 0
            if self._down_run >= self.down_ticks and self.state > NORMAL:
                self._transition(
                    n, self.state - 1,
                    f"calm {self._down_run}/{self.down_ticks}",
                )
                self._down_run = 0
        else:  # hysteresis band: hold the rung, reset both runs
            self._up_score = 0
            self._down_run = 0
        return self.state

    def _transition(self, obs: int, to: int, reason: str) -> None:
        frm = self.state
        self.state = to
        self.transitions.append((obs, frm, to, reason))
        self._m_state.set(to)
        c = self._m_trans.get((frm, to))
        if c is None:
            c = self._m_trans[(frm, to)] = metrics.counter(
                "overload_transitions_total",
                help="overload ladder transitions",
                process=self.name,
                **{"from": STATE_NAMES[frm], "to": STATE_NAMES[to]},
            )
        c.inc()
        logger.warning(
            "%s: overload %s -> %s at obs %d (%s)",
            self.name, STATE_NAMES[frm], STATE_NAMES[to], obs, reason,
        )
        # stamp the span ring so /trace shows the transition instant
        # alongside the tick spans and fault instants
        from goworld_tpu.utils import tracing

        tracing.recorder.record(
            f"overload:{STATE_NAMES[frm]}->{STATE_NAMES[to]}",
            f"overload:{self.name}", tracing.new_trace(), None,
            time.time() * 1e6, 0.0, {"obs": obs, "reason": reason},
        )
        if self.on_transition is not None:
            self.on_transition(frm, to, reason)

    # -- queries ---------------------------------------------------------
    @property
    def state_name(self) -> str:
        return STATE_NAMES[self.state]

    def shed_floor(self) -> int:
        """Cheapest class shed at ingress in the current state
        (``N_CLASSES`` = shed nothing)."""
        return _SHED_FLOOR[self.state]

    def should_shed(self, cls: int) -> bool:
        return cls >= _SHED_FLOOR[self.state]

    def log_lines(self) -> list[str]:
        """Deterministic transition log: one line per transition. Equal
        observation streams produce byte-identical logs."""
        return [
            f"#{obs} {STATE_NAMES[frm]}->{STATE_NAMES[to]} {reason}"
            for obs, frm, to, reason in self.transitions
        ]

    def snapshot(self) -> dict[str, Any]:
        return {
            "state": self.state_name,
            "observations": self.obs_count,
            "up_score": self._up_score,
            "down_run": self._down_run,
            "transitions": self.log_lines(),
        }


# =======================================================================
# bounded priority queues (game ingress)
# =======================================================================
class ClassQueues:
    """Per-class bounded FIFO queues drained in priority order.

    The network thread appends, the logic thread drains —
    ``deque.append`` / ``popleft`` are GIL-atomic, so no lock is needed
    on the hot path (the idiom the old single ``queue.Queue`` relied on
    too). Overflow drops the *incoming* packet of the overflowing class
    (bounds are per class, so a sync flood can never evict an RPC) and
    counts it in ``shed_total{class,stage}``.
    """

    def __init__(self, bounds: dict[int, int] | None = None,
                 stage: str = "game_queue"):
        b = {
            CLASS_CRITICAL: consts.MAX_PENDING_PACKETS_PER_GAME,
            CLASS_RPC: consts.MAX_PENDING_PACKETS_PER_GAME,
            CLASS_SYNC: consts.OVERLOAD_QUEUE_CAP_SYNC,
            CLASS_EVENTS: consts.OVERLOAD_QUEUE_CAP_EVENTS,
            CLASS_NOISE: consts.OVERLOAD_QUEUE_CAP_NOISE,
        }
        if bounds:
            b.update(bounds)
        self.bounds = b
        self.stage = stage
        self._qs: tuple[deque, ...] = tuple(
            deque() for _ in range(N_CLASSES)
        )

    def offer(self, cls: int, item: Any) -> bool:
        """Enqueue; False (and a counted drop) when the class is full."""
        q = self._qs[cls]
        if len(q) >= self.bounds[cls]:
            shed_counter(cls, self.stage).inc()
            return False
        q.append(item)
        return True

    def drain(self) -> "list[Any]":
        """Pop everything, highest priority class first (within a
        class, FIFO)."""
        out: list[Any] = []
        for q in self._qs:
            while True:
                try:
                    out.append(q.popleft())
                except IndexError:
                    break
        return out

    def pop(self) -> Any:
        """Pop one item from the highest-priority non-empty class;
        raises IndexError when empty."""
        for q in self._qs:
            try:
                return q.popleft()
            except IndexError:
                continue
        raise IndexError("all class queues empty")

    def qsize(self) -> int:
        return sum(len(q) for q in self._qs)

    def depth_frac(self) -> float:
        """Worst per-class fullness fraction across the BOUNDED classes
        (the unbounded-ish critical/rpc classes are excluded — their
        bound exists only as an OOM backstop)."""
        worst = 0.0
        for cls in (CLASS_SYNC, CLASS_EVENTS, CLASS_NOISE):
            bound = self.bounds[cls]
            if bound > 0:
                worst = max(worst, len(self._qs[cls]) / bound)
        return worst


# =======================================================================
# token bucket (gate admission)
# =======================================================================
class TokenBucket:
    """Classic token bucket; ``rate`` tokens/s refill up to ``burst``.
    ``clock`` is injectable for deterministic tests. ``rate <= 0``
    disables (always allows)."""

    __slots__ = ("rate", "burst", "_tokens", "_t", "_clock")

    def __init__(self, rate: float, burst: float | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None
                           else max(1.0, rate))
        self._tokens = self.burst
        self._clock = clock
        self._t = clock()

    def allow(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._t) * self.rate
        )
        self._t = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


# =======================================================================
# circuit breaker (kvdb / storage)
# =======================================================================
class CircuitBreaker:
    """Failure-budget breaker: ``failure_threshold`` consecutive
    failures open it; while open, ``allow()`` fails fast until
    ``reset_timeout`` elapses, then ONE half-open probe is let through
    — its success closes the breaker, its failure re-opens (and
    re-arms the timeout). Thread-safe (the kvdb worker and storage
    thread race the logic thread's snapshot reads)."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, name: str, *, failure_threshold: int = 5,
                 reset_timeout: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout = float(reset_timeout)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._probe_started = 0.0
        self._m_state = metrics.gauge(
            "circuit_state",
            help="circuit breaker: 0=closed 1=open 0.5=half-open",
            breaker=name,
        )
        self._m_opened = metrics.counter(
            "circuit_open_total",
            help="times the breaker opened", breaker=name,
        )

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May an operation proceed right now? While open, exactly one
        caller per reset window gets the half-open probe."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = self._clock()
            if self._state == self.OPEN:
                if now - self._opened_at >= self.reset_timeout:
                    self._state = self.HALF_OPEN
                    self._probing = True
                    self._probe_started = now
                    self._m_state.set(0.5)
                    return True
                return False
            # HALF_OPEN: one probe in flight holds everyone else — but
            # a probe that never reported back (caller crashed without
            # record_*) frees the slot after another reset window, so
            # an unsettled probe can never pin the breaker forever
            if not self._probing \
                    or now - self._probe_started >= self.reset_timeout:
                self._probing = True
                self._probe_started = now
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state != self.CLOSED:
                logger.info("circuit %s closed (probe succeeded)",
                            self.name)
            self._state = self.CLOSED
            self._failures = 0
            self._probing = False
            self._m_state.set(0.0)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == self.HALF_OPEN \
                    or (self._state == self.CLOSED
                        and self._failures >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._m_state.set(1.0)
                self._m_opened.inc()
                logger.error(
                    "circuit %s OPEN after %d failures (fail-fast for "
                    "%.1fs)", self.name, self._failures,
                    self.reset_timeout,
                )

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "failures": self._failures,
            }


class CircuitOpenError(ConnectionError):
    """Raised (or passed to callbacks) when an op is rejected fast
    because its backend's circuit breaker is open. Subclasses
    ConnectionError so existing error paths treat it like any backend
    failure — minus the stall."""


# =======================================================================
# process-wide registry (debug-http /overload)
# =======================================================================
_governors: dict[str, OverloadGovernor] = {}
_breakers: dict[str, CircuitBreaker] = {}


def register(gov: OverloadGovernor) -> OverloadGovernor:
    _governors[gov.name] = gov
    return gov


def unregister(name: str) -> None:
    _governors.pop(name, None)


def register_breaker(br: CircuitBreaker) -> CircuitBreaker:
    _breakers[br.name] = br
    return br


def snapshot() -> dict[str, Any]:
    """debug-http ``/overload`` payload."""
    return {
        "governors": {n: g.snapshot() for n, g in _governors.items()},
        "breakers": {n: b.snapshot() for n, b in _breakers.items()},
        "shed": shed_snapshot(),
        "classes": dict(zip(CLASS_NAMES, range(N_CLASSES))),
    }
