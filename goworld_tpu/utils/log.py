"""Leveled logging with per-process source tags.

Reference parity: ``engine/gwlog`` (zap-based; level from config/flag,
stderr + file, per-process source tag like ``game1``, ``TraceError`` dumps a
stack — ``gwlog.go:47-120``, ``binutil.go:50-66``). Here: thin wrappers over
:mod:`logging` so the rest of the framework has one import point.

When distributed tracing is sampling (:mod:`goworld_tpu.utils.tracing`),
every line emitted inside a traced hop carries ``trace=<trace_id>`` so
log lines correlate with the spans in a merged cluster trace.
"""

from __future__ import annotations

import logging
import sys
import traceback

# stdlib-only module, imports nothing back from log — no cycle
from goworld_tpu.utils import tracing

_root = logging.getLogger("goworld_tpu")
_source = "?"


class _TraceIdFilter(logging.Filter):
    """Stamp ``record.trace`` with the current trace id (empty when no
    traced hop is active — the common case costs one module-bool load)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.trace = ""
        if tracing.active:
            ctx = tracing.current()
            if ctx is not None:
                record.trace = f" trace={ctx.trace_hex}"
        return True


_trace_filter = _TraceIdFilter()


def setup(source: str, level: str = "info", logfile: str | None = None) -> None:
    """Configure logging for this process. ``source`` tags every line."""
    global _source
    _source = source
    _root.setLevel(getattr(logging, level.upper(), logging.INFO))
    _root.handlers.clear()
    fmt = logging.Formatter(
        f"%(asctime)s %(levelname).1s {source} %(name)s:"
        f"%(trace)s %(message)s"
    )
    h: logging.Handler = logging.StreamHandler(sys.stderr)
    h.setFormatter(fmt)
    h.addFilter(_trace_filter)
    _root.addHandler(h)
    if logfile:
        fh = logging.FileHandler(logfile)
        fh.setFormatter(fmt)
        fh.addFilter(_trace_filter)
        _root.addHandler(fh)
    _root.propagate = False


def get(name: str) -> logging.Logger:
    return _root.getChild(name)


def trace_error(msg: str, *args) -> None:
    """Log an error with the most useful stack available (reference
    ``gwlog.TraceError``): inside an ``except`` block that is the ACTIVE
    EXCEPTION's traceback (``exc_info``), not the call site's stack —
    the previous ``format_stack()`` showed where ``trace_error`` was
    called from and lost the actual failure. Outside an except block it
    falls back to the call-site stack. The current trace id (when
    sampling) rides the normal log format via :class:`_TraceIdFilter`."""
    if sys.exc_info()[1] is not None:
        _root.error(msg, *args, exc_info=True)
        return
    _root.error(msg, *args)
    _root.error("stack:\n%s", "".join(traceback.format_stack()[:-1]))


debug = _root.debug
info = _root.info
warning = _root.warning
error = _root.error
