"""Leveled logging with per-process source tags.

Reference parity: ``engine/gwlog`` (zap-based; level from config/flag,
stderr + file, per-process source tag like ``game1``, ``TraceError`` dumps a
stack — ``gwlog.go:47-120``, ``binutil.go:50-66``). Here: thin wrappers over
:mod:`logging` so the rest of the framework has one import point.
"""

from __future__ import annotations

import logging
import sys
import traceback

_root = logging.getLogger("goworld_tpu")
_source = "?"


def setup(source: str, level: str = "info", logfile: str | None = None) -> None:
    """Configure logging for this process. ``source`` tags every line."""
    global _source
    _source = source
    _root.setLevel(getattr(logging, level.upper(), logging.INFO))
    _root.handlers.clear()
    fmt = logging.Formatter(
        f"%(asctime)s %(levelname).1s {source} %(name)s: %(message)s"
    )
    h: logging.Handler = logging.StreamHandler(sys.stderr)
    h.setFormatter(fmt)
    _root.addHandler(h)
    if logfile:
        fh = logging.FileHandler(logfile)
        fh.setFormatter(fmt)
        _root.addHandler(fh)
    _root.propagate = False


def get(name: str) -> logging.Logger:
    return _root.getChild(name)


def trace_error(msg: str, *args) -> None:
    """Log an error with a stack trace (reference ``gwlog.TraceError``)."""
    _root.error(msg, *args)
    _root.error("stack:\n%s", "".join(traceback.format_stack()[:-1]))


debug = _root.debug
info = _root.info
warning = _root.warning
error = _root.error
