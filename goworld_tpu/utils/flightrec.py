"""Incident flight recorder: bounded per-tick frame ring + triggered
snapshot bundles for post-mortems.

The observability planes so far answer "what is the process doing NOW"
(/metrics, /costs, /workload) and "what did this request do" (/trace)
— but when a live p99 blows past the tick budget at 3am, the question
is "what was happening in the ticks AROUND the breach". This module
keeps a bounded ring of per-tick correlated frames (tick latency vs
budget, overload-ladder stage, AOI oracle gauges, workload-signature
marks, resolved kernel config) and, on a trigger, freezes the ring
tail into an incident bundle served at debug-http ``/incidents`` and
scraped by ``cli.py status`` / ``tools/scrape_metrics.py``.

Triggers (the grammar — docs/OBSERVABILITY.md):

* ``slo_breach`` — the frame's measured ``tick_ms`` exceeded its
  ``budget_ms`` (the process's own tick budget, 1000/tick_hz);
* ``overload_transition`` — the governor ladder changed stage
  (detail carries ``<from>><to>``);
* ``over_cap_after_quiet`` — the AOI ``over_cap`` oracle gauge fired
  after at least ``quiet_ticks`` silent frames (a density anomaly,
  not steady-state saturation — steady overflow alarms elsewhere);
* ``signature_change`` — the live workload signature's class string
  changed (the autotuning governor's input; recorded so a post-mortem
  can correlate a breach with a workload shift);
* ``governor_swap`` — the autotune governor committed a kernel-config
  swap or regret revert this tick (``goworld_tpu/autotune``); the
  frame carries ``from->to (reason)`` and the incident context freezes
  the full decision state (policy log, regret numbers, signature);
* ``sync_age_breach`` — the end-to-end sync-age p99 of a window
  exceeded its delivery target (``sync_age_p99_ms`` >
  ``sync_age_target_ms``; gate frames, utils/syncage.py) — a client
  saw stale positions even if every device tick made its budget; the
  frame carries the per-hop breakdown (``sync_age_hops``) so the
  bundle says WHICH hop ate the budget;
* ``residency_regression`` — the serve loop's windowed host-bubble p99
  exceeded its budget (``residency_bubble_p99_ms`` >
  ``residency_bubble_budget_ms``; game frames, utils/residency.py) —
  frame time the device sat idle with no host work to show for it,
  the regression ROADMAP item 5's resident-world runtime exists to
  prevent;
* ``audit_violation`` — the correctness audit plane recorded a
  violation (``audit_violation`` frame key; game frames,
  utils/audit.py): a lost/duplicated EntityID, a sampled interest set
  diverging from the brute-force oracle, a slot/client mirror or
  ``interested_by`` edge out of sync, or a SnapshotChain CRC failure —
  the detail names the EntityID and the incident context freezes the
  ledger event tail + cohort diff;
* ``standby_promoted`` — a hot standby won its kvreg-arbitrated
  promotion claim and took over a dead primary
  (``goworld_tpu/replication/``; the ``standby_promoted`` frame key
  names game/epoch/frame-seq/tick): the bundle freezes the
  promotion-side context, pairing with the primary's bundle frozen at
  its crash;
* ``rebalance_action`` — the self-healing rebalance plane took a
  topology action this tick (``goworld_tpu/rebalance/``; the
  ``rebalance`` frame key): a bounded entity-cohort handoff started,
  completed, or aborted — the detail names the target game, cohort
  size and (on abort) the cause, so a post-mortem can line the move
  up against the overload stages that triggered it.

Every trigger kind is deduped with a per-kind cooldown so one bad
minute yields a handful of bundles, not thousands. Determinism: the
recorder is a pure function of the (frame, clock) stream — equal
streams yield byte-identical incident lists (the clock is injectable;
tests replay it).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

from goworld_tpu.utils import log, metrics

logger = log.get("flightrec")

__all__ = [
    "FlightRecorder", "register", "unregister", "get", "snapshot_all",
    "set_workload_provider", "workload_snapshot", "reset",
    "DEFAULT_RING", "DEFAULT_COOLDOWN_SECS",
]

DEFAULT_RING = 512
DEFAULT_COOLDOWN_SECS = 30.0
DEFAULT_SNAPSHOT_FRAMES = 64
DEFAULT_QUIET_TICKS = 16
DEFAULT_MAX_INCIDENTS = 32


class FlightRecorder:
    """Bounded ring of per-tick frames + trigger/dedup/snapshot logic.

    ``record(frame)`` is called once per tick from the logic thread
    with a plain dict; any other thread may ``snapshot()`` (the
    ``/incidents`` handler). ``context_fn`` (optional) is called ONLY
    at incident-freeze time and its dict is attached to the bundle —
    the hook for expensive correlation data (last trace ids, the full
    resolved kernel config) that must not be paid per tick."""

    def __init__(self, ring: int = DEFAULT_RING,
                 cooldown_secs: float = DEFAULT_COOLDOWN_SECS,
                 snapshot_frames: int = DEFAULT_SNAPSHOT_FRAMES,
                 quiet_ticks: int = DEFAULT_QUIET_TICKS,
                 max_incidents: int = DEFAULT_MAX_INCIDENTS,
                 clock: Callable[[], float] = time.monotonic,
                 context_fn: Callable[[], dict] | None = None):
        if ring < 1:
            raise ValueError(f"ring must be >= 1 (got {ring})")
        self.ring = int(ring)
        self.cooldown_secs = float(cooldown_secs)
        self.snapshot_frames = min(int(snapshot_frames), self.ring)
        self.quiet_ticks = int(quiet_ticks)
        self.clock = clock
        self.context_fn = context_fn
        self._lock = threading.Lock()
        self._frames: deque = deque(maxlen=self.ring)
        self._incidents: deque = deque(maxlen=int(max_incidents))
        self._last_fire: dict[str, float] = {}   # kind -> clock()
        self._fired: dict[str, int] = {}         # kind -> total fires
        self._suppressed: dict[str, int] = {}    # kind -> cooldown hits
        self._frames_total = 0
        self._prev_stage: str | None = None
        self._prev_sig: str | None = None
        self._quiet_run = 0  # consecutive frames with over_cap == 0
        self._m_incidents = metrics.counter(
            "flightrec_incidents_total",
            help="flight-recorder incident bundles frozen")

    # -- per-tick feed --------------------------------------------------
    def record(self, frame: dict) -> list[dict]:
        """Append one tick's frame, evaluate triggers, freeze incident
        bundles past dedup/cooldown. Returns the NEW incidents (empty
        on a quiet tick). Expected frame keys (all optional —
        triggers only evaluate what is present): ``tick``,
        ``tick_ms``, ``budget_ms``, ``stage``, ``over_cap``,
        ``over_k``, ``signature``."""
        fired: list[tuple[str, str]] = []
        with self._lock:
            tick_ms = frame.get("tick_ms")
            budget = frame.get("budget_ms")
            if tick_ms is not None and budget is not None \
                    and tick_ms > budget:
                fired.append(
                    ("slo_breach", f"{tick_ms:g} ms > {budget:g} ms"))
            stage = frame.get("stage")
            if stage is not None:
                if self._prev_stage is not None \
                        and stage != self._prev_stage:
                    fired.append(("overload_transition",
                                  f"{self._prev_stage}>{stage}"))
                self._prev_stage = stage
            over_cap = frame.get("over_cap")
            if over_cap is not None:
                if over_cap > 0:
                    if self._quiet_run >= self.quiet_ticks:
                        fired.append((
                            "over_cap_after_quiet",
                            f"over_cap={over_cap} after "
                            f"{self._quiet_run} quiet ticks"))
                    self._quiet_run = 0
                else:
                    self._quiet_run += 1
            sig = frame.get("signature")
            if sig is not None:
                if self._prev_sig is not None and sig != self._prev_sig:
                    fired.append(("signature_change",
                                  f"{self._prev_sig}>{sig}"))
                self._prev_sig = sig
            sa_p99 = frame.get("sync_age_p99_ms")
            sa_target = frame.get("sync_age_target_ms")
            if sa_p99 == "inf":
                # the JSON-safe non-finite convention (syncage.ptiles):
                # mass past the last bucket is the strongest breach
                sa_p99 = float("inf")
            if sa_p99 is not None and sa_target is not None \
                    and sa_p99 > sa_target:
                fired.append((
                    "sync_age_breach",
                    f"e2e p99 {sa_p99:g} ms > {sa_target:g} ms"))
            rb_p99 = frame.get("residency_bubble_p99_ms")
            rb_budget = frame.get("residency_bubble_budget_ms")
            if rb_p99 == "inf":
                rb_p99 = float("inf")
            if rb_p99 is not None and rb_budget is not None \
                    and rb_p99 > rb_budget:
                # the serve loop's host bubble regressed past its
                # budget: frame time the device sat idle for no reason
                # (utils/residency.py; game frames)
                fired.append((
                    "residency_regression",
                    f"bubble p99 {rb_p99:g} ms > {rb_budget:g} ms"))
            gov = frame.get("governor")
            if gov is not None:
                # the autotune governor committed a kernel-config swap
                # this tick (goworld_tpu/autotune); context_fn freezes
                # the decision context into the bundle
                fired.append(("governor_swap", str(gov)))
            av = frame.get("audit_violation")
            if av is not None:
                # the correctness audit plane recorded a violation
                # (utils/audit.py: lost/duplicated entity, oracle
                # mismatch, mirror divergence, snapshot CRC);
                # context_fn freezes the ledger tail + cohort diff
                fired.append(("audit_violation", str(av)))
            sbp = frame.get("standby_promoted")
            if sbp is not None:
                # a standby won its promotion claim and took over a
                # dead primary (goworld_tpu/replication/): the frame
                # names game/epoch/seq/tick; the bundle freezes the
                # promotion-side context (the primary's ring froze at
                # its crash — both sides of the failover keep bundles)
                fired.append(("standby_promoted", str(sbp)))
            rba = frame.get("rebalance")
            if rba is not None:
                # the rebalance plane took a topology action this tick
                # (goworld_tpu/rebalance/): a handoff started,
                # completed or aborted; the detail carries the action
                # note (target, cohort, cause) and the bundle freezes
                # the decision context around the move
                fired.append(("rebalance_action", str(rba)))
            self._frames.append(dict(frame))
            self._frames_total += 1
            new = [self._freeze(kind, detail, frame)
                   for kind, detail in fired]
            return [i for i in new if i is not None]

    def _freeze(self, kind: str, detail: str,
                frame: dict) -> dict | None:
        """Dedup/cooldown gate + bundle freeze (lock held)."""
        self._fired[kind] = self._fired.get(kind, 0) + 1
        now = self.clock()
        last = self._last_fire.get(kind)
        if last is not None and now - last < self.cooldown_secs:
            self._suppressed[kind] = self._suppressed.get(kind, 0) + 1
            return None
        self._last_fire[kind] = now
        bundle: dict[str, Any] = {
            "trigger": kind,
            "detail": detail,
            "tick": frame.get("tick"),
            "at_mono": now,
            "wall_time": time.time(),
            # the ring tail, newest last — the "what was happening
            # around it" payload
            "frames": [dict(f) for f in
                       list(self._frames)[-self.snapshot_frames:]],
        }
        if self.context_fn is not None:
            try:
                bundle["context"] = self.context_fn()
            except Exception as exc:  # context must never kill a tick
                bundle["context"] = {"error": str(exc)[:200]}
        self._incidents.append(bundle)
        self._m_incidents.inc()
        logger.warning("flight recorder incident: %s (%s) at tick %s",
                       kind, detail, frame.get("tick"))
        return bundle

    # -- observation ----------------------------------------------------
    def incidents(self) -> list[dict]:
        with self._lock:
            return [dict(i) for i in self._incidents]

    def snapshot(self, frames: bool = False) -> dict:
        """The ``/incidents`` payload for this recorder. Incident
        bundles always carry their frozen frame tails; the LIVE ring is
        included only on request (``frames=True`` / ``?frames=1``)."""
        with self._lock:
            out: dict[str, Any] = {
                "ring": self.ring,
                "frames_recorded": self._frames_total,
                "cooldown_secs": self.cooldown_secs,
                "incident_count": len(self._incidents),
                "fired": dict(self._fired),
                "suppressed": dict(self._suppressed),
                "incidents": [dict(i) for i in self._incidents],
            }
            if frames:
                out["live_frames"] = [dict(f) for f in self._frames]
            return out


# =======================================================================
# process-local registry (served by debug_http /incidents + /workload)
# =======================================================================
_reg_lock = threading.Lock()
_recorders: dict[str, FlightRecorder] = {}
_workload_provider: Callable[[], dict | None] | None = None


def register(name: str, rec: FlightRecorder) -> FlightRecorder:
    with _reg_lock:
        _recorders[name] = rec
    return rec


def unregister(name: str) -> None:
    with _reg_lock:
        _recorders.pop(name, None)


def get(name: str) -> FlightRecorder | None:
    with _reg_lock:
        return _recorders.get(name)


def snapshot_all(frames: bool = False) -> dict:
    """``/incidents``: every registered recorder's snapshot."""
    with _reg_lock:
        recs = list(_recorders.items())
    return {name: rec.snapshot(frames=frames) for name, rec in recs}


def set_workload_provider(fn: Callable[[], dict | None] | None) -> None:
    """Install the live workload-signature provider (the GameServer
    registers a weakref-backed closure; latest wins, like the devprof
    provider convention)."""
    global _workload_provider
    with _reg_lock:
        _workload_provider = fn


def workload_snapshot() -> dict:
    """``/workload``: the live signature, or an honest absence."""
    with _reg_lock:
        fn = _workload_provider
    if fn is None:
        return {"error": "no live workload provider in this process"}
    try:
        sig = fn()
    except Exception as exc:  # a provider must never 500 the endpoint
        return {"error": str(exc)[:200]}
    if not sig:
        return {"error": "no telemetry samples yet"}
    return sig


def reset() -> None:
    """Drop all registered state (tests)."""
    global _workload_provider
    with _reg_lock:
        _recorders.clear()
        _workload_provider = None
