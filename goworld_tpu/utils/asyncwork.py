"""Named async worker groups — blocking work off the logic thread.

Reference being rebuilt: ``engine/async`` (``async.go:39-109``): named
groups, each one goroutine + a 10K-slot channel; ``AppendAsyncJob`` queues a
job whose result is posted back to the main loop; ``WaitClear`` drains all
groups at terminate/freeze time.

Here each group is one daemon thread + queue; completions post back through
a caller-supplied ``post`` callable (the world's PostQueue), preserving the
single-threaded logic model.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

from goworld_tpu.utils import log

logger = log.get("async")

QUEUE_CAP = 10_000  # reference consts.go:96


class _Group:
    def __init__(self, name: str, post: Callable[[Callable], None]):
        self.name = name
        self.post = post
        self.q: "queue.Queue" = queue.Queue(maxsize=QUEUE_CAP)
        self.idle = threading.Event()
        self.idle.set()
        self.thread = threading.Thread(
            target=self._run, name=f"async-{name}", daemon=True
        )
        self.thread.start()

    def _run(self) -> None:
        while True:
            job, cb = self.q.get()
            if job is None:  # shutdown sentinel
                self.q.task_done()
                return
            self.idle.clear()
            try:
                res, err = job(), None
            except Exception as e:  # job errors go to the callback
                res, err = None, e
            if cb is not None:
                self.post(lambda cb=cb, res=res, err=err: cb(res, err))
            self.q.task_done()
            if self.q.empty():
                self.idle.set()

    def submit(self, job: Callable[[], Any],
               cb: Callable[[Any, Exception | None], None] | None) -> None:
        self.q.put((job, cb))


class AsyncWorkers:
    """All async groups of one process (reference package-level state)."""

    def __init__(self, post: Callable[[Callable], None]):
        self._post = post
        self._groups: dict[str, _Group] = {}
        self._lock = threading.Lock()

    def submit(self, group: str, job: Callable[[], Any],
               cb: Callable[[Any, Exception | None], None] | None = None,
               ) -> None:
        with self._lock:
            g = self._groups.get(group)
            if g is None:
                g = self._groups[group] = _Group(group, self._post)
        g.submit(job, cb)

    def wait_clear(self, timeout: float = 30.0) -> bool:
        """Block until every group's queue drains (reference ``WaitClear``;
        called before terminate/freeze)."""
        import time

        deadline = time.monotonic() + timeout
        for g in list(self._groups.values()):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            if not g.idle.wait(remaining):
                return False
            g.q.join()
        return True
