"""Serve-loop residency plane: what does the production tick pay that
the scan-marginal headline never sees?

Every throughput number so far is a ``lax.scan`` marginal — the device
back-to-back cost with no host in the loop. The production serve loop
(``net/game.py``) pays three hidden taxes on top of it, and before this
module nothing in the repo could measure any of them:

* **bubble_ms** — host wall time between consecutive device dispatches
  covered by NEITHER useful host work (staging flush, decode/fan-out,
  pump) NOR the blocking output fetch (device presumed busy) NOR the
  serve loop's intentional pacing sleep. A nonzero bubble is frame time
  the device sits idle for no reason — exactly what ROADMAP item 5
  (resident-world runtime: donation + D2H overlap) promises to remove.
* **alloc churn** — per-tick deltas of ``device.memory_stats()``
  bytes-in-use / num-allocs sampled every N ticks, plus a
  donation-readiness census that fingerprints the SpaceState carry's
  ``unsafe_buffer_pointer``\\ s across sampled ticks: a lane whose
  pointer changes between samples is re-allocated by XLA every tick
  (donation work to do); a lane whose pointer never moves is already
  aliased in place. The census IS the per-lane worklist the future
  ``donate_argnums`` PR consumes.
* **serve_gap** — measured serve-loop ms/tick (inter-dispatch p50) over
  the same config's scan-marginal tick cost, the headline's hidden tax
  as one ratio. The reference is ``set_scan_marginal_ms()`` when a
  bench provides it, else the tracker's own measured device-step p50
  (dispatch + blocking fetch — the closest production proxy), stamped
  honestly as ``serve_gap_ref``.

Phase lanes (``residency_phase_ms{phase=...}``; instants are host
``perf_counter`` marks riding the tick's EXISTING structure — zero
added device syncs, transfer-guard-clean, the PR-11 convention):

================  =====================================================
``pre_dispatch``  tick begin -> device dispatch (timers + staging
                  flush; useful host work)
``device_wait``   fetch begin -> outputs host-visible (the blocking
                  ``_dget``; under ``pipeline_decode`` the true stall)
``decode_fanout`` outputs host-visible -> host decode done
``host_other``    covered host work declared by the serve loop between
                  dispatches (sync fan-out flush, pump, governor, ...)
``idle``          intentional pacing sleep declared by the serve loop
``bubble``        the residual: inter-dispatch gap minus all covered
                  and idle time, clamped at zero
================  =====================================================

Plus a ``gc``-callback pause tracker for the tick thread: ONE
process-global ``gc.callbacks`` entry (installed at most once, ever —
test churn can never stack callbacks) dispatching to a weak set of
subscribed trackers, each counting only collections that ran on its
bound tick thread.

Served at debug_http ``/residency`` (weakref registry, the
syncage/devprof convention), merged by ``tools/obs_aggregate.py``,
frozen by the flight recorder's ``residency_regression`` trigger and
stamped by bench.py as the ``residency`` block (r>=16).

Jax is imported lazily and only by the census/alloc samplers; the
timing core is jax-free.
"""

from __future__ import annotations

import gc
import threading
import time
import weakref
from typing import Any

from goworld_tpu.utils import metrics
from goworld_tpu.utils.syncage import ptiles

__all__ = [
    "ResidencyTracker", "GcPauseTracker", "PHASES",
    "DEFAULT_SAMPLE_EVERY", "DEFAULT_BUBBLE_BUDGET_MS", "register",
    "unregister", "snapshot_all", "reset", "gc_callback_count",
]

PHASES = ("pre_dispatch", "device_wait", "decode_fanout", "host_other",
          "idle", "bubble")

# census + memory_stats cadence (ticks); the timing lanes are always-on
DEFAULT_SAMPLE_EVERY = 16
# bubble budget for the pass verdict + the flight-recorder trigger:
# a quarter of the paper's 16.7 ms frame sitting idle is a regression
DEFAULT_BUBBLE_BUDGET_MS = 4.0


# sentinel for a DELETED leaf (carry donation consumed it): distinct
# from None/opaque — the census counts these honestly instead of
# treating a dead buffer as uninspectable
_DELETED = object()


def _leaf_pointer(leaf):
    """Device buffer address of one pytree leaf; ``_DELETED`` when the
    buffer was consumed by donation (``unsafe_buffer_pointer`` would
    raise); None when the leaf has no inspectable buffer (sharded
    across devices, non-array, ...). Reads the address only — no
    transfer, no sync."""
    try:
        if leaf.is_deleted():
            return _DELETED
    except Exception:
        pass
    try:
        return int(leaf.unsafe_buffer_pointer())
    except Exception:
        pass
    try:  # committed/sharded arrays: fingerprint the first local shard
        return int(
            leaf.addressable_shards[0].data.unsafe_buffer_pointer())
    except Exception:
        return None


# =======================================================================
# gc pause tracking: ONE process-global callback, weakly-subscribed
# trackers. gc.callbacks entries live for the process; appending a bound
# method per tracker would both stack callbacks under test churn and pin
# every discarded tracker forever.
# =======================================================================
_gc_lock = threading.Lock()
_gc_subscribers: "weakref.WeakSet[GcPauseTracker]" = weakref.WeakSet()
_gc_installed = False


def _gc_dispatch(phase: str, info: dict) -> None:
    for t in list(_gc_subscribers):
        try:
            t._on_gc(phase)
        except Exception:
            pass  # observability must never break collection


def _gc_subscribe(tracker: "GcPauseTracker") -> None:
    global _gc_installed
    with _gc_lock:
        _gc_subscribers.add(tracker)
        if not _gc_installed:
            gc.callbacks.append(_gc_dispatch)
            _gc_installed = True


def _gc_unsubscribe(tracker: "GcPauseTracker") -> None:
    global _gc_installed
    with _gc_lock:
        _gc_subscribers.discard(tracker)
        if _gc_installed and not len(_gc_subscribers):
            try:
                gc.callbacks.remove(_gc_dispatch)
            except ValueError:
                pass
            _gc_installed = False


def gc_callback_count() -> int:
    """How many entries this module holds in ``gc.callbacks`` (tests
    assert it never exceeds 1 no matter how many trackers churn)."""
    return sum(1 for cb in gc.callbacks if cb is _gc_dispatch)


class GcPauseTracker:
    """Collector-pause accounting for ONE thread (the tick thread).
    ``install()``/``uninstall()`` are idempotent — repeated installs
    subscribe once; the module-global ``gc.callbacks`` entry is shared
    by every tracker and removed when the last one unsubscribes."""

    def __init__(self, name: str = "game"):
        self.name = name
        self._h = metrics.histogram(
            "residency_gc_pause_ms",
            help="stop-the-world gc pauses observed on the tick thread",
            tracker=name)
        self._thread: int | None = None
        self._t0: float | None = None
        self._installed = False
        self.pauses = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def bind_thread(self, ident: int | None = None) -> None:
        """Only collections running on this thread count: gc callbacks
        fire on whichever thread triggered the collection, and a pause
        on an io thread never stalls the tick."""
        self._thread = threading.get_ident() if ident is None else ident

    def install(self) -> None:
        if not self._installed:
            _gc_subscribe(self)
            self._installed = True

    def uninstall(self) -> None:
        if self._installed:
            _gc_unsubscribe(self)
            self._installed = False

    def _on_gc(self, phase: str) -> None:
        if self._thread is not None \
                and threading.get_ident() != self._thread:
            return
        if phase == "start":
            self._t0 = time.perf_counter()
        elif phase == "stop" and self._t0 is not None:
            ms = (time.perf_counter() - self._t0) * 1e3
            self._t0 = None
            self.pauses += 1
            self.total_ms += ms
            self.max_ms = max(self.max_ms, ms)
            self._h.observe(ms)

    def snapshot(self) -> dict[str, Any]:
        return {
            "pauses": self.pauses,
            "total_ms": round(self.total_ms, 3),
            "max_ms": round(self.max_ms, 3),
        }


class ResidencyTracker:
    """Per-World serve-loop residency accumulator.

    The instrumented tick calls the marks in order —
    ``tick_begin`` -> ``mark_dispatch`` -> ``mark_fetch`` ->
    ``mark_visible`` -> ``mark_decode_done`` — and the serve loop
    declares its own covered work (``add_host``) and pacing sleep
    (``add_idle``) between dispatches. ``mark_dispatch`` closes the
    previous inter-dispatch gap: whatever the declared covered + idle
    time does not explain is the bubble. All marks are
    ``perf_counter`` reads + histogram inserts; nothing touches the
    device.
    """

    def __init__(self, name: str = "game", *,
                 sample_every: int = DEFAULT_SAMPLE_EVERY,
                 bubble_budget_ms: float = DEFAULT_BUBBLE_BUDGET_MS):
        sample_every = int(sample_every)
        if sample_every < 1:
            raise ValueError(
                f"residency_sample_every must be >= 1, got "
                f"{sample_every} (1 samples the census/memory stats "
                f"every tick; large values only stretch the cadence)")
        self.name = name
        self.sample_every = sample_every
        self.bubble_budget_ms = float(bubble_budget_ms)
        self._h_tick = metrics.histogram(
            "residency_tick_ms",
            help="serve-loop inter-dispatch gap (measured ms/tick)",
            tracker=name)
        self._h_bubble = metrics.histogram(
            "residency_bubble_ms",
            help="inter-dispatch host time covered by neither useful "
                 "host work nor device wait nor intentional idle",
            tracker=name)
        self._h_devstep = metrics.histogram(
            "residency_device_step_ms",
            help="dispatch + blocking fetch per tick (production "
                 "proxy for the device marginal)",
            tracker=name)
        self._h_phase = {
            p: metrics.histogram(
                "residency_phase_ms",
                help="serve-loop phase residence per tick",
                tracker=name, phase=p)
            for p in PHASES
        }
        self.gc = GcPauseTracker(name)
        self._lock = threading.Lock()
        # per-gap accumulators (tick thread only)
        self._t_begin: float | None = None
        self._t_dispatch: float | None = None
        self._t_fetch: float | None = None
        self._t_visible: float | None = None
        self._covered_ms = 0.0
        self._host_other_ms = 0.0
        self._idle_ms = 0.0
        self._gc_bound = False
        self.ticks = 0
        self.last_bubble_ms: float | None = None
        self.last_tick_ms: float | None = None
        # serve_gap reference
        self.scan_marginal_ms: float | None = None
        # alloc churn (sampled)
        self._mem_prev: tuple[int, dict] | None = None  # (tick, stats)
        self._mem: dict[str, Any] | None = None
        self._mem_err: str | None = None
        # buffer census (sampled)
        self._census_prev: dict[str, int] | None = None
        self._census_changes: dict[str, int] = {}
        self._census_opaque: set[str] = set()
        self._census_samples = 0
        # lanes seen deleted (donation consumed the sampled handle —
        # a caller passed the OLD carry); counted honestly, never a
        # crash: this plane is the one that judges donation
        self._census_skipped_deleted = 0
        # window mark for the flight-recorder regression trigger
        self._win_mark: list[int] | None = None

    # -- per-tick marks (called from World._tick_phases) -----------------
    def tick_begin(self) -> None:
        self._t_begin = time.perf_counter()
        if not self._gc_bound:
            # first tick on the serving thread: bind + install (idempotent)
            self.gc.bind_thread()
            self.gc.install()
            self._gc_bound = True

    def mark_dispatch(self) -> None:
        t = time.perf_counter()
        pre_ms = 0.0
        if self._t_begin is not None:
            pre_ms = (t - self._t_begin) * 1e3
            self._h_phase["pre_dispatch"].observe(pre_ms)
        if self._t_dispatch is not None:
            gap_ms = (t - self._t_dispatch) * 1e3
            covered = self._covered_ms + pre_ms
            bubble = max(0.0, gap_ms - covered - self._idle_ms)
            self._h_tick.observe(gap_ms)
            self._h_bubble.observe(bubble)
            self._h_phase["host_other"].observe(self._host_other_ms)
            self._h_phase["idle"].observe(self._idle_ms)
            self._h_phase["bubble"].observe(bubble)
            self.last_tick_ms = gap_ms
            self.last_bubble_ms = bubble
            self.ticks += 1
        self._t_dispatch = t
        self._t_begin = None
        self._covered_ms = 0.0
        self._host_other_ms = 0.0
        self._idle_ms = 0.0

    def mark_fetch(self) -> None:
        self._t_fetch = time.perf_counter()

    def mark_visible(self) -> None:
        t = time.perf_counter()
        if self._t_fetch is not None:
            ms = (t - self._t_fetch) * 1e3
            self._h_phase["device_wait"].observe(ms)
            self._covered_ms += ms
            self._t_fetch = None
        self._t_visible = t

    def mark_decode_done(self) -> None:
        t = time.perf_counter()
        if self._t_visible is not None:
            ms = (t - self._t_visible) * 1e3
            self._h_phase["decode_fanout"].observe(ms)
            self._covered_ms += ms
            self._t_visible = None

    def add_host(self, seconds: float) -> None:
        """Covered useful host work between dispatches (serve-loop
        fan-out flush, input pump, governor, recorder, ...)."""
        if seconds > 0:
            ms = seconds * 1e3
            self._covered_ms += ms
            self._host_other_ms += ms

    def add_idle(self, seconds: float) -> None:
        """Intentional pacing sleep — idle by design, never a bubble."""
        if seconds > 0:
            self._idle_ms += seconds * 1e3

    def observe_device_step(self, seconds: float) -> None:
        # fed from the World's existing device_step_s measurement
        # (dispatch + blocking fetch); note: tick_begin->mark_visible
        # time is already covered via the phase marks, this series only
        # backs the serve_gap reference
        self._h_devstep.observe(seconds * 1e3)

    def set_scan_marginal_ms(self, ms: float) -> None:
        """Pin the serve_gap reference to a measured scan-marginal tick
        cost (bench.py does; production falls back to device-step p50)."""
        self.scan_marginal_ms = float(ms)

    # -- sampled churn (every sample_every ticks) ------------------------
    def should_sample(self, tick: int) -> bool:
        return tick % self.sample_every == 0

    def sample_memory(self, device, tick: int) -> None:
        """Allocator churn from ``device.memory_stats()`` deltas.
        Honest absence: CPU jax serves no stats — recorded as
        ``unavailable``, never a zero pretending to be measured."""
        try:
            stats = device.memory_stats()
        except Exception as exc:
            self._mem_err = f"memory_stats failed: {exc}"[:120]
            return
        if not stats:
            self._mem_err = "memory_stats unavailable on this backend"
            return
        cur = {
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "num_allocs": int(stats.get("num_allocs", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
        }
        with self._lock:
            prev = self._mem_prev
            self._mem_prev = (tick, dict(cur))
            mem: dict[str, Any] = dict(cur)
            if prev is not None and tick > prev[0]:
                dt = tick - prev[0]
                mem["bytes_per_tick"] = round(
                    (cur["bytes_in_use"] - prev[1]["bytes_in_use"]) / dt)
                mem["allocs_per_tick"] = round(
                    (cur["num_allocs"] - prev[1]["num_allocs"]) / dt, 2)
            self._mem = mem
            self._mem_err = None

    def sample_census(self, state) -> None:
        """Donation-readiness census: fingerprint every carry lane's
        device buffer address. Lanes whose address changes between
        samples are re-allocated by XLA each tick — the worklist
        ``donate_argnums`` will consume; stable addresses are already
        aliased in place. Address reads only — no transfer, no sync.

        Donation-safe: callers pass the POST-dispatch carry (the state
        the step returned), whose buffers are live by construction.
        A deleted leaf (someone sampled an old carry donation already
        consumed) is counted in ``census_skipped_deleted`` and skipped
        — the plane that judges donation must never crash on it."""
        try:
            import jax

            leaves = jax.tree_util.tree_flatten_with_path(state)[0]
        except Exception:
            return
        ptrs: dict[str, int] = {}
        skipped = 0
        for path, leaf in leaves:
            lane = jax.tree_util.keystr(path).lstrip(".")
            p = _leaf_pointer(leaf)
            if p is _DELETED:
                skipped += 1
            elif p is None:
                self._census_opaque.add(lane)
            else:
                ptrs[lane] = p
        with self._lock:
            self._census_skipped_deleted += skipped
            prev, self._census_prev = self._census_prev, ptrs
            if prev is None:
                return
            self._census_samples += 1
            for lane, p in ptrs.items():
                q = prev.get(lane)
                if q is None:
                    continue
                self._census_changes.setdefault(lane, 0)
                if p != q:
                    self._census_changes[lane] += 1

    # -- reading ---------------------------------------------------------
    @staticmethod
    def _edges_counts(h: metrics.Histogram) -> tuple[list, list]:
        snap = h.snapshot()
        edges = [u for u, _c in snap["buckets"]]
        counts = [c for _u, c in snap["buckets"]] + [snap["inf"]]
        return edges, counts

    def window_verdict(self) -> tuple[float | None, int]:
        """(bubble p99 over the observations since the previous call,
        sample count). Drives the flight-recorder
        ``residency_regression`` frames — same windowed-delta grammar
        as ``syncage.AgeTracker.window_verdict``."""
        edges, counts = self._edges_counts(self._h_bubble)
        with self._lock:
            mark, self._win_mark = self._win_mark, list(counts)
        if mark is None or len(mark) != len(counts):
            return None, 0
        delta = [max(0, a - b) for a, b in zip(counts, mark)]
        n = sum(delta)
        if n <= 0:
            return None, 0
        from goworld_tpu.utils import devprof

        p99 = devprof.hist_quantile_interp(edges, delta, 0.99)
        return (None if p99 != p99 else p99), n

    def census_snapshot(self) -> dict[str, Any]:
        with self._lock:
            changes = dict(self._census_changes)
            samples = self._census_samples
            opaque = sorted(self._census_opaque)
            skipped = self._census_skipped_deleted
        return {
            "samples": samples,
            "lanes": len(changes),
            "realloc": sorted(l for l, c in changes.items() if c > 0),
            "aliased": sorted(l for l, c in changes.items() if c == 0),
            "opaque": opaque,
            "skipped_deleted": skipped,
            "changes": {l: c for l, c in sorted(changes.items())},
        }

    def snapshot(self) -> dict[str, Any]:
        """The ``/residency`` payload: raw count vectors (mergeable via
        ``Histogram.add_counts``) plus the three verdicts."""
        edges, tick_counts = self._edges_counts(self._h_tick)
        _, bubble_counts = self._edges_counts(self._h_bubble)
        tick_p = ptiles(edges, tick_counts)
        bubble_p = ptiles(edges, bubble_counts)
        phases: dict[str, Any] = {}
        phase_counts: dict[str, list] = {}
        for p in PHASES:
            pe, pc = self._edges_counts(self._h_phase[p])
            phases[p] = ptiles(pe, pc)
            phase_counts[p] = pc
        out: dict[str, Any] = {
            "ticks": self.ticks,
            "edges_ms": edges,
            "tick": tick_p,
            "tick_counts": tick_counts,
            "bubble": bubble_p,
            "bubble_counts": bubble_counts,
            "bubble_budget_ms": self.bubble_budget_ms,
            "phases": phases,
            "phase_counts": phase_counts,
            "gc": self.gc.snapshot(),
            "sample_every": self.sample_every,
        }
        # alloc churn: measured, or an honest absence
        with self._lock:
            mem, mem_err = self._mem, self._mem_err
        if mem is not None:
            out["alloc"] = dict(mem)
        else:
            out["alloc"] = {
                "unavailable": mem_err or "not sampled yet"}
        out["census"] = self.census_snapshot()
        # serve_gap: measured serve ms/tick over the scan-marginal
        # reference (honest about which reference backed it)
        serve_ms = tick_p.get("p50_ms")
        if self.scan_marginal_ms is not None:
            ref, ref_name = self.scan_marginal_ms, "scan_marginal"
        else:
            de, dc = self._edges_counts(self._h_devstep)
            ref = ptiles(de, dc).get("p50_ms")
            ref_name = "device_step_p50"
        if isinstance(serve_ms, (int, float)) \
                and isinstance(ref, (int, float)) and ref > 0:
            out["serve_ms_per_tick"] = serve_ms
            out["serve_gap"] = round(serve_ms / ref, 3)
            out["serve_gap_ref"] = ref_name
            out["serve_gap_ref_ms"] = round(ref, 3)
        p99 = bubble_p.get("p99_ms")
        if isinstance(p99, (int, float)):
            out["pass"] = bool(p99 <= self.bubble_budget_ms)
        return out

    def close(self) -> None:
        """Detach the gc subscription (idempotent)."""
        self.gc.uninstall()


# =======================================================================
# process-local registry (served by debug_http /residency). Weak values:
# the tracker belongs to its World and a discarded world must not be
# pinned by the registry (the syncage/flightrec/devprof convention).
# =======================================================================
_reg_lock = threading.Lock()
_trackers: "weakref.WeakValueDictionary[str, ResidencyTracker]" = \
    weakref.WeakValueDictionary()


def register(name: str, tracker: ResidencyTracker) -> ResidencyTracker:
    with _reg_lock:
        _trackers[name] = tracker
    return tracker


def unregister(name: str) -> None:
    with _reg_lock:
        _trackers.pop(name, None)


def snapshot_all() -> dict:
    """``/residency``: every registered tracker's snapshot, or an
    honest absence (gates/dispatchers serve the endpoint but tick no
    world — the aggregator skips them silently)."""
    with _reg_lock:
        trackers = dict(_trackers)
    if not trackers:
        return {"error": "no residency tracker in this process"}
    return {name: t.snapshot() for name, t in sorted(trackers.items())}


def reset() -> None:
    """Drop registered trackers (tests)."""
    with _reg_lock:
        _trackers.clear()
