"""Correctness audit plane: who owns every entity, and is every
interest set exact — continuously, in production.

Six observability planes grade *speed* (metrics, tracing, device cost,
workload signature, sync-age, residency); this one grades
*correctness*. The paper's migration protocol (Spaces & Entities
layer) claims an entity is never lost or duplicated mid-move, and the
AOI sweep claims exact interest sets at any density — claims the repo
asserts only in tests and end-state chaos checks. This module turns
them into live verdicts:

* :class:`EntityLedger` — an INDEPENDENT per-game census: every
  create/destroy/migrate hook feeds a second bookkeeping of
  ``eid -> type`` (deliberately not the ``World.entities`` dict it
  audits), monotone created/destroyed/migrated counters, a per-entity
  ownership sequence stamped into the migrate data on send and
  validated on restore (a stale or re-delivered ghost names itself),
  and bounded rings of in-flight migrate-out/in records. The census
  digest (count + CRC-chained fold over sorted EntityIDs per type)
  lets the deployment aggregator prove conservation WITHOUT shipping
  eid lists; ``?eids=1`` ships the (bounded) list on demand so a
  divergence can name its first differing EntityID.
* :func:`conservation_verdict` — the deployment equation: sum of
  per-game censuses + the in-flight migration window must equal
  created - destroyed exactly; an out-record unmatched by any
  in-record for more than ``grace_ticks`` names the lost EntityID.
  Shared verbatim by ``tools/obs_aggregate.py``, ``cli.py status``
  and the chaos-soak audit scenario — the proof layer the elastic
  rebalance and hot-standby ROADMAP items reuse.
* :class:`AuditPlane` — the per-world runtime: every
  ``audit_sample_every`` ticks a cohort (<= ``audit_cohort``
  entities) has its interest set recomputed by the brute-force oracle
  (the ``scenarios/runner.py check_oracle`` machinery generalized to
  a partial cohort, with the same overflow-gauge exactness
  precondition) against positions that rode the tick's EXISTING
  fetch-outputs transfer — zero added device syncs; the math runs on
  a background worker thread, never the tick. The same cohort gets
  its slot mirrors, client binding columns and ``interested_by``
  reverse edges spot-checked, and SnapshotChain files CRC-scrubbed on
  a slow cadence.

Violations feed ``audit_violations_total{kind}``, the
``audit_violation`` flight-recorder trigger (utils/flightrec.py —
freezes the ledger tail + cohort diff), and the ``/audit`` debug-http
endpoint. Honesty rules: a tick where the sweep ran degraded
(overflow gauges nonzero) or the sample could not be judged
(pipelined decode skew, megaspace tiles) is recorded as SKIPPED with
its reason, never silently passed; the plane itself must never take
serving down — worker failures disable it loudly.

Jax-free; shared by entity/manager, net/game, net/dispatcher,
net/gate, debug_http (``/audit``), bench.py, tools/obs_aggregate.py
and tools/chaos_soak.py.
"""

from __future__ import annotations

import os
import queue
import threading
import zlib
from collections import OrderedDict, deque
from typing import Any, Callable

from goworld_tpu.utils import log, metrics

logger = log.get("audit")

__all__ = [
    "EntityLedger", "AuditPlane", "CensusProbe", "GRACE_TICKS",
    "crc_fold", "cohort_oracle", "quantize_host",
    "conservation_verdict", "register", "unregister", "get",
    "snapshot_all", "reset",
]

# in-flight grace: a migrate-out unmatched by any migrate-in for more
# than this many source ticks is a LOST entity (the migration protocol
# completes in 2-3 dispatcher round trips — well under one tick of
# slack each — so 8 ticks at 60 Hz is ~130 ms of wire budget)
GRACE_TICKS = 8

# bounded state (the ledger must stay O(1) per hook at 1M entities):
# in-flight rings, violation ring, event-tail ring
OUT_RING = 512
IN_RING = 512
VIOLATION_RING = 64
TAIL_RING = 256
# ?eids=1 ships the sorted eid list only under this count — beyond it
# an honest {"truncated": n} is served instead (a 1M-entity JSON list
# is a DoS, not a diff aid)
EIDS_CAP = 20_000


def crc_fold(eids) -> int:
    """CRC-chained fold over EntityIDs in sorted order — the census
    digest. Chaining (each id's crc32 seeded by the running value)
    makes the digest order-sensitive, and sorting first makes it
    canonical: two processes agree iff their eid SETS agree."""
    crc = 0
    for eid in sorted(eids):
        crc = zlib.crc32(eid.encode("ascii", "replace"), crc)
    return crc & 0xFFFFFFFF


class EntityLedger:
    """Independent entity-ownership bookkeeping for one game.

    All mutation hooks are O(1) dict/deque work and run on the logic
    thread; ``snapshot()`` (http thread) takes the same lock, so the
    scrape cost (sorted-census fold, O(n log n)) is paid by the
    scraper, never the tick."""

    def __init__(self, name: str, grace_ticks: int = GRACE_TICKS):
        self.name = name
        self.grace_ticks = int(grace_ticks)
        self._lock = threading.Lock()
        self._eids: dict[str, str] = {}        # eid -> type name
        self._own_seq: dict[str, int] = {}     # eid -> ownership seq
        self.created = 0
        self.destroyed = 0
        self.migrated_out = 0
        self.migrated_in = 0
        # in-flight rings: (eid, seq) -> {target, tick}; matching a
        # migrate-in against them is the aggregator's job — a SOURCE
        # game can never see the restore on the target, so it must not
        # judge its own out-records (that verdict lives in
        # conservation_verdict)
        self._out: "OrderedDict[tuple[str, int], dict]" = OrderedDict()
        self._in: deque = deque(maxlen=IN_RING)
        self.violations: deque = deque(maxlen=VIOLATION_RING)
        self.violations_total: dict[str, int] = {}
        self.tail: deque = deque(maxlen=TAIL_RING)
        self._pending_violation: str | None = None
        self._m_violations: dict[str, Any] = {}

    # -- mutation hooks (logic thread) ---------------------------------
    def on_create(self, eid: str, type_name: str, tick: int) -> None:
        with self._lock:
            if eid in self._eids:
                self._violate(
                    "duplicate_create",
                    f"create of live EntityID {eid} "
                    f"(type {type_name}, tick {tick})", tick)
                return
            self._eids[eid] = type_name
            self._own_seq.setdefault(eid, 1)
            self.created += 1
            self.tail.append((tick, "create", eid, type_name))

    def on_destroy(self, eid: str, tick: int) -> None:
        with self._lock:
            if self._eids.pop(eid, None) is None:
                self._violate(
                    "destroy_unknown",
                    f"destroy of unknown EntityID {eid} (tick {tick})",
                    tick)
                return
            self._own_seq.pop(eid, None)
            self.destroyed += 1
            self.tail.append((tick, "destroy", eid, ""))

    def next_seq(self, eid: str) -> int:
        """The ownership seq the NEXT migrate-out of ``eid`` will
        carry — a pure read for ``get_migrate_data`` (which builds the
        payload before ``remove_for_migration`` commits the ledger
        move; the two agree because both run back-to-back on the
        logic thread)."""
        with self._lock:
            return self._own_seq.get(eid, 0) + 1

    def stamp_migrate_out(self, eid: str, tick: int,
                          target: int = 0) -> int:
        """Remove ``eid`` from the census, bump its ownership sequence
        and return it — the caller stamps the returned seq into the
        migrate data so the restoring game can reject stale or
        re-delivered ghosts. The last-seen seq is kept even after the
        entity leaves: it is the only defense against a re-delivered
        ghost of an entity this game once owned."""
        with self._lock:
            seq = self._own_seq.get(eid, 0) + 1
            if self._eids.pop(eid, None) is None:
                self._violate(
                    "migrate_out_unknown",
                    f"migrate-out of unknown EntityID {eid} "
                    f"(tick {tick})", tick)
            self._own_seq[eid] = seq
            self.migrated_out += 1
            while len(self._out) >= OUT_RING:
                self._out.popitem(last=False)
            self._out[(eid, seq)] = {"target": int(target),
                                     "tick": int(tick)}
            self.tail.append((tick, "migrate_out", eid, f"seq={seq}"))
            return seq

    def on_migrate_in(self, eid: str, type_name: str, seq: int,
                      tick: int) -> None:
        with self._lock:
            seq = int(seq)
            if eid in self._eids:
                self._violate(
                    "duplicate_entity",
                    f"migrate-in of live EntityID {eid} "
                    f"(seq {seq}, tick {tick}) — duplicated owner",
                    tick)
                return
            last = self._own_seq.get(eid, 0)
            # an in-record matching our OWN open out-record is a
            # self-round-trip (single-game worlds, A->B->A through the
            # same ledger), not a ghost: the seq equals the one we just
            # stamped. A RE-delivered ghost arrives after the record
            # below is retired and still fails the stale check.
            own_roundtrip = (eid, seq) in self._out
            if seq and seq <= last and not own_roundtrip:
                self._violate(
                    "stale_migrate",
                    f"migrate-in of EntityID {eid} with stale "
                    f"ownership seq {seq} <= {last} (tick {tick})",
                    tick)
                return
            self._eids[eid] = type_name
            # seq 0 = a peer predating the stamp: accept, re-anchor
            self._own_seq[eid] = seq or (last + 1)
            self.migrated_in += 1
            self._in.append((eid, seq, int(tick)))
            # our own out-record matched locally (self-migration in
            # tests / single-game worlds): retire it
            self._out.pop((eid, seq), None)
            self.tail.append((tick, "migrate_in", eid, f"seq={seq}"))

    def resync(self, live: dict[str, str], tick: int) -> dict:
        """Bulk re-anchor after a snapshot restore or a replicated
        frame apply (freeze.py and replication/standby.py rebuild
        ``world.entities`` directly, bypassing the per-entity hooks).
        ``created`` is re-derived so the local conservation identity
        ``live == created - destroyed - migrated_out + migrated_in``
        holds from the re-anchored census onward. Returns the census
        delta (``{"added": n, "removed": n}``) — the standby tracker
        and promotion decision log stamp it."""
        with self._lock:
            added = sum(1 for eid in live if eid not in self._eids)
            removed = sum(1 for eid in self._eids if eid not in live)
            self._eids = dict(live)
            for eid in live:
                self._own_seq.setdefault(eid, 1)
            self.created = (len(live) + self.destroyed
                            + self.migrated_out - self.migrated_in)
            self.tail.append((tick, "resync", "",
                              f"{len(live)} entities restored"))
            return {"added": added, "removed": removed}

    # -- violations ----------------------------------------------------
    def _violate(self, kind: str, detail: str, tick: int) -> None:
        # lock already held
        self.violations.append({"kind": kind, "detail": detail,
                                "tick": int(tick)})
        self.violations_total[kind] = \
            self.violations_total.get(kind, 0) + 1
        self._pending_violation = f"{kind}: {detail}"
        m = self._m_violations.get(kind)
        if m is None:
            m = self._m_violations[kind] = metrics.counter(
                "audit_violations_total",
                help="correctness audit violations by kind",
                kind=kind, game=self.name)
        m.inc()
        self.tail.append((tick, "VIOLATION", kind, detail))
        logger.error("[%s] audit violation %s: %s", self.name, kind,
                     detail)

    def note_violation(self, kind: str, detail: str, tick: int) -> None:
        """External probes (oracle, mirrors, scrub) record through the
        same ring/counter/trigger path as ledger-internal ones."""
        with self._lock:
            self._violate(kind, detail, tick)

    def take_violation(self) -> str | None:
        """Pop the freshest unconsumed violation note — the per-tick
        flight-recorder frame key (each violation fires the
        ``audit_violation`` trigger at most once)."""
        with self._lock:
            v, self._pending_violation = self._pending_violation, None
            return v

    # -- reading -------------------------------------------------------
    def live_eids(self) -> set[str]:
        with self._lock:
            return set(self._eids)

    def census(self) -> dict[str, dict]:
        """Per-type count + CRC-chained digest over sorted EntityIDs —
        two censuses agree iff the eid sets agree, without shipping a
        single eid."""
        with self._lock:
            by_type: dict[str, list[str]] = {}
            for eid, tname in self._eids.items():
                by_type.setdefault(tname, []).append(eid)
        return {
            tname: {"count": len(eids), "crc": crc_fold(eids)}
            for tname, eids in sorted(by_type.items())
        }

    def snapshot(self, tick: int = 0, eids: bool = False) -> dict:
        census = self.census()  # takes the lock itself
        with self._lock:
            out = {
                "kind": "game",
                "entities": len(self._eids),
                "crc": crc_fold(self._eids),
                "census": census,
                "created": self.created,
                "destroyed": self.destroyed,
                "migrated_out": self.migrated_out,
                "migrated_in": self.migrated_in,
                "tick": int(tick),
                "in_flight": [
                    {"eid": eid, "seq": seq, "target": rec["target"],
                     "tick": rec["tick"],
                     "age_ticks": max(0, int(tick) - rec["tick"])}
                    for (eid, seq), rec in self._out.items()
                ],
                "in_records": [
                    {"eid": eid, "seq": seq, "tick": t}
                    for eid, seq, t in self._in
                ],
                "grace_ticks": self.grace_ticks,
                "violations_total": dict(self.violations_total),
                "violations": list(self.violations),
            }
            if eids:
                if len(self._eids) <= EIDS_CAP:
                    out["eids"] = sorted(self._eids)
                else:
                    out["eids"] = {"truncated": len(self._eids)}
            return out

    def incident_context(self) -> dict:
        """The freeze-time payload: ledger event tail + violation ring
        (paid at freeze time only — the flightrec convention)."""
        with self._lock:
            return {
                "entities": len(self._eids),
                "created": self.created,
                "destroyed": self.destroyed,
                "migrated_out": self.migrated_out,
                "migrated_in": self.migrated_in,
                "tail": [list(t) for t in self.tail],
                "violations": list(self.violations),
            }


# =======================================================================
# sampled AOI oracle (jax-free numpy; the fetched planes arrive as host
# arrays off the tick's existing fetch-outputs transfer)
# =======================================================================
def quantize_host(pos, step: float, hi: int):
    """Host-side replica of ``ops/aoi.quantize_positions`` for fetched
    f32 planes: snap x/z onto the q16 lattice with the SAME f32
    arithmetic (multiply by a power of two, floor, multiply back — all
    exact), so the oracle judges the identical domain the sweep ran
    on."""
    import numpy as np

    p = np.asarray(pos, np.float32).copy()
    inv = np.float32(1.0 / step)
    st = np.float32(step)
    qx = np.clip(np.floor(p[:, 0] * inv), 0.0, float(hi))
    qz = np.clip(np.floor(p[:, 2] * inv), 0.0, float(hi))
    p[:, 0] = (qx * st).astype(np.float32)
    p[:, 2] = (qz * st).astype(np.float32)
    return p


def cohort_oracle(pos, alive, radius: float, cohort,
                  watch_radius=None) -> dict[int, set[int]]:
    """Brute-force interest rows for the cohort slots only — the
    ``ops/aoi.neighbors_oracle`` semantics (Chebyshev metric,
    per-entity watch radius, radius <= 0 excludes) without paying
    O(n^2) for a <=``audit_cohort`` sample."""
    import numpy as np

    pos = np.asarray(pos)
    alive = np.asarray(alive).astype(bool)
    n = pos.shape[0]
    if watch_radius is None:
        participates = alive
        reach = np.full(n, radius, np.float64)
    else:
        wr = np.asarray(watch_radius, np.float64)
        participates = alive & (wr > 0)
        reach = np.minimum(wr, radius)
    rows: dict[int, set[int]] = {}
    for i in cohort:
        i = int(i)
        if i >= n or not participates[i]:
            rows[i] = set()
            continue
        dx = np.abs(pos[:, 0] - pos[i, 0])
        dz = np.abs(pos[:, 2] - pos[i, 2])
        mask = (np.maximum(dx, dz) <= reach[i]) & participates
        mask[i] = False
        rows[i] = set(np.nonzero(mask)[0].tolist())
    return rows


# =======================================================================
# deployment conservation verdict (shared by obs_aggregate, cli status,
# chaos_soak --scenario audit and the in-process tests)
# =======================================================================
def conservation_verdict(games: list[dict],
                         dispatcher: dict | None = None,
                         grace_ticks: int = GRACE_TICKS) -> dict:
    """Prove (or refute) deployment-wide entity conservation from
    per-game ledger snapshots:

    ``sum(live) + in_flight == sum(created) - sum(destroyed)``

    where ``in_flight`` is the set of migrate-out records not matched
    by any game's migrate-in record (matched by (EntityID, ownership
    seq)). An unmatched out-record older than ``grace_ticks`` source
    ticks is a LOST entity and names its EntityID; local
    duplicate/stale violations (already named by the ledgers) are
    rolled up. The optional dispatcher census cross-checks the routing
    table's per-game counts against each game's own census."""
    games = [g for g in games if isinstance(g, dict)
             and g.get("kind") == "game"]
    live = sum(int(g.get("entities", 0)) for g in games)
    created = sum(int(g.get("created", 0)) for g in games)
    destroyed = sum(int(g.get("destroyed", 0)) for g in games)
    ins = {(r["eid"], r["seq"])
           for g in games for r in g.get("in_records", [])}
    outstanding = []
    for g in games:
        snap_tick = int(g.get("tick", 0))
        for r in g.get("in_flight", []):
            if (r["eid"], r["seq"]) in ins:
                continue
            # burst-aware grace (ISSUE 19): age each record from its
            # OWN migrate-out tick against the owning game's snapshot
            # tick — never from a precomputed age a batched scraper
            # may have anchored at the batch head. A rate-limited
            # rebalance of rebalance_batch entities straddling the
            # verdict then judges every record by how long IT has
            # been in flight, not how old the batch is.
            r = dict(r)
            if "tick" in r:
                r["age_ticks"] = max(0, snap_tick - int(r["tick"]))
            else:
                r["age_ticks"] = int(r.get("age_ticks", 0))
            outstanding.append(r)
    lost = [r for r in outstanding
            if int(r["age_ticks"]) > int(grace_ticks)]
    in_flight = len(outstanding)
    violations: dict[str, int] = {}
    for g in games:
        for kind, n in (g.get("violations_total") or {}).items():
            violations[kind] = violations.get(kind, 0) + int(n)
    problems: list[str] = []
    for r in lost:
        problems.append(
            f"lost EntityID {r['eid']} (seq {r['seq']}, migrated out "
            f"at tick {r.get('tick', '?')}, unmatched for "
            f"{r['age_ticks']} ticks)")
    balance = live + in_flight - (created - destroyed)
    if balance != 0:
        problems.append(
            f"conservation broken: live {live} + in-flight "
            f"{in_flight} != created {created} - destroyed "
            f"{destroyed} (off by {balance:+d})")
    for kind, n in sorted(violations.items()):
        if n:
            problems.append(f"{n} {kind} violation(s) recorded")
    out = {
        "games": len(games),
        "live": live,
        "created": created,
        "destroyed": destroyed,
        "in_flight": in_flight,
        "lost": lost,
        "violations_total": violations,
        "problems": problems,
        "ok": not problems,
    }
    if isinstance(dispatcher, dict) \
            and dispatcher.get("kind") == "dispatcher":
        out["dispatcher_entities"] = int(dispatcher.get("entities", 0))
        # the routing table lags by the in-flight window at most; a
        # larger divergence is a finding (named per-game upstream)
        drift = abs(out["dispatcher_entities"] - live)
        if drift > in_flight + len(lost):
            out["ok"] = False
            out["problems"] = problems + [
                f"dispatcher routes {out['dispatcher_entities']} "
                f"entities but games hold {live} "
                f"(in-flight {in_flight})"]
    return out


def first_divergent_eid(a: list[str] | dict | None,
                        b: list[str] | dict | None) -> str | None:
    """Name the first EntityID present in exactly one of two sorted
    eid lists (the ``?eids=1`` diff aid). ``None`` when either side
    was truncated or the sets agree."""
    if not isinstance(a, list) or not isinstance(b, list):
        return None
    diff = sorted(set(a) ^ set(b))
    return diff[0] if diff else None


# =======================================================================
# the per-world runtime: sampling worker, probe stats, scrub
# =======================================================================
class AuditPlane:
    """One world's audit runtime: the ledger plus the off-hot-path
    worker that judges sampled cohorts (AOI oracle + mirror probes)
    and scrubs SnapshotChain files. Submissions never block the tick:
    a full queue drops the sample and counts it
    (``audit_samples_dropped_total``)."""

    def __init__(self, name: str, sample_every: int = 64,
                 cohort: int = 64, grace_ticks: int = GRACE_TICKS):
        # loud validation, the GridSpec convention: a bad knob must
        # fail at construction, only runtime work degrades gracefully
        if sample_every < 1:
            raise ValueError(
                f"audit_sample_every must be >= 1, got {sample_every!r}")
        if cohort < 1:
            raise ValueError(
                f"audit_cohort must be >= 1, got {cohort!r}")
        self.name = name
        self.sample_every = int(sample_every)
        self.cohort = int(cohort)
        self.ledger = EntityLedger(name, grace_ticks=grace_ticks)
        self._lock = threading.Lock()
        self.oracle_stats = {"samples": 0, "entities_checked": 0,
                             "mismatches": 0, "skipped": {},
                             "last_tick": -1}
        self.probe_stats = {"samples": 0, "entities_checked": 0,
                            "mismatches": 0}
        self.scrub_stats = {"walks": 0, "files": 0, "corrupt": 0,
                            "last_error": None}
        self._sample_index = 0
        self._m_dropped = metrics.counter(
            "audit_samples_dropped_total",
            help="audit cohort samples dropped on a busy worker",
            game=name)
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._thread = threading.Thread(
            target=self._run, name=f"audit-{name}", daemon=True)
        self._thread.start()

    # -- worker --------------------------------------------------------
    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                self._q.task_done()
                return
            try:
                job()
            except Exception:
                logger.exception(
                    "[%s] audit worker job failed", self.name)
            self._q.task_done()

    def submit(self, job: Callable[[], None]) -> bool:
        try:
            self._q.put_nowait(job)
            return True
        except queue.Full:
            self._m_dropped.inc()
            return False

    def drain(self, timeout: float | None = None) -> None:
        """Block until queued work finished (tests, bench)."""
        self._q.join()

    def close(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=2.0)

    # -- sampling ------------------------------------------------------
    def want_sample(self, tick: int) -> bool:
        return tick % self.sample_every == 0

    def skip_sample(self, reason: str, tick: int) -> None:
        """An honest non-check: the tick was sampled but could not be
        judged (degraded sweep, pipelined decode skew, mega tiles)."""
        with self._lock:
            sk = self.oracle_stats["skipped"]
            sk[reason] = sk.get(reason, 0) + 1
            self.oracle_stats["last_tick"] = int(tick)

    def next_cohort(self, slots: list[int]) -> list[int]:
        """Rotating cohort pick: successive samples walk the slot list
        so every entity is eventually audited, deterministically (no
        RNG — replayable under the chaos seed discipline)."""
        if not slots:
            return []
        slots = sorted(slots)
        k = min(self.cohort, len(slots))
        start = (self._sample_index * self.cohort) % len(slots)
        self._sample_index += 1
        picked = slots[start:start + k]
        if len(picked) < k:
            picked += slots[:k - len(picked)]
        return picked

    def judge_sample(self, *, tick: int, pos, alive, watch_radius,
                     radius: float, cohort_slots: list[int],
                     owner: dict[int, str],
                     interest: dict[str, set],
                     quant_step: float | None = None,
                     quant_hi: int = 0) -> None:
        """The worker-side oracle judgment (callers wrap this in
        ``submit``): recompute the cohort's interest rows brute-force
        and diff them against the decoded ``interested_in`` sets
        captured on the logic thread."""
        if quant_step is not None:
            pos = quantize_host(pos, quant_step, quant_hi)
        rows = cohort_oracle(pos, alive, radius, cohort_slots,
                             watch_radius=watch_radius)
        mismatches = 0
        for slot in cohort_slots:
            eid = owner.get(int(slot))
            if eid is None or eid not in interest:
                continue
            want = {owner[j] for j in rows.get(int(slot), set())
                    if j in owner}
            have = interest[eid]
            if have != want:
                mismatches += 1
                missing = sorted(want - have)[:4]
                extra = sorted(have - want)[:4]
                self.ledger.note_violation(
                    "aoi_oracle",
                    f"EntityID {eid}@slot{slot}: interest set diverges "
                    f"from oracle (missing {missing}, extra {extra}) "
                    f"at tick {tick}", tick)
        with self._lock:
            self.oracle_stats["samples"] += 1
            self.oracle_stats["entities_checked"] += len(cohort_slots)
            self.oracle_stats["mismatches"] += mismatches
            self.oracle_stats["last_tick"] = int(tick)

    def note_probe(self, checked: int, mismatches: int) -> None:
        with self._lock:
            self.probe_stats["samples"] += 1
            self.probe_stats["entities_checked"] += int(checked)
            self.probe_stats["mismatches"] += int(mismatches)

    # -- SnapshotChain scrub -------------------------------------------
    def scrub_snapshots(self, directory: str, game_id: int,
                        tick: int) -> None:
        """CRC-walk the world's SnapshotChain files (worker thread).
        ``read_freeze_file`` already refuses a damaged keyframe/delta
        (per-plane CRCs); here that refusal becomes a named violation
        instead of a surprise at the next ``-restore`` boot."""
        from goworld_tpu import freeze as _freeze

        files = [
            os.path.join(directory, _freeze.chain_key_filename(game_id)),
            os.path.join(directory,
                         _freeze.chain_delta_filename(game_id)),
        ]
        walked = corrupt = 0
        err = None
        for path in files:
            if not os.path.exists(path):
                continue
            walked += 1
            try:
                _freeze.read_freeze_file(path)
            except Exception as exc:
                corrupt += 1
                err = f"{os.path.basename(path)}: {exc}"
                self.ledger.note_violation(
                    "snapshot_crc",
                    f"SnapshotChain scrub failed: {err}", tick)
        with self._lock:
            self.scrub_stats["walks"] += 1
            self.scrub_stats["files"] += walked
            self.scrub_stats["corrupt"] += corrupt
            if err:
                self.scrub_stats["last_error"] = err

    # -- reading -------------------------------------------------------
    def take_violation(self) -> str | None:
        return self.ledger.take_violation()

    def snapshot(self, tick: int = 0, eids: bool = False) -> dict:
        with self._lock:
            oracle = {
                "samples": self.oracle_stats["samples"],
                "entities_checked":
                    self.oracle_stats["entities_checked"],
                "mismatches": self.oracle_stats["mismatches"],
                "skipped": dict(self.oracle_stats["skipped"]),
                "last_tick": self.oracle_stats["last_tick"],
            }
            probes = dict(self.probe_stats)
            scrub = dict(self.scrub_stats)
        out = self.ledger.snapshot(tick=tick, eids=eids)
        out.update({
            "sample_every": self.sample_every,
            "cohort": self.cohort,
            "oracle": oracle,
            "probes": probes,
            "scrub": scrub,
            "samples_dropped": int(self._m_dropped.value),
        })
        return out

    def incident_context(self) -> dict:
        ctx = self.ledger.incident_context()
        with self._lock:
            ctx["oracle"] = dict(self.oracle_stats,
                                 skipped=dict(
                                     self.oracle_stats["skipped"]))
            ctx["probes"] = dict(self.probe_stats)
        return ctx


class CensusProbe:
    """Registry adapter for processes that hold an entity VIEW but no
    ledger (the dispatcher's routing table, a gate's client map): a
    snapshot provider called at scrape time. The provider receives
    ``eids`` and returns a plain dict; failures serve an honest
    ``{"error": ...}`` (observability must never take serving down)."""

    def __init__(self, provider: Callable[[bool], dict]):
        self._provider = provider

    def snapshot(self, tick: int = 0, eids: bool = False) -> dict:
        try:
            return self._provider(eids)
        except Exception as exc:
            return {"error": f"census provider failed: {exc!r}"}


# =======================================================================
# process-local registry (served by debug_http /audit). Weak values:
# a plane belongs to its World/service and a discarded owner must not
# be pinned by the registry (the flightrec/syncage convention).
# =======================================================================
import weakref  # noqa: E402

_reg_lock = threading.Lock()
_planes: "weakref.WeakValueDictionary[str, Any]" = \
    weakref.WeakValueDictionary()


def register(name: str, plane):
    with _reg_lock:
        _planes[name] = plane
    return plane


def unregister(name: str) -> None:
    with _reg_lock:
        _planes.pop(name, None)


def get(name: str):
    with _reg_lock:
        return _planes.get(name)


def snapshot_all(eids: bool = False) -> dict:
    """``/audit``: every registered plane/probe's snapshot, or an
    honest absence."""
    with _reg_lock:
        planes = dict(_planes)
    if not planes:
        return {"error": "no audit plane in this process"}
    out: dict[str, Any] = {}
    for name, p in sorted(planes.items()):
        try:
            out[name] = p.snapshot(eids=eids)
        except Exception as exc:
            out[name] = {"error": f"snapshot failed: {exc!r}"}
    return out


def reset() -> None:
    """Drop registered planes (tests)."""
    with _reg_lock:
        _planes.clear()
