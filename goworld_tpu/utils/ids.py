"""Entity / client identifier generation.

Reference parity: GoWorld represents ``EntityID``/``ClientID`` as 16-char
strings (``engine/common/types.go:9-46``) produced from a 12-byte
Mongo-ObjectId-style uuid — 4B unix time, 3B machine, 2B pid, 3B counter —
base64-encoded to 16 chars (``engine/common/uuid/uuid.go:27-60``), plus a
deterministic variant used for per-game nil-space ids
(``engine/entity/space_ops.go:33-47``).

We keep the same wire format (16-char url-safe base64 of 12 bytes) so that
ids stay fixed-width on the wire and sortable-by-creation-time, but device
kernels never see these strings: the host maps ``EntityID`` <-> (space shard,
slot, generation) and ships only int32 slot indices to the TPU.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
import threading
import time

ENTITYID_LENGTH = 16  # chars on the wire, = base64(12 bytes)

_counter_lock = threading.Lock()
_counter = int.from_bytes(os.urandom(3), "big")

_machine = hashlib.md5(socket.gethostname().encode()).digest()[:3]
_pid = struct.pack(">H", os.getpid() & 0xFFFF)


def _b64_12(raw: bytes) -> str:
    assert len(raw) == 12
    return base64.urlsafe_b64encode(raw).decode("ascii")  # 16 chars, no pad


def gen_entity_id() -> str:
    """Generate a fresh 16-char EntityID (time+machine+pid+counter)."""
    global _counter
    with _counter_lock:
        _counter = (_counter + 1) & 0xFFFFFF
        cnt = _counter
    raw = (
        struct.pack(">I", int(time.time()) & 0xFFFFFFFF)
        + _machine
        + _pid
        + cnt.to_bytes(3, "big")
    )
    return _b64_12(raw)


def gen_fixed_id(key: str) -> str:
    """Deterministic EntityID from a string key.

    Used for nil-space ids so every process derives the same id for game N,
    like the reference's ``GenFixedUUID`` (``uuid.go``/``space_ops.go:41``).
    """
    return _b64_12(hashlib.sha256(key.encode()).digest()[:12])


def nil_space_id(game_id: int) -> str:
    return gen_fixed_id(f"goworld_tpu.nilspace.{game_id}")


def eid_hash64(eids) -> "np.ndarray":
    """Vectorized 64-bit hash of an S16 EntityID array.

    The batched sync decoders (``World.stage_pos_sync_batch``,
    ``DispatcherService._h_sync_upstream``) key their intern indexes on
    this instead of the raw S16 bytes: ``searchsorted`` over u64 is ~4x
    cheaper than over S16 (one integer compare vs a memcmp per probe).
    Splitmix64-style mix of the two 8-byte halves. Collisions are handled
    by the callers (exact-match verify on candidates; index falls back to
    raw-byte keys if two LIVE ids ever collide — ~1e-7 at 1M ids).
    """
    import numpy as np

    a = np.ascontiguousarray(np.asarray(eids, "S16"))
    h = a.view(np.uint64).reshape(-1, 2)
    return (
        (h[:, 0] ^ (h[:, 0] >> np.uint64(31)))
        * np.uint64(0x9E3779B97F4A7C15)
    ) ^ (h[:, 1] + np.uint64(0xD1B54A32D192ED03))


def build_eid_index(eids) -> tuple:
    """Build a sorted lookup index over an S16 EntityID array.

    Returns ``(hashed, keys, sorted_eids, order)``: ``keys`` is sorted
    :func:`eid_hash64` values (fast u64 probes) unless two input ids
    hash-collide, in which case it falls back to the raw S16 bytes
    (``hashed=False``); ``sorted_eids``/``order`` align the inputs with
    ``keys`` so callers can permute their payload columns. Shared by the
    two vectorized sync decoders (game leg ``World._sync_pos_index``,
    router leg ``DispatcherService._route_index``) so the collision
    fallback and verify logic live in exactly one place.
    """
    import numpy as np

    eids = np.ascontiguousarray(np.asarray(eids, "S16"))
    keys = eid_hash64(eids)
    order = np.argsort(keys, kind="stable")
    keys = keys[order]
    hashed = True
    if keys.size and (keys[1:] == keys[:-1]).any():
        order = np.argsort(eids, kind="stable")
        keys = eids[order]
        hashed = False
    return hashed, keys, eids[order], order


def probe_eid_index(hashed: bool, keys, sorted_eids, query_eids) -> tuple:
    """Resolve S16 ``query_eids`` against a :func:`build_eid_index`.

    Returns ``(p, ok)``: candidate positions into the sorted index and
    the exact-match mask (hash candidates are byte-verified here, so a
    hash false positive can never resolve; ~1e-19/record with 64-bit
    keys, and zero once the build fell back to raw bytes).
    """
    import numpy as np

    query_eids = np.ascontiguousarray(np.asarray(query_eids, "S16"))
    probe = eid_hash64(query_eids) if hashed else query_eids
    p = np.minimum(np.searchsorted(keys, probe), keys.size - 1)
    ok = keys[p] == probe
    if hashed:
        ok &= sorted_eids[p] == query_eids
    return p, ok


def is_valid_entity_id(eid: str) -> bool:
    if not isinstance(eid, str) or len(eid) != ENTITYID_LENGTH:
        return False
    try:
        raw = base64.urlsafe_b64decode(eid)
    except Exception:
        return False
    # canonical ids are exactly base64(12 bytes), so no '=' padding and a
    # 12-byte decode; reject anything gen_entity_id could not have produced
    return len(raw) == 12 and "=" not in eid
