"""Entity / client identifier generation.

Reference parity: GoWorld represents ``EntityID``/``ClientID`` as 16-char
strings (``engine/common/types.go:9-46``) produced from a 12-byte
Mongo-ObjectId-style uuid — 4B unix time, 3B machine, 2B pid, 3B counter —
base64-encoded to 16 chars (``engine/common/uuid/uuid.go:27-60``), plus a
deterministic variant used for per-game nil-space ids
(``engine/entity/space_ops.go:33-47``).

We keep the same wire format (16-char url-safe base64 of 12 bytes) so that
ids stay fixed-width on the wire and sortable-by-creation-time, but device
kernels never see these strings: the host maps ``EntityID`` <-> (space shard,
slot, generation) and ships only int32 slot indices to the TPU.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
import threading
import time

ENTITYID_LENGTH = 16  # chars on the wire, = base64(12 bytes)

_counter_lock = threading.Lock()
_counter = int.from_bytes(os.urandom(3), "big")

_machine = hashlib.md5(socket.gethostname().encode()).digest()[:3]
_pid = struct.pack(">H", os.getpid() & 0xFFFF)


def _b64_12(raw: bytes) -> str:
    assert len(raw) == 12
    return base64.urlsafe_b64encode(raw).decode("ascii")  # 16 chars, no pad


def gen_entity_id() -> str:
    """Generate a fresh 16-char EntityID (time+machine+pid+counter)."""
    global _counter
    with _counter_lock:
        _counter = (_counter + 1) & 0xFFFFFF
        cnt = _counter
    raw = (
        struct.pack(">I", int(time.time()) & 0xFFFFFFFF)
        + _machine
        + _pid
        + cnt.to_bytes(3, "big")
    )
    return _b64_12(raw)


def gen_fixed_id(key: str) -> str:
    """Deterministic EntityID from a string key.

    Used for nil-space ids so every process derives the same id for game N,
    like the reference's ``GenFixedUUID`` (``uuid.go``/``space_ops.go:41``).
    """
    return _b64_12(hashlib.sha256(key.encode()).digest()[:12])


def nil_space_id(game_id: int) -> str:
    return gen_fixed_id(f"goworld_tpu.nilspace.{game_id}")


def is_valid_entity_id(eid: str) -> bool:
    if not isinstance(eid, str) or len(eid) != ENTITYID_LENGTH:
        return False
    try:
        raw = base64.urlsafe_b64decode(eid)
    except Exception:
        return False
    # canonical ids are exactly base64(12 bytes), so no '=' padding and a
    # 12-byte decode; reject anything gen_entity_id could not have produced
    return len(raw) == 12 and "=" not in eid
