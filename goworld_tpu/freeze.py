"""Freeze / restore — whole-game snapshot for hot reload.

Reference being rebuilt: ``engine/entity/EntityManager.go:520-617``
(``Freeze`` packs every entity's migrate-style data requiring exactly one
nil space; ``RestoreFreezedEntities`` rebuilds in 3 passes — nil space,
then spaces, then entities) plus ``components/game/GameService.go:220-269``
(``doFreeze`` drains pending work and writes ``game%d_freezed.dat``) and
``components/game/restore.go:16-34`` (read + unpack on ``-restore`` boot).

TPU adaptation: the reference walks heap objects; here the canonical hot
state (positions, yaw, npc_moving) lives in device SoA arrays, so freezing
does ONE ``jax.device_get`` of the relevant planes and joins them with the
host-side attr trees / timers / client bindings. Restore rebuilds the host
object graph and lets the normal staging path repopulate device rows on the
first tick — the same "spaces before entities" ordering the reference uses,
because entities need their target space's AOI shard to exist.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import msgpack
import numpy as np

from goworld_tpu.entity.entity import Entity, GameClient
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.utils import faults, log

logger = log.get("freeze")

FREEZE_FORMAT_VERSION = 1


class CorruptSnapshotError(RuntimeError):
    """A freeze/checkpoint file exists but cannot be parsed (truncated
    write, disk fault, crash before the atomic rename of a pre-1 format
    writer). The restore path REJECTS such a file whole — half-loading a
    world is worse than falling back to an older snapshot or a cold
    boot."""


def freeze_filename(game_id: int) -> str:
    """Reference ``game%d_freezed.dat`` (``GameService.go:252``)."""
    return f"game{game_id}_freezed.dat"


# =======================================================================
# pack
# =======================================================================
def _device_snapshot(world: World) -> dict[str, np.ndarray]:
    """One batched transfer of every plane freeze needs (per-entity reads
    would pay the host<->device latency once per entity)."""
    st = world.state
    return world._dget({
        "pos": st.pos, "yaw": st.yaw, "npc_moving": st.npc_moving,
    })


# sentinel: pack device-resident pos/yaw/moving LATER from a state
# reference (async checkpoints patch the records off-thread)
_DEFER = object()


def _pack_entity(world: World, e: Entity, snap) -> dict:
    """Migrate-style record (``GetMigrateData``, ``Entity.go:1060-1101``)
    plus the space binding freeze needs and migrate doesn't."""
    live_slot = (
        e.slot is not None and e.shard is not None
        and e._pending_pos is None
    )
    extra: dict = {}
    if live_slot and snap is _DEFER:
        # placeholders; the checkpoint worker patches pos/yaw/moving
        # from the captured state off-thread (no device read here)
        pos, yaw, moving = [0.0, 0.0, 0.0], 0.0, False
        extra["_slot"] = [e.shard, e.slot]
    elif live_slot and snap is not None:
        shard, slot = e.shard, e.slot
        pos = [float(v) for v in snap["pos"][shard, slot]]
        yaw = float(snap["yaw"][shard, slot])
        moving = bool(snap["npc_moving"][shard, slot])
    else:
        pos = [float(v) for v in e.position]
        yaw = float(e._pending_yaw or 0.0)
        moving = False
    return extra | {
        "type": e.type_name,
        "id": e.id,
        "attrs": e.attrs.to_dict(),
        "client": (
            [e.client.gate_id, e.client.client_id]
            if e.client is not None else None
        ),
        "pos": pos,
        "yaw": yaw,
        "moving": moving,
        "space_id": e.space.id if e.space is not None else None,
        "timers": world.timers.dump(list(e.timer_ids)),
    }


def freeze_world(world: World, *, _snap=None, run_hooks: bool = True
                 ) -> dict:
    """Pack the entire world. Requires exactly one nil space (the same
    invariant the reference asserts, ``EntityManager.go:536-541``).

    ``_snap=_DEFER`` packs host state only, embedding (shard, slot) refs
    for the checkpoint worker to patch later; ``run_hooks=False`` skips
    OnFreeze (async checkpoints snapshot a RUNNING world — the reload
    hook contract doesn't apply)."""
    if world.nil_space is None:
        raise RuntimeError("cannot freeze: no nil space")
    # a pipelined world may hold one tick's outputs undecoded — the
    # snapshot must not lose their client sends / interest updates
    world.flush_pending_outputs()
    snap = _snap if _snap is not None else _device_snapshot(world)

    if run_hooks:
        for e in list(world.entities.values()):
            if not e.destroyed:
                try:
                    e.OnFreeze()
                except Exception:
                    logger.exception("OnFreeze failed for %s", e)

    spaces: list[dict] = []
    entities: list[dict] = []
    for e in world.entities.values():
        if e.destroyed:
            continue
        if e is world.nil_space:
            continue
        if isinstance(e, Space):
            spaces.append({
                "type": e.type_name,
                "id": e.id,
                "attrs": e.attrs.to_dict(),
                "use_aoi": e.shard is not None,
                "mega": e.is_mega,
                "timers": world.timers.dump(list(e.timer_ids)),
            })
        else:
            entities.append(_pack_entity(world, e, snap))

    nil = world.nil_space
    return {
        "version": FREEZE_FORMAT_VERSION,
        "game_id": world.game_id,
        "nil_space": {
            "attrs": nil.attrs.to_dict(),
            "timers": world.timers.dump(list(nil.timer_ids)),
        },
        "spaces": spaces,
        "entities": entities,
    }


# =======================================================================
# unpack
# =======================================================================
def _load_attrs_quiet(e: Entity, attrs: dict) -> None:
    """Fill the attr tree without journaling deltas: the restore path must
    not fan out attr-change messages (clients either reconnect fresh or
    already hold the values — reference 're-assign clients quietly')."""
    from goworld_tpu.entity.attrs import load_into

    cb = e.attrs._root_cb
    e.attrs._root_cb = None
    try:
        load_into(e.attrs, attrs)
    finally:
        e.attrs._root_cb = cb


def restore_world(world: World, data: dict) -> None:
    """3-pass rebuild into a freshly constructed World (reference
    ``RestoreFreezedEntities``, ``EntityManager.go:556-617``)."""
    if data.get("version") != FREEZE_FORMAT_VERSION:
        raise ValueError(f"freeze format {data.get('version')!r} unsupported")
    if world.entities and not (
        len(world.entities) == 1 and world.nil_space is not None
    ):
        raise RuntimeError("restore requires an empty world")

    # pass 1: nil space (the migration anchor; its id is deterministic
    # from game_id so routing and CallNilSpaces keep working)
    nil = world.nil_space or world.create_nil_space()
    _load_attrs_quiet(nil, data["nil_space"].get("attrs", {}))
    for tid in world.timers.restore(data["nil_space"].get("timers", [])):
        nil.timer_ids.add(tid)

    # pass 2: spaces (entities need their shard to exist before entering)
    for sd in data["spaces"]:
        desc = world.registry.get(sd["type"])
        sp: Space = desc.cls()
        sp._type_desc = desc
        world._attach(sp, sd["id"])
        if sd.get("mega"):
            if world.mega is None:
                raise RuntimeError(
                    f"restore: space {sd['id']} is a megaspace but the "
                    "World was not built with megaspace=True"
                )
            for i in range(world.n_spaces):
                world._shard_space[i] = sp.id
            sp.is_mega = True
        elif sd.get("use_aoi", True):
            try:
                shard = world._shard_space.index(None)
            except ValueError:
                raise RuntimeError(
                    f"restore: no free shard for space {sd['id']} "
                    f"({world.n_spaces} configured)"
                ) from None
            world._shard_space[shard] = sp.id
            sp.shard = shard
        world.entities[sp.id] = sp
        world.spaces[sp.id] = sp
        _load_attrs_quiet(sp, sd.get("attrs", {}))
        for tid in world.timers.restore(sd.get("timers", [])):
            sp.timer_ids.add(tid)
        sp.OnRestored()

    # pass 3: entities — client bound BEFORE entering the space so the
    # spawn staging records has_client/client_gate in the same tick
    for ed in data["entities"]:
        desc = world.registry.get(ed["type"])
        e: Entity = desc.cls()
        e._type_desc = desc
        world._attach(e, ed["id"])
        world.entities[e.id] = e
        _load_attrs_quiet(e, ed.get("attrs", {}))
        if ed.get("client"):
            e.client = GameClient(ed["client"][0], ed["client"][1], world,
                                  owner=e)
        target = world.spaces.get(ed.get("space_id") or "") or world.nil_space
        world._enter_space_local(
            e, target, tuple(ed["pos"]), moving=bool(ed.get("moving"))
        )
        e._pending_yaw = float(ed.get("yaw", 0.0))
        world.stage_pos_set(e)
        for tid in world.timers.restore(ed.get("timers", [])):
            e.timer_ids.add(tid)
        e.OnRestored()

    logger.info(
        "restored %d spaces + %d entities into game%d",
        len(data["spaces"]), len(data["entities"]), world.game_id,
    )


# =======================================================================
# file IO
# =======================================================================
def write_freeze_file(path: str, data: dict) -> None:
    """Atomic write (tmp + rename): a crash mid-freeze must never leave a
    truncated file that a ``-restore`` boot would half-load."""
    blob = msgpack.packb(data, use_bin_type=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    # chaos crashpoint (`crash:freeze.write:...`): dying HERE — after
    # the tmp write, before the rename — models the worst mid-freeze
    # crash; the invariant under test is that only the .tmp is left and
    # the -restore boot falls back instead of half-loading
    faults.maybe_crash("freeze.write")
    os.replace(tmp, path)
    logger.info("froze %d bytes -> %s", len(blob), path)


def read_freeze_file(path: str) -> dict:
    with open(path, "rb") as f:
        raw = f.read()
    try:
        data = msgpack.unpackb(raw, raw=False, strict_map_key=False)
    except Exception as exc:
        raise CorruptSnapshotError(
            f"snapshot {path!r} is corrupt ({len(raw)} bytes): {exc}"
        ) from exc
    if not isinstance(data, dict) or "version" not in data:
        raise CorruptSnapshotError(
            f"snapshot {path!r} parsed but is not a freeze record"
        )
    return data


def freeze_to_file(world: World, directory: str = ".") -> str:
    path = os.path.join(directory, freeze_filename(world.game_id))
    write_freeze_file(path, freeze_world(world))
    return path


def snapshot_candidates(game_id: int, directory: str = ".") -> list[str]:
    """Existing snapshot files for a game, freshest (by mtime) first:
    the freeze file (intentional reload) and the periodic crash-recovery
    checkpoint. Mtime orders because either can be stale — a freeze file
    left over from an old reload must not shadow hours of newer
    checkpoints after a crash, and vice versa."""
    cands = []
    for p in (os.path.join(directory, freeze_filename(game_id)),
              os.path.join(directory, checkpoint_filename(game_id))):
        try:
            cands.append((os.path.getmtime(p), p))
        except OSError:
            continue
    return [p for _, p in sorted(cands, reverse=True)]


def latest_snapshot_path(game_id: int, directory: str = ".") -> str | None:
    cands = snapshot_candidates(game_id, directory)
    return cands[0] if cands else None


def has_restorable_snapshot(game_id: int, directory: str = ".") -> bool:
    """True when at least one snapshot candidate PARSES. The boot path
    decides restore-vs-cold on this, so an all-corrupt snapshot set
    degrades to a loud cold boot instead of a supervisor crash loop."""
    for path in snapshot_candidates(game_id, directory):
        try:
            read_freeze_file(path)
            return True
        except CorruptSnapshotError as exc:
            logger.error("ignoring unrestorable snapshot: %s", exc)
    return False


def restore_from_file(world: World, directory: str = ".") -> None:
    """Restore for a ``-restore`` boot from the freshest PARSEABLE
    snapshot (:func:`snapshot_candidates`): a freeze file written by a
    reload, or a crash-recovery checkpoint written by the periodic
    cadence — the capability the reference lacks (a crashed, unfrozen
    game there loses everything since the last persistence save;
    SURVEY.md §5.3). A corrupt candidate (truncated write, disk fault)
    is rejected WHOLE and the next-freshest tried — recovery invariant:
    a damaged snapshot may cost freshness, never a half-loaded world or
    a supervisor crash loop."""
    cands = snapshot_candidates(world.game_id, directory)
    if not cands:
        raise FileNotFoundError(
            f"no freeze or checkpoint snapshot for game{world.game_id} "
            f"in {directory!r}"
        )
    data = None
    for path in cands:
        try:
            data = read_freeze_file(path)
            break
        except CorruptSnapshotError as exc:
            logger.error("rejecting snapshot: %s", exc)
    if data is None:
        raise CorruptSnapshotError(
            f"every snapshot candidate for game{world.game_id} is "
            f"corrupt: {cands}"
        )
    logger.info("restoring game%d from %s", world.game_id, path)
    restore_world(world, data)


# =======================================================================
# async checkpoint (crash recovery while the world keeps running)
# =======================================================================
def checkpoint_filename(game_id: int) -> str:
    return f"game{game_id}_checkpoint.dat"


class CheckpointHandle:
    """Handle to an in-flight async checkpoint: ``join()`` waits, then
    ``path``/``error`` report the outcome."""

    def __init__(self):
        self.path: str | None = None
        self.error: BaseException | None = None
        self._thread: "threading.Thread | None" = None

    def join(self, timeout: float | None = None) -> "CheckpointHandle":
        assert self._thread is not None
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint still in flight")
        if self.error is not None:
            raise self.error
        return self


def checkpoint_async(world: World, directory: str = ".") -> CheckpointHandle:
    """Snapshot a RUNNING world without stalling its tick loop.

    The reference has only stop-the-world freeze (SIGHUP reload, SURVEY.md
    §3.6) plus per-entity attr persistence; a TPU world can do better
    because device state is immutable — capturing ``world.state`` costs
    nothing, and the host part (attrs, timers, client bindings) packs
    synchronously at the same tick boundary. The slow work — the
    device->host transfer of the captured planes and the file write —
    runs on a background thread while ticks continue. The file is the
    standard freeze format (written atomically), restorable with
    :func:`restore_world` / :func:`restore_from_file`.

    Call from the logic thread, between ticks.
    """
    import threading

    if getattr(world, "_multihost", False):
        # a background device fetch would be a one-sided collective
        # under multi-controller (manager._dget contract); checkpoint
        # synchronously there instead
        raise RuntimeError(
            "checkpoint_async is single-controller only; multihost "
            "worlds must checkpoint synchronously (freeze_to_file)"
        )
    if getattr(world, "_ckpt_inflight", False):
        # overlapping checkpoints would race on the same output path;
        # calls come from the logic thread, so a plain flag suffices
        raise RuntimeError("a checkpoint is already in flight")
    world._ckpt_inflight = True
    state_ref = world.state            # immutable pytree: the snapshot
    data = freeze_world(world, _snap=_DEFER, run_hooks=False)
    path = os.path.join(directory, checkpoint_filename(world.game_id))
    handle = CheckpointHandle()

    def work() -> None:
        try:
            snap = jax.device_get({
                "pos": state_ref.pos,
                "yaw": state_ref.yaw,
                "npc_moving": state_ref.npc_moving,
            })
            for rec in data["entities"]:
                ref = rec.pop("_slot", None)
                if ref is not None:
                    sh, sl = ref
                    rec["pos"] = [float(v) for v in snap["pos"][sh, sl]]
                    rec["yaw"] = float(snap["yaw"][sh, sl])
                    rec["moving"] = bool(snap["npc_moving"][sh, sl])
            write_freeze_file(path, data)   # already atomic (tmp+replace)
            handle.path = path
        except BaseException as exc:  # surfaced via join()
            handle.error = exc
            logger.exception("async checkpoint failed")
        finally:
            world._ckpt_inflight = False

    t = threading.Thread(target=work, name="ckpt", daemon=True)
    handle._thread = t
    t.start()
    return handle
