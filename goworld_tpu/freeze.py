"""Freeze / restore — whole-game snapshot for hot reload.

Reference being rebuilt: ``engine/entity/EntityManager.go:520-617``
(``Freeze`` packs every entity's migrate-style data requiring exactly one
nil space; ``RestoreFreezedEntities`` rebuilds in 3 passes — nil space,
then spaces, then entities) plus ``components/game/GameService.go:220-269``
(``doFreeze`` drains pending work and writes ``game%d_freezed.dat``) and
``components/game/restore.go:16-34`` (read + unpack on ``-restore`` boot).

TPU adaptation: the reference walks heap objects; here the canonical hot
state (positions, yaw, npc_moving) lives in device SoA arrays, so freezing
does ONE ``jax.device_get`` of the relevant planes and joins them with the
host-side attr trees / timers / client bindings. Restore rebuilds the host
object graph and lets the normal staging path repopulate device rows on the
first tick — the same "spaces before entities" ordering the reference uses,
because entities need their target space's AOI shard to exist.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import msgpack
import numpy as np

from goworld_tpu.entity.entity import Entity, GameClient
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.utils import faults, log

logger = log.get("freeze")

FREEZE_FORMAT_VERSION = 1


class CorruptSnapshotError(RuntimeError):
    """A freeze/checkpoint file exists but cannot be parsed (truncated
    write, disk fault, crash before the atomic rename of a pre-1 format
    writer). The restore path REJECTS such a file whole — half-loading a
    world is worse than falling back to an older snapshot or a cold
    boot."""


def freeze_filename(game_id: int) -> str:
    """Reference ``game%d_freezed.dat`` (``GameService.go:252``)."""
    return f"game{game_id}_freezed.dat"


# =======================================================================
# pack
# =======================================================================
def _device_snapshot(world: World) -> dict[str, np.ndarray]:
    """One batched transfer of every plane freeze needs (per-entity reads
    would pay the host<->device latency once per entity)."""
    st = world.state
    return world._dget({
        "pos": st.pos, "yaw": st.yaw, "npc_moving": st.npc_moving,
    })


# sentinel: pack device-resident pos/yaw/moving LATER from a state
# reference (async checkpoints patch the records off-thread)
_DEFER = object()


def _pack_entity(world: World, e: Entity, snap) -> dict:
    """Migrate-style record (``GetMigrateData``, ``Entity.go:1060-1101``)
    plus the space binding freeze needs and migrate doesn't."""
    live_slot = (
        e.slot is not None and e.shard is not None
        and e._pending_pos is None
    )
    extra: dict = {}
    if live_slot and snap is _DEFER:
        # placeholders; the checkpoint worker patches pos/yaw/moving
        # from the captured state off-thread (no device read here)
        pos, yaw, moving = [0.0, 0.0, 0.0], 0.0, False
        extra["_slot"] = [e.shard, e.slot]
    elif live_slot and snap is not None:
        shard, slot = e.shard, e.slot
        pos = [float(v) for v in snap["pos"][shard, slot]]
        yaw = float(snap["yaw"][shard, slot])
        moving = bool(snap["npc_moving"][shard, slot])
    else:
        pos = [float(v) for v in e.position]
        yaw = float(e._pending_yaw or 0.0)
        moving = False
    return extra | {
        "type": e.type_name,
        "id": e.id,
        "attrs": e.attrs.to_dict(),
        "client": (
            [e.client.gate_id, e.client.client_id]
            if e.client is not None else None
        ),
        "pos": pos,
        "yaw": yaw,
        "moving": moving,
        "space_id": e.space.id if e.space is not None else None,
        "timers": world.timers.dump(list(e.timer_ids)),
    }


def freeze_world(world: World, *, _snap=None, run_hooks: bool = True
                 ) -> dict:
    """Pack the entire world. Requires exactly one nil space (the same
    invariant the reference asserts, ``EntityManager.go:536-541``).

    ``_snap=_DEFER`` packs host state only, embedding (shard, slot) refs
    for the checkpoint worker to patch later; ``run_hooks=False`` skips
    OnFreeze (async checkpoints snapshot a RUNNING world — the reload
    hook contract doesn't apply)."""
    if world.nil_space is None:
        raise RuntimeError("cannot freeze: no nil space")
    # a pipelined world may hold one tick's outputs undecoded — the
    # snapshot must not lose their client sends / interest updates
    world.flush_pending_outputs()
    snap = _snap if _snap is not None else _device_snapshot(world)

    if run_hooks:
        for e in list(world.entities.values()):
            if not e.destroyed:
                try:
                    e.OnFreeze()
                except Exception:
                    logger.exception("OnFreeze failed for %s", e)

    spaces: list[dict] = []
    entities: list[dict] = []
    for e in world.entities.values():
        if e.destroyed:
            continue
        if e is world.nil_space:
            continue
        if isinstance(e, Space):
            spaces.append({
                "type": e.type_name,
                "id": e.id,
                "attrs": e.attrs.to_dict(),
                "use_aoi": e.shard is not None,
                "mega": e.is_mega,
                "timers": world.timers.dump(list(e.timer_ids)),
            })
        else:
            entities.append(_pack_entity(world, e, snap))

    nil = world.nil_space
    return {
        "version": FREEZE_FORMAT_VERSION,
        "game_id": world.game_id,
        "nil_space": {
            "attrs": nil.attrs.to_dict(),
            "timers": world.timers.dump(list(nil.timer_ids)),
        },
        "spaces": spaces,
        "entities": entities,
    }


# =======================================================================
# unpack
# =======================================================================
def _load_attrs_quiet(e: Entity, attrs: dict) -> None:
    """Fill the attr tree without journaling deltas: the restore path must
    not fan out attr-change messages (clients either reconnect fresh or
    already hold the values — reference 're-assign clients quietly')."""
    from goworld_tpu.entity.attrs import load_into

    cb = e.attrs._root_cb
    e.attrs._root_cb = None
    try:
        load_into(e.attrs, attrs)
    finally:
        e.attrs._root_cb = cb


def restore_world(world: World, data: dict) -> None:
    """3-pass rebuild into a freshly constructed World (reference
    ``RestoreFreezedEntities``, ``EntityManager.go:556-617``)."""
    if data.get("version") != FREEZE_FORMAT_VERSION:
        raise ValueError(f"freeze format {data.get('version')!r} unsupported")
    if world.entities and not (
        len(world.entities) == 1 and world.nil_space is not None
    ):
        raise RuntimeError("restore requires an empty world")

    # pass 1: nil space (the migration anchor; its id is deterministic
    # from game_id so routing and CallNilSpaces keep working)
    nil = world.nil_space or world.create_nil_space()
    _load_attrs_quiet(nil, data["nil_space"].get("attrs", {}))
    for tid in world.timers.restore(data["nil_space"].get("timers", [])):
        nil.timer_ids.add(tid)

    # pass 2: spaces (entities need their shard to exist before entering)
    for sd in data["spaces"]:
        desc = world.registry.get(sd["type"])
        sp: Space = desc.cls()
        sp._type_desc = desc
        world._attach(sp, sd["id"])
        if sd.get("mega"):
            if world.mega is None:
                raise RuntimeError(
                    f"restore: space {sd['id']} is a megaspace but the "
                    "World was not built with megaspace=True"
                )
            for i in range(world.n_spaces):
                world._shard_space[i] = sp.id
            sp.is_mega = True
        elif sd.get("use_aoi", True):
            try:
                shard = world._shard_space.index(None)
            except ValueError:
                raise RuntimeError(
                    f"restore: no free shard for space {sd['id']} "
                    f"({world.n_spaces} configured)"
                ) from None
            world._shard_space[shard] = sp.id
            sp.shard = shard
        world.entities[sp.id] = sp
        world.spaces[sp.id] = sp
        _load_attrs_quiet(sp, sd.get("attrs", {}))
        for tid in world.timers.restore(sd.get("timers", [])):
            sp.timer_ids.add(tid)
        sp.OnRestored()

    # pass 3: entities — client bound BEFORE entering the space so the
    # spawn staging records has_client/client_gate in the same tick
    for ed in data["entities"]:
        desc = world.registry.get(ed["type"])
        e: Entity = desc.cls()
        e._type_desc = desc
        world._attach(e, ed["id"])
        world.entities[e.id] = e
        _load_attrs_quiet(e, ed.get("attrs", {}))
        if ed.get("client"):
            e.client = GameClient(ed["client"][0], ed["client"][1], world,
                                  owner=e)
        target = world.spaces.get(ed.get("space_id") or "") or world.nil_space
        world._enter_space_local(
            e, target, tuple(ed["pos"]), moving=bool(ed.get("moving"))
        )
        world.stage_pose(e, ed["pos"], float(ed.get("yaw", 0.0)))
        for tid in world.timers.restore(ed.get("timers", [])):
            e.timer_ids.add(tid)
        e.OnRestored()

    if world.audit is not None:
        # the direct rebuilds above bypass the ledger hooks: re-anchor
        # the audit census on the restored population (ISSUE 17)
        world.audit.ledger.resync(
            {e.id: e.type_name for e in world.entities.values()
             if not e.destroyed},
            world.tick_count)

    logger.info(
        "restored %d spaces + %d entities into game%d",
        len(data["spaces"]), len(data["entities"]), world.game_id,
    )


# =======================================================================
# file IO
# =======================================================================
def write_freeze_file(path: str, data: dict) -> None:
    """Atomic write (tmp + rename): a crash mid-freeze must never leave a
    truncated file that a ``-restore`` boot would half-load."""
    blob = msgpack.packb(data, use_bin_type=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    # chaos crashpoint (`crash:freeze.write:...`): dying HERE — after
    # the tmp write, before the rename — models the worst mid-freeze
    # crash; the invariant under test is that only the .tmp is left and
    # the -restore boot falls back instead of half-loading
    faults.maybe_crash("freeze.write")
    os.replace(tmp, path)
    logger.info("froze %d bytes -> %s", len(blob), path)


def read_freeze_file(path: str) -> dict:
    """Read + parse one snapshot. Version-2 (quantized/delta plane)
    files are RESOLVED here — a delta re-reads its keyframe, verifies
    the per-plane CRCs it recorded against the keyframe's actual
    planes, and reconstructs a version-1 record — so every caller
    (restore_world, has_restorable_snapshot, the candidate fallback
    walk) keeps working on the v1 shape, and ANY chain damage
    (truncated delta, missing/rewritten keyframe, CRC mismatch)
    surfaces as the same CorruptSnapshotError the freshest-parseable
    fallback already handles."""
    with open(path, "rb") as f:
        raw = f.read()
    try:
        data = msgpack.unpackb(raw, raw=False, strict_map_key=False)
    except Exception as exc:
        raise CorruptSnapshotError(
            f"snapshot {path!r} is corrupt ({len(raw)} bytes): {exc}"
        ) from exc
    if not isinstance(data, dict) or "version" not in data:
        raise CorruptSnapshotError(
            f"snapshot {path!r} parsed but is not a freeze record"
        )
    if data.get("version") == SNAPSHOT_PLANE_VERSION:
        return _resolve_snapshot_v2(path, data)
    return data


def freeze_to_file(world: World, directory: str = ".") -> str:
    path = os.path.join(directory, freeze_filename(world.game_id))
    write_freeze_file(path, freeze_world(world))
    return path


def snapshot_candidates(game_id: int, directory: str = ".") -> list[str]:
    """Existing snapshot files for a game, freshest (by mtime) first:
    the freeze file (intentional reload), the periodic crash-recovery
    checkpoint, and the quantized/delta snapshot chain (delta first —
    it is the newest state; a corrupt or base-mismatched delta raises
    CorruptSnapshotError and the walk falls back to its keyframe).
    Mtime orders because any can be stale — a freeze file left over
    from an old reload must not shadow hours of newer checkpoints
    after a crash, and vice versa."""
    cands = []
    for p in (os.path.join(directory, freeze_filename(game_id)),
              os.path.join(directory, checkpoint_filename(game_id)),
              os.path.join(directory, chain_delta_filename(game_id)),
              os.path.join(directory, chain_key_filename(game_id))):
        try:
            cands.append((os.path.getmtime(p), p))
        except OSError:
            continue
    return [p for _, p in sorted(cands, reverse=True)]


def latest_snapshot_path(game_id: int, directory: str = ".") -> str | None:
    cands = snapshot_candidates(game_id, directory)
    return cands[0] if cands else None


def has_restorable_snapshot(game_id: int, directory: str = ".") -> bool:
    """True when at least one snapshot candidate PARSES. The boot path
    decides restore-vs-cold on this, so an all-corrupt snapshot set
    degrades to a loud cold boot instead of a supervisor crash loop."""
    for path in snapshot_candidates(game_id, directory):
        try:
            read_freeze_file(path)
            return True
        except CorruptSnapshotError as exc:
            logger.error("ignoring unrestorable snapshot: %s", exc)
    return False


def restore_from_file(world: World, directory: str = ".") -> None:
    """Restore for a ``-restore`` boot from the freshest PARSEABLE
    snapshot (:func:`snapshot_candidates`): a freeze file written by a
    reload, or a crash-recovery checkpoint written by the periodic
    cadence — the capability the reference lacks (a crashed, unfrozen
    game there loses everything since the last persistence save;
    SURVEY.md §5.3). A corrupt candidate (truncated write, disk fault)
    is rejected WHOLE and the next-freshest tried — recovery invariant:
    a damaged snapshot may cost freshness, never a half-loaded world or
    a supervisor crash loop."""
    cands = snapshot_candidates(world.game_id, directory)
    if not cands:
        raise FileNotFoundError(
            f"no freeze or checkpoint snapshot for game{world.game_id} "
            f"in {directory!r}"
        )
    data = None
    for path in cands:
        try:
            data = read_freeze_file(path)
            break
        except CorruptSnapshotError as exc:
            logger.error("rejecting snapshot: %s", exc)
    if data is None:
        raise CorruptSnapshotError(
            f"every snapshot candidate for game{world.game_id} is "
            f"corrupt: {cands}"
        )
    logger.info("restoring game%d from %s", world.game_id, path)
    restore_world(world, data)


# =======================================================================
# async checkpoint (crash recovery while the world keeps running)
# =======================================================================
def checkpoint_filename(game_id: int) -> str:
    return f"game{game_id}_checkpoint.dat"


class CheckpointHandle:
    """Handle to an in-flight async checkpoint: ``join()`` waits, then
    ``path``/``error`` report the outcome."""

    def __init__(self):
        self.path: str | None = None
        self.error: BaseException | None = None
        self._thread: "threading.Thread | None" = None

    def join(self, timeout: float | None = None) -> "CheckpointHandle":
        assert self._thread is not None
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint still in flight")
        if self.error is not None:
            raise self.error
        return self


def _pin_snapshot_planes(world):
    """Resident-world fence (ISSUE 20): background snapshot workers
    fetch pos/yaw/npc_moving from a state reference captured on the
    tick thread — under carry donation the NEXT tick DELETES those
    buffers, and the deferred ``jax.device_get`` would raise on a
    deleted array mid-write. When the world is resident, pin the three
    planes with an explicit device copy taken NOW (between ticks, on
    the tick thread): the copies are fresh buffers the donated step
    never sees, so they survive any number of subsequent ticks.
    Non-resident worlds keep the zero-copy capture of the immutable
    state pytree. The fallback is loud once per world — an operator
    sizing snapshot cost should know the copy-mode tax exists."""
    state = world.state
    if not getattr(world, "resident", False):
        return state
    if not getattr(world, "_resident_copy_warned", True):
        world._resident_copy_warned = True
        logger.info(
            "resident world %s: snapshot capture pins pos/yaw/"
            "npc_moving with a device copy (carry donation deletes "
            "the live buffers next tick)", world.game_id)
    from types import SimpleNamespace

    return SimpleNamespace(
        pos=jax.numpy.copy(state.pos),
        yaw=jax.numpy.copy(state.yaw),
        npc_moving=jax.numpy.copy(state.npc_moving),
    )


def checkpoint_async(world: World, directory: str = ".") -> CheckpointHandle:
    """Snapshot a RUNNING world without stalling its tick loop.

    The reference has only stop-the-world freeze (SIGHUP reload, SURVEY.md
    §3.6) plus per-entity attr persistence; a TPU world can do better
    because device state is immutable — capturing ``world.state`` costs
    nothing, and the host part (attrs, timers, client bindings) packs
    synchronously at the same tick boundary. The slow work — the
    device->host transfer of the captured planes and the file write —
    runs on a background thread while ticks continue. The file is the
    standard freeze format (written atomically), restorable with
    :func:`restore_world` / :func:`restore_from_file`.

    Call from the logic thread, between ticks.
    """
    import threading

    if getattr(world, "_multihost", False):
        # a background device fetch would be a one-sided collective
        # under multi-controller (manager._dget contract); checkpoint
        # synchronously there instead
        raise RuntimeError(
            "checkpoint_async is single-controller only; multihost "
            "worlds must checkpoint synchronously (freeze_to_file)"
        )
    if getattr(world, "_ckpt_inflight", False):
        # overlapping checkpoints would race on the same output path;
        # calls come from the logic thread, so a plain flag suffices
        raise RuntimeError("a checkpoint is already in flight")
    world._ckpt_inflight = True
    state_ref = _pin_snapshot_planes(world)  # the snapshot (pinned
    # device copies when the world donates its carry, else the
    # immutable pytree itself)
    data = freeze_world(world, _snap=_DEFER, run_hooks=False)
    path = os.path.join(directory, checkpoint_filename(world.game_id))
    handle = CheckpointHandle()

    def work() -> None:
        try:
            snap = jax.device_get({
                "pos": state_ref.pos,
                "yaw": state_ref.yaw,
                "npc_moving": state_ref.npc_moving,
            })
            for rec in data["entities"]:
                ref = rec.pop("_slot", None)
                if ref is not None:
                    sh, sl = ref
                    rec["pos"] = [float(v) for v in snap["pos"][sh, sl]]
                    rec["yaw"] = float(snap["yaw"][sh, sl])
                    rec["moving"] = bool(snap["npc_moving"][sh, sl])
            write_freeze_file(path, data)   # already atomic (tmp+replace)
            handle.path = path
        except BaseException as exc:  # surfaced via join()
            handle.error = exc
            logger.exception("async checkpoint failed")
        finally:
            world._ckpt_inflight = False

    t = threading.Thread(target=work, name="ckpt", daemon=True)
    handle._thread = t
    t.start()
    return handle


# =======================================================================
# quantized + delta-compressed snapshot chain (ISSUE 12)
# =======================================================================
# The monolithic msgpack snapshot re-serializes every entity's full
# f32 position/yaw each cadence. The chain writes the device planes
# QUANTIZED (int16 lattice coordinates — the same power-of-two lattice
# the precision sweep and the delta-sync wire use, GridSpec.quant_step)
# and DELTA-COMPRESSED: every `keyframe_every`-th write is a full
# keyframe, the writes between ship only the rows whose quantized
# planes changed, against the keyframe — with a per-plane CRC of the
# base recorded in each delta so a rewritten/damaged keyframe can
# never be silently merged (mismatch => CorruptSnapshotError => the
# candidate walk falls back to the keyframe itself, then the legacy
# files). Restore of a quantized snapshot is BIT-EXACT in the lattice
# domain: lattice points re-quantize to themselves, so
# write->restore->write produces byte-identical planes (tested in
# tests/test_freeze.py).

SNAPSHOT_PLANE_VERSION = 2
_PLANES = ("pos_xz", "pos_y", "yaw", "moving")
# yaw wire/plane step: full turn in 2^16 int16 steps (headings are
# modular, so int16 wraparound IS the mod-2pi wrap)
YAW_STEP = (2.0 * 3.141592653589793) / 65536.0


def chain_key_filename(game_id: int) -> str:
    return f"game{game_id}_ckpt_key.dat"


def chain_delta_filename(game_id: int) -> str:
    return f"game{game_id}_ckpt_delta.dat"


def _crc(b: bytes) -> int:
    import zlib

    return zlib.crc32(b) & 0xFFFFFFFF


def snapshot_quant_step(world: World) -> float:
    """The chain's position lattice step — GridSpec.quant_step, i.e.
    the EXACT step the precision sweep runs on when precision=q16
    (those worlds roundtrip bit-for-bit against their own AOI-visible
    positions), and the same <=2^15-points-per-axis power-of-two
    derivation for f32 worlds."""
    return world.cfg.grid.quant_step


def _extract_planes(data: dict, step: float,
                    origin: tuple = (0.0, 0.0)) -> dict:
    """Strip pos/yaw/moving out of a v1 record's entity list into
    quantized column planes (row i == entities[i]). ``origin`` is the
    grid origin — lattice coordinates are ORIGIN-RELATIVE so worlds
    with shifted/negative bounds quantize correctly (positions outside
    [origin, origin + 2^15*step) clamp into that window, the same
    clamp-into-bounds semantic the grid applies)."""
    ents = data["entities"]
    m = len(ents)
    ox, oz = float(origin[0]), float(origin[1])
    qxz = np.zeros((m, 2), np.int16)
    py = np.zeros((m,), np.float32)
    qyaw = np.zeros((m,), np.int16)
    mov = np.zeros((m,), np.uint8)
    hi = 32767
    for i, e in enumerate(ents):
        px, pyv, pz = e.pop("pos")
        qxz[i, 0] = min(max(int(np.floor((px - ox) / step)), 0), hi)
        qxz[i, 1] = min(max(int(np.floor((pz - oz) / step)), 0), hi)
        py[i] = np.float32(pyv)
        # modular wrap: int16 overflow of a heading is the 2pi wrap
        qyaw[i] = np.int16(
            np.uint16(int(round(e.pop("yaw") / YAW_STEP)) & 0xFFFF))
        mov[i] = 1 if e.pop("moving") else 0
    return {
        "pos_xz": qxz.tobytes(), "pos_y": py.tobytes(),
        "yaw": qyaw.tobytes(), "moving": mov.tobytes(),
    }


def _inject_planes(data: dict, planes: dict, step: float,
                   origin: tuple = (0.0, 0.0)) -> dict:
    """Inverse of :func:`_extract_planes`: dequantize the planes back
    into the entity records (v1 shape)."""
    ents = data["entities"]
    m = len(ents)
    ox, oz = float(origin[0]), float(origin[1])
    qxz = np.frombuffer(planes["pos_xz"], np.int16).reshape(m, 2)
    py = np.frombuffer(planes["pos_y"], np.float32)
    qyaw = np.frombuffer(planes["yaw"], np.int16)
    mov = np.frombuffer(planes["moving"], np.uint8)
    for i, e in enumerate(ents):
        e["pos"] = [float(np.float32(ox + int(qxz[i, 0]) * step)),
                    float(py[i]),
                    float(np.float32(oz + int(qxz[i, 1]) * step))]
        e["yaw"] = float((int(qyaw[i]) & 0xFFFF) * YAW_STEP)
        e["moving"] = bool(mov[i])
    return data


def _resolve_snapshot_v2(path: str, data: dict) -> dict:
    """Resolve a version-2 snapshot into the v1 record shape
    (read_freeze_file calls this; ALL failures — missing keys, wrong
    shapes, short planes — surface as CorruptSnapshotError so the
    freshest-parseable fallback walk handles them; a raw
    KeyError/ValueError here would crash the -restore boot instead of
    falling back)."""
    try:
        return _resolve_snapshot_v2_inner(path, data)
    except CorruptSnapshotError:
        raise
    except Exception as exc:
        raise CorruptSnapshotError(
            f"snapshot {path!r}: malformed v2 record ({exc!r})"
        ) from exc


def _resolve_snapshot_v2_inner(path: str, data: dict) -> dict:
    kind = data["kind"]
    step = float(data["quant"]["step"])
    origin = tuple(data["quant"].get("origin", (0.0, 0.0)))
    host = data["host"]
    planes = {nm: data["planes"][nm] for nm in _PLANES} \
        if kind == "key" else None
    if kind == "key":
        for nm in _PLANES:
            if _crc(planes[nm]) != data["plane_crcs"][nm]:
                raise CorruptSnapshotError(
                    f"snapshot {path!r}: plane {nm!r} CRC mismatch"
                )
    elif kind == "delta":
        base_path = os.path.join(os.path.dirname(path) or ".",
                                 data["base"]["file"])
        try:
            with open(base_path, "rb") as f:
                base = msgpack.unpackb(f.read(), raw=False,
                                       strict_map_key=False)
        except Exception as exc:
            raise CorruptSnapshotError(
                f"snapshot {path!r}: keyframe {base_path!r} "
                f"unreadable ({exc})"
            ) from exc
        if not isinstance(base, dict) or base.get("kind") != "key":
            raise CorruptSnapshotError(
                f"snapshot {path!r}: {base_path!r} is not a keyframe")
        for nm in _PLANES:
            if _crc(base["planes"][nm]) != data["base"]["plane_crcs"][nm]:
                # the keyframe moved on (or was damaged) under this
                # delta — merging would mix two worlds' planes
                raise CorruptSnapshotError(
                    f"snapshot {path!r}: base plane {nm!r} CRC "
                    f"mismatch vs {base_path!r}"
                )
        # reconstruct: each delta row either references a keyframe row
        # (by index) or ships its own values in the sparse section
        try:
            m = len(host["entities"])
            rows = np.frombuffer(data["rows"], np.int32)
            sparse = data["sparse"]
            widths = {"pos_xz": (np.int16, 2), "pos_y": (np.float32, 1),
                      "yaw": (np.int16, 1), "moving": (np.uint8, 1)}
            planes = {}
            for nm, (dt, w) in widths.items():
                bp = np.frombuffer(base["planes"][nm], dt)
                sp = np.frombuffer(sparse[nm], dt)
                bp = bp.reshape(-1, w)
                sp = sp.reshape(-1, w)
                out = np.zeros((m, w), dt)
                ref = rows >= 0
                out[ref] = bp[rows[ref]]
                out[~ref] = sp
                planes[nm] = out.tobytes()
        except Exception as exc:
            raise CorruptSnapshotError(
                f"snapshot {path!r}: delta reconstruction failed "
                f"({exc!r})"
            ) from exc
    else:
        raise CorruptSnapshotError(
            f"snapshot {path!r}: unknown v2 kind {kind!r}")
    return _inject_planes(dict(host), planes, step, origin)


class SnapshotChain:
    """Quantized/delta snapshot writer for one world (checkpoint
    cadence). ``write()`` freezes the world synchronously; every
    ``keyframe_every``-th write (and the first) is a full keyframe,
    the rest are deltas against the last WRITTEN keyframe (held in
    memory, so delta writes never re-read disk). Files are written
    atomically via the same tmp+rename path as every snapshot.

    Scope honesty: the DELTA treatment covers the DEVICE planes
    (pos/yaw/moving — the bulk at NPC scale); the host section (ids,
    attrs, timers, bindings) still serializes whole each write,
    because attrs mutate outside any dirty tracking this writer can
    see — attr-heavy worlds keep correctness but less of the byte
    win.

    Threading: ``write()`` stays the synchronous whole path (tests,
    multihost leaders). The production game routes chain writes
    through the bounded replication worker instead
    (goworld_tpu/replication/worker.py — retiring the PR 12 tradeoff
    of diffing on the tick thread): the tick thread calls
    :meth:`capture` (cheap — host records with deferred plane refs),
    the worker calls :meth:`complete_capture` (the device fetch),
    :meth:`build` (quantize + diff) and :meth:`write_record` (disk).
    The keyframe memory (``_key_planes``/``_key_rows``) is touched
    only by build(), so exactly ONE thread may build — the worker's,
    or the caller's via write(), never both."""

    def __init__(self, world: World, directory: str = ".",
                 keyframe_every: int = 8):
        if keyframe_every < 1:
            raise ValueError(
                f"keyframe_every must be >= 1, got {keyframe_every!r}")
        self.world = world
        self.directory = directory
        self.keyframe_every = int(keyframe_every)
        self.step = snapshot_quant_step(world)
        # lattice coordinates are origin-relative (shifted/negative
        # worlds must not clamp to the zero corner)
        g = world.cfg.grid
        self.origin = (float(g.origin_x), float(g.origin_z))
        self._count = 0
        self._key_planes: dict | None = None
        self._key_crcs: dict | None = None
        self._key_rows: dict | None = None   # eid -> keyframe row

    def capture(self) -> tuple:
        """Tick-thread half of an off-thread chain write: host records
        with (shard, slot) plane refs deferred (no device read) plus
        the captured planes to fetch them from later (pinned device
        copies on a resident world — see :func:`_pin_snapshot_planes`).
        Pair with :meth:`complete_capture` on the worker thread."""
        state_ref = _pin_snapshot_planes(self.world)
        data = freeze_world(self.world, _snap=_DEFER, run_hooks=False)
        return data, state_ref, int(self.world.tick_count)

    @staticmethod
    def complete_capture(captured: tuple) -> tuple[dict, int]:
        """Worker-thread half: one batched device fetch of the captured
        planes, patched into the deferred records (the checkpoint_async
        worker's exact dance). Returns ``(data, tick)`` ready for
        :meth:`build`."""
        data, state_ref, tick = captured
        snap = jax.device_get({
            "pos": state_ref.pos,
            "yaw": state_ref.yaw,
            "npc_moving": state_ref.npc_moving,
        })
        for rec in data["entities"]:
            ref = rec.pop("_slot", None)
            if ref is not None:
                sh, sl = ref
                rec["pos"] = [float(v) for v in snap["pos"][sh, sl]]
                rec["yaw"] = float(snap["yaw"][sh, sl])
                rec["moving"] = bool(snap["npc_moving"][sh, sl])
        return data, tick

    def write(self) -> str:
        data = freeze_world(self.world, run_hooks=False)
        kind, rec = self.build(data)
        return self.write_record(kind, rec)

    def write_record(self, kind: str, rec: dict) -> str:
        """Write one built record to its chain file (atomic, same
        tmp+rename path as every snapshot)."""
        name = chain_key_filename(self.world.game_id) if kind == "key" \
            else chain_delta_filename(self.world.game_id)
        path = os.path.join(self.directory, name)
        write_freeze_file(path, rec)
        return path

    def build(self, data: dict, force_key: bool = False
              ) -> tuple[str, dict]:
        """Quantize + diff one captured v1 freeze dict into a chain
        record — ``("key"|"delta", record)`` — WITHOUT touching disk
        (the replication stream ships the same records in-band).
        Mutates the keyframe memory: single-builder-thread contract
        (class docstring). ``force_key`` forces a keyframe out of
        cadence (standby attach, CRC resync, backlog collapse)."""
        planes = _extract_planes(data, self.step,   # pops pos/yaw/moving
                                 self.origin)
        eids = [e["id"] for e in data["entities"]]
        is_key = (force_key or self._key_planes is None
                  or self._count % self.keyframe_every == 0)
        self._count += 1
        if is_key:
            crcs = {nm: _crc(planes[nm]) for nm in _PLANES}
            rec = {
                "version": SNAPSHOT_PLANE_VERSION, "kind": "key",
                "quant": {"step": self.step, "yaw_step": YAW_STEP,
                          "origin": list(self.origin)},
                "planes": planes, "plane_crcs": crcs, "host": data,
            }
            self._key_planes = planes
            self._key_crcs = crcs
            self._key_rows = {eid: i for i, eid in enumerate(eids)}
            return "key", rec
        # delta vs the remembered keyframe: a row is a REFERENCE when
        # the entity existed at the keyframe with identical quantized
        # planes, else its values ship in the sparse section
        widths = {"pos_xz": (np.int16, 2), "pos_y": (np.float32, 1),
                  "yaw": (np.int16, 1), "moving": (np.uint8, 1)}
        cur = {nm: np.frombuffer(planes[nm], dt).reshape(-1, w)
               for nm, (dt, w) in widths.items()}
        key = {nm: np.frombuffer(self._key_planes[nm], dt)
               .reshape(-1, w) for nm, (dt, w) in widths.items()}
        m = len(eids)
        # vectorized row diff: only the eid->row dict lookups loop;
        # the 4 plane compares run as whole-array numpy equality
        # (an O(entities) Python compare loop on the tick thread is
        # exactly the cost this chain exists to avoid)
        kr = np.asarray([self._key_rows.get(eid, -1) for eid in eids],
                        np.int32)
        same = kr >= 0
        krc = np.maximum(kr, 0)
        for nm in _PLANES:
            same &= (cur[nm][np.arange(m)] ==
                     key[nm][krc]).all(axis=1)
        rows = np.where(same, kr, np.int32(-1))
        sp_mask = rows < 0
        sparse = {nm: cur[nm][sp_mask].tobytes() for nm in _PLANES}
        rec = {
            "version": SNAPSHOT_PLANE_VERSION, "kind": "delta",
            "quant": {"step": self.step, "yaw_step": YAW_STEP,
                          "origin": list(self.origin)},
            "base": {
                "file": chain_key_filename(self.world.game_id),
                "plane_crcs": self._key_crcs,
            },
            "rows": rows.tobytes(), "sparse": sparse, "host": data,
        }
        return "delta", rec
