"""Global async key-value store (account->avatar maps, mail ids, ...).

Reference being rebuilt: ``engine/kvdb`` (``kvdb.go:42-200``): a cluster-
global KV store with pluggable backends, all ops running on a dedicated
async group (``_kvdb``) with callbacks posted to the logic thread:
``Get/Put/GetOrPut/GetRange/NextLargerKey``. Backends here: ``redis``
(networked RESP, reference ``kvdb/backend/kvdbredis``), ``filesystem``
(single msgpack file with ordered keys) and ``memory``; the interface
matches the reference's backend iface (``kvdb/types/kvdb_types.go``) so
a mongo/redis-cluster backend can slot in where a driver exists.
"""

from __future__ import annotations

import bisect
import os
import threading
from typing import Callable

import msgpack

from goworld_tpu.utils import log
from goworld_tpu.utils.asyncwork import AsyncWorkers

logger = log.get("kvdb")

_GROUP = "_kvdb"  # dedicated worker group (reference kvdb.go:42)


class KVDBBackend:
    def get(self, key: str) -> str | None:
        raise NotImplementedError

    def put(self, key: str, val: str) -> None:
        raise NotImplementedError

    def get_range(self, begin: str, end: str) -> list[tuple[str, str]]:
        """Items with begin <= key < end, ascending."""
        raise NotImplementedError

    def close(self) -> None: ...


class MemoryKVDB(KVDBBackend):
    def __init__(self):
        self._d: dict[str, str] = {}

    def get(self, key):
        return self._d.get(key)

    def put(self, key, val):
        self._d[key] = val

    def get_range(self, begin, end):
        keys = sorted(k for k in self._d if begin <= k < end)
        return [(k, self._d[k]) for k in keys]


class FilesystemKVDB(KVDBBackend):
    """Append-friendly single-file store; full rewrite on flush (small
    cluster metadata workloads, not bulk data)."""

    def __init__(self, path: str):
        self.path = path
        self._d: dict[str, str] = {}
        self._lock = threading.Lock()
        if os.path.exists(path):
            with open(path, "rb") as f:
                raw = f.read()
            if raw:
                self._d = msgpack.unpackb(raw, raw=False)

    def _flush(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(self._d, use_bin_type=True))
        os.replace(tmp, self.path)

    def get(self, key):
        with self._lock:
            return self._d.get(key)

    def put(self, key, val):
        with self._lock:
            self._d[key] = val
            self._flush()

    def get_range(self, begin, end):
        with self._lock:
            keys = sorted(k for k in self._d if begin <= k < end)
            return [(k, self._d[k]) for k in keys]


class RedisKVDB(KVDBBackend):
    """Networked backend over RESP (reference ``kvdb/backend/kvdbredis``;
    keys are namespaced ``kv:<key>`` so one redis db can host both the
    kvdb and entity storage). Range queries sweep SCAN and filter/sort
    client-side — the same shape the reference's redis backend uses
    (redis has no ordered keyspace)."""

    PREFIX = "kv:"

    def __init__(self, addr: str):
        from goworld_tpu.ext.db.resp import RespClient

        self._c = RespClient.from_addr(addr)

    def get(self, key):
        raw = self._c.get(self.PREFIX + key)
        return None if raw is None else raw.decode()

    def put(self, key, val):
        self._c.set(self.PREFIX + key, val)

    def get_range(self, begin, end):
        return _range_on(self._c, begin, end)

    def close(self):
        self._c.close()


def _range_on(client, begin: str, end: str) -> list[tuple[str, str]]:
    """SCAN-sweep one redis endpoint and return the [begin, end) window,
    values fetched in a single MGET round-trip."""
    pre = RedisKVDB.PREFIX
    keys = sorted(
        k.decode()[len(pre):] for k in client.scan_keys(pre + "*")
    )
    lo = bisect.bisect_left(keys, begin)
    hi = bisect.bisect_left(keys, end)
    sel = keys[lo:hi]
    vals = client.mget([pre + k for k in sel])
    return [(k, v.decode()) for k, v in zip(sel, vals) if v is not None]


def _crc16(data: bytes) -> int:
    """CRC16-CCITT (XModem) — redis cluster's key-slot hash function."""
    crc = 0
    for b in data:
        crc ^= b << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021) if crc & 0x8000 else (crc << 1)
            crc &= 0xFFFF
    return crc


class RedisClusterKVDB(KVDBBackend):
    """Client-side sharding over N INDEPENDENT redis endpoints (the
    architecture of the reference's ``kvdb/backend/kvdbrediscluster``
    role: horizontal kvdb capacity). Keys route by CRC16 (redis
    cluster's slot hash function) modulo the node count; range queries
    fan out to every node and merge.

    DEVIATION: this is NOT the redis cluster-mode protocol — there is
    no 16384-slot map, hashtag parsing, or MOVED-redirect handling, so
    point it at plain redis instances (or miniredis), not at the nodes
    of an actual cluster-mode deployment."""

    def __init__(self, addrs: list[str]):
        from goworld_tpu.ext.db.resp import RespClient

        if not addrs:
            raise ValueError("redis-cluster needs at least one node")
        self._nodes = [RespClient.from_addr(a) for a in addrs]

    def _node(self, key: str):
        return self._nodes[_crc16(key.encode()) % len(self._nodes)]

    def get(self, key):
        raw = self._node(key).get(RedisKVDB.PREFIX + key)
        return None if raw is None else raw.decode()

    def put(self, key, val):
        self._node(key).set(RedisKVDB.PREFIX + key, val)

    def get_range(self, begin, end):
        out: list[tuple[str, str]] = []
        for node in self._nodes:
            out.extend(_range_on(node, begin, end))
        out.sort()
        return out

    def close(self):
        for n in self._nodes:
            n.close()


def open_kvdb_backend(kind: str, location: str = "") -> KVDBBackend:
    if kind == "memory":
        return MemoryKVDB()
    if kind == "filesystem":
        return FilesystemKVDB(location or "kvdb_data.mp")
    if kind == "redis":
        return RedisKVDB(location or "127.0.0.1:6379")
    if kind in ("redis_cluster", "redis-cluster"):
        return RedisClusterKVDB(
            [a.strip() for a in location.split(",") if a.strip()]
        )
    raise ValueError(f"unknown kvdb backend {kind!r}")


def next_larger_key(key: str) -> str:
    """The smallest key strictly greater than every key prefixed by
    ``key`` is not needed — the reference's ``NextLargerKey`` returns
    ``key + "\\x00"``, the immediate successor (``kvdb.go:196-200``)."""
    return key + "\x00"


class KVDB:
    """Async facade (``world.kvdb = KVDB(backend, workers)``); callbacks
    run on the logic thread via the worlds's post queue."""

    def __init__(self, backend: KVDBBackend, workers: AsyncWorkers):
        self.backend = backend
        self.workers = workers

    def get(self, key: str,
            cb: Callable[[str | None, Exception | None], None]) -> None:
        self.workers.submit(_GROUP, lambda: self.backend.get(key), cb)

    def put(self, key: str, val: str,
            cb: Callable[[None, Exception | None], None] | None = None,
            ) -> None:
        self.workers.submit(_GROUP, lambda: self.backend.put(key, val), cb)

    def get_or_put(self, key: str, val: str,
                   cb: Callable[[str | None, Exception | None], None],
                   ) -> None:
        """Atomic read-else-write (reference ``GetOrPut``): returns the
        existing value (put skipped) or None (val written). Atomicity holds
        because all kvdb ops serialize on the single ``_kvdb`` worker."""

        def job():
            old = self.backend.get(key)
            if old is None:
                self.backend.put(key, val)
            return old

        self.workers.submit(_GROUP, job, cb)

    def get_range(self, begin: str, end: str,
                  cb: Callable[[list, Exception | None], None]) -> None:
        self.workers.submit(
            _GROUP, lambda: self.backend.get_range(begin, end), cb
        )
