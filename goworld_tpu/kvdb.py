"""Global async key-value store (account->avatar maps, mail ids, ...).

Reference being rebuilt: ``engine/kvdb`` (``kvdb.go:42-200``): a cluster-
global KV store with pluggable backends, all ops running on a dedicated
async group (``_kvdb``) with callbacks posted to the logic thread:
``Get/Put/GetOrPut/GetRange/NextLargerKey``. Backends here (matching the
reference's backend iface, ``kvdb/types/kvdb_types.go``): ``memory``,
``filesystem`` (single msgpack file with ordered keys), ``redis``
(networked RESP, reference ``kvdb/backend/kvdbredis``),
``redis_cluster`` (slot-map + MOVED/ASK redirect client, the
``kvdbrediscluster`` role) and ``mongodb`` (BSON/OP_MSG wire, the
``kvdb_mongodb`` layout) — the networked ones ride the from-scratch wire
clients in :mod:`goworld_tpu.ext.db`, no drivers required. Transient
backend errors are retried with capped exponential backoff before the
error reaches the caller's callback (``kvdb_retry_total`` counts
retries; see docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Callable

import msgpack

from goworld_tpu.utils import consts, faults, log, metrics, opmon, \
    overload
from goworld_tpu.utils.asyncwork import AsyncWorkers

logger = log.get("kvdb")

_GROUP = "_kvdb"  # dedicated worker group (reference kvdb.go:42)

# transient-error retry policy for backend ops: bounded attempts under a
# wall-clock budget with exponential backoff — a blip on the redis/mongo
# link must not surface as an op error, but a dead backend must fail the
# callback instead of wedging the single _kvdb worker forever
RETRY_ATTEMPTS = 3
RETRY_BASE_DELAY = 0.05
RETRY_DEADLINE = 5.0
_TRANSIENT = (ConnectionError, TimeoutError, OSError)


class KVDBBackend:
    def get(self, key: str) -> str | None:
        raise NotImplementedError

    def put(self, key: str, val: str) -> None:
        raise NotImplementedError

    def get_range(self, begin: str, end: str) -> list[tuple[str, str]]:
        """Items with begin <= key < end, ascending."""
        raise NotImplementedError

    def close(self) -> None: ...


class MemoryKVDB(KVDBBackend):
    def __init__(self):
        self._d: dict[str, str] = {}

    def get(self, key):
        return self._d.get(key)

    def put(self, key, val):
        self._d[key] = val

    def get_range(self, begin, end):
        keys = sorted(k for k in self._d if begin <= k < end)
        return [(k, self._d[k]) for k in keys]


class FilesystemKVDB(KVDBBackend):
    """Append-friendly single-file store; full rewrite on flush (small
    cluster metadata workloads, not bulk data)."""

    def __init__(self, path: str):
        self.path = path
        self._d: dict[str, str] = {}
        self._lock = threading.Lock()
        if os.path.exists(path):
            with open(path, "rb") as f:
                raw = f.read()
            if raw:
                self._d = msgpack.unpackb(raw, raw=False)

    def _flush(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(self._d, use_bin_type=True))
        os.replace(tmp, self.path)

    def get(self, key):
        with self._lock:
            return self._d.get(key)

    def put(self, key, val):
        with self._lock:
            self._d[key] = val
            self._flush()

    def get_range(self, begin, end):
        with self._lock:
            keys = sorted(k for k in self._d if begin <= k < end)
            return [(k, self._d[k]) for k in keys]


class RedisKVDB(KVDBBackend):
    """Networked backend over RESP (reference ``kvdb/backend/kvdbredis``;
    keys are namespaced ``kv:<key>`` so one redis db can host both the
    kvdb and entity storage). Range queries sweep SCAN and filter/sort
    client-side — the same shape the reference's redis backend uses
    (redis has no ordered keyspace)."""

    PREFIX = "kv:"

    def __init__(self, addr: str):
        from goworld_tpu.ext.db.resp import RespClient

        self._c = RespClient.from_addr(addr)

    def get(self, key):
        raw = self._c.get(self.PREFIX + key)
        return None if raw is None else raw.decode()

    def put(self, key, val):
        self._c.set(self.PREFIX + key, val)

    def get_range(self, begin, end):
        return _range_on(self._c, begin, end)

    def close(self):
        self._c.close()


def _range_on(client, begin: str, end: str) -> list[tuple[str, str]]:
    """SCAN-sweep one redis endpoint and return the [begin, end) window,
    values fetched in a single MGET round-trip."""
    pre = RedisKVDB.PREFIX
    keys = sorted(
        k.decode()[len(pre):] for k in client.scan_keys(pre + "*")
    )
    lo = bisect.bisect_left(keys, begin)
    hi = bisect.bisect_left(keys, end)
    sel = keys[lo:hi]
    vals = client.mget([pre + k for k in sel])
    return [(k, v.decode()) for k, v in zip(sel, vals) if v is not None]


class RedisClusterKVDB(KVDBBackend):
    """Redis CLUSTER-MODE client (the reference's
    ``kvdb/backend/kvdbrediscluster`` role: horizontal kvdb capacity),
    from scratch over the RESP client:

    * On connect it asks any reachable node ``CLUSTER SLOTS`` and
      builds the 16384-entry slot map; keys route by
      ``CRC16(key) % 16384`` with ``{hashtag}`` semantics — hashing
      the FULL key as sent (prefix included), so routing agrees with
      the server's own hash.
    * ``-MOVED slot host:port`` repairs the slot map and retries at
      the new owner (new nodes are dialed on demand, so the client
      follows resharding it was never told about); ``-ASK`` sends
      ``ASKING`` then retries once at the target WITHOUT a map update,
      per the migration protocol. Redirect chains are bounded.
    * Nodes that have cluster support disabled (plain redis/miniredis)
      fall back to LEGACY client-side sharding:
      ``CRC16 % len(nodes)`` over the configured endpoints — the
      pre-round-5 behavior, kept so independent-node deployments work
      unchanged.

    Range queries fan out to every known node and merge (same
    architecture as the reference's scan-across-shards)."""

    _MAX_REDIRECTS = 5

    def __init__(self, addrs: list[str]):
        from goworld_tpu.ext.db import resp

        if not addrs:
            raise ValueError("redis-cluster needs at least one node")
        self._resp = resp
        self._clients: dict[str, resp.RespClient] = {
            a: resp.RespClient.from_addr(a) for a in addrs
        }
        self._seed_addrs = list(addrs)
        # slot -> addr; None = legacy (cluster support disabled)
        self._slot_map: list[str] | None = None
        self._refresh_slot_map()

    # -- topology ------------------------------------------------------
    def _refresh_slot_map(self) -> None:
        from goworld_tpu.ext.db.resp import NUM_SLOTS, RespError

        transient: Exception | None = None
        for addr in list(self._clients):
            try:
                entries = self._clients[addr].command(
                    b"CLUSTER", b"SLOTS")
            except RespError as e:
                msg = str(e).lower()
                if "cluster support disabled" in msg \
                        or "unknown command" in msg:
                    # definitively a NON-cluster node -> legacy
                    # client-side sharding over the seed endpoints
                    self._slot_map = None
                    return
                # transient (-LOADING, permissions, ...): a cluster
                # node that cannot answer RIGHT NOW must not silently
                # demote the client to legacy routing — try the next
                # node, fail loud if none answers
                transient = e
                continue
            except ConnectionError as e:
                transient = e
                continue
            m: list[str | None] = [None] * NUM_SLOTS
            for lo, hi, node, *_ in entries:
                host = node[0].decode()
                naddr = f"{host}:{int(node[1])}"
                for s in range(int(lo), int(hi) + 1):
                    m[s] = naddr
            # unassigned slots route to the seed we asked (they will
            # MOVED-correct themselves)
            self._slot_map = [s or addr for s in m]
            return
        raise ConnectionError(
            f"no redis-cluster node could serve CLUSTER SLOTS "
            f"(last error: {transient})"
        )

    def _client_for(self, addr: str):
        c = self._clients.get(addr)
        if c is None:
            c = self._clients[addr] = self._resp.RespClient.from_addr(addr)
        return c

    def _route(self, full_key: bytes, bare_key: bytes):
        from goworld_tpu.ext.db.resp import crc16, key_slot

        if self._slot_map is None:
            # legacy mode hashes the BARE key, exactly like the
            # pre-cluster-protocol client — an existing independent-
            # node deployment keeps finding its data on the same nodes
            nodes = [self._clients[a] for a in self._seed_addrs]
            return nodes[crc16(bare_key) % len(nodes)]
        return self._client_for(self._slot_map[key_slot(full_key)])

    def _command(self, full_key: bytes, bare_key: bytes, *args):
        """Run one keyed command with MOVED/ASK redirect handling."""
        from goworld_tpu.ext.db.resp import RespError, key_slot

        client = self._route(full_key, bare_key)
        asking = False
        for _ in range(self._MAX_REDIRECTS):
            try:
                if asking:
                    client.command(b"ASKING")
                    asking = False
                return client.command(*args)
            except RespError as e:
                words = str(e).split()
                if len(words) == 3 and words[0] in ("MOVED", "ASK"):
                    slot, addr = int(words[1]), words[2]
                    client = self._client_for(addr)
                    if words[0] == "MOVED" and self._slot_map is not None:
                        self._slot_map[slot] = addr
                    asking = words[0] == "ASK"
                    continue
                raise
        raise ConnectionError(
            f"redis-cluster redirect chain exceeded "
            f"{self._MAX_REDIRECTS} for slot {key_slot(full_key)}"
        )

    # -- KVDB backend --------------------------------------------------
    def get(self, key):
        bk = key.encode()
        fk = RedisKVDB.PREFIX.encode() + bk
        raw = self._command(fk, bk, b"GET", fk)
        return None if raw is None else raw.decode()

    def put(self, key, val):
        bk = key.encode()
        fk = RedisKVDB.PREFIX.encode() + bk
        self._command(fk, bk, b"SET", fk,
                      val.encode() if isinstance(val, str) else val)

    def get_range(self, begin, end):
        from goworld_tpu.ext.db.resp import key_slot

        out: list[tuple[str, str]] = []
        if self._slot_map is None:
            for addr in self._seed_addrs:
                out.extend(_range_on(self._clients[addr], begin, end))
            out.sort()
            return out
        # cluster mode: SCAN is node-local (allowed), but MGET must be
        # SAME-SLOT only (real cluster redis rejects cross-slot MGET
        # with -CROSSSLOT) — group each node's matches by slot and
        # fetch per group through the redirect-capable path, so a
        # group mid-migration follows its MOVED/ASK. Merge through a
        # dict keyed by k: during a live slot migration the source and
        # target node can BOTH report the same key, and the reader must
        # not see it twice (ADVICE.md)
        pre = RedisKVDB.PREFIX
        lo_b, hi_b = begin.encode(), end.encode()
        merged: dict[str, str] = {}
        for addr in sorted(set(self._slot_map)):
            node = self._client_for(addr)
            keys = [k[len(pre):] for k in node.scan_keys(pre + "*")]
            sel = sorted(k for k in keys if lo_b <= k < hi_b)
            groups: dict[int, list[bytes]] = {}
            for k in sel:
                fk = pre.encode() + k
                groups.setdefault(key_slot(fk), []).append(k)
            for ks in groups.values():
                fks = [pre.encode() + k for k in ks]
                vals = self._command(fks[0], ks[0], b"MGET", *fks)
                merged.update(
                    (k.decode(), v.decode())
                    for k, v in zip(ks, vals) if v is not None
                )
        return sorted(merged.items())

    def close(self):
        for c in self._clients.values():
            c.close()


class MongoKVDB(KVDBBackend):
    """The reference's mongo kvdb engine
    (``kvdb/backend/kvdb_mongodb/mongodb.go``), same document layout:
    one collection, ``_id`` = key, value under ``"_"`` (its
    ``_VAL_KEY``); Put = UpsertId, Get = FindId, Find = range query
    ``{"_id": {"$gte": begin, "$lt": end}}``. Collection ``__kv__``
    (the name the reference's own backend test uses). Rides the
    from-scratch BSON/OP_MSG wire client — works against a real
    mongod or the in-process minimongo."""

    COLLECTION = "__kv__"

    def __init__(self, addr: str):
        from goworld_tpu.ext.db.mongowire import MongoClient

        self._c = MongoClient.from_addr(addr)

    def get(self, key):
        doc = self._c.find_id(self.COLLECTION, key)
        return None if doc is None else doc.get("_")

    def put(self, key, val):
        self._c.upsert_id(self.COLLECTION, key, {"_": val})

    def get_range(self, begin, end):
        docs = self._c.find(
            self.COLLECTION, {"_id": {"$gte": begin, "$lt": end}},
            sort={"_id": 1},
        )
        return [(d["_id"], d["_"]) for d in docs]

    def close(self):
        self._c.close()


def open_kvdb_backend(kind: str, location: str = "") -> KVDBBackend:
    if kind == "memory":
        return MemoryKVDB()
    if kind == "filesystem":
        return FilesystemKVDB(location or "kvdb_data.mp")
    if kind == "redis":
        return RedisKVDB(location or "127.0.0.1:6379")
    if kind in ("redis_cluster", "redis-cluster"):
        return RedisClusterKVDB(
            [a.strip() for a in location.split(",") if a.strip()]
        )
    if kind == "mongodb":
        return MongoKVDB(location or "127.0.0.1:27017/goworld")
    raise ValueError(f"unknown kvdb backend {kind!r}")


def next_larger_key(key: str) -> str:
    """The smallest key strictly greater than every key prefixed by
    ``key`` is not needed — the reference's ``NextLargerKey`` returns
    ``key + "\\x00"``, the immediate successor (``kvdb.go:196-200``)."""
    return key + "\x00"


class KVDB:
    """Async facade (``world.kvdb = KVDB(backend, workers)``); callbacks
    run on the logic thread via the worlds's post queue. Every op runs
    through a timing shim that feeds both the metrics registry
    (``kvdb_op_ms{op=...}`` histogram on ``/metrics``) and the existing
    :data:`opmon.monitor` table (``kvdb.<op>`` rows on ``/ops``)."""

    def __init__(self, backend: KVDBBackend, workers: AsyncWorkers):
        self.backend = backend
        self.workers = workers
        self._hists = {
            op: metrics.histogram("kvdb_op_ms", op=op,
                                  help="kvdb backend op latency")
            for op in ("get", "put", "get_or_put", "get_range")
        }
        self._m_retry = {
            op: metrics.counter("kvdb_retry_total", op=op,
                                help="kvdb ops retried after a "
                                     "transient backend error")
            for op in ("get", "put", "get_or_put", "get_range")
        }
        self._m_err = metrics.counter(
            "kvdb_op_errors_total",
            help="kvdb ops that exhausted retries")
        # circuit breaker around the backend (docs/ROBUSTNESS.md
        # "Overload & degradation"): after the failure budget the
        # breaker opens and every op fails FAST through the callback —
        # a dead backend degrades kvdb service instead of stacking
        # retry sleeps on the single _kvdb worker; a half-open probe
        # per reset window closes it again when the backend recovers
        self.breaker = overload.register_breaker(overload.CircuitBreaker(
            "kvdb",
            failure_threshold=consts.CIRCUIT_FAILURE_THRESHOLD,
            reset_timeout=consts.CIRCUIT_RESET_TIMEOUT,
        ))
        self._m_circuit_rejected = metrics.counter(
            "kvdb_circuit_rejected_total",
            help="kvdb ops failed fast while the circuit was open")

    def _timed(self, op: str, fn: Callable):
        """Timing + bounded-retry shim around one backend op. Transient
        errors (ConnectionError/TimeoutError/OSError — including
        injected ``err:kvdb.*`` faults) retry with exponential backoff
        until RETRY_ATTEMPTS or the RETRY_DEADLINE budget runs out, then
        surface through the callback like any other op error."""
        hist = self._hists[op]
        retry = self._m_retry[op]

        def job():
            if not self.breaker.allow():
                # open circuit: fail fast WITHOUT touching the backend
                # or burning retry sleeps on the single _kvdb worker
                self._m_circuit_rejected.inc()
                raise overload.CircuitOpenError(
                    f"kvdb circuit open; {op} rejected fast"
                )
            deadline = time.perf_counter() + RETRY_DEADLINE
            # the histogram records PER-ATTEMPT backend latency (the
            # last attempt's, success or final failure) — folding the
            # backoff sleeps in would make kvdb_op_ms report injected
            # wait, not backend behavior
            t0 = time.perf_counter()
            try:
                for attempt in range(RETRY_ATTEMPTS):
                    t0 = time.perf_counter()
                    try:
                        faults.maybe_op_fault("kvdb", op)
                        res = fn()
                        self.breaker.record_success()
                        return res
                    except _TRANSIENT as exc:
                        self.breaker.record_failure()
                        delay = RETRY_BASE_DELAY * (2 ** attempt)
                        if attempt + 1 >= RETRY_ATTEMPTS \
                                or time.perf_counter() + delay > deadline \
                                or self.breaker.state \
                                == overload.CircuitBreaker.OPEN:
                            self._m_err.inc()
                            logger.error(
                                "kvdb %s failed after %d attempts: %s",
                                op, attempt + 1, exc,
                            )
                            raise
                        retry.inc()
                        logger.warning("kvdb %s transient error (%s); "
                                       "retry %d", op, exc, attempt + 1)
                        time.sleep(delay)
                    except Exception:
                        # NON-transient failure (protocol garbage, a
                        # bug): still settle the breaker's half-open
                        # probe — leaving it unrecorded would pin the
                        # breaker HALF_OPEN with its one probe slot
                        # consumed, failing every later op forever
                        self.breaker.record_failure()
                        raise
            finally:
                dt = time.perf_counter() - t0
                hist.observe(dt * 1e3)
                opmon.monitor.record(f"kvdb.{op}", dt)

        return job

    def get(self, key: str,
            cb: Callable[[str | None, Exception | None], None]) -> None:
        self.workers.submit(
            _GROUP, self._timed("get", lambda: self.backend.get(key)), cb
        )

    def put(self, key: str, val: str,
            cb: Callable[[None, Exception | None], None] | None = None,
            ) -> None:
        self.workers.submit(
            _GROUP,
            self._timed("put", lambda: self.backend.put(key, val)), cb,
        )

    def get_or_put(self, key: str, val: str,
                   cb: Callable[[str | None, Exception | None], None],
                   ) -> None:
        """Atomic read-else-write (reference ``GetOrPut``): returns the
        existing value (put skipped) or None (val written). Atomicity holds
        because all kvdb ops serialize on the single ``_kvdb`` worker."""

        def job():
            old = self.backend.get(key)
            if old is None:
                self.backend.put(key, val)
            return old

        self.workers.submit(_GROUP, self._timed("get_or_put", job), cb)

    def get_range(self, begin: str, end: str,
                  cb: Callable[[list, Exception | None], None]) -> None:
        self.workers.submit(
            _GROUP,
            self._timed("get_range",
                        lambda: self.backend.get_range(begin, end)), cb,
        )
