// Snappy block-format codec + CRC32C, from scratch (C ABI for ctypes).
//
// Role: the reference's client edge compresses gate<->client streams
// with snappy (ClientProxy.go:38-53 via netconnutil); this provides a
// wire-compatible codec without any third-party library. The BLOCK
// format implemented here is the public one from google/snappy's
// format_description.txt:
//   preamble: uncompressed length, varint32
//   elements: tag byte, low 2 bits = type
//     00 literal  (len-1 in tag>>2; 60..63 mean 1..4 extra LE len bytes)
//     01 copy     (len = ((tag>>2)&7)+4, offset = ((tag>>5)<<8)|byte)
//     10 copy     (len = (tag>>2)+1, 2-byte LE offset)
//     11 copy     (len = (tag>>2)+1, 4-byte LE offset)
// Any format-compliant element stream is valid snappy, so the encoder
// here (greedy hash-table matcher, the standard approach) need not be
// byte-identical to Google's — every spec-conforming decoder reads it,
// and this decoder reads Google-encoded blocks.
//
// CRC32C (Castagnoli, polynomial 0x82f63b78, reflected) is what the
// snappy FRAMING format checksums with; the Python side applies the
// framing-format mask ((crc>>15 | crc<<17) + 0xa282ead8).

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------- crc32c --
static uint32_t crc_table[8][256];
static bool crc_init_done = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (0x82f63b78u ^ (c >> 1)) : (c >> 1);
        crc_table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = crc_table[0][i];
        for (int t = 1; t < 8; t++) {
            c = crc_table[0][c & 0xff] ^ (c >> 8);
            crc_table[t][i] = c;
        }
    }
    crc_init_done = true;
}

uint32_t gw_crc32c(const uint8_t* p, int64_t n) {
    if (!crc_init_done) crc_init();
    uint32_t crc = 0xffffffffu;
    // slice-by-8
    while (n >= 8) {
        uint32_t lo;
        uint32_t hi;
        memcpy(&lo, p, 4);
        memcpy(&hi, p + 4, 4);
        lo ^= crc;
        crc = crc_table[0][(hi >> 24) & 0xff] ^
              crc_table[1][(hi >> 16) & 0xff] ^
              crc_table[2][(hi >> 8) & 0xff] ^
              crc_table[3][hi & 0xff] ^
              crc_table[4][(lo >> 24) & 0xff] ^
              crc_table[5][(lo >> 16) & 0xff] ^
              crc_table[6][(lo >> 8) & 0xff] ^
              crc_table[7][lo & 0xff];
        p += 8;
        n -= 8;
    }
    while (n-- > 0)
        crc = crc_table[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
    return crc ^ 0xffffffffu;
}

// ------------------------------------------------------------- compress --
// worst case: the spec's MaxCompressedLength formula
int64_t gw_snappy_max_compressed_length(int64_t n) {
    return 32 + n + n / 6;
}

static inline uint8_t* emit_varint(uint8_t* dst, uint64_t v) {
    while (v >= 0x80) {
        *dst++ = (uint8_t)(v | 0x80);
        v >>= 7;
    }
    *dst++ = (uint8_t)v;
    return dst;
}

static inline uint8_t* emit_literal(uint8_t* dst, const uint8_t* src,
                                    int64_t len) {
    int64_t n = len - 1;
    if (n < 60) {
        *dst++ = (uint8_t)(n << 2);
    } else if (n < (1 << 8)) {
        *dst++ = 60 << 2;
        *dst++ = (uint8_t)n;
    } else if (n < (1 << 16)) {
        *dst++ = 61 << 2;
        *dst++ = (uint8_t)n;
        *dst++ = (uint8_t)(n >> 8);
    } else if (n < (1 << 24)) {
        *dst++ = 62 << 2;
        *dst++ = (uint8_t)n;
        *dst++ = (uint8_t)(n >> 8);
        *dst++ = (uint8_t)(n >> 16);
    } else {
        *dst++ = 63 << 2;
        *dst++ = (uint8_t)n;
        *dst++ = (uint8_t)(n >> 8);
        *dst++ = (uint8_t)(n >> 16);
        *dst++ = (uint8_t)(n >> 24);
    }
    memcpy(dst, src, (size_t)len);
    return dst + len;
}

// emit one copy element for len in [4..64], offset < 2^16 always here
// (the matcher never reaches 4-byte offsets: window = this block)
static inline uint8_t* emit_copy_le64(uint8_t* dst, int64_t offset,
                                      int64_t len) {
    if (len < 12 && offset < 2048) {
        *dst++ = (uint8_t)(1 | ((len - 4) << 2) | ((offset >> 8) << 5));
        *dst++ = (uint8_t)offset;
    } else {
        *dst++ = (uint8_t)(2 | ((len - 1) << 2));
        *dst++ = (uint8_t)offset;
        *dst++ = (uint8_t)(offset >> 8);
    }
    return dst;
}

static inline uint8_t* emit_copy(uint8_t* dst, int64_t offset,
                                 int64_t len) {
    while (len >= 68) {
        dst = emit_copy_le64(dst, offset, 64);
        len -= 64;
    }
    if (len > 64) {
        dst = emit_copy_le64(dst, offset, 60);
        len -= 60;
    }
    return emit_copy_le64(dst, offset, len);
}

static inline uint32_t load32(const uint8_t* p) {
    uint32_t v;
    memcpy(&v, p, 4);
    return v;
}

// returns compressed size
int64_t gw_snappy_compress(const uint8_t* src, int64_t n, uint8_t* dst) {
    uint8_t* d = emit_varint(dst, (uint64_t)n);
    if (n == 0) return d - dst;
    if (n < 16) {  // too short to match
        d = emit_literal(d, src, n);
        return d - dst;
    }
    // hash table of positions, 14-bit
    const int HASH_BITS = 14;
    int32_t table[1 << HASH_BITS];
    memset(table, -1, sizeof(table));
    const uint32_t HASH_MUL = 0x1e35a7bd;
    int64_t ip = 0;        // next byte to examine
    int64_t lit_start = 0; // start of pending literal run
    const int64_t limit = n - 4;  // last position a 4-byte load is safe
    while (ip <= limit) {
        uint32_t h = (load32(src + ip) * HASH_MUL) >> (32 - HASH_BITS);
        int32_t cand = table[h];
        table[h] = (int32_t)ip;
        if (cand >= 0 && load32(src + cand) == load32(src + ip) &&
            ip - cand < 65536) {
            // extend match
            int64_t mlen = 4;
            while (ip + mlen < n && src[cand + mlen] == src[ip + mlen])
                mlen++;
            if (ip > lit_start)
                d = emit_literal(d, src + lit_start, ip - lit_start);
            d = emit_copy(d, ip - cand, mlen);
            ip += mlen;
            lit_start = ip;
        } else {
            ip++;
        }
    }
    if (lit_start < n)
        d = emit_literal(d, src + lit_start, n - lit_start);
    return d - dst;
}

// ----------------------------------------------------------- uncompress --
// returns decompressed size, or -1 on malformed input / dst_cap overflow
int64_t gw_snappy_uncompress(const uint8_t* src, int64_t n,
                             uint8_t* dst, int64_t dst_cap) {
    // varint preamble
    uint64_t ulen = 0;
    int shift = 0;
    int64_t ip = 0;
    for (;;) {
        if (ip >= n || shift > 28) return -1;
        uint8_t b = src[ip++];
        ulen |= (uint64_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    if ((int64_t)ulen > dst_cap) return -1;
    int64_t op = 0;
    while (ip < n) {
        uint8_t tag = src[ip++];
        uint32_t kind = tag & 3;
        if (kind == 0) {                        // literal
            int64_t len = (tag >> 2) + 1;
            if (len > 60) {
                int extra = (int)len - 60;
                if (ip + extra > n) return -1;
                len = 0;
                for (int i = 0; i < extra; i++)
                    len |= (int64_t)src[ip + i] << (8 * i);
                len += 1;
                ip += extra;
            }
            if (ip + len > n || op + len > (int64_t)ulen) return -1;
            memcpy(dst + op, src + ip, (size_t)len);
            ip += len;
            op += len;
        } else {                                // copy
            int64_t len;
            int64_t offset;
            if (kind == 1) {
                if (ip >= n) return -1;
                len = ((tag >> 2) & 7) + 4;
                offset = ((int64_t)(tag >> 5) << 8) | src[ip++];
            } else if (kind == 2) {
                if (ip + 2 > n) return -1;
                len = (tag >> 2) + 1;
                offset = (int64_t)src[ip] | ((int64_t)src[ip + 1] << 8);
                ip += 2;
            } else {
                if (ip + 4 > n) return -1;
                len = (tag >> 2) + 1;
                offset = (int64_t)src[ip] | ((int64_t)src[ip + 1] << 8) |
                         ((int64_t)src[ip + 2] << 16) |
                         ((int64_t)src[ip + 3] << 24);
                ip += 4;
            }
            if (offset == 0 || offset > op ||
                op + len > (int64_t)ulen) return -1;
            // byte-by-byte: overlapping copies (offset < len) replicate
            const uint8_t* from = dst + op - offset;
            uint8_t* to = dst + op;
            for (int64_t i = 0; i < len; i++) to[i] = from[i];
            op += len;
        }
    }
    return op == (int64_t)ulen ? op : -1;
}

}  // extern "C"
