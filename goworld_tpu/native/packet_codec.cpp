// Native packet codec — hot-path byte work for the wire layer.
//
// Reference being rebuilt: the per-record loops in GoWorld's sync pipeline
// (gate batching   components/gate/GateService.go:402-429,
//  dispatcher re-batching components/dispatcher/DispatcherService.go:770-808,
//  game decode      components/game/GameService.go:395-407) and the packet
// framing scan of engine/netutil/PacketConnection.go. The reference does all
// of this in Go per record; here the per-record loops run in C++ over whole
// batches so the Python hosts only touch numpy arrays.
//
// Record layout (little-endian, see goworld_tpu/net/proto.py):
//   sync record:        [16B entity id][f32 x][f32 y][f32 z][f32 yaw] = 32B
//   client sync record: [16B client id][32B sync record]              = 48B
//
// Build: make -C goworld_tpu/native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>

extern "C" {

// Interleave ids (n*16 bytes) and vals (n*4 f32) into out (n*32 bytes).
void encode_sync_records(const char* ids, const float* vals, int32_t n,
                         char* out) {
    for (int32_t i = 0; i < n; ++i) {
        char* rec = out + (size_t)i * 32;
        std::memcpy(rec, ids + (size_t)i * 16, 16);
        std::memcpy(rec + 16, vals + (size_t)i * 4, 16);
    }
}

// Split buf (n*32 bytes) into ids (n*16) and vals (n*4 f32).
void decode_sync_records(const char* buf, int32_t n, char* ids,
                         float* vals) {
    for (int32_t i = 0; i < n; ++i) {
        const char* rec = buf + (size_t)i * 32;
        std::memcpy(ids + (size_t)i * 16, rec, 16);
        std::memcpy(vals + (size_t)i * 4, rec + 16, 16);
    }
}

// Interleave cids (n*16), ids (n*16), vals (n*4 f32) into out (n*48).
void encode_client_sync_records(const char* cids, const char* ids,
                                const float* vals, int32_t n, char* out) {
    for (int32_t i = 0; i < n; ++i) {
        char* rec = out + (size_t)i * 48;
        std::memcpy(rec, cids + (size_t)i * 16, 16);
        std::memcpy(rec + 16, ids + (size_t)i * 16, 16);
        std::memcpy(rec + 32, vals + (size_t)i * 4, 16);
    }
}

void decode_client_sync_records(const char* buf, int32_t n, char* cids,
                                char* ids, float* vals) {
    for (int32_t i = 0; i < n; ++i) {
        const char* rec = buf + (size_t)i * 48;
        std::memcpy(cids + (size_t)i * 16, rec, 16);
        std::memcpy(ids + (size_t)i * 16, rec + 16, 16);
        std::memcpy(vals + (size_t)i * 4, rec + 32, 16);
    }
}

// Scan a receive buffer of length-prefixed frames ([u32 size][payload]).
// Writes up to max_frames (offset, size) pairs of COMPLETE frames into
// offsets/sizes (offset points at the payload, past the prefix). Returns
// the number of complete frames found; *consumed is the byte count covered
// by them (the caller keeps the tail). Returns -1 on a malformed size.
int32_t scan_frames(const char* buf, int64_t len, int64_t max_payload,
                    int64_t* offsets, int64_t* sizes, int32_t max_frames,
                    int64_t* consumed) {
    int32_t count = 0;
    int64_t pos = 0;
    while (count < max_frames && pos + 4 <= len) {
        uint32_t size;
        std::memcpy(&size, buf + pos, 4);  // little-endian hosts only
        if (size < 2 || (int64_t)size > max_payload) return -1;
        if (pos + 4 + (int64_t)size > len) break;
        offsets[count] = pos + 4;
        sizes[count] = (int64_t)size;
        ++count;
        pos += 4 + (int64_t)size;
    }
    *consumed = pos;
    return count;
}

// Route sync records to per-shard compact arrays on the dispatcher/game
// boundary: given each record's routing key (precomputed shard index, -1 to
// drop), produce for each shard the packed record indices.
// counts must be zeroed, capacity = per-shard cap of out_idx rows.
void bucket_by_shard(const int32_t* shard_of, int32_t n, int32_t n_shards,
                     int32_t capacity, int32_t* out_idx, int32_t* counts) {
    for (int32_t i = 0; i < n; ++i) {
        int32_t s = shard_of[i];
        if (s < 0 || s >= n_shards) continue;
        int32_t c = counts[s];
        if (c < capacity) {
            out_idx[(size_t)s * capacity + c] = i;
            counts[s] = c + 1;
        }
    }
}

}  // extern "C"
