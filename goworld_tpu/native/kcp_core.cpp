// Native KCP ARQ core — the reliable-UDP state machine of the client edge.
//
// Reference being rebuilt: the reference gate links kcp-go (a native-speed
// Go library) for its KCP listener (components/gate/GateService.go:129-161,
// turbo tuning engine/consts/consts.go:99-106). The Python mirror of this
// state machine lives in goworld_tpu/net/kcp.py (KcpCore) and stays the
// canonical/fallback implementation; this C++ core processes segments off
// the interpreter's hot path for high-session gates. Wire format and
// semantics are identical (skywind3000 KCP, stream mode, nodelay):
//
//   conv u32 | cmd u8 | frg u8 | wnd u16 | ts u32 | sn u32 | una u32
//   | len u32 | data[len]                         (little-endian, 24B)
//
// Time is injected by the caller (now_ms params) so tests control the
// clock exactly like they monkeypatch the Python core's _now_ms.
//
// Build: make -C goworld_tpu/native  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <vector>

namespace {

constexpr int OVERHEAD = 24;
constexpr uint8_t CMD_PUSH = 81, CMD_ACK = 82, CMD_WASK = 83, CMD_WINS = 84;
constexpr int DEAD_LINK = 20;

// Signed serial distance under u32 wrap (kcp-go's _itimediff): every
// sn/una window compare must go through this so a conversation that
// crosses sn 2^32 keeps flowing (and so the Python core's _sn_diff
// arithmetic stays bit-identical).
inline int32_t sn_diff(uint32_t a, uint32_t b) {
    return (int32_t)(a - b);
}

struct Seg {
    uint32_t sn;
    uint32_t ts;
    std::vector<char> data;
    int64_t resendts = 0;
    int64_t rto = 0;
    int fastack = 0;
    int xmit = 0;
};

struct Kcp {
    uint32_t conv;
    int mtu, mss;
    int snd_wnd, rcv_wnd, interval, resend, rx_minrto;

    uint32_t snd_una = 0, snd_nxt = 0, rcv_nxt = 0;
    uint32_t rmt_wnd;

    std::deque<std::vector<char>> snd_queue;
    std::deque<Seg> snd_buf;
    std::map<uint32_t, std::vector<char>> rcv_buf;
    std::deque<std::vector<char>> rcv_queue;
    std::vector<std::pair<uint32_t, uint32_t>> acklist;
    std::deque<std::vector<char>> out_queue;  // datagrams awaiting sendto

    int64_t rx_srtt = 0, rx_rttval = 0, rx_rto = 200;
    bool dead = false;
    bool wins_pending = false;
    bool wask_pending = false;  // liveness probe: WASK elicits a WINS

    Kcp(uint32_t c, int mtu_, int sw, int rw, int iv, int rs, int minrto)
        : conv(c), mtu(mtu_), mss(mtu_ - OVERHEAD), snd_wnd(sw),
          rcv_wnd(rw), interval(iv), resend(rs), rx_minrto(minrto),
          rmt_wnd(rw) {}

    int wnd_unused() const {
        int w = rcv_wnd - (int)rcv_queue.size();
        return w > 0 ? w : 0;
    }

    void update_rtt(int64_t rtt) {
        if (rtt < 0) return;
        if (rx_srtt == 0) {
            rx_srtt = rtt;
            rx_rttval = rtt / 2;
        } else {
            int64_t delta = rtt > rx_srtt ? rtt - rx_srtt : rx_srtt - rtt;
            rx_rttval = (3 * rx_rttval + delta) / 4;
            rx_srtt = (7 * rx_srtt + rtt) / 8;
            if (rx_srtt < 1) rx_srtt = 1;
        }
        int64_t rto = rx_srtt +
            (interval > 4 * rx_rttval ? interval : 4 * rx_rttval);
        rx_rto = rto < rx_minrto ? rx_minrto : (rto > 60000 ? 60000 : rto);
    }

    void parse_una(uint32_t una) {
        while (!snd_buf.empty() && sn_diff(snd_buf.front().sn, una) < 0)
            snd_buf.pop_front();
        snd_una = snd_buf.empty() ? snd_nxt : snd_buf.front().sn;
    }

    void parse_ack(uint32_t sn, uint32_t ts, uint32_t now32) {
        uint32_t rtt = now32 - ts;   // u32 wrap-safe
        if (rtt < 60000) update_rtt((int64_t)rtt);
        for (auto it = snd_buf.begin(); it != snd_buf.end(); ++it) {
            if (it->sn == sn) { snd_buf.erase(it); break; }
            if (sn_diff(it->sn, sn) > 0) break;
        }
        for (auto& seg : snd_buf)
            if (sn_diff(seg.sn, sn) < 0) seg.fastack++;
        snd_una = snd_buf.empty() ? snd_nxt : snd_buf.front().sn;
    }

    void input(const char* p, int n, uint32_t now32) {
        // 64-bit offset math: a crafted len near 2^31 must fail the
        // bounds check, not wrap negative into a wild memcpy
        int64_t off = 0;
        while (off + OVERHEAD <= n) {
            uint32_t c, ts, sn, una, len;
            uint8_t cmd, frg;
            uint16_t wnd;
            std::memcpy(&c, p + off, 4);
            cmd = (uint8_t)p[off + 4];
            frg = (uint8_t)p[off + 5];
            (void)frg;
            std::memcpy(&wnd, p + off + 6, 2);
            std::memcpy(&ts, p + off + 8, 4);
            std::memcpy(&sn, p + off + 12, 4);
            std::memcpy(&una, p + off + 16, 4);
            std::memcpy(&len, p + off + 20, 4);
            off += OVERHEAD;
            if (c != conv || off + (int64_t)len > n) return;
            const char* data = p + off;
            off += len;
            rmt_wnd = wnd;
            parse_una(una);
            if (cmd == CMD_ACK) {
                parse_ack(sn, ts, now32);
            } else if (cmd == CMD_PUSH) {
                int32_t ahead = sn_diff(sn, rcv_nxt);
                if (ahead >= 0 && ahead < rcv_wnd) {
                    acklist.emplace_back(sn, ts);
                    if (!rcv_buf.count(sn))
                        rcv_buf[sn] = std::vector<char>(data, data + len);
                    for (auto it = rcv_buf.find(rcv_nxt);
                         it != rcv_buf.end() && it->first == rcv_nxt;
                         it = rcv_buf.find(rcv_nxt)) {
                        // 0-len PUSH segments (legal on the wire) are
                        // acked but never queued: kcp_recv's 0 return
                        // must unambiguously mean "queue empty"
                        if (!it->second.empty())
                            rcv_queue.push_back(std::move(it->second));
                        rcv_buf.erase(it);
                        rcv_nxt++;  // uint32_t: wraps with the wire
                    }
                } else if (ahead < 0) {
                    acklist.emplace_back(sn, ts);  // re-ack duplicate
                }
            } else if (cmd == CMD_WASK) {
                wins_pending = true;
            }
            // CMD_WINS: header side effects already applied
        }
    }

    std::vector<char>* cur_dgram() {
        if (out_queue.empty() || (int)out_queue.back().size() >= mtu)
            out_queue.emplace_back();
        return &out_queue.back();
    }

    void emit(uint8_t cmd, uint32_t sn, uint32_t ts, uint16_t wnd,
              const char* data, uint32_t len) {
        std::vector<char>* d = cur_dgram();
        if ((int)(d->size() + OVERHEAD + len) > mtu && !d->empty()) {
            out_queue.emplace_back();
            d = &out_queue.back();
        }
        size_t base = d->size();
        d->resize(base + OVERHEAD + len);
        char* w = d->data() + base;
        std::memcpy(w, &conv, 4);
        w[4] = (char)cmd;
        w[5] = 0;
        std::memcpy(w + 6, &wnd, 2);
        std::memcpy(w + 8, &ts, 4);
        std::memcpy(w + 12, &sn, 4);
        std::memcpy(w + 16, &rcv_nxt, 4);
        std::memcpy(w + 20, &len, 4);
        if (len) std::memcpy(w + OVERHEAD, data, len);
    }

    void flush(int64_t now) {
        uint32_t now32 = (uint32_t)now;
        uint16_t wnd = (uint16_t)wnd_unused();
        for (auto& a : acklist) emit(CMD_ACK, a.first, a.second, wnd,
                                     nullptr, 0);
        acklist.clear();
        if (wins_pending) {
            emit(CMD_WINS, 0, now32, wnd, nullptr, 0);
            wins_pending = false;
        }
        if (wask_pending) {
            emit(CMD_WASK, 0, now32, wnd, nullptr, 0);
            wask_pending = false;
        }
        uint32_t cwnd = (uint32_t)snd_wnd;
        uint32_t rw = rmt_wnd > 0 ? rmt_wnd : 1;
        if (rw < cwnd) cwnd = rw;
        while (!snd_queue.empty() && sn_diff(snd_nxt, snd_una + cwnd) < 0) {
            Seg s;
            s.sn = snd_nxt++;
            s.data = std::move(snd_queue.front());
            snd_queue.pop_front();
            snd_buf.push_back(std::move(s));
        }
        for (auto& seg : snd_buf) {
            bool need = false;
            if (seg.xmit == 0) {
                need = true;
                seg.rto = rx_rto;
                seg.resendts = now + seg.rto;
            } else if (seg.fastack >= resend) {
                need = true;
                seg.fastack = 0;
                seg.resendts = now + seg.rto;
            } else if (now >= seg.resendts) {
                need = true;
                seg.rto += seg.rto / 2;           // nodelay backoff
                seg.resendts = now + seg.rto;
            }
            if (need) {
                seg.xmit++;
                seg.ts = now32;
                if (seg.xmit >= DEAD_LINK) dead = true;
                emit(CMD_PUSH, seg.sn, now32, wnd, seg.data.data(),
                     (uint32_t)seg.data.size());
            }
        }
    }
};

}  // namespace

extern "C" {

void* kcp_create(uint32_t conv, int mtu, int snd_wnd, int rcv_wnd,
                 int interval, int resend, int minrto) {
    return new Kcp(conv, mtu, snd_wnd, rcv_wnd, interval, resend, minrto);
}

void kcp_free(void* k) { delete (Kcp*)k; }

void kcp_send(void* k, const char* data, int len) {
    Kcp* kc = (Kcp*)k;
    for (int off = 0; off < len; off += kc->mss) {
        int n = len - off < kc->mss ? len - off : kc->mss;
        kc->snd_queue.emplace_back(data + off, data + off + n);
    }
}

void kcp_input(void* k, const char* dgram, int len, int64_t now_ms) {
    ((Kcp*)k)->input(dgram, len, (uint32_t)now_ms);
}

// Pop the next reassembled in-order chunk into buf; returns its length,
// 0 when empty, -1 when cap is too small (chunk stays queued).
int kcp_recv(void* k, char* buf, int cap) {
    Kcp* kc = (Kcp*)k;
    if (kc->rcv_queue.empty()) return 0;
    std::vector<char>& c = kc->rcv_queue.front();
    if ((int)c.size() > cap) return -1;
    int n = (int)c.size();
    std::memcpy(buf, c.data(), n);
    kc->rcv_queue.pop_front();
    return n;
}

void kcp_flush(void* k, int64_t now_ms) { ((Kcp*)k)->flush(now_ms); }

// Pop the next outgoing datagram; same return contract as kcp_recv.
int kcp_drain_out(void* k, char* buf, int cap) {
    Kcp* kc = (Kcp*)k;
    if (kc->out_queue.empty()) return 0;
    std::vector<char>& d = kc->out_queue.front();
    if (d.empty()) { kc->out_queue.pop_front(); return 0; }
    if ((int)d.size() > cap) return -1;
    int n = (int)d.size();
    std::memcpy(buf, d.data(), n);
    kc->out_queue.pop_front();
    return n;
}

int kcp_unsent(void* k) {
    Kcp* kc = (Kcp*)k;
    return (int)(kc->snd_queue.size() + kc->snd_buf.size());
}

int kcp_dead(void* k) { return ((Kcp*)k)->dead ? 1 : 0; }

void kcp_announce(void* k, int64_t now_ms) {
    Kcp* kc = (Kcp*)k;
    kc->emit(CMD_WINS, 0, (uint32_t)now_ms,
             (uint16_t)kc->wnd_unused(), nullptr, 0);
}

// Queue a WASK (window probe) for the next flush. The peer answers with
// a WINS, so this doubles as a liveness probe for idle-session reaping
// (KcpServer): a silent-but-alive peer refreshes last_heard, a dead one
// does not.
void kcp_probe(void* k) { ((Kcp*)k)->wask_pending = true; }

// TEST HOOK: preset the serial counters so u32-wrap behavior can be
// exercised without pushing 2^32 segments (tests/test_kcp.py).
void kcp_test_set_serials(void* k, uint32_t snd_nxt, uint32_t snd_una,
                          uint32_t rcv_nxt) {
    Kcp* kc = (Kcp*)k;
    kc->snd_nxt = snd_nxt;
    kc->snd_una = snd_una;
    kc->rcv_nxt = rcv_nxt;
}

}  // extern "C"
