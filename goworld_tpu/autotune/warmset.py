"""Warm-set: AOT-compiled executables for candidate tick configs.

The whole point of the governor is a swap with ZERO mid-serving compile
stalls, so the target config's executable must exist BEFORE the swap
commits. Two facts make that cheap:

* the production tick signature has **fixed shapes** — staging is
  applied by eager scatters in ``_flush_staging``, so the compiled step
  always sees ``(state[S,...], TickInputs[S,ic], policy)`` at the same
  avals every tick;
* devprof already proved the **executable-reuse path**: an AOT
  ``jit(...).lower(...).compile()`` product is directly callable with
  the live pytrees (and ``cost_report`` accepts it with zero extra
  compiles), so the World can run the compiled object itself instead
  of re-entering the jit cache.

Each :class:`WarmEntry` therefore carries the candidate's resolved
``WorldConfig``, the AOT-compiled step, the matching AOT-compiled live
telemetry fold (the lane set changes when the skin toggles) and its
zeroed accumulator — everything a swap needs to commit atomically
between ticks. Compiles run on ONE daemon worker thread (the
``/costs?analyze=1`` precedent: lower+compile off the logic thread is
safe), never on the tick thread.

State carry-over lives here too (:func:`carry_state`): flipping the
Verlet skin on allocates a fresh INVALID cache (the next tick rebuilds
— exact by construction), flipping it off drops the cache arrays, and
any cache-shape-affecting knob change (verlet_cap, precision, skin
width) reallocates. Everything else in ``SpaceState`` is
config-independent and carries through untouched — the oracle suite
asserts a swap mid-churn stays exact on the very next tick.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

from goworld_tpu.autotune.policy import (
    DEFAULT_CANDIDATES,
    candidate_overrides,
)
from goworld_tpu.utils import consts, log

logger = log.get("autotune")

__all__ = ["WarmEntry", "WarmSet", "candidate_config", "carry_state"]


def candidate_config(cfg, overrides: dict):
    """Resolve a candidate's ``WorldConfig`` from the base config +
    GridSpec overrides. Validation rides ``GridSpec.__post_init__``
    (typo'd impls fail loudly at build, never at trace time); the
    packed-id capacity bound clears a requested skin exactly like
    ``api._build_world`` does."""
    kw = dict(overrides)
    if kw.get("skin", cfg.grid.skin) > 0 \
            and cfg.capacity >= (1 << consts.AOI_ID_BITS):
        kw["skin"] = 0.0  # the Verlet reuse rides the packed-id path
    grid = dataclasses.replace(cfg.grid, **kw)
    return dataclasses.replace(cfg, grid=grid)


def _cache_shape_key(grid) -> tuple:
    """The knobs that decide the Verlet cache's existence and layout —
    equal keys mean a carried cache stays VALID across the swap (the
    candidate superset bound is impl-independent)."""
    return (grid.skin > 0, grid.verlet_cap, grid.precision, grid.skin,
            grid.radius)


def carry_state(state, old_cfg, new_cfg, *, stacked: bool = True):
    """Carry a live ``SpaceState`` across a config flip.

    Only the Verlet cache is config-shaped; everything else carries
    bit-identically. A fresh cache is allocated INVALID, so the first
    tick under the new config rebuilds the front half — the swap is
    exact from its very first tick.

    Resident-world note (ISSUE 20): this runs BETWEEN dispatches on
    the carry the last tick RETURNED (apply_tick_config rebinds
    ``world.state`` to the result), so under carry donation every leaf
    read here is live — the deleted buffers are the PREVIOUS tick's
    inputs, which this function never sees. Callers must not pass a
    state reference captured before an intervening tick."""
    import jax
    import jax.numpy as jnp

    from goworld_tpu.ops.aoi import init_verlet_cache

    old_key = _cache_shape_key(old_cfg.grid)
    new_key = _cache_shape_key(new_cfg.grid)
    if old_key == new_key:
        return state
    if new_cfg.grid.skin <= 0:
        return state.replace(aoi_cache=None)
    cache = init_verlet_cache(new_cfg.grid, new_cfg.capacity)
    if stacked:
        # the stacked [S=1] production shape (the governor only serves
        # single-shard worlds; the vmapped S>1 step clears the skin)
        cache = jax.tree.map(lambda x: jnp.expand_dims(x, 0), cache)
    return state.replace(aoi_cache=cache)


@dataclasses.dataclass
class WarmEntry:
    """One candidate's compiled artifacts (immutable once warm)."""

    label: str
    cfg: Any                      # resolved WorldConfig
    exe: Any = None               # AOT-compiled step executable
    fold_exe: Any = None          # AOT-compiled telemetry fold (or None)
    acc0: Any = None              # zeroed telemetry accumulator
    skin_on: bool = False
    half_skin: float = 0.0
    error: str | None = None
    compile_s: float = 0.0

    @property
    def warm(self) -> bool:
        return self.exe is not None and self.error is None


class WarmSet:
    """Candidate-config executable cache for ONE World shape.

    ``ensure(label)`` schedules an off-thread compile (idempotent);
    ``is_warm(label)`` gates the swap commit; ``entry(label)`` hands
    the governor the compiled artifacts. ``block=True`` compiles
    synchronously (tests, bench prewarm)."""

    def __init__(self, cfg, n_spaces: int, policy=None, *,
                 candidates=DEFAULT_CANDIDATES,
                 telemetry: bool = True,
                 donate: bool = False,
                 donate_fold: bool = False):
        if n_spaces != 1:
            raise ValueError(
                "WarmSet serves the single-shard production shape "
                f"(n_spaces=1), got n_spaces={n_spaces}"
            )
        self.base_cfg = cfg
        self.n_spaces = n_spaces
        self.policy = policy
        self.candidates = tuple(candidates)
        self.telemetry = telemetry
        # resident-world donation (ISSUE 20): every candidate
        # executable is compiled with the SAME donation contract as
        # the World it will swap into — AOT lower().compile()
        # preserves donate_argnums, so a swap never changes the
        # carry's aliasing behavior. donate_fold mirrors the World's
        # fold gating (off under pipeline_decode).
        self.donate = donate
        self.donate_fold = donate_fold
        self._entries: dict[str, WarmEntry] = {}
        self._lock = threading.Lock()
        self._inflight: set[str] = set()
        self._worker: threading.Thread | None = None
        self._queue: list[str] = []
        self._wake = threading.Condition(self._lock)
        self.compile_count = 0  # tests assert no re-compiles on re-swap

    # -- public ----------------------------------------------------------
    def labels(self) -> list[str]:
        return [lbl for lbl, _ in self.candidates]

    def is_warm(self, label: str) -> bool:
        with self._lock:
            e = self._entries.get(label)
            return e is not None and e.warm

    def entry(self, label: str) -> WarmEntry | None:
        with self._lock:
            return self._entries.get(label)

    def ensure(self, label: str, block: bool = False) -> bool:
        """Schedule (or synchronously run) the candidate's compile;
        returns True when it is warm on return. ``block=True`` with
        the same label already compiling on the worker thread WAITS
        for that compile instead of duplicating it (two concurrent XLA
        compiles of one config would double-count compile_count and
        race the entry slot)."""
        candidate_overrides(label, self.candidates)  # loud on typos
        with self._lock:
            e = self._entries.get(label)
            if e is not None and (e.warm or e.error):
                return e.warm
            inflight = label in self._inflight
            if not block:
                if not inflight:
                    self._inflight.add(label)
                    self._queue.append(label)
                    self._wake.notify()
                if self._worker is None or not self._worker.is_alive():
                    self._worker = threading.Thread(
                        target=self._worker_loop,
                        name="autotune-warmset", daemon=True)
                    self._worker.start()
                return False
            if not inflight:
                # claim the label so a concurrent async ensure() can
                # never queue a duplicate while we compile inline
                self._inflight.add(label)
        if inflight:
            # the worker owns this compile; wait it out (it clears
            # _inflight in its finally)
            import time as _time

            while True:
                with self._lock:
                    done = label not in self._inflight
                if done:
                    # outside the lock: is_warm() re-acquires it (the
                    # Lock is non-reentrant)
                    return self.is_warm(label)
                _time.sleep(0.05)
        try:
            self._compile(label)
        finally:
            with self._lock:
                self._inflight.discard(label)
        return self.is_warm(label)

    def warm_all(self) -> None:
        """Synchronously compile every candidate (bench prewarm)."""
        for lbl in self.labels():
            self.ensure(lbl, block=True)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                lbl: {
                    "warm": e.warm,
                    "error": e.error,
                    "compile_s": round(e.compile_s, 3),
                    "config": {
                        "sweep_impl": e.cfg.grid.sweep_impl,
                        "sort_impl": e.cfg.grid.sort_impl,
                        "topk_impl": e.cfg.grid.topk_impl,
                        "skin": e.cfg.grid.skin,
                    },
                }
                for lbl, e in self._entries.items()
            } | {"inflight": sorted(self._inflight),
                 "compiles": self.compile_count}

    # -- worker ----------------------------------------------------------
    def _worker_loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue:
                    self._wake.wait(timeout=60.0)
                    if not self._queue:
                        # idle worker retires. Clear the handle UNDER
                        # THE LOCK before returning: ensure() checks
                        # `self._worker is None or not is_alive()`,
                        # and a retiring-but-not-yet-dead thread would
                        # otherwise swallow a notify and wedge the
                        # pending swap forever (lost-wakeup race).
                        if self._worker is threading.current_thread():
                            self._worker = None
                        return
                label = self._queue.pop(0)
            try:
                self._compile(label)
            finally:
                with self._lock:
                    self._inflight.discard(label)

    def _compile(self, label: str) -> None:
        import time

        import jax

        from goworld_tpu.core.step import TickInputs
        from goworld_tpu.entity.manager import _make_local_tick
        from goworld_tpu.parallel.mesh import create_multi_state

        t0 = time.perf_counter()
        try:
            cfg2 = candidate_config(
                self.base_cfg, candidate_overrides(label,
                                                   self.candidates))
            entry = WarmEntry(label=label, cfg=cfg2)
            step = _make_local_tick(cfg2, self.n_spaces,
                                    donate=self.donate)
            # templates, never real arrays: eval_shape gives the exact
            # avals the live tick passes (fixed shapes by construction)
            tstate = jax.eval_shape(
                lambda: create_multi_state(cfg2, self.n_spaces))
            tinputs = jax.eval_shape(
                lambda: jax.tree.map(
                    lambda x: jax.numpy.broadcast_to(
                        x, (self.n_spaces,) + x.shape),
                    TickInputs.empty(cfg2)))
            tpolicy = (None if self.policy is None
                       else jax.eval_shape(lambda: self.policy))
            entry.exe = step.lower(tstate, tinputs, tpolicy).compile()
            if self.telemetry:
                self._compile_fold(entry, step, tstate, tinputs,
                                   tpolicy)
            entry.compile_s = time.perf_counter() - t0
            with self._lock:
                self._entries[label] = entry
                self.compile_count += 1
            logger.info("warmset: %s compiled in %.2fs", label,
                        entry.compile_s)
        except Exception as exc:
            logger.exception("warmset: compiling %s failed", label)
            with self._lock:
                self._entries[label] = WarmEntry(
                    label=label,
                    cfg=self.base_cfg,
                    error=f"{type(exc).__name__}: {str(exc)[:200]}",
                    compile_s=time.perf_counter() - t0,
                )

    def _compile_fold(self, entry: WarmEntry, step, tstate, tinputs,
                      tpolicy) -> None:
        """AOT-compile the candidate's live telemetry fold: its lane
        set follows the skin (skin_slack lane exists only when the
        Verlet cache is live in the compiled step), so a skin flip
        needs a matching fold + fresh accumulator, pre-warmed with the
        step so a swap never traces anything."""
        import jax

        from goworld_tpu.ops import telemetry as telem

        cfg2 = entry.cfg
        skin_on = (cfg2.grid.skin > 0
                   and cfg2.capacity < (1 << consts.AOI_ID_BITS))
        entry.skin_on = skin_on
        entry.half_skin = cfg2.grid.skin / 2.0 if skin_on else 0.0
        entry.acc0 = telem.telemetry_init(
            skin_on, mega=False, occupancy=True,
            n_tiles=self.n_spaces)
        half_skin = entry.half_skin

        def _fold(acc, outs):
            return telem.telemetry_update_live(
                acc, outs, mega=False, half_skin=half_skin)

        _fold = jax.jit(
            _fold, donate_argnums=(0,) if self.donate_fold else ())

        # the fold's outs aval is the step's own output template
        _, touts = jax.eval_shape(step, tstate, tinputs, tpolicy)
        tacc = jax.eval_shape(lambda: entry.acc0)
        entry.fold_exe = _fold.lower(tacc, touts).compile()
