"""Online kernel autotuning: the governor that closes ROADMAP item 2.

Three layers, one per module:

* :mod:`goworld_tpu.autotune.policy` — jax-free decisions. A pure
  function of the workload-signature stream (the reducer PR 11's live
  telemetry lanes already rotate) picks a kernel-config candidate with
  overload-ladder-style hysteresis and a deterministic transition log.
* :mod:`goworld_tpu.autotune.warmset` — AOT executable cache. Candidate
  tick configs are ``lower().compile()``d OFF the tick thread (the
  devprof executable-reuse path); a swap only commits when the target
  executable is warm, so a live game never pays a mid-serving compile.
* :mod:`goworld_tpu.autotune.governor` — the :class:`KernelGovernor`
  that wires both to a live :class:`~goworld_tpu.entity.manager.World`:
  per-window decisions, warm-gated commits, the post-swap regret guard
  (measured truth beats the table), metrics/flight-recorder/endpoint
  surfacing (debug-http ``/governor``).

See docs/AUTOTUNE.md for the decision grammar and knob reference.
"""

from goworld_tpu.autotune.governor import (
    KernelGovernor,
    register,
    snapshot,
    unregister,
)
from goworld_tpu.autotune.policy import (
    DEFAULT_CANDIDATES,
    GovernorPolicy,
    candidate_overrides,
    classify_signature,
    parse_table,
    seed_table,
)
from goworld_tpu.autotune.warmset import WarmSet, candidate_config, carry_state

__all__ = [
    "DEFAULT_CANDIDATES", "GovernorPolicy", "candidate_overrides",
    "classify_signature", "parse_table", "seed_table",
    "WarmSet", "candidate_config", "carry_state",
    "KernelGovernor", "register", "unregister", "snapshot",
]
