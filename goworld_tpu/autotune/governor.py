"""KernelGovernor — the online autotuner that closes ROADMAP item 2.

One governor serves one live :class:`~goworld_tpu.entity.manager.World`
(the single-shard, non-mesh production shape — the only shape whose
step carries the Verlet-skin runtime branches the candidates toggle).
Per signature window (the World's drained-lane rotation) it:

1. runs the **regret guard** on the most recently committed swap —
   if the measured tick-latency p90 of the post-swap window worsened
   past ``regret_pct`` vs the pre-swap window, it reverts (the old
   executable is warm by construction) and PINS the policy for
   ``regret_pin_windows``. Measured truth beats the table: the
   mapping is CPU-derived until the TPU relay answers (ROADMAP 1);
2. **commits** a previously decided swap iff the target's executable
   is warm (:mod:`warmset`) — never a mid-serving compile;
3. feeds the window's workload signature to the **policy**
   (:mod:`policy`), and schedules an off-thread warm compile for any
   newly decided target.

Every commit/revert increments
``governor_swaps_total{from,to,reason}``, is returned to the caller as
an event dict (the GameServer stamps it into the flight-recorder frame
— the ``governor_swap`` trigger freezes the decision context into the
incident bundle), and lands in the deterministic swap log served at
debug-http ``/governor``.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any

from goworld_tpu.autotune.policy import (
    DEFAULT_CANDIDATES,
    GovernorPolicy,
    seed_table,
)
from goworld_tpu.autotune.warmset import WarmSet, carry_state
from goworld_tpu.utils import log, metrics

logger = log.get("autotune")

__all__ = ["KernelGovernor", "register", "unregister", "snapshot"]

# swap counters cached per (from, to, reason) — the shed_counter idiom
_swap_counters: dict[tuple, metrics.Counter] = {}


def _swap_counter(frm: str, to: str, reason: str) -> metrics.Counter:
    key = (frm, to, reason)
    c = _swap_counters.get(key)
    if c is None:
        c = _swap_counters[key] = metrics.counter(
            "governor_swaps_total",
            help="kernel-config swaps committed by the autotune "
                 "governor",
            **{"from": frm, "to": to, "reason": reason},
        )
    return c


class KernelGovernor:
    """Online kernel-config governor for one live World."""

    def __init__(
        self,
        world,
        *,
        name: str = "game",
        table: dict[str, str] | None = None,
        candidates=DEFAULT_CANDIDATES,
        up_windows: int = 2,
        down_windows: int = 2,
        cooldown_windows: int = 4,
        regret_pct: float = 0.25,
        regret_pin_windows: int = 8,
    ):
        if world.mega is not None or world.mesh is not None \
                or world.n_spaces != 1:
            raise ValueError(
                "the kernel governor serves single-shard non-mesh "
                "worlds (the shape whose step carries the skin's "
                "runtime branches); megaspace/mesh kernel choice is "
                "the TPU A/B plane's job"
            )
        self.name = name
        self._world = weakref.ref(world)
        self.policy = GovernorPolicy(
            table=table if table is not None else seed_table(),
            candidates=candidates,
            up_windows=up_windows,
            down_windows=down_windows,
            cooldown_windows=cooldown_windows,
        )
        resident = bool(getattr(world, "resident", False))
        self.warmset = WarmSet(
            world.cfg, world.n_spaces, world.policy,
            candidates=candidates,
            telemetry=getattr(world, "telemetry_live", False),
            # candidate executables carry the World's donation
            # contract (ISSUE 20) so a swap never changes aliasing;
            # the fold gating mirrors _init_live_telemetry's
            donate=resident,
            donate_fold=resident and not getattr(
                world, "pipeline_decode", False),
        )
        self.regret_pct = float(regret_pct)
        self.regret_pin_windows = int(regret_pin_windows)
        self.current = "default"
        self.pending: str | None = None
        self.windows = 0
        self._last_p90: float | None = None
        # armed after a commit: (previous label, pre-swap p90,
        # windows left to judge)
        self._regret: tuple[str, float | None, int] | None = None
        self._lock = threading.Lock()
        # (window, from, to, reason) — deterministic, mirrors the
        # policy's transition log plus warm-gated commit/revert facts
        self.swaps: list[tuple[int, str, str, str]] = []
        self.last_signature: dict | None = None
        # "default" is the running config: mark it warm-equivalent by
        # compiling it lazily only if a revert ever needs it — the live
        # step IS the default executable, captured here
        self._boot_entry = None

    # -- the per-window drive -------------------------------------------
    def on_window(self, sig: dict | None,
                  tick_ms_p90: float | None = None) -> dict | None:
        """Feed one signature window (+ the window's measured tick-ms
        p90). Returns an event dict when a swap/revert COMMITTED this
        window, else None. Must be called from the tick thread (the
        commit mutates the World between ticks)."""
        with self._lock:
            self.windows += 1
            self.last_signature = sig if isinstance(sig, dict) else None
            ev = self._check_regret(tick_ms_p90)
            if ev is None:
                ev = self._maybe_commit(tick_ms_p90)
            if ev is None and isinstance(sig, dict):
                want = self.policy.observe(sig)
                if want is not None:
                    if want == self.current:
                        # the policy walked back to the config still
                        # serving while the previous target compiled:
                        # drop the stale pending, or it would commit
                        # (unwanted) the moment its compile warms
                        self.pending = None
                    else:
                        self.pending = want
                        self.warmset.ensure(want)
                        # commit in the SAME window when already warm
                        # (a revisited config pays zero decision lag)
                        ev = self._maybe_commit(tick_ms_p90)
            if tick_ms_p90 is not None:
                self._last_p90 = tick_ms_p90
            return ev

    # -- internals (lock held) ------------------------------------------
    def _maybe_commit(self, tick_ms_p90: float | None) -> dict | None:
        label = self.pending
        if label is None:
            return None
        entry = self.warmset.entry(label)
        if entry is not None and entry.error:
            # un-warmable candidate: stop asking for it
            logger.warning("governor %s: candidate %s failed to "
                           "compile (%s); pinning %s", self.name,
                           label, entry.error, self.current)
            self.pending = None
            self.policy.pin(self.current, self.regret_pin_windows,
                            f"compile-failed({label})")
            return None
        if entry is None or not entry.warm:
            return None  # keep serving the current config until warm
        self.pending = None
        return self._commit(label, "policy",
                            pre_p90=self._last_p90
                            if tick_ms_p90 is None else tick_ms_p90)

    def _commit(self, label: str, reason: str,
                pre_p90: float | None) -> dict | None:
        w = self._world()
        if w is None:
            return None
        prev = self.current
        if self._boot_entry is None:
            # capture the boot config as the "default" revert target
            # (its executable is the currently-running step — warm by
            # definition). acc0 must be a ZEROED accumulator with the
            # boot lane set — capturing the live cumulative one would
            # re-feed every boot-era sample into the metrics registry
            # (and classify the first post-revert window on lifetime
            # averages) when a later swap commits back to "default"
            from goworld_tpu.autotune.warmset import WarmEntry
            from goworld_tpu.ops import telemetry as telem

            skin_on = getattr(w, "_telem_skin_on", False)
            acc0 = None
            if getattr(w, "_telem_fn", None) is not None:
                acc0 = telem.telemetry_init(
                    skin_on, mega=False, occupancy=True,
                    n_tiles=w.n_spaces)
            self._boot_entry = WarmEntry(
                label="default", cfg=w.cfg, exe=w._step,
                fold_exe=getattr(w, "_telem_fn", None),
                acc0=acc0,
                skin_on=skin_on,
                half_skin=getattr(w, "_telem_half_skin", 0.0),
            )
            with self.warmset._lock:
                self.warmset._entries.setdefault("default",
                                                 self._boot_entry)
        entry = self.warmset.entry(label)
        if entry is None or not entry.warm:
            return None
        w.apply_tick_config(
            entry.cfg, entry.exe,
            telem_fold=entry.fold_exe, telem_acc0=entry.acc0,
            telem_skin_on=entry.skin_on,
            telem_half_skin=entry.half_skin,
        )
        self.current = label
        self.swaps.append((self.windows, prev, label, reason))
        _swap_counter(prev, label, reason).inc()
        self._regret = (prev, pre_p90, 2) if reason != "regret" \
            else None
        ev = {
            "window": self.windows,
            "from": prev,
            "to": label,
            "reason": reason,
            "tick": getattr(w, "tick_count", None),
        }
        logger.info("governor %s: swapped %s -> %s (%s) at tick %s",
                    self.name, prev, label, reason, ev["tick"])
        return ev

    def _check_regret(self, tick_ms_p90: float | None) -> dict | None:
        if self._regret is None:
            return None
        prev, pre_p90, left = self._regret
        if pre_p90 is None or pre_p90 <= 0:
            # no pre-swap baseline was ever measured: the guard cannot
            # judge — disarm instead of staying armed (and displayed)
            # forever
            self._regret = None
            return None
        if tick_ms_p90 is None or tick_ms_p90 != tick_ms_p90:  # NaN
            # no measured truth this window; wait, but boundedly — an
            # unmeasurable post-swap period must not pin the guard
            left -= 1
            self._regret = None if left <= 0 else (prev, pre_p90, left)
            return None
        if tick_ms_p90 > (1.0 + self.regret_pct) * pre_p90:
            bad = self.current
            self._regret = None
            self.pending = None
            ev = self._commit(prev, "regret", pre_p90=None)
            if ev is not None:
                ev["regret"] = {
                    "pre_p90_ms": round(pre_p90, 3),
                    "post_p90_ms": round(tick_ms_p90, 3),
                    "threshold_pct": self.regret_pct,
                }
                self.policy.pin(prev, self.regret_pin_windows,
                                f"regret({bad}: "
                                f"{pre_p90:.3g}->{tick_ms_p90:.3g}ms)")
            return ev
        left -= 1
        self._regret = None if left <= 0 else (prev, pre_p90, left)
        return None

    # -- observation -----------------------------------------------------
    def log_lines(self) -> list[str]:
        """Deterministic swap log (commit/revert facts — the policy's
        decision log is served alongside in :meth:`snapshot`)."""
        return [f"#{w} {frm}->{to} {reason}"
                for w, frm, to, reason in self.swaps]

    def snapshot(self) -> dict:
        with self._lock:
            reg = None
            if self._regret is not None:
                prev, pre, left = self._regret
                reg = {"revert_to": prev, "pre_p90_ms": pre,
                       "windows_left": left}
            return {
                "current": self.current,
                "pending": self.pending,
                "windows": self.windows,
                "swaps": self.log_lines(),
                "policy": self.policy.snapshot(),
                "warmset": self.warmset.snapshot(),
                "regret_guard": reg,
                "regret_pct": self.regret_pct,
                "signature": self.last_signature,
            }


# =======================================================================
# process-local registry (debug-http /governor, cli.py status)
# =======================================================================
_reg_lock = threading.Lock()
_governors: dict[str, Any] = {}  # name -> weakref.ref(KernelGovernor)


def register(name: str, gov: KernelGovernor) -> KernelGovernor:
    """Latest-wins registration (the devprof provider convention);
    weakref-backed so the registry never pins a discarded server's
    World."""
    with _reg_lock:
        _governors[name] = weakref.ref(gov)
    return gov


def unregister(name: str) -> None:
    with _reg_lock:
        _governors.pop(name, None)


def snapshot() -> dict:
    """The ``/governor`` payload: every live governor's snapshot, or
    an honest absence."""
    with _reg_lock:
        refs = list(_governors.items())
    out: dict = {}
    for name, ref in refs:
        gov = ref()
        if gov is None:
            continue
        try:
            out[name] = gov.snapshot()
        except Exception as exc:  # an endpoint must never 500
            out[name] = {"error": str(exc)[:200]}
    if not out:
        return {"error": "no kernel governor in this process "
                         "([gameN] governor = true enables it)"}
    return out


def reset() -> None:
    """Drop registry state (tests)."""
    with _reg_lock:
        _governors.clear()
