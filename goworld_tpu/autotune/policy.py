"""Governor decision plane — jax-free, deterministic, replayable.

The inputs are the workload-signature records the live telemetry plane
already reduces (``ops/telemetry.workload_signature`` over the rotating
drained-lane windows): rebuild rate, skin-slack p50, over_k/over_cap
duty cycles, enter/leave volume. The output is a **config key** — one
of the candidate labels over the scenario matrix's kernel A/B pool
(``SCENARIO_KERNEL_CANDIDATES``; the same labels every BENCH artifact
stamps per-scenario ``kernels_ms`` tables and ``best_kernel`` under).

Decisions are a **pure function of the signature stream** with the same
contract as :class:`goworld_tpu.utils.overload.OverloadGovernor`:

* **hysteresis** — a target config must win ``up_windows`` consecutive
  windows before a swap is decided (``down_windows`` for returning to
  the table default), a signature inside the hold band (rebuild rate
  near the churn-class boundary) holds the current config and resets
  the run, and every committed swap starts a ``cooldown_windows``
  refractory period;
* **determinism** — no wall clock, no RNG: equal signature streams
  replay byte-identical transition logs (``log_lines()``), asserted by
  tests/test_governor.py exactly like the overload ladder's seeded
  replay.

The class→candidate **mapping table** seeds from the checked-in
per-scenario ``best_kernel`` stamps (the measured CPU truth of the
flock-vs-teleport skin inversion, :func:`seed_table`) with built-in
fallbacks, and is overridable per ``[gameN]`` via ``governor_table``
(:func:`parse_table`). Until the TPU relay answers, the tables are
CPU-derived — which is exactly why the runtime regret guard
(:mod:`goworld_tpu.autotune.governor`) outranks them.
"""

from __future__ import annotations

import glob
import json
import os

__all__ = [
    "DEFAULT_CANDIDATES", "CANDIDATE_GRID_KEYS", "DEFAULT_TABLE",
    "SCENARIO_CLASS_MAP", "classify_signature", "candidate_overrides",
    "seed_table", "parse_table", "GovernorPolicy",
]

# The candidate pool: (label, GridSpec overrides). ONE home for the
# per-scenario kernel A/B pool — bench.py's SCENARIO_KERNEL_CANDIDATES
# re-exports this list, so the labels the policy decides between are
# exactly the labels the checked-in `kernels_ms` tables and
# `best_kernel` stamps are keyed by. Every override key must be a
# GridSpec field (contract-tested), and every candidate is EXACT at
# provisioned capacity — the pool deliberately excludes approx/shift
# style fidelity trades (the autotune "selectable" convention).
DEFAULT_CANDIDATES: tuple[tuple[str, dict], ...] = (
    ("default", {}),
    ("skin=0", {"skin": 0.0}),
    ("sweep=table,skin=0", {"sweep_impl": "table", "skin": 0.0}),
    ("sort=counting,skin=0", {"sort_impl": "counting", "skin": 0.0}),
)

# the GridSpec knob families a candidate override may touch (the
# recommendation-key contract test holds candidates to this set)
CANDIDATE_GRID_KEYS = ("skin", "sweep_impl", "sort_impl", "topk_impl",
                       "verlet_cap")

# signature class -> candidate label, the built-in fallback mapping.
# Grounded in the measured per-scenario tables (BENCH_r12 CPU):
#   flock      -> the skin holds (reuse ticks win)      -> default
#   teleport   -> every jump defeats the skin           -> skin=0
#   hotspot    -> density pressure, structure churn     -> counting
# `skinless` worlds (no skin lane) and ambiguous windows keep default.
DEFAULT_TABLE: dict[str, str] = {
    "flock_like": "default",
    "teleport_like": "skin=0",
    "density": "sort=counting,skin=0",
    "default": "default",
}

# which signature class each checked-in per-scenario best_kernel stamp
# seeds (the scenario IS the class's adversarial exemplar)
SCENARIO_CLASS_MAP = {
    "flock": "flock_like",
    "teleport": "teleport_like",
    "hotspot": "density",
}

# hold band half-width on the rebuild-rate churn boundary (the reducer
# classifies at 0.5; inside 0.5 +- band the policy holds its config)
CHURN_HOLD_BAND = 0.1
# minimum over_k duty cycle (fraction of ticks with truncated rows)
# before the density class outranks churn — see classify_signature
DENSITY_DUTY_MIN = 0.1


def candidate_overrides(
    label: str,
    candidates=DEFAULT_CANDIDATES,
) -> dict:
    """GridSpec overrides for a candidate label (KeyError lists the
    pool — a typo'd table entry must fail loudly at build time)."""
    for lbl, ov in candidates:
        if lbl == label:
            return dict(ov)
    raise KeyError(
        f"unknown kernel candidate {label!r}; pool: "
        f"{[lbl for lbl, _ in candidates]}"
    )


def classify_signature(sig: dict) -> str | None:
    """Reduce one workload-signature record to the policy's class key:
    ``teleport_like`` / ``flock_like`` / ``density`` / ``default``, or
    ``None`` inside a hold band (ambiguous window — hold the rung).

    Density pressure outranks the churn classes: a sustained over_k/
    over_cap duty cycle means interest sets are DEGRADING, and the
    counting-sort front half is the structure-churn lever regardless of
    how the population moves.

    ``skinless`` windows (the world currently runs skin=0, so the
    rebuild-rate signal does not exist) classify by the enter/leave
    event volume instead — interest-set churn is the observable proxy
    that survives the skin being off. Heavy/moderate volume keeps the
    teleport-like verdict (the skin would thrash), quiet volume says
    the skin would hold (flock-like), and ``low`` is the hold band.
    Without this, swapping to skin=0 would blind the policy and flap
    it straight back."""
    if not isinstance(sig, dict) or "error" in sig:
        return None
    # density keys on ROWS ACTUALLY TRUNCATED to nearest-k (over_k
    # duty cycle), not on bare over_cap ticks: at production density a
    # uniform world's Poisson tail puts the occasional cell past
    # cell_cap (~1 cell in thousands) without truncating any row —
    # the ranges sweep's pooled 3*cell_cap absorbs it — and a policy
    # that swapped on that noise would chase ghosts. A real density
    # collapse (hotspot) truncates rows at 100% duty.
    ok = sig.get("over_k_frac")
    if sig.get("density") in ("over_k", "over_cap") \
            and isinstance(ok, (int, float)) and ok > DENSITY_DUTY_MIN:
        return "density"
    churn = sig.get("churn")
    rr = sig.get("rebuild_rate")
    if churn in ("flock_like", "teleport_like") and rr is not None:
        if abs(float(rr) - 0.5) < CHURN_HOLD_BAND:
            return None  # hold band: too close to call
        return churn
    if churn == "skinless":
        ev = sig.get("events")
        if ev in ("moderate", "heavy"):
            return "teleport_like"
        if ev == "quiet":
            return "flock_like"
        return None  # "low": ambiguous without the skin lane
    return "default"


def seed_table(repo_dir: str | None = None,
               candidates=DEFAULT_CANDIDATES) -> dict[str, str]:
    """The class->label mapping table, seeded from the checked-in
    BENCH artifacts' per-scenario ``best_kernel`` stamps (latest round
    carrying one wins) over the :data:`DEFAULT_TABLE` fallbacks.

    jax-free and failure-proof: unreadable artifacts, missing blocks or
    best_kernel labels outside the candidate pool leave the fallback in
    place — the table must never be worse than the built-in defaults
    because an artifact rotted."""
    table = dict(DEFAULT_TABLE)
    if repo_dir is None:
        repo_dir = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    labels = {lbl for lbl, _ in candidates}
    for path in sorted(glob.glob(os.path.join(repo_dir,
                                              "BENCH_r*.json"))):
        if "_interim" in os.path.basename(path):
            continue
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        # the ONE headline definition shared with bench_schema/trend
        # (driver wrapper or bare artifact both resolve)
        from goworld_tpu.utils.devprof import artifact_headline

        rec = artifact_headline(doc) if isinstance(doc, dict) else None
        scenarios = (rec or {}).get("scenarios")
        if not isinstance(scenarios, dict):
            continue
        for scen, cls in SCENARIO_CLASS_MAP.items():
            blk = scenarios.get(scen)
            if not isinstance(blk, dict):
                continue
            best = blk.get("best_kernel")
            if isinstance(best, str) and best in labels:
                table[cls] = best
    return table


def parse_table(spec: str,
                candidates=DEFAULT_CANDIDATES) -> dict[str, str]:
    """Parse the ``[gameN] governor_table`` override string:
    ``class:label;class:label`` (labels may contain ``,``/``=``, so the
    separators are ``;`` and the FIRST ``:``). Unknown classes or
    labels outside the candidate pool are rejected loudly at config
    time, never silently at decision time."""
    out: dict[str, str] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        cls, sep, label = part.partition(":")
        cls, label = cls.strip(), label.strip()
        if not sep or not label:
            raise ValueError(
                f"governor_table entry {part!r} must be class:label")
        if cls not in DEFAULT_TABLE:
            raise ValueError(
                f"governor_table class {cls!r} unknown; classes: "
                f"{sorted(DEFAULT_TABLE)}")
        candidate_overrides(label, candidates)  # KeyError -> loud
        out[cls] = label
    return out


class GovernorPolicy:
    """The per-process kernel-config decision machine.

    ``observe(sig)`` is called once per signature window with the
    drained workload-signature record and returns the candidate label
    to swap to when a swap is DECIDED this window (``None`` otherwise
    — the common case). The caller (:class:`KernelGovernor`) commits
    the swap when the target executable is warm; the policy itself
    never touches jax.

    State machine (per window):

    * want = table[classify_signature(sig)] (hold band -> keep);
    * want == current resets the run; a changed want resets it too
      (a flapping signature never accumulates);
    * the run must reach ``up_windows`` (``down_windows`` when want is
      the default label) before a swap is decided;
    * a decided swap arms ``cooldown_windows`` of refractory windows;
    * ``pin(label, windows, reason)`` (the regret guard's revert path)
      forces ``current`` and suppresses decisions for ``windows``.

    Everything is a pure function of the observation sequence —
    equal signature streams replay byte-identical ``log_lines()``.
    """

    def __init__(
        self,
        *,
        table: dict[str, str] | None = None,
        candidates=DEFAULT_CANDIDATES,
        up_windows: int = 2,
        down_windows: int = 2,
        cooldown_windows: int = 4,
        initial: str = "default",
    ):
        self.candidates = tuple(candidates)
        self.table = dict(table if table is not None else DEFAULT_TABLE)
        for cls, lbl in self.table.items():
            candidate_overrides(lbl, self.candidates)  # loud on typos
        self.up_windows = max(1, int(up_windows))
        self.down_windows = max(1, int(down_windows))
        self.cooldown_windows = max(0, int(cooldown_windows))
        self.default_label = self.table.get("default", "default")
        self.current = initial
        self.window = 0           # observation index
        self._want: str | None = None
        self._run = 0
        self._cooldown_until = 0  # window index the refractory ends at
        self._pin_until = 0
        # (window, from, to, reason) — the deterministic transition log
        self.transitions: list[tuple[int, str, str, str]] = []

    # -- per-window observation -----------------------------------------
    def observe(self, sig: dict) -> str | None:
        """Feed one window's signature; returns the label to swap to
        when a swap is decided NOW, else None."""
        w = self.window
        self.window = w + 1
        cls = classify_signature(sig)
        if cls is None:
            # hold band: keep the rung, reset the run (the overload
            # ladder's hysteresis-band semantics)
            self._want, self._run = None, 0
            return None
        want = self.table.get(cls, self.default_label)
        if want == self.current:
            self._want, self._run = None, 0
            return None
        if want != self._want:
            self._want, self._run = want, 1
        else:
            self._run += 1
        needed = (self.down_windows if want == self.default_label
                  else self.up_windows)
        if self._run < needed:
            return None
        if w < self._pin_until:
            return None  # regret pin: measured truth beat the table
        if w < self._cooldown_until:
            return None  # per-swap cooldown
        self._log(w, self.current, want,
                  f"class={cls} run={self._run}/{needed}")
        self.current = want
        self._want, self._run = None, 0
        self._cooldown_until = self.window + self.cooldown_windows
        return want

    def pin(self, label: str, windows: int, reason: str) -> None:
        """Regret-guard revert: force ``label`` as current and suppress
        decisions for ``windows`` (the table was wrong for this
        workload on this hardware — stop re-trying it)."""
        w = self.window
        if label != self.current:
            self._log(w, self.current, label, f"revert {reason}")
            self.current = label
        self._pin_until = w + max(0, int(windows))
        self._want, self._run = None, 0

    def _log(self, window: int, frm: str, to: str, reason: str) -> None:
        self.transitions.append((window, frm, to, reason))

    # -- queries ---------------------------------------------------------
    def log_lines(self) -> list[str]:
        """One line per transition; equal signature streams produce
        byte-identical logs (the determinism contract)."""
        return [f"#{w} {frm}->{to} {reason}"
                for w, frm, to, reason in self.transitions]

    def snapshot(self) -> dict:
        return {
            "current": self.current,
            "window": self.window,
            "run": self._run,
            "want": self._want,
            "cooldown_until": self._cooldown_until,
            "pin_until": self._pin_until,
            "table": dict(self.table),
            "transitions": self.log_lines(),
        }
