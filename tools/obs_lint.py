#!/usr/bin/env python
"""Observability drift lint (ISSUE 17 satellite): the docs must keep
up with the debug plane, mechanically.

Two contracts, both checked TEXTUALLY (this tool is jax-free and runs
as a tier-1 test, tests/test_obs_lint.py):

1. every endpoint in ``debug_http._ENDPOINTS`` has a row in the
   docs/OBSERVABILITY.md endpoint table (a markdown table row whose
   first cell backticks the path), and every documented path is a
   real endpoint — a doc row for a deleted endpoint is drift too;
2. every pytest marker registered in tests/conftest.py
   (``config.addinivalue_line("markers", "<name>: ...")``) appears in
   README.md (as ``-m <name>`` or a backticked ``<name>``) — an
   undocumented marker is a test suite nobody knows how to select.

Exit codes: 0 clean, 1 usage/missing file, 2 drift found.

Usage::

    python tools/obs_lint.py            # lint the repo this file is in
    python tools/obs_lint.py --repo DIR
"""

from __future__ import annotations

import argparse
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parse_endpoints(debug_http_src: str) -> list[str]:
    """The ``_ENDPOINTS = [...]`` literal, textually (importing
    debug_http would drag in the serving stack; the lint must stay
    dependency-free)."""
    m = re.search(r"_ENDPOINTS\s*=\s*\[([^\]]*)\]", debug_http_src,
                  re.S)
    if m is None:
        return []
    return re.findall(r'"(/[a-z_]+)"', m.group(1))


def parse_doc_endpoints(doc_src: str) -> list[str]:
    """Every path documented in a markdown table row: lines starting
    with ``|`` whose FIRST cell carries a backticked ``/path``."""
    out: list[str] = []
    for line in doc_src.splitlines():
        if not line.lstrip().startswith("|"):
            continue
        first_cell = line.lstrip().lstrip("|").split("|", 1)[0]
        m = re.search(r"`(/[a-z_]+)`", first_cell)
        if m is not None:
            out.append(m.group(1))
    return out


def parse_markers(conftest_src: str) -> list[str]:
    """Every registered pytest marker name: the word before the first
    colon in the string literal following ``"markers"``."""
    return re.findall(
        r'addinivalue_line\(\s*"markers",\s*"(\w+):', conftest_src)


def marker_documented(name: str, readme_src: str) -> bool:
    return f"-m {name}" in readme_src \
        or f"`{name}`" in readme_src


def lint(repo: str) -> tuple[list[str], dict]:
    """Returns (drift problems, summary facts). A missing input file
    is a problem too — the contract can't be silently vacuous."""
    problems: list[str] = []
    paths = {
        "debug_http": os.path.join(repo, "goworld_tpu", "utils",
                                   "debug_http.py"),
        "doc": os.path.join(repo, "docs", "OBSERVABILITY.md"),
        "conftest": os.path.join(repo, "tests", "conftest.py"),
        "readme": os.path.join(repo, "README.md"),
    }
    src: dict[str, str] = {}
    for key, p in paths.items():
        try:
            with open(p, encoding="utf-8") as fh:
                src[key] = fh.read()
        except OSError as exc:
            problems.append(f"unreadable {p}: {exc}")
            return problems, {}

    endpoints = parse_endpoints(src["debug_http"])
    documented = parse_doc_endpoints(src["doc"])
    markers = parse_markers(src["conftest"])
    if not endpoints:
        problems.append("no _ENDPOINTS list found in debug_http.py "
                        "(parser drift?)")
    if not markers:
        problems.append("no markers found in tests/conftest.py "
                        "(parser drift?)")
    for ep in endpoints:
        if ep not in documented:
            problems.append(
                f"endpoint {ep} (debug_http._ENDPOINTS) has no row in "
                "the docs/OBSERVABILITY.md endpoint table")
    for ep in documented:
        if ep not in endpoints:
            problems.append(
                f"docs/OBSERVABILITY.md documents {ep} but "
                "debug_http._ENDPOINTS does not serve it")
    for name in markers:
        if not marker_documented(name, src["readme"]):
            problems.append(
                f"pytest marker '{name}' (tests/conftest.py) is not "
                "documented in README.md")
    return problems, {
        "endpoints": len(endpoints),
        "documented_endpoints": len(set(documented)),
        "markers": len(markers),
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="lint debug-http endpoints and pytest markers "
                    "against their docs")
    ap.add_argument("--repo", default=REPO)
    args = ap.parse_args(argv)
    if not os.path.isdir(args.repo):
        print(f"no such repo dir: {args.repo}", file=sys.stderr)
        return 1
    problems, facts = lint(args.repo)
    for p in problems:
        print(f"DRIFT: {p}", file=sys.stderr)
    if problems:
        return 2
    print(f"obs_lint: ok ({facts.get('endpoints', 0)} endpoints "
          f"documented, {facts.get('markers', 0)} markers documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
