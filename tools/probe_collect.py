"""Micro-bisect the collect phase at 131K: interest_pairs vs
collect_sync vs collect_attr_deltas, marginal timing like bench."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
from jax import lax

from goworld_tpu.ops.delta import interest_pairs
from goworld_tpu.ops.sync import collect_attr_deltas, collect_sync

N = int(os.environ.get("PROBE_N", 131072))
K = 32
L = 5
ENTER_CAP = LEAVE_CAP = SYNC_CAP = 65536
ATTR_CAP = 4096
DELTA_ROWS = 65536

rng = np.random.default_rng(0)
nbr = np.sort(
    rng.integers(0, N + 1, (N, K)).astype(np.int32), axis=1
)
nbr = jnp.asarray(nbr)
has_client = jnp.asarray(rng.random(N) < 0.01)
pos = jnp.asarray(rng.random((N, 3)).astype(np.float32) * 1000)
yaw = jnp.zeros(N)
hot = jnp.zeros((N, 8))
adirty = jnp.asarray((rng.random(N) < 0.03).astype(np.uint32))
fl = jnp.asarray(rng.integers(0, 4, (N, K)).astype(np.int32))


def timeit(name, mk):
    r1, r2 = jax.jit(mk(L)), jax.jit(mk(2 * L))
    float(np.asarray(r1(nbr)))
    float(np.asarray(r2(nbr)))
    es = []
    for i in range(2):
        t0 = time.perf_counter(); float(np.asarray(r1(nbr)))
        e1 = time.perf_counter() - t0
        t0 = time.perf_counter(); float(np.asarray(r2(nbr)))
        e2 = time.perf_counter() - t0
        es.append((e1, e2))
    ms = 1000.0 * max(min(e[1] for e in es) - min(e[0] for e in es),
                      1e-9) / L
    print(f"{name:28s} {ms:9.3f} ms/iter", flush=True)


def mk_pairs(length):
    def run(nb):
        def body(carry, _):
            prev_dirty = carry
            prev = jnp.where(prev_dirty[:, None],
                             jnp.roll(nb, 1, axis=0), nb)
            ew, ej, en, lw, lj, ln, drn = interest_pairs(
                prev, nb, N, ENTER_CAP, LEAVE_CAP, DELTA_ROWS)
            return jnp.roll(prev_dirty, 1), en + ln + drn + ew.sum()
        c, s = lax.scan(body, (jnp.arange(N) % 16) == 0, None,
                        length=length)
        return s.sum()
    return run


def mk_sync(length):
    def run(nb):
        def body(carry, _):
            dirty = carry
            sw, sj, sv, sn = collect_sync(
                nb, dirty, has_client, pos, yaw, SYNC_CAP,
                nbr_dirty=(fl & 1).astype(bool) & dirty[:, None])
            return jnp.roll(dirty, 3), sn + sw.sum() + sv.sum()
        c, s = lax.scan(body, jnp.ones(N, bool), None, length=length)
        return s.sum()
    return run


def mk_attrs(length):
    def run(nb):
        def body(carry, _):
            ad = carry
            ae, ai, av, an = collect_attr_deltas(hot, ad, ATTR_CAP)
            return jnp.roll(ad, 1), an + ae.sum() + av.sum()
        c, s = lax.scan(body, adirty, None, length=length)
        return s.sum()
    return run


print(f"device={jax.devices()[0]} N={N}", flush=True)
timeit("interest_pairs", mk_pairs)
timeit("collect_sync", mk_sync)
timeit("collect_attr_deltas", mk_attrs)
print("done", flush=True)
