"""Gate-leg wire cost: per-message packets vs the per-tick batch.

Quantifies what MT_CLIENT_EVENTS_BATCH buys at churn volume: the old
path sent ONE dispatcher packet per client message (pack + 4-byte
frame + asyncio send x 2 hops); the new path coalesces a tick's
messages into one bundle per gate. This probe measures, for a
4096-create/4096-destroy churn tick (the library event caps):

  * packets on the game->dispatcher leg (framing/send-call count)
  * total bytes (framing + routing-prefix overhead delta)
  * host CPU to pack both shapes

Run: python -u tools/probe_wire.py   (no jax, no sockets)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from goworld_tpu.net import proto
from goworld_tpu.net.packet import frame

N = 4096
CID = "c" * 16
EID = "e" * 16
ATTRS = {"name": "walker-1234", "level": 42}
POS = (123.0, 0.0, 456.0)


def per_message():
    t0 = time.perf_counter()
    n_pkts = 0
    n_bytes = 0
    for i in range(N):
        p = proto.pack_create_entity_on_client(
            1, CID, EID, "Walker", False, ATTRS, POS, 1.5)
        n_bytes += len(frame(p))
        n_pkts += 1
        p.release()  # production _send releases too — keep the pool
                     # comparison symmetric with batched()
    for i in range(N):
        p = proto.pack_destroy_entity_on_client(1, CID, EID, False)
        n_bytes += len(frame(p))
        n_pkts += 1
        p.release()
    dt = time.perf_counter() - t0
    return n_pkts, n_bytes, dt


def batched():
    t0 = time.perf_counter()
    recs = []
    for i in range(N):
        p = proto.pack_create_entity_on_client(
            1, CID, EID, "Walker", False, ATTRS, POS, 1.5)
        recs.append((proto.MT_CREATE_ENTITY_ON_CLIENT,
                     bytes(memoryview(p.buf)[4:])))
        p.release()
    for i in range(N):
        p = proto.pack_destroy_entity_on_client(1, CID, EID, False)
        recs.append((proto.MT_DESTROY_ENTITY_ON_CLIENT,
                     bytes(memoryview(p.buf)[4:])))
        p.release()
    wire = frame(proto.pack_client_events_batch(1, recs))
    dt = time.perf_counter() - t0
    return 1, len(wire), dt


def main():
    # warm allocators/pools
    per_message()
    batched()
    op_, ob, ot = min((per_message() for _ in range(5)),
                      key=lambda r: r[2])
    np_, nb, nt = min((batched() for _ in range(5)), key=lambda r: r[2])
    print(f"per-message: {op_} packets  {ob} bytes  {1000*ot:.2f} ms")
    print(f"batched:     {np_} packets  {nb} bytes  {1000*nt:.2f} ms")
    print(f"=> {op_ / np_:.0f}x fewer dispatcher packets, "
          f"{100 * (1 - nb / ob):.1f}% fewer bytes, "
          f"{ot / nt:.2f}x pack-side CPU "
          f"for a {N}+{N} churn tick on one gate")


if __name__ == "__main__":
    main()
