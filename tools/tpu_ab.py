"""One-shot TPU A/B session: runs every staged experiment in ONE
process (one backend init), streaming results to stdout as they land.

Order puts the decision-critical experiments first in case the backend
dies mid-run:
  1. full-sweep impl matrix at 131K (table/shift x exact/sort/f32 +
     approx + ranges + the r6 FUSED Pallas back half) — picks the
     production config.
  1b. Verlet skin reuse (rebuild vs reuse tick) + front-half sort impl
     (argsort vs counting vs pallas) — the r5 levers.
  1c. fused-vs-split sweep A/B at the SECOND shape (PROBE_N2, default
     1M when PROBE_N is the 131K shard): fused against every split
     impl, the ISSUE-6 headline rows. TPU-only — interpret-mode fused
     at 1M would eat the session; off-TPU these rows print SKIP (the
     CPU fused number is recorded by bench.py's backhalf_ab instead).
  2. multichip mesh A/B at the bench shape (ISSUE 10): halo_impl
     ppermute-vs-async, a migrate_cap sweep, border_churn on/off —
     scan-marginal mega-tick rows over the real mesh. TPU-only like
     1c (interpret-mode async halo + an N-device mesh emulated on CPU
     would stall the session; the tier-1 multichip marker covers the
     small-N CPU truth).
  2b. governor-vs-best-static A/B (ISSUE 13): the full phase-switching
     schedule through bench.measure_governor at the probe shape, so a
     relay window audits the CPU-derived kernel mapping tables against
     silicon. TPU-only; `bench.py --governor` records the CPU truth.
  3. back-half stage bisect (gather / +key / +topk / +final-sort).
  4. collect-phase bisect (interest_pairs / collect_sync / attrs).
  5. move-phase bisect (inputs scatter / random_walk / integrate).
Never wrapped in `timeout`; exits cleanly on its own.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
from jax import lax

from goworld_tpu.ops.aoi import (
    GridSpec, _cell_rows, _sort_cells, _sorted_src, _build_table,
    grid_neighbors, grid_neighbors_flags,
)

N = int(os.environ.get("PROBE_N", 131072))
L = int(os.environ.get("PROBE_TICKS", 5))
K = 32
CC = 12
extent = float(int((N * 10000 / 12) ** 0.5))

# --workload <scenario>: every experiment measures on the ADVERSARIAL
# layout that scenario converges to (hotspot blob, shrink ring, ...)
# instead of the uniform start — the ISSUE-7 passthrough; scenario
# registry names (goworld_tpu/scenarios/spec.py). Env PROBE_WORKLOAD
# works too (the relay driver is env-oriented).
WORKLOAD = os.environ.get("PROBE_WORKLOAD", "")
if "--workload" in sys.argv:
    WORKLOAD = sys.argv[sys.argv.index("--workload") + 1]


def _layout(workload: str, n: int, ext: float, seed: int = 0):
    from goworld_tpu.scenarios.runner import scenario_layout

    return jnp.asarray(scenario_layout(workload, n, ext, seed=seed))


key = jax.random.PRNGKey(0)
k1, k2, k3 = jax.random.split(key, 3)
if WORKLOAD:
    pos = _layout(WORKLOAD, N, extent)   # KeyError lists the registry
else:
    pos = jnp.stack([
        jax.random.uniform(k1, (N,), maxval=extent),
        jnp.zeros(N),
        jax.random.uniform(k2, (N,), maxval=extent)], axis=1)
alive = jnp.ones(N, bool)
flags = (jax.random.uniform(k3, (N,)) < 0.5).astype(jnp.int32)

print(f"device={jax.devices()[0]} N={N} "
      f"workload={WORKLOAD or 'uniform'}", flush=True)


def timeit(name, mk, arg=None):
    a = pos if arg is None else arg
    try:
        r1, r2 = jax.jit(mk(L)), jax.jit(mk(2 * L))
        t0 = time.perf_counter()
        float(np.asarray(r1(a)))
        c1 = time.perf_counter() - t0
        float(np.asarray(r2(a + 0.001)))
        es = []
        for i in range(2):
            t0 = time.perf_counter()
            float(np.asarray(r1(a + 0.002 * (i + 1))))
            e1 = time.perf_counter() - t0
            t0 = time.perf_counter()
            float(np.asarray(r2(a + 0.003 * (i + 1))))
            e2 = time.perf_counter() - t0
            es.append((e1, e2))
        ms = 1000.0 * max(min(e[1] for e in es) - min(e[0] for e in es),
                          1e-9) / L
        print(f"{name:34s} {ms:10.3f} ms/iter   (compile {c1:.1f}s)",
              flush=True)
        return ms
    except Exception as exc:
        print(f"{name:34s} FAILED: {str(exc)[:160]}", flush=True)
        return None


# ---- 1. full-sweep impl matrix (with flags = the real tick path) ----

def mk_full(impl, topk):
    sp = GridSpec(radius=50.0, extent_x=extent, extent_z=extent,
                  k=K, cell_cap=CC, row_block=65536,
                  sweep_impl=impl, topk_impl=topk)

    def make(length):
        def run(p0):
            def body(p, _):
                nbr, cnt, fl = grid_neighbors_flags(
                    sp, p, alive, flag_bits=flags)
                p = p + (cnt[:, None] % 2).astype(p.dtype) * 1e-6
                return p, cnt.sum() + fl.sum()
            pp, ss = lax.scan(body, p0, None, length=length)
            return ss.sum().astype(jnp.float32) + pp.sum()
        return run
    return make


for impl, topk in (("ranges", "sort"), ("table", "sort"),
                   ("cellrow", "sort"), ("cellrow", "f32"),
                   ("table", "f32"), ("ranges", "f32"),
                   ("shift", "sort"), ("shift", "f32"),
                   ("table", "exact"), ("table", "approx"),
                   # r6: one-kernel back half (bit-identical to
                   # ranges; in-kernel ranking so topk only changes
                   # the key encoding it packs)
                   ("fused", "sort"), ("fused", "f32")):
    timeit(f"sweep {impl}/{topk}", mk_full(impl, topk))

# ---- 1a. hotspot row at the matrix shape (ISSUE 7) ------------------
# The matrix above measures the uniform density; this row times the
# production sweep on the hotspot-CONVERGED blob (max cap overflow,
# every row truncating) so the relay answers "fast under the named
# worst case" too, not just at one workload point. Skipped when
# --workload already made the whole matrix adversarial.
if WORKLOAD not in ("", "hotspot"):
    print("sweep @hotspot                     SKIP "
          f"(--workload {WORKLOAD} owns the layout)", flush=True)
elif not WORKLOAD:
    hot_pos = _layout("hotspot", N, extent, seed=2)
    for impl, topk in (("table", "sort"), ("ranges", "sort"),
                       ("fused", "sort")):
        timeit(f"sweep {impl}/{topk} @hotspot", mk_full(impl, topk),
               arg=hot_pos)

# ---- 1b. Verlet skin + front-half sort impls ------------------------

from goworld_tpu.ops.aoi import grid_neighbors_verlet, init_verlet_cache


def mk_verlet(skin, force_rebuild, sort_impl="argsort"):
    sp = GridSpec(radius=50.0, extent_x=extent, extent_z=extent,
                  k=K, cell_cap=CC, row_block=65536, skin=skin,
                  sort_impl=sort_impl)
    cache0 = init_verlet_cache(sp, N)

    def make(length):
        def run(p0):
            def body(carry, _):
                p, cache = carry
                nbr, cnt, fl, _s, cache2, _rb, _sl = \
                    grid_neighbors_verlet(
                        sp, p, alive, cache0 if force_rebuild else cache,
                        flag_bits=flags)
                p = p + (cnt[:, None] % 2).astype(p.dtype) * 1e-6
                return (p, cache2), cnt.sum() + fl.sum()
            (pp, _c), ss = lax.scan(body, (p0, cache0), None,
                                    length=length)
            return ss.sum().astype(jnp.float32) + pp.sum()
        return run
    return make


timeit("verlet reuse  (skin=4)", mk_verlet(4.0, False))
timeit("verlet rebuild(skin=4)", mk_verlet(4.0, True))
timeit("verlet reuse  (skin=8)", mk_verlet(8.0, False))


def mk_sort(sort_impl):
    sp = GridSpec(radius=50.0, extent_x=extent, extent_z=extent,
                  k=K, cell_cap=CC, sort_impl=sort_impl)

    def make(length):
        def run(p0):
            def body(p, _):
                cx, cz, srow, al2, czp, n_rows = _cell_rows(
                    sp, p, alive, None)
                order, sorted_row = _sort_cells(
                    N, n_rows, srow, sp.sort_impl)
                s = order.sum() + sorted_row.sum()
                p = p + (s.astype(p.dtype) % 2) * 1e-7
                return p, s
            pp, ss = lax.scan(body, p0, None, length=length)
            return ss.sum().astype(jnp.float32) + pp.sum()
        return run
    return make


for si in ("argsort", "counting", "pallas"):
    timeit(f"front sort {si}", mk_sort(si))

# ---- 1c. fused-vs-split A/B at the second shape ---------------------
# The ISSUE-6 headline rows: the fused Pallas back half against every
# split sweep at the OTHER deployment shape (131K per-chip shard and
# the 1M north-star world are both one env flip away). TPU-only: the
# fused kernel off-TPU runs in interpret mode, where a 1M row would
# burn the whole relay window emulating — bench.py's backhalf_ab
# already records that CPU number at a sane shape.

from goworld_tpu.ops.pallas_compat import on_tpu

N2 = int(os.environ.get("PROBE_N2", 1048576 if N <= 262144 else 131072))
if on_tpu():
    extent2 = float(int((N2 * 10000 / 12) ** 0.5))
    kk1, kk2, kk3 = jax.random.split(jax.random.PRNGKey(1), 3)
    if WORKLOAD:
        pos2 = _layout(WORKLOAD, N2, extent2, seed=1)
    else:
        pos2 = jnp.stack([
            jax.random.uniform(kk1, (N2,), maxval=extent2),
            jnp.zeros(N2),
            jax.random.uniform(kk2, (N2,), maxval=extent2)], axis=1)
    alive2_ab = jnp.ones(N2, bool)
    flags2 = (jax.random.uniform(kk3, (N2,)) < 0.5).astype(jnp.int32)

    def mk_full2(impl):
        sp = GridSpec(radius=50.0, extent_x=extent2, extent_z=extent2,
                      k=K, cell_cap=CC, row_block=65536,
                      sweep_impl=impl, topk_impl="sort")

        def make(length):
            def run(p0):
                def body(p, _):
                    nbr, cnt, fl = grid_neighbors_flags(
                        sp, p, alive2_ab, flag_bits=flags2)
                    p = p + (cnt[:, None] % 2).astype(p.dtype) * 1e-6
                    return p, cnt.sum() + fl.sum()
                pp, ss = lax.scan(body, p0, None, length=length)
                return ss.sum().astype(jnp.float32) + pp.sum()
            return run
        return make

    for impl in ("fused", "ranges", "table", "cellrow", "shift"):
        timeit(f"sweep@{N2} {impl}/sort", mk_full2(impl), arg=pos2)
else:
    print(f"sweep@{N2} fused-vs-split       SKIP (no TPU backend; "
          "interpret-mode fused at this shape would stall the session "
          "— see bench.py backhalf_ab for the CPU record)", flush=True)

# ---- 1d. precision (quantized planes) on/off A/B (ISSUE 12) ---------
# The q16 lattice sweep at 131K AND the second shape (default 1M):
# the packed sorted view + int16-pair distance math against the f32
# baseline, same workload/layout, skin off then on (the reuse re-rank
# is where the packed cand cache pays). TPU-only like 1c: the CPU
# marginal is recorded by bench.py's precision_ab every round.

if on_tpu():
    def mk_prec(impl, prec, skin, nq, ext_q, pos_q, alive_q, flags_q):
        sp = GridSpec(radius=50.0, extent_x=ext_q, extent_z=ext_q,
                      k=K, cell_cap=CC, row_block=65536,
                      sweep_impl=impl, topk_impl="sort", skin=skin,
                      precision=prec)
        if skin > 0:
            cache0 = init_verlet_cache(sp, nq)

        def make(length):
            def run(p0):
                if skin > 0:
                    def body(carry, _):
                        p, cache = carry
                        nbr, cnt, fl, _s, cache2, _rb, _sl = \
                            grid_neighbors_verlet(
                                sp, p, alive_q, cache,
                                flag_bits=flags_q)
                        p = p + (cnt[:, None] % 2).astype(p.dtype) \
                            * 1e-6
                        return (p, cache2), cnt.sum() + fl.sum()
                    (pp, _c), ss = lax.scan(body, (p0, cache0), None,
                                            length=length)
                    return ss.sum().astype(jnp.float32) + pp.sum()

                def body(p, _):
                    nbr, cnt, fl = grid_neighbors_flags(
                        sp, p, alive_q, flag_bits=flags_q)
                    p = p + (cnt[:, None] % 2).astype(p.dtype) * 1e-6
                    return p, cnt.sum() + fl.sum()
                pp, ss = lax.scan(body, p0, None, length=length)
                return ss.sum().astype(jnp.float32) + pp.sum()
            return run
        return make

    shapes = [(N, extent, pos, alive, flags)]
    if on_tpu():
        N2p = int(os.environ.get("PROBE_N2",
                                 1048576 if N <= 262144 else 131072))
        ext2p = float(int((N2p * 10000 / 12) ** 0.5))
        pk1, pk2, pk3 = jax.random.split(jax.random.PRNGKey(4), 3)
        pos2p = jnp.stack([
            jax.random.uniform(pk1, (N2p,), maxval=ext2p),
            jnp.zeros(N2p),
            jax.random.uniform(pk2, (N2p,), maxval=ext2p)], axis=1)
        shapes.append((N2p, ext2p, pos2p, jnp.ones(N2p, bool),
                       (jax.random.uniform(pk3, (N2p,)) < 0.5)
                       .astype(jnp.int32)))
    for nq, ext_q, pos_q, alive_q, flags_q in shapes:
        for prec in ("off", "q16"):
            timeit(f"prec@{nq} ranges/{prec} skin=0",
                   mk_prec("ranges", prec, 0.0, nq, ext_q, pos_q,
                           alive_q, flags_q), arg=pos_q)
            timeit(f"prec@{nq} ranges/{prec} skin=4",
                   mk_prec("ranges", prec, 4.0, nq, ext_q, pos_q,
                           alive_q, flags_q), arg=pos_q)
else:
    print("prec@131K/1M q16-vs-off          SKIP (no TPU backend; "
          "bench.py precision_ab records the CPU marginal + modeled "
          "bytes every round)", flush=True)

# ---- 2. multichip mesh A/B at the bench shape (ISSUE 10) ------------
# halo_impl ppermute-vs-async, migrate_cap sweep, border_churn on/off:
# scan-marginal mega-tick ms over the real ICI mesh via bench.py's
# build_mega/_mega_tick_ms (the EXACT harness the --multichip headline
# times, so these rows transfer 1:1 to the artifact).

N_MESH = int(os.environ.get("PROBE_MULTI_N", 1048576))
if on_tpu() and len(jax.devices()) > 1:
    import importlib.util as _ilu

    _bs = _ilu.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    _bench = _ilu.module_from_spec(_bs)
    sys.modules.setdefault("bench", _bench)
    _bs.loader.exec_module(_bench)
    from goworld_tpu.parallel.megaspace import make_mega_tick
    from goworld_tpu.scenarios.spec import get_scenario as _get_sc

    def mesh_row(label, **kw):
        try:
            mc, mesh_, st, ins, pol = _bench.build_mega(N_MESH, **kw)
            tick = make_mega_tick(mc, mesh_)
            per, scale, _r = _bench._mega_tick_ms(tick, st, ins, pol, L)
            print(f"mega@{N_MESH} {label:22s} "
                  f"{1000.0 * per:10.3f} ms/tick   "
                  f"(scale_2x {scale:.2f}, halo_cap {mc.halo_cap})",
                  flush=True)
        except Exception as exc:
            print(f"mega@{N_MESH} {label:22s} FAILED: "
                  f"{str(exc)[:160]}", flush=True)

    for impl in ("ppermute", "async"):
        mesh_row(f"halo={impl}", halo_impl=impl)
    # modeled ICI halo bytes under the quantized planes (ISSUE 12):
    # the packing itself is staged — these rows are what the relay
    # arbitrates against the measured halo marginals above
    try:
        from goworld_tpu.utils.devprof import (
            roofline_model_bytes_multichip as _rmm,
        )

        n_dev_m = len(jax.devices())
        mk_m = {"n_dev": n_dev_m,
                "halo_cap": int(os.environ.get("BENCH_HALO_CAP", 4096)),
                "migrate_cap": int(os.environ.get("BENCH_MIGRATE_CAP",
                                                  256))}
        for prec in ("off", "q16"):
            gk_m = {"k": K, "cell_cap": CC, "precision": prec}
            for impl in ("ppermute", "async"):
                mk_m["halo_impl"] = impl
                mb = _rmm(N_MESH // n_dev_m, gk_m, mk_m)["ici_halo"] \
                    / 1e6
                print(f"mega model ici_halo {impl}/{prec:4s}"
                      f"{mb:10.3f} MB/chip/tick", flush=True)
    except Exception as exc:
        print(f"mega model ici_halo FAILED: {str(exc)[:120]}",
              flush=True)
    for cap in (128, 256, 512, 1024):
        os.environ["BENCH_MIGRATE_CAP"] = str(cap)
        mesh_row(f"migrate_cap={cap}")
    os.environ.pop("BENCH_MIGRATE_CAP", None)
    mesh_row("border_churn=off")
    mesh_row("border_churn=on", scenario=_get_sc("hotspot"),
             npc_speed=25.0)
else:
    print(f"mega@{N_MESH} halo/migrate/churn   SKIP (no TPU mesh; "
          "interpret-mode async halo over emulated devices would "
          "stall the session — the tier-1 `-m multichip` suite covers "
          "the small-N CPU truth)", flush=True)

# ---- 2b. governor-vs-best-static A/B at the bench shape (ISSUE 13) --
# The full phase-switching schedule (flock -> teleport -> hotspot)
# through bench.measure_governor: the governor's end-to-end throughput
# vs every static candidate pin, per-phase chosen configs and swap
# latencies — ON HARDWARE, so ROADMAP item 1's relay window audits the
# CPU-derived mapping tables (and the regret thresholds) against
# silicon. TPU-only: the CPU truth is recorded by `bench.py
# --governor` into every round artifact.
if on_tpu():
    try:
        if "bench" not in sys.modules:
            import importlib.util as _ilu2

            _bs2 = _ilu2.spec_from_file_location(
                "bench", os.path.join(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))), "bench.py"))
            _bench2 = _ilu2.module_from_spec(_bs2)
            sys.modules["bench"] = _bench2
            _bs2.loader.exec_module(_bench2)
        _bench_g = sys.modules["bench"]
        g = _bench_g.measure_governor(N)
        print(f"governor@{g['n']} schedule {'->'.join(g['schedule'])} "
              f"{g['throughput']:12.0f} et/s over {g['ticks']} ticks "
              f"({g['swaps_total']} swaps, prewarm {g['prewarm_s']}s)",
              flush=True)
        for ph in g.get("phases", []):
            print(f"governor phase {ph['scenario']:10s} chosen="
                  f"{ph['chosen']:22s} expected={ph['expected']:22s} "
                  f"swap_latency={ph['swap_latency_ticks']} ticks",
                  flush=True)
        for lbl, s in sorted((g.get("static_wall_s") or {}).items()):
            print(f"governor static {lbl:24s} {s!s:>10} s", flush=True)
        print(f"governor vs_best_static {g.get('vs_best_static')} "
              f"(best {str((g.get('best_static') or {}).get('label'))}"
              f", worst "
              f"{str((g.get('worst_static') or {}).get('label'))}; "
              f"compile-free={g.get('trace_counts_stable')})",
              flush=True)
    except Exception as exc:
        print(f"governor@{N} schedule            FAILED: "
              f"{str(exc)[:200]}", flush=True)
else:
    print(f"governor@{N} vs-best-static      SKIP (no TPU backend; "
          "the CPU schedule truth is stamped by `bench.py --governor` "
          "into every round artifact)", flush=True)

# ---- 3. back-half stage bisect (table impl, no flags) ---------------

spec = GridSpec(radius=50.0, extent_x=extent, extent_z=extent,
                k=K, cell_cap=CC, row_block=65536)
cc = CC


def front_half(p):
    cx, cz, srow, alive2, czp, n_rows = _cell_rows(spec, p, alive, None)
    order, sorted_row = _sort_cells(N, n_rows, srow)
    src, table_sentinel, sentinel_bits = _sorted_src(spec, p, None, order)
    table = _build_table(cc, n_rows, sorted_row, src,
                         (jnp.inf, jnp.inf, sentinel_bits))
    return cx, cz, czp, n_rows, table, table_sentinel


def mk_stage(stage):
    def make(length):
        def run(p0):
            def body(p, _):
                cx, cz, czp, n_rows, table, sentinel = front_half(p)
                rows = jnp.arange(spec.row_block, dtype=jnp.int32)
                dxs = jnp.array([-1, 0, 1], jnp.int32)
                starts = (cx[rows][:, None] + dxs[None, :] + 1) * czp \
                    + cz[rows][:, None]
                b = rows.shape[0]
                if stage == "gather_take":
                    rows9 = (starts[:, :, None]
                             + jnp.arange(3)[None, None, :]) \
                        .reshape(b, 9)
                    win = jnp.take(table, rows9, axis=0)
                    s = jnp.where(jnp.isfinite(win), win, 0.0).sum()
                    return p + (s % 2) * 1e-7, s
                win = jax.vmap(jax.vmap(
                    lambda s: lax.dynamic_slice(table, (s, 0),
                                                (3, 3 * cc))
                ))(starts)
                win = win.reshape(b, 9, 3 * cc)
                if stage == "gather":
                    s = jnp.where(jnp.isfinite(win), win, 0.0).sum()
                    return p + (s % 2) * 1e-7, s
                cand_px = win[:, :, :cc].reshape(b, 9 * cc)
                cand_pz = win[:, :, cc:2 * cc].reshape(b, 9 * cc)
                cand_w = lax.bitcast_convert_type(
                    win[:, :, 2 * cc:], jnp.int32).reshape(b, 9 * cc)
                ddx = jnp.abs(cand_px - p[rows, 0][:, None])
                ddz = jnp.abs(cand_pz - p[rows, 2][:, None])
                dist = jnp.maximum(ddx, ddz)
                valid = ((cand_w != N) & (dist <= spec.radius)
                         & (cand_w != rows[:, None]))
                qd = jnp.minimum(
                    (dist * (1024.0 / spec.radius)).astype(jnp.int32),
                    1023)
                packed = jnp.where(valid, (qd << 21) | cand_w,
                                   jnp.int32(2**31 - 1))
                if stage == "key":
                    s = packed.sum().astype(jnp.float32)
                    return p + (s % 2) * 1e-7, s
                top = -lax.top_k(-packed, K)[0]
                if stage == "topk":
                    s = top.sum().astype(jnp.float32)
                    return p + (s % 2) * 1e-7, s
                ok = top < jnp.int32(2**31 - 1)
                nbr_b = jnp.sort(
                    jnp.where(ok, top & ((1 << 21) - 1), N), axis=1)
                s = nbr_b.sum().astype(jnp.float32)
                return p + (s % 2) * 1e-7, s
            pp, ss = lax.scan(body, p0, None, length=length)
            return ss.sum() + pp.sum()
        return run
    return make


for st in ("gather", "gather_take", "key", "topk", "all"):
    timeit(f"stage {st}", mk_stage(st))

# ---- 4. collect bisect ---------------------------------------------

from goworld_tpu.ops.delta import interest_pairs
from goworld_tpu.ops.sync import collect_attr_deltas, collect_sync

rngn = np.random.default_rng(0)
nbr0 = jnp.asarray(np.sort(
    rngn.integers(0, N + 1, (N, K)).astype(np.int32), axis=1))
has_client = jnp.asarray(rngn.random(N) < 0.01)
yaw = jnp.zeros(N)
hot = jnp.zeros((N, 8))
adirty = jnp.asarray((rngn.random(N) < 0.03).astype(np.uint32))
flk = jnp.asarray(rngn.integers(0, 4, (N, K)).astype(np.int32))
CAP = 65536


def mk_pairs(length):
    def run(_p):
        def body(carry, _):
            prev_dirty = carry
            prev = jnp.where(prev_dirty[:, None],
                             jnp.roll(nbr0, 1, axis=0), nbr0)
            ew, ej, en, lw, lj, ln, drn = interest_pairs(
                prev, nbr0, N, CAP, CAP, CAP)
            return jnp.roll(prev_dirty, 1), en + ln + drn + ew.sum()
        c, s = lax.scan(body, (jnp.arange(N) % 16) == 0, None,
                        length=length)
        return s.sum().astype(jnp.float32)
    return run


def mk_sync(length):
    def run(_p):
        def body(carry, _):
            dirty = carry
            sw, sj, sv, sn = collect_sync(
                nbr0, dirty, has_client, pos, yaw, CAP,
                nbr_dirty=(flk & 1).astype(bool) & dirty[:, None])
            return jnp.roll(dirty, 3), sn + sw.sum() + sv.sum()
        c, s = lax.scan(body, jnp.ones(N, bool), None, length=length)
        return s.sum().astype(jnp.float32)
    return run


def mk_attrs(length):
    def run(_p):
        def body(carry, _):
            ad = carry
            ae, ai, av, an = collect_attr_deltas(hot, ad, 4096)
            return jnp.roll(ad, 1), an + ae.sum() + av.sum()
        c, s = lax.scan(body, adirty, None, length=length)
        return s.sum().astype(jnp.float32)
    return run


timeit("collect interest_pairs", mk_pairs)
timeit("collect sync", mk_sync)
timeit("collect attrs", mk_attrs)

# ---- 5. move bisect -------------------------------------------------

from goworld_tpu.models.random_walk import random_walk_step
from goworld_tpu.ops.integrate import apply_pos_inputs, integrate

in_idx = jnp.asarray(rngn.integers(0, N, 4096).astype(np.int32))
in_vals = jnp.asarray(rngn.random((4096, 4)).astype(np.float32))
npc_moving = jnp.ones(N, bool)


def mk_move(stage):
    def make(length):
        def run(p0):
            def body(carry, _):
                p, rng = carry
                if stage in ("inputs", "all"):
                    p2, yw, touched = apply_pos_inputs(
                        p, yaw, in_idx, in_vals,
                        jnp.asarray(4096, jnp.int32))
                else:
                    p2 = p
                if stage in ("walk", "all"):
                    rng, kk = jax.random.split(rng)
                    vel = random_walk_step(kk, jnp.zeros((N, 3)),
                                           npc_moving, 5.0, 0.1)
                else:
                    vel = jnp.ones((N, 3)) * 0.01
                if stage in ("integrate", "all"):
                    p3, moved = integrate(p2, vel, npc_moving, 1 / 30,
                                          jnp.zeros(3),
                                          jnp.full(3, extent))
                else:
                    p3 = p2 + vel * 1e-6
                return (p3, rng), p3.sum()
            c, s = lax.scan(body, (p0, jax.random.PRNGKey(9)), None,
                            length=length)
            return s.sum() + c[0].sum()
        return run
    return make


for st in ("inputs", "walk", "integrate", "all"):
    timeit(f"move {st}", mk_move(st))

# rbg vs threefry for the walk stage (jax_default_prng_impl is read at
# PRNGKey creation, so flipping it mid-process A/Bs cleanly; "rbg"
# rides the TPU hardware RNG instead of ~20 threefry rounds per draw)
try:
    jax.config.update("jax_default_prng_impl", "rbg")
    timeit("move walk (rbg)", mk_move("walk"))
finally:
    jax.config.update("jax_default_prng_impl", "threefry2x32")

print("AB done", flush=True)
