#!/usr/bin/env python
"""Chaos soak: run a standalone gate->dispatcher->game cluster under a
seeded fault schedule and report convergence + the deterministic fault
log.

One invocation = one full chaos scenario against a throwaway server
dir. Two scenarios share the harness (``--scenario``):

``kill`` (default):

1. build a 1-dispatcher/1-game/1-gate cluster (persistent Vault entity,
   1 s crash-recovery checkpoints, gate /faults endpoint),
2. start it with ``GOWORLD_FAULTS`` armed (wire faults on the
   gate->dispatcher edge + a deterministic ``crash:game.tick@n=...``
   game kill),
3. drive deposits through a bot, wait for a post-deposit checkpoint,
4. let the kill fire, supervise the cluster back to health
   (``cli.cmd_supervise`` machinery), audit the Vault from a fresh
   client,
5. scrape the gate's ``/faults`` log and write a JSON report.

``overload`` (ISSUE 4): flood the cluster with slow RPCs + position
spam at ``--msg-rate`` msg/s for ``--flood-secs`` while seeded delay
faults are active, then scrape the game's ``/overload`` ladder and the
``shed_total`` counters; ``converged`` means the ladder ENGAGED
(reached SHEDDING), the critical/rpc classes shed nothing, and the
process RETURNED to NORMAL after the flood stopped.

``governor`` (ISSUE 13), ``audit`` (ISSUE 17) and ``failover``
(ISSUE 18) run IN-PROCESS (no cluster): the governor soak hot-swaps
kernel configs under a scenario-switching schedule; the audit soak
proves the correctness plane — a clean churn + migration-storm phase
must record ZERO violations, then an injected entity drop
(migrate-out, restore suppressed) must be detected by the
conservation verdict within <= 8 ticks, naming the EntityID and
freezing an ``audit_violation`` flight-recorder bundle
(``run_audit``); the failover soak streams a primary under
churn-and-migration into a hot standby, kills the primary at a
deterministic tick, promotes through the kvreg-arbitrated protocol
(both stale-claim race orders replayed and refused, decision log
byte-replayable), proves ZERO lost/duplicated EntityIDs by census +
conservation verdict, and times the warm promotion against a cold
chain restore of the same crash (must be >= 10x faster —
``run_failover``).

``rebalance`` (ISSUE 19) also runs IN-PROCESS: a donor world under
sustained-DEGRADED load and an underloaded receiver are watched by the
real :class:`RebalancePolicy` + :class:`HandoffExecutor` stack; one
run proves BOTH variants — the clean handoff (fires after
``hold_windows`` sustained windows, rate-limited cohort moves through
the production migration hooks, donor recovers to NORMAL within the
report's window budget, zero entities lost or duplicated, the
deployment conservation verdict green EVERY window including
mid-batch, the decision log byte-replayable) and the target-kill abort
(the receiver dies mid-handoff with a batch in flight; the timeout
abort must restore every unacked entity LIVE on the source and the
census must account for every original EntityID) — ``run_rebalance``.

Running either scenario TWICE with the same ``--seed`` must produce
byte-identical fault/transition behavior — the seeded-replay guarantee
(tests/test_chaos.py::test_chaos_soak_same_seed_replays_identical_log
automates the kill double run behind ``-m slow``;
tests/test_overload.py covers the overload scenario).

Usage::

    python tools/chaos_soak.py --dir /tmp/chaos --seed 77 \
        --deposits 25 --out chaos_report.json
    python tools/chaos_soak.py --scenario overload --dir /tmp/ov \
        --seed 77 --flood-secs 6 --msg-rate 120 --out ov_report.json
    python tools/chaos_soak.py --dir /tmp/chaos --seed 77 \
        --workload teleport   # faults under adversarial NPC motion
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import socket
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from goworld_tpu.net import proto  # noqa: E402 (after sys.path insert)

SERVER_PY = '''\
import goworld_tpu as gw

VAULT_EID = "Vault00000000001"


@gw.register_entity("Vault")
class Vault(gw.Entity):
    ATTRS = {"gold": "persistent"}


@gw.register_entity("Account")
class Account(gw.Entity):
    ATTRS = {"status": "client", "audit": "client"}

    def OnClientConnected(self):
        self.attrs["status"] = "online"

    def Deposit_Client(self, amount):
        v = gw.get_entity(VAULT_EID)
        if v is None:
            v = gw.create_entity("Vault", eid=VAULT_EID)
        v.attrs["gold"] = v.attrs.get("gold", 0) + amount
        v.save()
        self.attrs["audit"] = v.attrs["gold"]

    def Audit_Client(self):
        v = gw.get_entity(VAULT_EID)
        self.attrs["audit"] = -1 if v is None else v.attrs.get("gold", 0)

    def Stress_Client(self, ms):
        # overload scenario: a deliberately slow handler — the flood's
        # tick-budget hog (never shed: RPCs are a protected class)
        import time as _t
        _t.sleep(ms / 1000.0)


if __name__ == "__main__":
    gw.run()
'''

RPC_MT = proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT
KILL_TICK = 900   # ~15 s of serve loop at 60 Hz: past the deposit
                  # phase, deterministic regardless of boot-compile time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def build_server_dir(path: str,
                     overload_knobs: bool = False,
                     workload: str = "") -> tuple[str, int, int]:
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "server.py"), "w") as f:
        f.write(SERVER_PY)
    dport, gport, hport = _free_port(), _free_port(), _free_port()
    ghport = _free_port()  # game debug-http (/overload scrapes)
    extra = ""
    if workload:
        # --workload <scenario>: the game tick runs the adversarial
        # behavior mix (goworld_tpu/scenarios registry) instead of the
        # homogeneous random_walk, so faults/overload land under
        # adversarial motion (ISSUE 7). Validated jax-free up front —
        # a typo must not surface as a mid-soak game crash.
        from goworld_tpu.scenarios.spec import get_scenario

        get_scenario(workload)  # KeyError lists the registry
        extra += f"scenario = {workload}\n"
    if overload_knobs:
        # aggressive ladder so a short flood engages it, a fast
        # descent so the report's recovery wait stays bounded, and a
        # 10 Hz tick budget a loaded CI box can actually hold when
        # idle (the governor judges wall time against 1/tick_hz — on a
        # budget the host can never meet, NORMAL is unreachable)
        extra += ("tick_hz = 10\n"
                  "overload_up_ticks = 3\noverload_down_ticks = 30\n"
                  "degraded_sync_stride = 2\n")
    with open(os.path.join(path, "goworld_tpu.ini"), "w") as f:
        f.write(
            f"[dispatcher1]\nhost = 127.0.0.1\nport = {dport}\n"
            "[game_common]\nboot_entity = Account\ncapacity = 256\n"
            "n_spaces = 1\ncheckpoint_interval = 1\n"
            f"http_port = {ghport}\n{extra}"
            "[game1]\n"
            f"[gate1]\nhost = 127.0.0.1\nport = {gport}\n"
            f"http_port = {hport}\n"
            "[storage]\nkind = filesystem\ndirectory = entity_storage\n"
            "[kvdb]\nkind = memory\n"
        )
    return path, gport, hport


def spec_for(kill_tick: int = KILL_TICK) -> str:
    return (
        f"drop:gate->dispatcher:mt={RPC_MT}:0.25,"
        f"dup:gate->dispatcher:mt={RPC_MT}:0.25,"
        f"delay:gate->dispatcher:mt={RPC_MT}:0.5:5ms,"
        f"crash:game.tick@n={kill_tick}"
    )


async def _session(gport: int, actions):
    from goworld_tpu.net.botclient import BotClient

    bot = BotClient("127.0.0.1", gport)
    await bot.connect()
    recv = asyncio.ensure_future(bot._recv_loop())
    try:
        await asyncio.wait_for(bot.player_ready.wait(), 90)
        for _ in range(200):
            if bot.player.attrs.get("status") == "online":
                break
            await asyncio.sleep(0.05)
        return await actions(bot)
    finally:
        recv.cancel()
        await bot.conn.close()


def run_soak(server_dir: str, seed: int, deposits: int,
             kill_tick: int = KILL_TICK) -> dict:
    from goworld_tpu import cli
    from goworld_tpu.utils import faults as faults_mod

    spec = spec_for(kill_tick)
    report: dict = {"seed": seed, "spec": spec, "converged": False}
    os.environ["GOWORLD_FAULTS"] = spec
    os.environ["GOWORLD_FAULTS_SEED"] = str(seed)
    stop = threading.Event()
    sup = None
    try:
        if cli.cmd_start(server_dir) != 0:
            report["error"] = "initial start failed"
            return report
        os.environ.pop("GOWORLD_FAULTS")
        os.environ.pop("GOWORLD_FAULTS_SEED")
        _, gport, hport = (
            server_dir,
            _ini_port(server_dir, "gate1", "port"),
            _ini_port(server_dir, "gate1", "http_port"),
        )
        game_pid = cli._read_pid(server_dir, "game", 1)

        async def deposit(bot):
            for _ in range(deposits):
                bot.call_server("Deposit_Client", 1)
                await asyncio.sleep(0.02)
            deadline = time.time() + 20
            while time.time() < deadline:
                a = bot.player.attrs.get("audit")
                if a is not None:
                    await asyncio.sleep(1.0)
                    return bot.player.attrs.get("audit")
                await asyncio.sleep(0.1)
            return None

        gold = asyncio.run(asyncio.wait_for(_session(gport, deposit),
                                            180))
        t_gold = time.time()
        report["gold"] = gold
        if not gold:
            report["error"] = "no deposit survived"
            return report

        # poll until every deposit passed the gate's decision point
        # (ordered client stream: the first rule's trial count grows to
        # exactly the RPC count)
        def _scrape():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{hport}/faults", timeout=5
            ) as r:
                return json.loads(r.read())

        snap = _scrape()
        deadline = time.time() + 30
        while time.time() < deadline \
                and snap["rules"][0]["trials"] < deposits:
            time.sleep(0.2)
            snap = _scrape()
        report["fault_log"] = snap["log"]
        report["injected_total"] = snap["injected_total"]
        # sanity: the live log IS the seeded pure function
        expected = faults_mod.FaultPlane(
            faults_mod.parse_schedule(spec), seed)
        for _ in range(deposits):
            expected.wire_fault("gate->dispatcher", RPC_MT)
        report["replay_matches"] = snap["log"] == expected.log_lines()

        ckpt = os.path.join(server_dir, "game1_checkpoint.dat")
        deadline = time.time() + 60
        while time.time() < deadline and (
            not os.path.exists(ckpt)
            or os.path.getmtime(ckpt) < t_gold + 0.5
        ):
            time.sleep(0.2)

        deadline = time.time() + 120
        while time.time() < deadline and cli._alive(game_pid):
            time.sleep(0.2)
        if cli._alive(game_pid):
            report["error"] = "kill never fired"
            return report
        report["killed"] = True

        sup = threading.Thread(
            target=cli.cmd_supervise, args=(server_dir,),
            kwargs=dict(interval=0.5, stop=stop), daemon=True,
        )
        sup.start()
        deadline = time.time() + 240
        while time.time() < deadline:
            pid = cli._read_pid(server_dir, "game", 1)
            if pid != game_pid and cli._alive(pid):
                break
            time.sleep(0.3)
        else:
            report["error"] = "supervisor never recovered the game"
            return report
        report["restarted"] = True

        async def audit(bot):
            bot.call_server("Audit_Client")
            deadline = time.time() + 30
            while time.time() < deadline:
                a = bot.player.attrs.get("audit")
                if a is not None:
                    return a
                await asyncio.sleep(0.1)
            return None

        seen = asyncio.run(asyncio.wait_for(_session(gport, audit), 240))
        report["audited"] = seen
        report["converged"] = bool(
            seen == gold and report.get("replay_matches")
        )
        return report
    finally:
        stop.set()
        if sup is not None:
            sup.join(timeout=60)
        from goworld_tpu import cli as _cli

        _cli.cmd_stop(server_dir)


OVERLOAD_STRESS_MS = 30   # per-RPC handler sleep: ~12 per 100 ms tick
                          # (tick_hz = 10) at 120 msg/s -> tick latency
                          # ratio ~3.6, severely pressured while the
                          # flood lasts, drainable within seconds after


def overload_spec() -> str:
    return "delay:gate->dispatcher:0.5:5ms"


def run_overload(server_dir: str, seed: int, flood_secs: float,
                 msg_rate: float) -> dict:
    """The ISSUE-4 overload scenario: bot flood + delay faults, then
    judge the ladder from /overload and the shed counters from
    /metrics. Same report shape as the kill scenario (seed / spec /
    converged + scenario fields)."""
    from goworld_tpu import cli
    from goworld_tpu.utils import metrics as metrics_mod

    spec = overload_spec()
    report: dict = {"scenario": "overload", "seed": seed, "spec": spec,
                    "flood_secs": flood_secs, "msg_rate": msg_rate,
                    "converged": False}
    os.environ["GOWORLD_FAULTS"] = spec
    os.environ["GOWORLD_FAULTS_SEED"] = str(seed)
    try:
        if cli.cmd_start(server_dir) != 0:
            report["error"] = "initial start failed"
            return report
        os.environ.pop("GOWORLD_FAULTS")
        os.environ.pop("GOWORLD_FAULTS_SEED")
        gport = _ini_port(server_dir, "gate1", "port")
        game_hport = _ini_port(server_dir, "game_common", "http_port")

        def _scrape(path: str, port: int):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ) as r:
                return r.read()

        def _game_gov() -> dict | None:
            try:
                snap = json.loads(_scrape("/overload", game_hport))
            except OSError:
                return None
            for n, g in snap.get("governors", {}).items():
                if n.startswith("game"):
                    g["_shed"] = snap.get("shed", {})
                    return g
            return None

        def _wait_state(want: str, secs: float) -> dict | None:
            deadline = time.monotonic() + secs
            gov = None
            while time.monotonic() < deadline:
                gov = _game_gov()
                if gov is not None and gov["state"] == want:
                    return gov
                time.sleep(0.5)
            return gov

        # phase 0: warm the compile paths (boot + the FIRST position
        # sync batch each re-jit the step; on a CI box that is a
        # multi-second mega-tick that would swallow the whole flood
        # window), then let the spike decay — engagement must be
        # judged against a calm baseline, not startup transients
        async def warmup(bot):
            for i in range(10):
                bot.send_position(float(i), 0.0, 1.0, 0.0)
                await asyncio.sleep(0.05)
            bot.call_server("Stress_Client", 1)
            await asyncio.sleep(1.0)
            return True

        asyncio.run(asyncio.wait_for(_session(gport, warmup), 180))
        gov = _wait_state("NORMAL", 120)
        if gov is None or gov["state"] != "NORMAL":
            report["error"] = "never settled to NORMAL after boot"
            report["transitions"] = (gov or {}).get("transitions")
            return report
        n0 = len(gov["transitions"])

        async def flood(bot):
            interval = 1.0 / max(1.0, msg_rate)
            end = time.monotonic() + flood_secs
            sent = 0
            while time.monotonic() < end:
                bot.call_server("Stress_Client", OVERLOAD_STRESS_MS)
                bot.send_position(float(sent % 9), 0.0,
                                  float(sent % 7), 0.0)
                sent += 1
                await asyncio.sleep(interval)
            return sent

        report["sent"] = asyncio.run(
            asyncio.wait_for(_session(gport, flood), flood_secs + 180)
        )

        # recovery: the ladder must walk back to NORMAL after the flood
        gov = _wait_state("NORMAL", 120)
        state = None if gov is None else gov["state"]
        flood_transitions = (gov or {}).get("transitions", [])[n0:]
        report["final_state"] = state
        report["transitions"] = flood_transitions
        report["shed"] = (gov or {}).get("_shed", {})
        report["engaged"] = any(
            "->SHEDDING" in t for t in flood_transitions
        )
        report["returned_normal"] = state == "NORMAL"
        report["cheap_shed"] = sum(
            v for k, v in report["shed"].items()
            if not (k.startswith("critical/") or k.startswith("rpc/"))
        )

        # zero sheds in the protected classes, cluster-wide (game /
        # gate /metrics both carry shed_total)
        critical_shed = 0.0
        for port in (game_hport,
                     _ini_port(server_dir, "gate1", "http_port")):
            try:
                series = metrics_mod.parse_prometheus_text(
                    _scrape("/metrics", port).decode())
            except OSError:
                continue
            for name, val in series.items():
                if name.startswith("shed_total") and (
                    'class="critical"' in name or 'class="rpc"' in name
                ):
                    critical_shed += val
        report["critical_shed"] = critical_shed
        report["converged"] = bool(
            report["engaged"] and report["returned_normal"]
            and critical_shed == 0 and report["cheap_shed"] > 0
        )
        return report
    finally:
        from goworld_tpu import cli as _cli

        _cli.cmd_stop(server_dir)


# governor soak knobs: boosted teleport churn so the skinless event
# proxy reads "moderate" at soak scale (the registry default's handful
# of jumps/tick is indistinguishable from flock at n~100)
GOV_SOAK_N = 96
GOV_SOAK_WINDOW = 16
GOV_SOAK_WINDOWS = 4


def run_governor(seed: int, phases: tuple = ("flock", "teleport",
                                             "flock", "teleport"),
                 n: int = GOV_SOAK_N,
                 window: int = GOV_SOAK_WINDOW,
                 windows_per_phase: int = GOV_SOAK_WINDOWS) -> dict:
    """The ISSUE-13 governor scenario: ONE live in-process World driven
    through a scenario-switching schedule while the autotune policy
    hot-swaps its kernel config from the real drained signature
    windows. In-process (no cluster) because the assertions need
    direct World access: ``check_oracle`` exactness (interest sets +
    client mirrors, both overflow gauges zero) after EVERY swap and on
    a cadence, zero entity loss across the whole run, >= 3 live swaps,
    and a deterministic decision log (the recorded signature stream
    replayed through a fresh policy must reproduce it byte-identically
    — the seeded-replay guarantee of the kill/overload scenarios)."""
    import dataclasses

    from goworld_tpu.autotune import GovernorPolicy, WarmSet, seed_table
    from goworld_tpu.scenarios.spec import get_scenario
    from goworld_tpu.scenarios.runner import build_world, check_oracle

    _specs: dict = {}

    def spec_of(name: str):
        if name not in _specs:
            if name == "teleport":
                # boosted jump rate: the event-volume churn proxy must
                # read moderate/heavy even at soak n (see module knob)
                _specs[name] = dataclasses.replace(
                    get_scenario("teleport"), name="teleport_soak",
                    teleport_prob=0.2)
            else:
                _specs[name] = get_scenario(name)
        return _specs[name]

    report: dict = {"scenario": "governor", "seed": seed,
                    "phases": list(phases), "n": n,
                    "window_ticks": window,
                    "windows_per_phase": windows_per_phase,
                    "converged": False}
    w, ents, clients = build_world(
        spec_of(phases[0]), n=n, skin=4.0, client_frac=0.15, seed=seed)
    w.SIG_WINDOW_TICKS = window  # one signature window per decision
    eids0 = set(w.entities)
    boot_cfg = w.cfg
    policy = GovernorPolicy(table=seed_table(), up_windows=1,
                            down_windows=1, cooldown_windows=0)
    label = "default"
    warmsets: dict = {}
    sig_stream: list = []
    swaps: list = []
    oracle_checks = 0
    mismatches: list = []

    def warm(spec, lbl: str):
        ws = warmsets.get(spec.name)
        if ws is None:
            base = dataclasses.replace(boot_cfg, scenario=spec)
            ws = warmsets[spec.name] = WarmSet(
                base, 1, w.policy, telemetry=w.telemetry_live)
        ws.ensure(lbl, block=True)
        e = ws.entry(lbl)
        if e is None or not e.warm:
            raise RuntimeError(
                f"candidate {lbl} failed to warm: "
                f"{getattr(e, 'error', 'missing')}")
        return e

    def commit(e) -> None:
        w.apply_tick_config(
            e.cfg, e.exe, telem_fold=e.fold_exe, telem_acc0=e.acc0,
            telem_skin_on=e.skin_on, telem_half_skin=e.half_skin)

    try:
        for nm in phases:
            spec = spec_of(nm)
            if w.cfg.scenario is not spec:
                # the WORKLOAD switch (production analog: the
                # population's behavior turns) — same swap machinery,
                # same kernel label, new scenario trace
                commit(warm(spec, label))
            for _w in range(windows_per_phase):
                for _t in range(window):
                    w.tick()
                # judge COMPLETED rotation windows like the production
                # _drive_governor (window_signature); the running
                # delta can cover ~0 ticks right after a rotation or a
                # swap's window reset and would misclassify. Fall back
                # to the running delta only before the first rotation.
                sig = w.window_signature() or w.workload_signature()
                sig_stream.append(sig)
                want = policy.observe(sig)
                if want is not None and want != label:
                    commit(warm(spec, want))
                    swaps.append({
                        "phase": nm, "window": policy.window,
                        "from": label, "to": want,
                        "sig": (sig or {}).get("sig"),
                    })
                    label = want
                    # the acceptance tick: a swap mid-churn must keep
                    # the full interest contract exact IMMEDIATELY
                    w.tick()
                    bad = check_oracle(w, clients)
                    oracle_checks += 1
                    mismatches.extend(
                        f"post-swap {label}: {m}" for m in bad[:8])
            bad = check_oracle(w, clients)
            oracle_checks += 1
            mismatches.extend(f"phase {nm}: {m}" for m in bad[:8])
    except Exception as exc:
        report["error"] = f"{type(exc).__name__}: {str(exc)[:300]}"
        return report

    report["swaps"] = swaps
    report["decision_log"] = policy.log_lines()
    report["oracle_ticks_checked"] = oracle_checks
    report["mismatches"] = mismatches[:16]
    report["entities_before"] = len(eids0)
    report["entities_after"] = len(
        [e for e in w.entities.values() if not e.destroyed])
    report["entity_ids_stable"] = set(w.entities) == eids0
    # determinism: the recorded signature stream through a FRESH
    # policy reproduces the decision log byte-identically
    replay = GovernorPolicy(table=seed_table(), up_windows=1,
                            down_windows=1, cooldown_windows=0)
    for sig in sig_stream:
        replay.observe(sig)
    report["replay_matches"] = (replay.log_lines()
                                == report["decision_log"])
    report["converged"] = bool(
        len(swaps) >= 3
        and not mismatches
        and report["entity_ids_stable"]
        and report["replay_matches"]
    )
    return report


# audit soak knobs: clean-churn length, migration-storm cadence, and
# the verdict's in-flight grace — 6 ticks so the injected drop is
# judged lost at age 7, inside the <= 8-tick detection criterion
AUDIT_SOAK_N = 96
AUDIT_SOAK_CLEAN_TICKS = 48
AUDIT_SOAK_GRACE = 6


def run_audit(seed: int, n: int = AUDIT_SOAK_N,
              clean_ticks: int = AUDIT_SOAK_CLEAN_TICKS,
              grace_ticks: int = AUDIT_SOAK_GRACE) -> dict:
    """The ISSUE-17 audit scenario, in-process like the governor soak
    (the assertions need direct World + ledger access). Two phases:

    1. CLEAN soak: a live world with the audit plane sampling the AOI
       oracle EVERY tick, under create/destroy churn plus a
       migration storm (full out->in round-trips through the real
       ``get_migrate_data``/``remove_for_migration``/
       ``restore_from_migration`` protocol). Must end with ZERO
       violations of any kind, zero oracle mismatches and a passing
       conservation verdict — the plane must not cry wolf.
    2. INJECTED drop: one more migrate-out whose restore is
       deliberately suppressed (the lost-update every migration bug
       taxonomy fears). The conservation verdict must name the
       dropped EntityID within <= 8 ticks, and routing the finding
       back through the ledger's violation path must freeze an
       ``audit_violation`` flight-recorder bundle carrying the ledger
       tail.

    ``converged`` = both phases held. Same-seed reruns replay the same
    world evolution (the seeded-replay guarantee)."""
    from goworld_tpu.scenarios.runner import build_world
    from goworld_tpu.scenarios.spec import get_scenario
    from goworld_tpu.utils import audit as audit_mod
    from goworld_tpu.utils import flightrec

    report: dict = {"scenario": "audit", "seed": seed, "n": n,
                    "clean_ticks": clean_ticks,
                    "grace_ticks": grace_ticks, "converged": False}
    w, ents, clients = build_world(
        get_scenario("mixed"), n=n, skin=4.0, client_frac=0.15,
        seed=seed)
    ap = w.audit
    if ap is None:
        report["error"] = "world built without an audit plane"
        return report
    ap.sample_every = 1  # soak-grade scrutiny: oracle every tick
    rec = flightrec.FlightRecorder(ring=64,
                                   context_fn=ap.incident_context)
    incidents: list = []

    def tick_and_record() -> None:
        w.tick()
        frame = {"tick": w.tick_count}
        av = ap.take_violation()
        if av is not None:
            frame["audit_violation"] = av
        incidents.extend(rec.record(frame))

    def verdict() -> dict:
        ap.drain()
        return audit_mod.conservation_verdict(
            [ap.snapshot(tick=w.tick_count)], grace_ticks=grace_ticks)

    try:
        # ---- phase 1: clean churn + migration storm ------------------
        alive = [e for e in ents if not e.destroyed]
        storm = 0
        for t in range(clean_ticks):
            if t % 4 == 2 and alive:
                # one full migration round-trip through the real
                # protocol: out-record opened, in-record retires it
                e = alive[t % len(alive)]
                if not e.destroyed and e._migrating is None:
                    data = w.get_migrate_data(e)
                    w.remove_for_migration(e)
                    moved = w.restore_from_migration(data)
                    alive[t % len(alive)] = moved
                    storm += 1
            tick_and_record()
        clean = verdict()
        snap = ap.snapshot(tick=w.tick_count)
        report["migration_round_trips"] = storm
        report["oracle"] = snap["oracle"]
        report["violations_total"] = snap["violations_total"]
        report["clean_verdict"] = {
            k: clean.get(k) for k in ("ok", "live", "in_flight",
                                      "created", "destroyed",
                                      "problems")
        }
        clean_ok = (
            clean.get("ok") is True
            and not any(snap["violations_total"].values())
            and snap["oracle"]["mismatches"] == 0
            and snap["oracle"]["samples"] > 0
            and not incidents
        )
        report["clean_ok"] = clean_ok

        # ---- phase 2: injected entity drop ---------------------------
        victim = next(e for e in alive
                      if not e.destroyed and e._migrating is None)
        report["dropped_eid"] = victim.id
        w.get_migrate_data(victim)        # stamps the outgoing seq
        w.remove_for_migration(victim)    # ... and the restore never
        drop_tick = w.tick_count          # happens: the entity is lost
        detected_at = None
        problem = ""
        for _ in range(grace_ticks + 4):
            tick_and_record()
            v = verdict()
            named = [p for p in v.get("problems", [])
                     if victim.id in p]
            if not v.get("ok") and named:
                detected_at = w.tick_count - drop_tick
                problem = named[0]
                break
        report["detected_after_ticks"] = detected_at
        report["problem"] = problem
        detect_ok = detected_at is not None and detected_at <= 8
        report["detect_ok"] = detect_ok

        # the finding routes back through the ledger's violation path
        # (the aggregator's role in production): counter bumped, tail
        # annotated, and the flightrec trigger freezes the bundle
        bundle_ok = False
        if detect_ok:
            ap.ledger.note_violation("lost_entity", problem,
                                     w.tick_count)
            tick_and_record()
            frozen = [i for i in incidents
                      if i.get("trigger") == "audit_violation"]
            bundle_ok = bool(
                frozen and victim.id in frozen[-1].get("detail", "")
                and "tail" in (frozen[-1].get("context") or {}))
            report["incident"] = {
                "trigger": frozen[-1]["trigger"],
                "detail": frozen[-1]["detail"],
                "tick": frozen[-1]["tick"],
            } if frozen else None
        report["bundle_ok"] = bundle_ok
        report["converged"] = bool(clean_ok and detect_ok and bundle_ok)
        return report
    except Exception as exc:
        report["error"] = f"{type(exc).__name__}: {str(exc)[:300]}"
        return report
    finally:
        audit_mod.unregister(f"game{w.game_id}")


FAILOVER_SOAK_N = 96
FAILOVER_SOAK_TICKS = 40
FAILOVER_KEYFRAME_EVERY = 8


def _mirror_world(spec, cfg, game_id: int, seed: int):
    """A bare world sharing the primary's type registry (the shape a
    standby process boots with: classes registered, no population)."""
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space

    _INF = float("inf")
    w = World(cfg, n_spaces=1, seed=seed, game_id=game_id)
    w.register_space("ScnSpace", type("ScnSpace", (Space,), {}))
    for i, (r, _f) in enumerate(spec.radius_mix):
        tname = f"Scn{i}"
        w.register_entity(
            tname, type(tname, (Entity,), {}),
            aoi_distance=0.0 if r == _INF else float(r))
    return w


def _census(w) -> set:
    """Live EntityIDs minus the world's OWN nil space (each game's nil
    space id is deterministic from ITS game_id and never replicated)."""
    out = {e.id for e in w.entities.values() if not e.destroyed}
    if w.nil_space is not None:
        out.discard(w.nil_space.id)
    return out


def run_failover(seed: int, n: int = FAILOVER_SOAK_N,
                 ticks: int = FAILOVER_SOAK_TICKS,
                 keyframe_every: int = FAILOVER_KEYFRAME_EVERY) -> dict:
    """The ISSUE-18 failover scenario, in-process like the audit soak
    (the conservation assertions need direct World + ledger access on
    BOTH sides). One run proves the whole hot-standby story:

    1. STREAM: a primary world under churn + a migration storm
       replicates through the real path — ``SnapshotChain.capture`` on
       the tick thread, the bounded :class:`ReplicationWorker` building
       key/delta records off-thread (disk chain riding the same jobs),
       ``StreamEncoder`` framing, ``StandbyApplier`` reconciling every
       frame into a live standby world with per-frame ledger resync.
    2. KILL: the primary dies at a deterministic tick (mid-churn,
       mid-migration — the worst case).
    3. PROMOTE: the standby claims through the kvreg-arbitrated
       protocol (first-writer-wins + epoch guard, emulated with the
       dispatcher's exact register semantics), wins, resumes ticking
       from its last applied frame. Both stale-claim race orders are
       replayed against the arbitration and must be refused, and the
       decision log must replay byte-for-byte
       (:func:`goworld_tpu.replication.promote.replay_decisions`).
    4. VERDICT: the promoted census must equal the primary's census at
       the last applied frame — zero lost, zero duplicated EntityIDs —
       and the standby's own conservation verdict must pass.
    5. A/B: the same crash recovered COLD (fresh World + chain restore
       from the disk records the worker wrote) is timed against the
       warm promotion; the paper's claim is >= 10x. The cold time is a
       LOWER bound (a real cold restore also pays process boot).

    Same-seed reruns replay the same world evolution and the same
    decision log (the seeded-replay guarantee)."""
    from goworld_tpu import freeze as freeze_mod
    from goworld_tpu.replication.promote import (
        DecisionLog, adjudicate, claim_key, claim_value,
        replay_decisions)
    from goworld_tpu.replication.standby import (
        StandbyApplier, StandbyTracker)
    from goworld_tpu.replication.worker import ReplicationWorker
    from goworld_tpu.scenarios.runner import build_world
    from goworld_tpu.scenarios.spec import get_scenario
    from goworld_tpu.utils import audit as audit_mod

    import tempfile

    report: dict = {"scenario": "failover", "seed": seed, "n": n,
                    "ticks": ticks, "keyframe_every": keyframe_every,
                    "converged": False}
    spec = get_scenario("mixed")
    kill_tick = ticks  # deterministic: the last streamed tick
    tmpdir = tempfile.mkdtemp(prefix="failover_soak_")
    primary, ents, _clients = build_world(
        spec, n=n, skin=4.0, client_frac=0.15, seed=seed)
    standby = _mirror_world(spec, primary.cfg, game_id=2, seed=seed)
    # the standby's attach-time warmup (net/game.py _standby_tick):
    # compile the jit'd tick program on the still-empty world — SoA
    # shapes are capacity-static, so this is the same program the
    # promoted tick runs; without it the "warm" promotion would pay
    # seconds of compile, the exact cost hot standby exists to avoid
    standby.tick()
    standby.tick_count = 0
    tracker = StandbyTracker(2, primary.game_id, tick_hz=60.0)
    applier = StandbyApplier(standby, primary.game_id,
                             tracker=tracker)
    frames: list = []
    lock = threading.Lock()

    def send_fn(blob: bytes, kind: str, tick: int) -> None:
        with lock:
            frames.append((blob, kind, tick))

    chain = freeze_mod.SnapshotChain(primary, tmpdir,
                                     keyframe_every=keyframe_every)
    worker = ReplicationWorker(chain, game_id=primary.game_id,
                               queue_max=4, send_fn=send_fn)
    census_by_tick: dict[int, set] = {}
    try:
        # ---- phase 1: stream under churn + migration storm -----------
        alive = [e for e in ents if not e.destroyed]
        storm = 0
        applied = rejected = 0
        bytes_stream = 0
        apply_ms = 0.0
        for t in range(ticks):
            if t % 4 == 2 and alive:
                e = alive[t % len(alive)]
                if not e.destroyed and e._migrating is None:
                    data = primary.get_migrate_data(e)
                    primary.remove_for_migration(e)
                    moved = primary.restore_from_migration(data)
                    alive[t % len(alive)] = moved
                    storm += 1
            primary.tick()
            census_by_tick[primary.tick_count] = _census(primary)
            worker.submit(chain.capture(), to_disk=True,
                          to_stream=True)
            worker.drain()  # deterministic soak: no backlog drops
            with lock:
                batch, frames[:] = frames[:], []
            for blob, _kind, _tick in batch:
                t0 = time.perf_counter()
                out = applier.apply(blob)
                apply_ms += (time.perf_counter() - t0) * 1e3
                bytes_stream += len(blob)
                if out["ok"]:
                    applied += 1
                else:
                    rejected += 1
        report["migration_round_trips"] = storm
        report["frames_applied"] = applied
        report["frames_rejected"] = rejected
        report["replication_bytes_per_tick"] = round(
            bytes_stream / max(1, ticks), 1)
        report["standby_apply_ms_per_tick"] = round(
            apply_ms / max(1, ticks), 3)
        report["worker"] = worker.stats()
        stream_ok = applied > 0 and rejected == 0
        report["stream_ok"] = stream_ok

        # ---- phase 2: deterministic kill + arbitrated promotion ------
        # the primary is dead from here on: nothing submits, nothing
        # streams. The standby promotes from its last APPLIED frame.
        applied_tick = applier.decoder.applied_tick
        applied_seq = applier.decoder.applied_seq
        report["kill_tick"] = kill_tick
        report["applied_tick_at_kill"] = applied_tick

        kvreg: dict[str, str] = {}

        def kv_register(key: str, val: str, force: bool = False) -> str:
            # the dispatcher's exact first-writer-wins semantics
            # (net/dispatcher.py _h_kvreg): a later non-force register
            # gets the existing value broadcast back
            if key not in kvreg or force:
                kvreg[key] = val
            return kvreg[key]

        key = claim_key(primary.game_id)
        epoch = 1
        mine = claim_value(2, epoch, applied_seq)
        log = DecisionLog()
        log.note("claim", key=key, value=mine, epoch=epoch,
                 applied_seq=applied_seq, applied_tick=applied_tick)
        t_warm0 = time.perf_counter()
        winner = kv_register(key, mine)
        verdict = adjudicate(winner, mine)
        log.note("adjudicate", winner=winner, mine=mine,
                 verdict=verdict)
        promote_ok = verdict == "won"
        standby.tick_count = max(standby.tick_count, applied_tick)
        log.note("promoted", epoch=epoch, tick=standby.tick_count,
                 seq=applied_seq, entities=len(_census(standby)))
        standby.tick()  # first served tick: staged mirror state
        warm_secs = time.perf_counter() - t_warm0  # flushes to device
        # promotion latency in TICKS: staleness at the kill (frames
        # behind the dead primary) + the one resume tick
        promotion_latency_ticks = (kill_tick - max(0, applied_tick)) + 1
        tracker.note_promoted(epoch, applied_tick)
        report["promotion_latency_ticks"] = promotion_latency_ticks
        report["promotion_secs"] = round(warm_secs, 4)
        report["promote_ok"] = promote_ok

        # both stale-claim race orders must be refused:
        # (a) stale-second — a zombie replays an OLD claim after the
        #     live winner registered: first-writer-wins broadcasts the
        #     live winner; the zombie adjudicates "lost"
        stale = claim_value(7, 0, 3)
        zl = DecisionLog()
        zl.note("claim", key=key, value=stale, epoch=0, applied_seq=3,
                applied_tick=-1)
        zw = kv_register(key, stale)
        zv = adjudicate(zw, stale)
        zl.note("adjudicate", winner=zw, mine=stale, verdict=zv)
        stale_second_refused = zv == "lost" and kvreg[key] == mine
        # (b) stale-first — the replay lands BEFORE the live claim on a
        #     fresh key: the live claimant sees a lower-epoch winner
        #     ("stale_winner"), force-re-registers (legitimate exactly
        #     then), and wins the next broadcast
        key2 = claim_key(99)
        kv_register(key2, claim_value(7, 0, 3))  # zombie lands first
        mine2 = claim_value(2, 1, applied_seq)
        fl = DecisionLog()
        w1 = kv_register(key2, mine2)
        v1 = adjudicate(w1, mine2)
        fl.note("adjudicate", winner=w1, mine=mine2, verdict=v1)
        stale_first_named = v1 == "stale_winner"
        w2 = kv_register(key2, mine2, force=True)
        v2 = adjudicate(w2, mine2)
        fl.note("force_reregister", winner=w2, mine=mine2, verdict=v2)
        stale_first_recovered = v2 == "won"
        arbitration_ok = bool(stale_second_refused and stale_first_named
                              and stale_first_recovered)
        report["arbitration"] = {
            "stale_second_refused": stale_second_refused,
            "stale_first_named": stale_first_named,
            "stale_first_recovered": stale_first_recovered,
        }
        report["arbitration_ok"] = arbitration_ok
        # the decision logs must replay byte-for-byte from their inputs
        replay_ok = all(
            replay_decisions(d.inputs) == d.dump()
            for d in (log, zl, fl))
        report["decision_log_replay_ok"] = replay_ok
        report["decision_log"] = log.lines

        # ---- phase 3: conservation verdict ---------------------------
        want = census_by_tick.get(applied_tick, set())
        got = _census(standby)
        lost = sorted(want - got)
        extra = sorted(got - want)
        report["entities_expected"] = len(want)
        report["entities_promoted"] = len(got)
        report["entities_lost"] = len(lost)
        report["entities_duplicated"] = len(extra)
        report["lost_eids"] = lost[:8]
        report["duplicated_eids"] = extra[:8]
        ap2 = standby.audit
        conservation_ok = False
        if ap2 is not None:
            ap2.drain()
            v = audit_mod.conservation_verdict(
                [ap2.snapshot(tick=standby.tick_count)])
            report["conservation_verdict"] = {
                k: v.get(k) for k in ("ok", "live", "in_flight",
                                      "created", "destroyed",
                                      "problems")}
            conservation_ok = v.get("ok") is True
        census_ok = not lost and not extra
        report["census_ok"] = census_ok
        report["conservation_ok"] = conservation_ok

        # ---- phase 4: cold-restore A/B -------------------------------
        # the SAME crash recovered the pre-standby way: fresh World,
        # chain records resolved from disk (the worker wrote them),
        # restore_world, first tick. A real cold restore ALSO pays
        # process boot + jit warmup, so this is a conservative floor.
        t_cold0 = time.perf_counter()
        snap_path = freeze_mod.latest_snapshot_path(
            primary.game_id, tmpdir)
        cold_ok = False
        if snap_path is not None:
            data = freeze_mod.read_freeze_file(snap_path)
            cold = _mirror_world(spec, primary.cfg, game_id=3,
                                 seed=seed)
            try:
                freeze_mod.restore_world(cold, data)
                cold.tick()
                cold_ok = True
            finally:
                audit_mod.unregister("game3")
        cold_secs = time.perf_counter() - t_cold0
        report["cold_restore_secs"] = round(cold_secs, 4)
        report["cold_restore_ok"] = cold_ok
        speedup = cold_secs / max(warm_secs, 1e-9)
        report["warm_vs_cold_speedup"] = round(speedup, 1)
        ab_ok = cold_ok and speedup >= 10.0
        report["ab_ok"] = ab_ok

        report["standby"] = tracker.snapshot()
        report["converged"] = bool(
            stream_ok and promote_ok and arbitration_ok and replay_ok
            and census_ok and conservation_ok and ab_ok)
        return report
    except Exception as exc:
        report["error"] = f"{type(exc).__name__}: {str(exc)[:300]}"
        return report
    finally:
        worker.close()
        audit_mod.unregister(f"game{primary.game_id}")
        audit_mod.unregister("game2")
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)


REBALANCE_SOAK_N = 96
REBALANCE_SOAK_BATCH = 24
REBALANCE_SOAK_WINDOWS = 20
REBALANCE_HOLD_WINDOWS = 3
REBALANCE_COOLDOWN_WINDOWS = 12
REBALANCE_TIMEOUT_WINDOWS = 4
# windows from the commit to the donor OBSERVING NORMAL again — the
# report's recovery budget (clean variant)
REBALANCE_RECOVERY_BUDGET = 6


def _run_rebalance_variant(seed: int, kill_target: bool,
                           n: int = REBALANCE_SOAK_N,
                           batch: int = REBALANCE_SOAK_BATCH,
                           windows: int = REBALANCE_SOAK_WINDOWS
                           ) -> dict:
    """One donor/receiver pair driven through the REAL rebalance stack
    (:class:`RebalancePolicy` + :class:`HandoffExecutor` +
    :class:`RebalanceController`): the donor world holds a
    sustained-DEGRADED occupancy proxy, the receiver is an underloaded
    mirror world, and the transport delivers each pump window's sends
    one window later (a one-window wire). ``kill_target=False`` proves
    the clean handoff; ``kill_target=True`` kills the receiver after
    the first delivered sub-batch — the remaining sends vanish into
    the dead target, the executor's idle-window timeout must abort,
    and every unacked entity must come back LIVE on the source with
    the deployment conservation verdict green the whole way."""
    from goworld_tpu.rebalance.controller import RebalanceController
    from goworld_tpu.rebalance.executor import HandoffExecutor
    from goworld_tpu.rebalance.policy import RebalancePolicy
    from goworld_tpu.scenarios.runner import build_world
    from goworld_tpu.scenarios.spec import get_scenario
    from goworld_tpu.utils import audit as audit_mod
    from goworld_tpu.utils import flightrec

    variant = "target_kill" if kill_target else "clean"
    rep: dict = {"variant": variant, "seed": seed, "n": n,
                 "batch": batch, "windows": windows,
                 "converged": False}
    spec = get_scenario("mixed")
    donor, _ents, _clients = build_world(
        spec, n=n, skin=4.0, client_frac=0.15, seed=seed)
    recv = _mirror_world(spec, donor.cfg, game_id=2, seed=seed)
    recv.create_nil_space()
    recv_space = recv.create_space("ScnSpace")
    recv.tick()  # jit warmup off the measured path
    recv.tick_count = 0
    try:
        dap, rap = donor.audit, recv.audit
        if dap is None or rap is None:
            rep["error"] = "world built without an audit plane"
            return rep
        original = _census(donor)
        recv_base = _census(recv)  # the receiver's own space entities
        c0 = len(original)
        # occupancy-proxy overload stage: DEGRADED while the census
        # holds at least (c0 - batch/2) entities, so a COMPLETED
        # handoff of `batch` flips the donor NORMAL and an aborted one
        # (half the cohort restored) does not — the stage is a pure
        # deterministic function of world state, seeded-replay safe
        hot_threshold = c0 - batch // 2
        rep["hot_threshold"] = hot_threshold

        def stage_of(w) -> str:
            return ("DEGRADED" if len(_census(w)) >= hot_threshold
                    else "NORMAL")

        policy = RebalancePolicy(
            hold_windows=REBALANCE_HOLD_WINDOWS, batch=batch,
            cooldown_windows=REBALANCE_COOLDOWN_WINDOWS)
        agent = HandoffExecutor(donor, game_id=donor.game_id,
                                batch=batch)
        donor_name = f"game{donor.game_id}"
        mailbox: list = []
        receiver_alive = True
        recv_dead_snap: dict | None = None
        dropped = delivered = 0

        def transport(action):
            # the committed action's send callable: one-window wire
            return lambda eid, data: mailbox.append((eid, data))

        ctl = RebalanceController(
            policy, agents={donor_name: agent}, transport=transport,
            rate=max(1, batch // 2),
            timeout_windows=REBALANCE_TIMEOUT_WINDOWS)

        def deliver() -> None:
            nonlocal dropped, delivered
            arriving, mailbox[:] = mailbox[:], []
            for eid, data in arriving:
                if not receiver_alive:
                    dropped += 1  # the dead target never acks
                    continue
                recv.restore_from_migration(data, space=recv_space)
                agent.ack(eid)
                delivered += 1

        def recv_snapshot() -> dict:
            # a dead game's planes stop answering; the aggregator (and
            # this verdict) judges from its LAST scrape
            if recv_dead_snap is not None:
                return recv_dead_snap
            rap.drain()
            return rap.snapshot(tick=recv.tick_count)

        def verdict() -> dict:
            dap.drain()
            return audit_mod.conservation_verdict(
                [dap.snapshot(tick=donor.tick_count), recv_snapshot()])

        rec = flightrec.FlightRecorder(
            ring=64, context_fn=dap.incident_context)
        incidents: list = []
        verdict_ok_all = True
        max_in_flight = 0
        commit_window = recovered_window = None
        for w_i in range(1, windows + 1):
            deliver()  # last window's sends arrive on the wire
            if kill_target and receiver_alive and delivered > 0:
                # the receiver dies with a sub-batch still queued on
                # the donor: the worst case — mid-handoff, after acks
                recv_dead_snap = rap.snapshot(tick=recv.tick_count)
                receiver_alive = False
                rep["killed_at_window"] = w_i
                rep["acked_before_kill"] = delivered
            donor.tick()
            if receiver_alive:
                recv.tick()
            obs = {
                donor_name: {"stage": stage_of(donor),
                             "entities": len(_census(donor)),
                             "present": True},
                "game2": {"stage": stage_of(recv),
                          "entities":
                              len(_census(recv) - recv_base),
                          "present": receiver_alive},
            }
            if (commit_window is not None and recovered_window is None
                    and obs[donor_name]["stage"] == "NORMAL"):
                recovered_window = w_i  # donor OBSERVED healthy again
            action = ctl.step(obs)
            if action is not None and commit_window is None:
                commit_window = w_i
            v = verdict()
            max_in_flight = max(max_in_flight, int(v["in_flight"]))
            if not v["ok"]:
                verdict_ok_all = False
                rep.setdefault("verdict_problems", v["problems"])
            frame = {"tick": donor.tick_count}
            note = agent.take_action_note()
            if note is not None:
                frame["rebalance"] = note
            incidents.extend(rec.record(frame))

        # ---- the verdicts --------------------------------------------
        results = [dict(f) for ev, f in policy.log.inputs
                   if ev == "result"]
        aborts = [r for r in results if r.get("kind") == "abort"]
        dones = [r for r in results if r.get("kind") == "done"]
        donor_final = _census(donor)
        moved_final = _census(recv) - recv_base
        lost = sorted(original - (donor_final | moved_final))
        dup = sorted(donor_final & moved_final)
        ghosts = sorted((donor_final | moved_final) - original)
        replay_ok = RebalancePolicy.replay(
            policy.log.inputs,
            hold_windows=REBALANCE_HOLD_WINDOWS, batch=batch,
            cooldown_windows=REBALANCE_COOLDOWN_WINDOWS,
        ) == policy.log.dump()
        trigger_fired = sum(
            1 for i in incidents
            if i.get("trigger") == "rebalance_action")
        rep.update({
            "handoff_fired": commit_window is not None,
            "commit_window": commit_window,
            "committed": policy.committed,
            "entities_moved": len(moved_final),
            "entities_lost": len(lost),
            "entities_duplicated": len(dup) + len(ghosts),
            "lost_eids": lost[:8],
            "duplicated_eids": (dup + ghosts)[:8],
            "sends_dropped": dropped,
            "conservation_ok_all_windows": verdict_ok_all,
            "max_in_flight_seen": max_in_flight,
            "decision_log_replay_ok": replay_ok,
            "rebalance_action_triggers": trigger_fired,
            "moves_total": agent.snapshot()["moves_total"],
            "aborts_total": dict(agent.aborts_total),
            "decision_log": list(policy.log.lines),
        })
        zero_loss = not lost and not dup and not ghosts
        if kill_target:
            abort = aborts[0] if aborts else {}
            rep["abort_cause"] = abort.get("cause")
            rep["entities_restored"] = int(abort.get("restored", 0))
            rep["converged"] = bool(
                commit_window is not None
                and agent.aborted == 1 and not dones
                and abort.get("cause") == "timeout"
                # mid-batch: some of the cohort was acked before the
                # kill, the rest must be restored live on the source
                and 0 < len(moved_final) < batch
                and rep["entities_restored"] == batch
                - len(moved_final)
                and zero_loss and verdict_ok_all and replay_ok
                and trigger_fired > 0)
        else:
            rep["donor_recovery_windows"] = (
                None if recovered_window is None or commit_window
                is None else recovered_window - commit_window)
            rep["converged"] = bool(
                commit_window is not None
                and policy.committed == 1 and agent.completed == 1
                and not aborts
                and len(moved_final) == batch
                and rep["donor_recovery_windows"] is not None
                and rep["donor_recovery_windows"]
                <= REBALANCE_RECOVERY_BUDGET
                and zero_loss and verdict_ok_all
                # the verdict judged a window with a batch in flight
                and max_in_flight > 0
                and replay_ok and trigger_fired > 0)
        return rep
    except Exception as exc:
        rep["error"] = f"{type(exc).__name__}: {str(exc)[:300]}"
        return rep
    finally:
        from goworld_tpu.utils import audit as audit_mod

        audit_mod.unregister(f"game{donor.game_id}")
        audit_mod.unregister("game2")


def run_rebalance(seed: int) -> dict:
    """The ISSUE-19 self-healing rebalance scenario, in-process like
    the audit and failover soaks. ONE run proves BOTH halves of the
    story on the same seed:

    - ``clean``: sustained DEGRADED fires exactly one rate-limited
      cohort handoff through the production migration machinery, the
      donor recovers to NORMAL within the recovery budget, zero
      entities are lost or duplicated, the deployment conservation
      verdict is green EVERY window (including mid-batch, with the
      cohort in flight), and the decision log replays byte-for-byte.
    - ``target_kill``: the receiver dies mid-handoff with a sub-batch
      unacked; the executor's timeout abort must restore every unacked
      entity LIVE on the source (ledger out-record/seq machinery —
      the self-round-trip retires the record), already-acked entities
      stay moved, and the donor + receiver censuses still partition
      the original entity set exactly.

    Same-seed reruns replay the same observation stream and therefore
    the same decision log (the seeded-replay guarantee)."""
    report: dict = {"scenario": "rebalance", "seed": seed,
                    "converged": False}
    report["clean"] = _run_rebalance_variant(seed, kill_target=False)
    report["target_kill"] = _run_rebalance_variant(
        seed, kill_target=True)
    report["converged"] = bool(
        report["clean"].get("converged")
        and report["target_kill"].get("converged"))
    return report


def _ini_port(server_dir: str, section: str, key: str) -> int:
    import configparser

    cp = configparser.ConfigParser()
    cp.read(os.path.join(server_dir, "goworld_tpu.ini"))
    return int(cp[section][key])


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=None,
                    help="throwaway server dir (created); required for "
                         "the cluster scenarios (kill, overload), "
                         "unused by the in-process ones "
                         "(governor, audit, failover, rebalance)")
    ap.add_argument("--scenario",
                    choices=("kill", "overload", "governor", "audit",
                             "failover", "rebalance"),
                    default="kill")
    ap.add_argument("--seed", type=int, default=77)
    ap.add_argument("--deposits", type=int, default=25)
    ap.add_argument("--kill-tick", type=int, default=KILL_TICK)
    ap.add_argument("--flood-secs", type=float, default=6.0,
                    help="overload scenario: bot flood duration")
    ap.add_argument("--msg-rate", type=float, default=120.0,
                    help="overload scenario: flood messages per second")
    ap.add_argument("--workload", default="",
                    help="adversarial NPC workload for the game under "
                         "test (goworld_tpu/scenarios registry name, "
                         "e.g. hotspot|teleport|mixed); default: the "
                         "homogeneous random_walk")
    ap.add_argument("--out", default="chaos_report.json")
    args = ap.parse_args()
    if args.scenario in ("governor", "audit", "failover",
                         "rebalance"):
        # in-process (no cluster dir needed): the oracle + entity
        # audits need direct World access; --dir is accepted but
        # unused for symmetry with the other scenarios
        if args.scenario == "governor":
            report = run_governor(args.seed)
            report["workload"] = "governor-schedule"
        elif args.scenario == "failover":
            report = run_failover(args.seed)
            report["workload"] = "failover-churn"
        elif args.scenario == "rebalance":
            report = run_rebalance(args.seed)
            report["workload"] = "rebalance-handoff"
        else:
            report = run_audit(args.seed)
            report["workload"] = "audit-churn"
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
        print(json.dumps(report, indent=2))
        return 0 if report.get("converged") else 1
    if not args.dir:
        ap.error(f"--dir is required for the {args.scenario} scenario")
    server_dir, _, _ = build_server_dir(
        args.dir, overload_knobs=args.scenario == "overload",
        workload=args.workload)
    if args.scenario == "overload":
        report = run_overload(server_dir, args.seed, args.flood_secs,
                              args.msg_rate)
    else:
        report = run_soak(server_dir, args.seed, args.deposits,
                          kill_tick=args.kill_tick)
    report["workload"] = args.workload or "random_walk"
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))
    return 0 if report.get("converged") else 1


if __name__ == "__main__":
    sys.exit(main())
