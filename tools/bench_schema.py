#!/usr/bin/env python
"""Schema checker for the checked-in BENCH_r*.json / MULTICHIP_r*.json.

The round artifacts are the repo's performance memory — trend gating
(tools/bench_trend.py), the roofline audit and the ROADMAP all read
them — so a malformed stamp is corruption that compounds. This
validates every file's shape and is run as a tier-1 test
(tests/test_bench_schema.py), so a malformed stamp can never land
again.

Rules are VERSIONED by round number (the artifact grew stamps over
time; old rounds are grandfathered, new rounds are held to the current
contract):

* every BENCH file: either a headline record with the base contract
  (metric/value/unit/vs_baseline/entities/tick_ms/platform/attempts),
  or an honestly-recorded failed round (no headline, rc != 0);
* rounds >= 6 (the first artifacts produced by the stamp-carrying
  bench): resolved kernel stamps (sweep/topk/sort/skin);
* rounds >= 8 (the device-plane era): ``slo``, ``op_stats`` and
  ``roofline_audit`` blocks with their required inner shape (an
  ``{"error": ...}`` record is an accepted honest failure, a
  ``{"skipped": ...}`` record a documented deliberate skip —
  BENCH_DEVPROF=0/BENCH_SLO=0/BENCH_PHASES=0);
* MULTICHIP files: n_devices/rc/ok/tail, with ok => rc == 0;
* MULTICHIP rounds >= 10 (the measured-mesh era, bench.py --multichip):
  a ``headline`` block ({entity_ticks_per_sec_mesh,
  per_chip_efficiency, n_entities, platform}), ``gauges``,
  ``cost_report``/``roofline_audit`` (``{"error": ...}`` accepted as
  honest failure) and a ``phases.border_churn`` block; failed rounds
  (rc != 0) and ``skipped`` records stay exempt, old dryrun-only
  artifacts are grandfathered;
* rounds >= 11 (the workload-signature era, ISSUE 11): a
  ``workload_signature`` block — the live ``/workload`` grammar
  (sig/churn/density/events/recommendation) stamped by the same
  jax-free reducer — in BENCH headlines and MULTICHIP documents alike
  (``{"error"/"skipped": ...}`` accepted as honest failure);
* rounds >= 12 (the quantized-plane era, ISSUE 12): a ``precision``
  block (resolved plane on/off, pos scale bits, delta-sync keyframe
  cadence) next to the kernel stamps, plus the ``precision_ab``
  on/off A/B record (measured marginal both ways + modeled bytes at
  the shape and at 1M; honest error/skip records accepted);
* rounds >= 13 (the kernel-governor era, ISSUE 13): a ``governor``
  block — the ``bench.py --governor`` phase-switching schedule
  (per-phase chosen config + swap latency, throughput vs best/worst
  static) when it ran, or an honest ``{"skipped": "--governor not
  requested"}`` / ``{"error": ...}`` record otherwise;
* rounds >= 15 (the sync-age era, ISSUE 15): a ``sync_age`` block —
  the end-to-end device-tick-epoch -> gate-delivery age measured
  through the real game->gate loopback (per-hop + e2e p50/p90/p99,
  the verdict vs the 16 ms target, the measured stamp overhead) —
  honest ``{"error"/"skipped": ...}`` records accepted;
* rounds >= 16 (the serve-loop residency era, ISSUE 16): a
  ``residency`` block — the instrumented-World serve-loop plane
  (bubble/tick percentiles, phase lanes, the donation-readiness
  buffer census, alloc churn or its honest absence, serve_gap vs the
  pinned scan-marginal, the measured mark overhead) — honest
  ``{"error"/"skipped": ...}`` records accepted;
* rounds >= 17 (the correctness-audit era, ISSUE 17): an ``audit``
  block — the entity-ownership ledger census + deployment
  conservation verdict, the sampled AOI-oracle progress, by-kind
  violation totals (the zero-violation gate) and the measured A/B
  overhead of the plane vs the 60 Hz tick budget — honest
  ``{"error"/"skipped": ...}`` records accepted;
* rounds >= 18 (the hot-standby era, ISSUE 18): a ``failover`` block
  — the streamed primary->standby replication cost (bytes/tick, next
  to the client-sync bytes/tick the same workload ships), the
  standby's apply cost, the promotion latency in ticks and the
  conservation counts across the arbitrated promotion (zero lost /
  zero duplicated EntityIDs is the gate) — honest
  ``{"error"/"skipped": ...}`` records accepted;
* rounds >= 19 (the self-healing rebalance era, ISSUE 19): a
  ``rebalance`` block — donor tick p99 before/after the automated
  handoff, entities moved vs the batch cap, abort count, the donor
  recovery latency in observation windows (the lower-is-better trend
  series) and the conservation counts across the move (zero lost /
  zero duplicated is the unconditional gate), plus the byte-identical
  DecisionLog replay verdict — honest ``{"error"/"skipped": ...}``
  records accepted;
* rounds >= 20 (the resident-world era, ISSUE 20): a ``resident_ab``
  block — serve-loop ms/tick with carry donation + the
  double-buffered drain on vs off at the same shape (the
  interleaved paced-window protocol), the residency census counts
  for BOTH arms (0 re-allocated lanes on the donated arm is the
  trend gate; >= 1 on the copy arm proves the A/B measured the
  knob) and allocs/tick where the backend serves memory_stats —
  honest ``{"error"/"skipped": ...}`` records accepted.

Exit codes: 0 all valid, 1 usage/missing, 2 schema violations.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# jax-free artifact conventions shared with bench_trend/roofline_audit
from goworld_tpu.utils.devprof import (  # noqa: E402
    artifact_headline,
    artifact_round as round_no,
)

BASE_KEYS = ("metric", "value", "unit", "vs_baseline", "entities",
             "tick_ms", "platform", "attempts")
KERNEL_STAMPS = ("sweep_impl", "topk_impl", "sort_impl", "skin")
SLO_KEYS = ("target_ms", "p50_ms", "p90_ms", "p99_ms", "pass",
            "source")
# round number from which a stamp family is REQUIRED (the stamps
# landed in the r5 SESSION, so the first artifact carrying them is r6)
KERNEL_STAMPS_SINCE = 6
DEVICE_PLANE_SINCE = 8
# MULTICHIP graduates from a dryrun log to a measured mesh headline
# (bench.py --multichip, ISSUE 10): required from r10, old dryrun-only
# artifacts grandfathered
MULTI_HEADLINE_SINCE = 10
# the workload-signature era (ISSUE 11): every BENCH/MULTICHIP round
# stamps the jax-free signature reduction of its drained telemetry
# lanes — the same grammar the live /workload endpoint serves
# ({"error"/"skipped": ...} accepted as honest failure, like every
# device-plane block)
WORKLOAD_SIG_SINCE = 11
WORKLOAD_SIG_KEYS = ("sig", "churn", "density", "events",
                     "recommendation")
# the quantized-plane era (ISSUE 12): every BENCH headline stamps the
# resolved `precision` block (plane on/off, pos scale bits, delta-sync
# keyframe cadence) next to the kernel stamps, plus the precision
# on/off A/B record ({"error"/"skipped": ...} accepted as honest
# failure, the device-plane convention)
PRECISION_SINCE = 12
PRECISION_KEYS = ("plane", "pos_scale_bits", "sync_keyframe_every")
# the kernel-governor era (ISSUE 13): bench.py --governor stamps the
# phase-switching schedule block; rounds that didn't run it must say
# so honestly ({"skipped"/"error": ...} — the device-plane convention)
GOVERNOR_SINCE = 13
GOVERNOR_KEYS = ("schedule", "phases", "throughput", "static_wall_s")
# the sync-age era (ISSUE 15): every BENCH round stamps the
# game->gate loopback's age-at-delivery block — per-hop + e2e
# percentiles, the verdict vs the paper's 16 ms target, and the
# measured overhead of the always-on stamp (the <1% criterion)
SYNC_AGE_SINCE = 15
SYNC_AGE_KEYS = ("target_ms", "e2e", "hops", "records_per_tick",
                 "pass", "stamp_overhead_pct_of_budget")
SYNC_AGE_HOPS = ("device_tick", "drain_decode", "encode",
                 "dispatcher", "gate_flush")
# the serve-loop residency era (ISSUE 16): every BENCH round stamps
# the instrumented serve loop's residency plane — the host bubble vs
# its budget, the phase lanes, the donation-readiness census (the
# donate_argnums worklist), alloc churn (or its honest absence on
# backends without memory_stats), serve_gap vs the pinned
# scan-marginal, and the measured overhead of the always-on marks
RESIDENCY_SINCE = 16
RESIDENCY_KEYS = ("bubble", "tick", "phases", "census", "alloc",
                  "serve_gap", "serve_gap_ref", "scan_marginal_ms",
                  "bubble_budget_ms", "mark_overhead_pct_of_budget")
# the correctness-audit era (ISSUE 17): every BENCH round stamps the
# audit plane's block — ledger census + conservation verdict, AOI
# oracle sample/mismatch counts, the by-kind violation totals (the
# zero-violation gate) and the measured A/B overhead of the plane vs
# the 60 Hz tick budget (the <1% criterion)
AUDIT_SINCE = 17
AUDIT_KEYS = ("ledger", "oracle", "violations_total", "conservation",
              "overhead_pct_of_budget", "pass")
# the hot-standby era (ISSUE 18): every BENCH round stamps the
# failover block — replication stream bytes/tick next to the
# client-sync bytes/tick the same workload ships, the standby's apply
# cost, the promotion latency in ticks and the conservation counts
# across the promotion (zero lost / zero duplicated is the gate)
FAILOVER_SINCE = 18
FAILOVER_KEYS = ("replication_bytes_per_tick",
                 "client_sync_bytes_per_tick",
                 "standby_apply_ms_per_tick",
                 "promotion_latency_ticks", "entities_lost",
                 "entities_duplicated", "frames_applied",
                 "frames_rejected", "decision_log_replay_ok", "pass")
# the self-healing rebalance era (ISSUE 19): every BENCH round stamps
# the rebalance block — donor tick p99 before/after the handoff,
# entities moved vs the batch cap, abort count, donor recovery
# latency in observation windows (the lower-is-better trend series)
# and the conservation counts across the move (zero lost / zero
# duplicated is the unconditional gate)
REBALANCE_SINCE = 19
REBALANCE_KEYS = ("donor_p99_before_ms", "donor_p99_after_ms",
                  "entities_moved", "batch", "aborts",
                  "donor_recovery_windows", "entities_lost",
                  "entities_duplicated", "decision_log_replay_ok",
                  "pass")
# the resident-world era (ISSUE 20): every BENCH round stamps the
# donation + double-buffered-drain A/B — serve-loop ms/tick on vs off
# at the same shape, the residency census counts on BOTH arms (the
# donated arm's 0-realloc verdict is the trend gate) and allocs/tick
# where the backend serves memory_stats
RESIDENT_AB_SINCE = 20
RESIDENT_AB_KEYS = ("on_ms_per_tick", "off_ms_per_tick", "ratio",
                    "on_census", "off_census", "windows",
                    "ticks_per_window", "pass")
MULTI_HEADLINE_KEYS = ("entity_ticks_per_sec_mesh",
                       "per_chip_efficiency", "n_entities", "platform")
MULTI_GAUGE_KEYS = ("halo_demand_max", "migrate_demand_max",
                    "migrate_dropped_total")


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_block(rec: dict, key: str, inner: tuple,
                 errs: list[str]) -> None:
    """A device-plane block: present, a dict, and either an honest
    ``{"error": ...}`` / ``{"skipped": ...}`` record (an exception in
    the stamping, or a documented BENCH_DEVPROF=0/BENCH_SLO=0/
    BENCH_PHASES=0 skip) or the full inner shape."""
    blk = rec.get(key)
    if not isinstance(blk, dict):
        errs.append(f"missing/invalid {key} block")
        return
    if "error" in blk or "skipped" in blk:
        return  # honestly-recorded failure or deliberate skip
    for k in inner:
        if k not in blk:
            errs.append(f"{key} missing key {k!r}")


def validate_bench(path: str, doc: dict) -> list[str]:
    errs: list[str] = []
    rno = round_no(path)
    # the ONE headline definition shared with bench_trend/
    # roofline_audit (devprof.artifact_headline): a value-0 error
    # record (compose()'s "no stage completed" artifact) is a FAILED
    # round, not a headline to hold to the headline contract
    rec = artifact_headline(doc)
    if rec is None:
        # a failed round: honest only when its rc says so
        if doc.get("rc", 1) == 0 and "parsed" in doc:
            errs.append("no headline record but rc == 0")
        return errs
    for k in BASE_KEYS:
        if k not in rec:
            errs.append(f"missing base key {k!r}")
    if "value" in rec and not _is_num(rec["value"]):
        errs.append(f"value is {type(rec['value']).__name__}, "
                    "not a number")
    if _is_num(rec.get("value")) and rec["value"] < 0:
        errs.append("negative headline value")
    if not isinstance(rec.get("attempts", []), list):
        errs.append("attempts is not a list")
    if rno >= KERNEL_STAMPS_SINCE:
        for k in KERNEL_STAMPS:
            if k not in rec:
                errs.append(f"missing kernel stamp {k!r} "
                            f"(required since r{KERNEL_STAMPS_SINCE:02d})")
    if rno >= DEVICE_PLANE_SINCE:
        _check_block(rec, "slo", SLO_KEYS, errs)
        _check_block(rec, "roofline_audit", ("phases",), errs)
        ost = rec.get("op_stats")
        if not isinstance(ost, dict) or not (
                {"error", "skipped"} & set(ost) or "tick_ms" in ost):
            errs.append("missing/invalid op_stats block")
    if rno >= WORKLOAD_SIG_SINCE:
        _check_block(rec, "workload_signature", WORKLOAD_SIG_KEYS,
                     errs)
    if rno >= PRECISION_SINCE:
        _check_block(rec, "precision", PRECISION_KEYS, errs)
        _check_block(rec, "precision_ab",
                     ("off_ms", "q16_ms", "model_off_gb_1m",
                      "model_q16_gb_1m"), errs)
    if rno >= GOVERNOR_SINCE:
        _check_block(rec, "governor", GOVERNOR_KEYS, errs)
        gv = rec.get("governor")
        if isinstance(gv, dict) and "error" not in gv \
                and "skipped" not in gv:
            for ph in gv.get("phases") or []:
                if not isinstance(ph, dict) or not (
                        {"scenario", "chosen", "expected"} <= set(ph)):
                    errs.append(
                        f"governor phase record malformed: {ph!r:.120}")
    if rno >= SYNC_AGE_SINCE:
        _check_block(rec, "sync_age", SYNC_AGE_KEYS, errs)
        sa = rec.get("sync_age")
        if isinstance(sa, dict) and "error" not in sa \
                and "skipped" not in sa:
            e2e = sa.get("e2e")
            if not (isinstance(e2e, dict)
                    and {"p50_ms", "p90_ms", "p99_ms", "samples"}
                    <= set(e2e)):
                errs.append(f"sync_age e2e malformed: {e2e!r:.120}")
            hops = sa.get("hops")
            if isinstance(hops, dict):
                for hop in SYNC_AGE_HOPS:
                    if hop not in hops:
                        errs.append(f"sync_age missing hop {hop!r}")
            else:
                errs.append(f"sync_age hops malformed: {hops!r:.120}")
    if rno >= RESIDENCY_SINCE:
        _check_block(rec, "residency", RESIDENCY_KEYS, errs)
        rs = rec.get("residency")
        if isinstance(rs, dict) and "error" not in rs \
                and "skipped" not in rs:
            bub = rs.get("bubble")
            if not (isinstance(bub, dict)
                    and {"p50_ms", "p90_ms", "p99_ms", "samples"}
                    <= set(bub)):
                errs.append(f"residency bubble malformed: {bub!r:.120}")
            cen = rs.get("census")
            if not (isinstance(cen, dict)
                    and {"samples", "realloc", "aliased"} <= set(cen)):
                errs.append(f"residency census malformed: {cen!r:.120}")
            if not isinstance(rs.get("alloc"), dict):
                # measured stats or {"unavailable": ...} — never absent
                errs.append(
                    f"residency alloc malformed: {rs.get('alloc')!r:.120}")
    if rno >= AUDIT_SINCE:
        _check_block(rec, "audit", AUDIT_KEYS, errs)
        au = rec.get("audit")
        if isinstance(au, dict) and "error" not in au \
                and "skipped" not in au:
            vt = au.get("violations_total")
            if not isinstance(vt, dict):
                errs.append(f"audit violations_total malformed: "
                            f"{vt!r:.120}")
            orc = au.get("oracle")
            if not (isinstance(orc, dict)
                    and {"samples", "entities_checked", "mismatches"}
                    <= set(orc)):
                errs.append(f"audit oracle malformed: {orc!r:.120}")
            con = au.get("conservation")
            if not (isinstance(con, dict) and "ok" in con):
                errs.append(f"audit conservation malformed: "
                            f"{con!r:.120}")
    if rno >= FAILOVER_SINCE:
        _check_block(rec, "failover", FAILOVER_KEYS, errs)
        fo = rec.get("failover")
        if isinstance(fo, dict) and "error" not in fo \
                and "skipped" not in fo:
            for k in ("entities_lost", "entities_duplicated",
                      "promotion_latency_ticks"):
                if k in fo and not _is_num(fo[k]):
                    errs.append(f"failover {k} malformed: "
                                f"{fo.get(k)!r:.120}")
    if rno >= REBALANCE_SINCE:
        _check_block(rec, "rebalance", REBALANCE_KEYS, errs)
        rb = rec.get("rebalance")
        if isinstance(rb, dict) and "error" not in rb \
                and "skipped" not in rb:
            for k in ("entities_lost", "entities_duplicated",
                      "entities_moved", "aborts",
                      "donor_recovery_windows"):
                if k in rb and rb[k] is not None \
                        and not _is_num(rb[k]):
                    errs.append(f"rebalance {k} malformed: "
                                f"{rb.get(k)!r:.120}")
    if rno >= RESIDENT_AB_SINCE:
        _check_block(rec, "resident_ab", RESIDENT_AB_KEYS, errs)
        ra = rec.get("resident_ab")
        if isinstance(ra, dict) and "error" not in ra \
                and "skipped" not in ra:
            for k in ("on_ms_per_tick", "off_ms_per_tick", "ratio"):
                if not _is_num(ra.get(k)):
                    errs.append(f"resident_ab {k} malformed: "
                                f"{ra.get(k)!r:.120}")
            for arm in ("on_census", "off_census"):
                cen = ra.get(arm)
                if not (isinstance(cen, dict)
                        and {"samples", "realloc", "aliased"}
                        <= set(cen)):
                    errs.append(f"resident_ab {arm} malformed: "
                                f"{cen!r:.120}")
    # per-scenario blocks, wherever present: each needs either a
    # headline-style shape or an honest error
    for sc, blk in (rec.get("scenarios") or {}).items():
        if not isinstance(blk, dict):
            errs.append(f"scenario {sc}: not a dict")
            continue
        if "error" in blk:
            continue
        for k in ("value", "tick_ms", "entities"):
            if k not in blk:
                errs.append(f"scenario {sc}: missing {k!r}")
    return errs


def validate_multichip(path: str, doc: dict) -> list[str]:
    errs: list[str] = []
    for k in ("n_devices", "rc", "ok", "tail"):
        if k not in doc:
            errs.append(f"missing key {k!r}")
    if doc.get("ok") and doc.get("rc", 0) != 0:
        errs.append(f"ok but rc={doc.get('rc')}")
    if "n_devices" in doc and (not _is_num(doc["n_devices"])
                               or doc["n_devices"] <= 0):
        errs.append(f"n_devices={doc.get('n_devices')!r}")
    rno = round_no(path)
    if rno < MULTI_HEADLINE_SINCE or doc.get("skipped"):
        return errs
    # the measured-mesh era (r >= 10): a real headline block with the
    # scan-marginal mesh number + efficiency, comms gauges, and the
    # device-plane stamps ({"error": ...} accepted as honest failure).
    # A FAILED round (rc != 0) is exempt like the BENCH contract —
    # its failure is already recorded honestly.
    if doc.get("rc", 1) != 0 and not doc.get("ok"):
        return errs
    hl = doc.get("headline")
    if not isinstance(hl, dict):
        errs.append("missing/invalid headline block "
                    f"(required since r{MULTI_HEADLINE_SINCE:02d})")
    elif "error" not in hl:
        for k in MULTI_HEADLINE_KEYS:
            if k not in hl:
                errs.append(f"headline missing key {k!r}")
        v = hl.get("entity_ticks_per_sec_mesh")
        if v is not None and (not _is_num(v) or v < 0):
            errs.append(f"entity_ticks_per_sec_mesh={v!r}")
        if doc.get("ok") and not hl.get("entity_ticks_per_sec_mesh"):
            errs.append("ok but headline carries no mesh number")
    _check_block(doc, "gauges", MULTI_GAUGE_KEYS, errs)
    _check_block(doc, "cost_report", ("name",), errs)
    _check_block(doc, "roofline_audit", ("phases",), errs)
    if rno >= WORKLOAD_SIG_SINCE:
        _check_block(doc, "workload_signature", WORKLOAD_SIG_KEYS,
                     errs)
    phases = doc.get("phases")
    if not isinstance(phases, dict) \
            or not isinstance(phases.get("border_churn"), dict):
        errs.append("missing phases.border_churn block "
                    f"(required since r{MULTI_HEADLINE_SINCE:02d})")
    return errs


def validate_file(path: str) -> list[str]:
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        return [f"unreadable: {exc}"]
    if not isinstance(doc, dict):
        return ["top level is not a JSON object"]
    if "MULTICHIP" in os.path.basename(path):
        return validate_multichip(path, doc)
    return validate_bench(path, doc)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="validate checked-in BENCH/MULTICHIP artifacts")
    ap.add_argument("files", nargs="*",
                    help="explicit files (default: repo glob)")
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    args = ap.parse_args(argv)

    files = args.files or sorted(
        glob.glob(os.path.join(args.dir, "BENCH_r*.json"))
        + glob.glob(os.path.join(args.dir, "MULTICHIP_r*.json"))
    )
    if not files:
        print(f"no artifacts under {args.dir}", file=sys.stderr)
        return 1
    bad = 0
    for path in files:
        if not os.path.exists(path):
            print(f"missing file: {path}", file=sys.stderr)
            return 1
        errs = validate_file(path)
        name = os.path.basename(path)
        if errs:
            bad += 1
            for e in errs:
                print(f"{name}: {e}", file=sys.stderr)
        else:
            print(f"{name}: ok")
    return 2 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
