#!/usr/bin/env python
"""Regression gate over the BENCH_r*.json / MULTICHIP_r*.json trajectory.

The round artifacts were a pile of snapshots; this turns them into an
ENFORCED contract: read the whole checked-in trajectory and exit
non-zero when the LATEST round regresses against its comparable
predecessors. Runs in tier-1 against the checked-in files (jax-free,
milliseconds) and in CI after any new round lands.

Gating policy — the latest round only (historic inter-round swings,
e.g. r02->r03's workload change, are the recorded past, not a
regression introduced by the change under test):

* headline ``value`` (higher is better): latest must be within
  ``--threshold`` of the BEST prior round at the same
  (entities, platform) shape;
* ``tick_ms`` and every shared ``phase_ms`` entry (lower is better):
  latest vs the MOST RECENT comparable prior round — but when the
  same round's headline IMPROVED past the threshold vs that
  predecessor, split regressions demote to informational NOTES (the
  split gate exists to catch a phase rotting UNDER a flat headline;
  a much faster headline with a slower split is a machine/balance
  change the headline could not have hidden);
* per-scenario block ``value``s: same rule, matched by scenario name
  at equal entities;
* ``slo.pass``: a true -> false transition at the same shape fails;
* ``workload_signature``: a class-string drift vs the most recent
  comparable round is an informational NOTE, never a gate (the
  signature describes the workload, not the implementation — but a
  drift next to a perf swing is the first thing to read);
* ``rebalance`` (ISSUE 19): any lost/duplicated entity across the
  automated handoff or a failed DecisionLog byte replay in a real
  latest block is an UNCONDITIONAL failure (conservation needs no
  prior); ``donor_recovery_windows`` is a lower-is-better series
  gated against the best prior at the same (entities_moved,
  platform) shape with +1 window absolute slack;
* ``resident_ab`` (ISSUE 20): any re-allocated carry lane in the
  donation-on arm's census of a real latest block is an UNCONDITIONAL
  failure (the resident runtime's whole contract is zero steady-state
  allocation — no prior needed, like the audit's zero-violation
  gate); the on/off ``ratio`` (serve ms/tick with donation+overlap
  over without, lower is better, a pure ratio so no absolute slack)
  gates against the best prior at the same (entities, platform)
  shape; a pass->fail flip at the same shape is always a problem;
* MULTICHIP: the latest record must keep ``ok`` (when any prior round
  had it) and ``rc == 0``; measured mesh headlines (r >= 10) gate
  ``entity_ticks_per_sec_mesh`` against the best prior at the same
  (entities, platform, n_devices) shape and fail a
  ``per_chip_efficiency`` drop past the threshold.

Exit codes: 0 pass, 1 usage/missing file, 2 regression.

Usage::

    python tools/bench_trend.py                     # repo trajectory
    python tools/bench_trend.py --threshold 0.2
    python tools/bench_trend.py BENCH_r04.json BENCH_r05.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# jax-free artifact conventions shared with bench_schema/roofline_audit
from goworld_tpu.utils.devprof import (  # noqa: E402
    artifact_headline,
    artifact_round as _round_no,
)

DEFAULT_THRESHOLD = 0.30  # fractional regression that fails the gate


def load_headline(path: str) -> dict | None:
    """The stamped artifact dict (driver wrapper or bare); None when
    the round recorded no usable headline (failed rounds are skipped,
    not gated — their failure is already recorded honestly)."""
    with open(path) as fh:
        rec = artifact_headline(json.load(fh))
    if rec is not None and rec.get("timing_suspect"):
        return None  # a flagged headline is not a trustworthy baseline
    return rec


def _shape(rec: dict) -> tuple:
    """(entities, platform, mode): a headline measured under a
    governor schedule (``bench_mode = "governor"``) anchors its OWN
    series — its number includes swap dynamics and a scenario
    schedule, so gating it against a static-workload round (or vice
    versa) would compare different experiments. NOTE: today's
    ``bench.py --governor`` keeps the headline static and stamps the
    schedule as a separate ``governor`` block (gated by its own
    series below) — no current round stamps ``bench_mode``; this
    component is the enforcement hook for a future round whose
    HEADLINE runs governed, kept so such an artifact can never
    silently gate against the static history."""
    return (rec.get("entities"), rec.get("platform"),
            rec.get("bench_mode", "static"))


def _check_governor_series(rounds: list, latest: dict, name: str,
                           threshold: float, problems: list[str],
                           notes: list[str]) -> None:
    """The governor schedule block (ISSUE 13): its throughput is a
    series of its own, gated against the best prior round that ran
    the SAME (n, platform, schedule) shape — never against static
    headlines (and static headlines never gate against it).
    Skipped/error records neither gate nor anchor."""
    def _gov_ok(g) -> bool:
        return (isinstance(g, dict)
                and isinstance(g.get("throughput"), (int, float))
                and g["throughput"] > 0)

    lgov = latest.get("governor")
    if not _gov_ok(lgov):
        return
    gshape = (lgov.get("n"), latest.get("platform"),
              tuple(lgov.get("schedule") or ()))
    gprior = [
        (p, r["governor"]) for p, r in rounds[:-1]
        if _gov_ok(r.get("governor"))
        and (r["governor"].get("n"), r.get("platform"),
             tuple(r["governor"].get("schedule") or ())) == gshape
    ]
    if not gprior:
        notes.append(f"{name}: governor shape {gshape} has no "
                     "prior round — not gated")
        return
    gbest_path, gbest = max(gprior, key=lambda pr: pr[1]["throughput"])
    gfloor = (1.0 - threshold) * gbest["throughput"]
    if lgov["throughput"] < gfloor:
        problems.append(
            f"{name}: governor throughput "
            f"{lgov['throughput']:.0f} < {gfloor:.0f} "
            f"({(1 - threshold) * 100:.0f}% of "
            f"{os.path.basename(gbest_path)}'s "
            f"{gbest['throughput']:.0f})")
    else:
        notes.append(
            f"{name}: governor throughput "
            f"{lgov['throughput']:.0f} vs best prior "
            f"{gbest['throughput']:.0f} — ok")


def _check_sync_age_series(rounds: list, latest: dict, name: str,
                           threshold: float, problems: list[str],
                           notes: list[str]) -> None:
    """The sync-age loopback block (ISSUE 15): its e2e p99 is a
    delivery-latency series of its own, gated LOWER-IS-BETTER against
    the best (lowest-p99) prior round at the SAME (records_per_tick,
    clients, platform) shape. Skipped/error rounds and rounds whose
    p99 never resolved to a number neither gate nor anchor; a
    pass->fail flip at the same shape is always a problem (the slo
    rule)."""
    def _sa_ok(s) -> bool:
        return (isinstance(s, dict) and "error" not in s
                and "skipped" not in s
                and isinstance((s.get("e2e") or {}).get("p99_ms"),
                               (int, float)))

    lsa = latest.get("sync_age")
    if not _sa_ok(lsa):
        return
    sshape = (lsa.get("records_per_tick"), lsa.get("clients"),
              latest.get("platform"))
    sprior = [
        (p, r["sync_age"]) for p, r in rounds[:-1]
        if _sa_ok(r.get("sync_age"))
        and (r["sync_age"].get("records_per_tick"),
             r["sync_age"].get("clients"),
             r.get("platform")) == sshape
    ]
    if not sprior:
        notes.append(f"{name}: sync_age shape {sshape} has no prior "
                     "round — not gated")
        return
    lp99 = lsa["e2e"]["p99_ms"]
    best_path, best = min(sprior,
                          key=lambda pr: pr[1]["e2e"]["p99_ms"])
    ceil = (1.0 + threshold) * best["e2e"]["p99_ms"]
    if lp99 > ceil:
        problems.append(
            f"{name}: sync_age e2e p99 {lp99} ms > "
            f"{(1 + threshold) * 100:.0f}% of "
            f"{os.path.basename(best_path)}'s "
            f"{best['e2e']['p99_ms']} ms")
    else:
        notes.append(
            f"{name}: sync_age e2e p99 {lp99} ms vs best prior "
            f"{best['e2e']['p99_ms']} ms — ok")
    prev_path, prev = sprior[-1]
    if prev.get("pass") and not lsa.get("pass"):
        problems.append(
            f"{name}: sync_age verdict regressed pass -> fail "
            f"(e2e p99 {lp99} vs target {lsa.get('target_ms')} ms, "
            f"prior {os.path.basename(prev_path)})")


def _check_residency_series(rounds: list, latest: dict, name: str,
                            threshold: float, problems: list[str],
                            notes: list[str]) -> None:
    """The serve-loop residency block (ISSUE 16): its bubble p99 and
    serve_gap are lower-is-better series of their own, gated against
    the best (lowest) prior round at the SAME (entities, platform)
    shape. Skipped/error rounds neither gate nor anchor; a bubble p99
    of ``"inf"`` (mass past the last bucket, the ptiles convention) is
    the strongest regression a latest round can stamp but never
    anchors; a pass->fail flip at the same shape is always a problem
    (the slo rule)."""
    def _p99(s) -> float | None:
        v = (s.get("bubble") or {}).get("p99_ms")
        if v == "inf":
            return float("inf")
        return float(v) if isinstance(v, (int, float)) \
            and not isinstance(v, bool) else None

    def _rs_ok(s) -> bool:
        return (isinstance(s, dict) and "error" not in s
                and "skipped" not in s and _p99(s) is not None
                and isinstance(s.get("serve_gap"), (int, float)))

    lrs = latest.get("residency")
    if not _rs_ok(lrs):
        return
    rshape = (lrs.get("entities"), latest.get("platform"))
    rprior = [
        (p, r["residency"]) for p, r in rounds[:-1]
        if _rs_ok(r.get("residency"))
        and (r["residency"].get("entities"),
             r.get("platform")) == rshape
    ]
    if not rprior:
        notes.append(f"{name}: residency shape {rshape} has no prior "
                     "round — not gated")
        return
    # bubble p99 vs the best (lowest) FINITE prior. The +0.25 ms
    # absolute slack is one histogram bucket: a zero-bubble prior must
    # not turn timer noise on an otherwise-healthy round into a gate
    lp99 = _p99(lrs)
    finite = [(p, s) for p, s in rprior
              if _p99(s) != float("inf")]
    if finite:
        best_path, best = min(finite, key=lambda pr: _p99(pr[1]))
        ceil = (1.0 + threshold) * _p99(best) + 0.25
        if lp99 > ceil:
            problems.append(
                f"{name}: residency bubble p99 {lrs['bubble']['p99_ms']}"
                f" ms > {ceil:.3g} ms "
                f"({(1 + threshold) * 100:.0f}% of "
                f"{os.path.basename(best_path)}'s "
                f"{best['bubble']['p99_ms']} ms + 0.25)")
        else:
            notes.append(
                f"{name}: residency bubble p99 "
                f"{lrs['bubble']['p99_ms']} ms vs best prior "
                f"{best['bubble']['p99_ms']} ms — ok")
    # serve_gap (serve ms/tick over the scan-marginal reference):
    # lower is better, a pure ratio so no absolute slack needed
    lgap = lrs["serve_gap"]
    gbest_path, gbest = min(rprior, key=lambda pr: pr[1]["serve_gap"])
    gceil = (1.0 + threshold) * gbest["serve_gap"]
    if lgap > gceil:
        problems.append(
            f"{name}: residency serve_gap {lgap} > {gceil:.3g} "
            f"({(1 + threshold) * 100:.0f}% of "
            f"{os.path.basename(gbest_path)}'s {gbest['serve_gap']})")
    else:
        notes.append(
            f"{name}: residency serve_gap {lgap} vs best prior "
            f"{gbest['serve_gap']} — ok")
    prev_path, prev = rprior[-1]
    if prev.get("pass") and not lrs.get("pass"):
        problems.append(
            f"{name}: residency verdict regressed pass -> fail "
            f"(bubble p99 {lrs['bubble']['p99_ms']} vs budget "
            f"{lrs.get('bubble_budget_ms')} ms, prior "
            f"{os.path.basename(prev_path)})")


def _check_audit_series(rounds: list, latest: dict, name: str,
                        threshold: float, problems: list[str],
                        notes: list[str]) -> None:
    """The correctness-audit block (ISSUE 17): any recorded violation
    in a real latest block is ALWAYS a problem (the zero-violation
    gate needs no prior — a lost entity is a bug, not a trend); the
    measured plane overhead is a lower-is-better series gated against
    the best prior at the same (entities, platform) shape with a
    small absolute slack (timer noise on a sub-percent number); a
    conservation pass->fail flip at the same shape is always a
    problem (the slo rule)."""
    def _au_ok(s) -> bool:
        return (isinstance(s, dict) and "error" not in s
                and "skipped" not in s
                and isinstance(s.get("overhead_pct_of_budget"),
                               (int, float)))

    lau = latest.get("audit")
    if not _au_ok(lau):
        return
    viol = sum((lau.get("violations_total") or {}).values())
    if viol:
        kinds = ", ".join(sorted((lau.get("violations_total")
                                  or {}).keys()))
        problems.append(
            f"{name}: audit recorded {viol} violation(s) ({kinds}) — "
            "the bench soak must be violation-free")
    if not (lau.get("conservation") or {}).get("ok", True):
        problems.append(f"{name}: audit conservation verdict FAILED")
    ashape = (lau.get("entities"), latest.get("platform"))
    aprior = [
        (p, r["audit"]) for p, r in rounds[:-1]
        if _au_ok(r.get("audit"))
        and (r["audit"].get("entities"), r.get("platform")) == ashape
    ]
    if not aprior:
        notes.append(f"{name}: audit shape {ashape} has no prior "
                     "round — overhead not gated")
        return
    # overhead vs the best (lowest) prior; +0.1 pct-point absolute
    # slack keeps timer noise on a ~0.x% number from gating
    lov = lau["overhead_pct_of_budget"]
    best_path, best = min(aprior,
                          key=lambda pr: pr[1]["overhead_pct_of_budget"])
    ceil = (1.0 + threshold) * best["overhead_pct_of_budget"] + 0.1
    if lov > ceil:
        problems.append(
            f"{name}: audit overhead {lov}% of budget > {ceil:.3g}% "
            f"({(1 + threshold) * 100:.0f}% of "
            f"{os.path.basename(best_path)}'s "
            f"{best['overhead_pct_of_budget']}% + 0.1)")
    else:
        notes.append(
            f"{name}: audit overhead {lov}% of budget vs best prior "
            f"{best['overhead_pct_of_budget']}% — ok")
    prev_path, prev = aprior[-1]
    if prev.get("pass") and not lau.get("pass"):
        problems.append(
            f"{name}: audit verdict regressed pass -> fail "
            f"(prior {os.path.basename(prev_path)})")


def _check_failover_series(rounds: list, latest: dict, name: str,
                           threshold: float, problems: list[str],
                           notes: list[str]) -> None:
    """The hot-standby failover block (ISSUE 18): any lost or
    duplicated EntityID across promotion in a real latest block is
    ALWAYS a problem (conservation needs no prior — a lost entity is
    a bug, not a trend), as is any torn frame or a failed decision-log
    replay; the promotion latency is a lower-is-better series gated
    against the best prior at the same (entities, platform) shape
    with a 1-tick absolute slack (the resume tick quantizes it)."""
    def _fo_ok(s) -> bool:
        return (isinstance(s, dict) and "error" not in s
                and "skipped" not in s
                and isinstance(s.get("promotion_latency_ticks"),
                               (int, float)))

    lfo = latest.get("failover")
    if not _fo_ok(lfo):
        return
    lost = lfo.get("entities_lost", 0) or 0
    dup = lfo.get("entities_duplicated", 0) or 0
    if lost or dup:
        problems.append(
            f"{name}: failover lost {lost} / duplicated {dup} "
            "entity id(s) across promotion — conservation must hold")
    if lfo.get("frames_rejected", 0):
        problems.append(
            f"{name}: failover rejected "
            f"{lfo['frames_rejected']} torn frame(s) on a clean "
            "loopback stream")
    if lfo.get("decision_log_replay_ok") is False:
        problems.append(
            f"{name}: failover decision log failed byte replay")
    fshape = (lfo.get("entities"), latest.get("platform"))
    fprior = [
        (p, r["failover"]) for p, r in rounds[:-1]
        if _fo_ok(r.get("failover"))
        and (r["failover"].get("entities"),
             r.get("platform")) == fshape
    ]
    if not fprior:
        notes.append(f"{name}: failover shape {fshape} has no prior "
                     "round — promotion latency not gated")
        return
    # promotion latency vs the best (lowest) prior; +1 tick absolute
    # slack (the +1 resume tick quantizes the number)
    lat = lfo["promotion_latency_ticks"]
    best_path, best = min(
        fprior, key=lambda pr: pr[1]["promotion_latency_ticks"])
    ceil = ((1.0 + threshold) * best["promotion_latency_ticks"]) + 1
    if lat > ceil:
        problems.append(
            f"{name}: failover promotion latency {lat} ticks > "
            f"{ceil:.3g} ({(1 + threshold) * 100:.0f}% of "
            f"{os.path.basename(best_path)}'s "
            f"{best['promotion_latency_ticks']} + 1)")
    else:
        notes.append(
            f"{name}: failover promotion latency {lat} ticks vs best "
            f"prior {best['promotion_latency_ticks']} — ok")
    prev_path, prev = fprior[-1]
    if prev.get("pass") and not lfo.get("pass"):
        problems.append(
            f"{name}: failover verdict regressed pass -> fail "
            f"(prior {os.path.basename(prev_path)})")


def _check_rebalance_series(rounds: list, latest: dict, name: str,
                            threshold: float, problems: list[str],
                            notes: list[str]) -> None:
    """The self-healing rebalance block (ISSUE 19): any lost or
    duplicated entity across the automated handoff in a real latest
    block is ALWAYS a problem (conservation needs no prior), as is a
    failed DecisionLog byte replay; the donor recovery latency (in
    observation windows, None on an aborted round) is a
    lower-is-better series gated against the best prior at the same
    (entities_moved, platform) shape with a 1-window absolute slack
    (the observe cadence quantizes it)."""
    def _rb_ok(s) -> bool:
        return (isinstance(s, dict) and "error" not in s
                and "skipped" not in s)

    lrb = latest.get("rebalance")
    if not _rb_ok(lrb):
        return
    lost = lrb.get("entities_lost", 0) or 0
    dup = lrb.get("entities_duplicated", 0) or 0
    if lost or dup:
        problems.append(
            f"{name}: rebalance lost {lost} / duplicated {dup} "
            "entity id(s) across handoff — conservation must hold")
    if lrb.get("decision_log_replay_ok") is False:
        problems.append(
            f"{name}: rebalance decision log failed byte replay")
    lat = lrb.get("donor_recovery_windows")
    if not isinstance(lat, (int, float)):
        notes.append(f"{name}: rebalance donor recovery latency "
                     "absent (aborted/degenerate round) — not gated")
        return
    rshape = (lrb.get("entities_moved"), latest.get("platform"))
    rprior = [
        (p, r["rebalance"]) for p, r in rounds[:-1]
        if _rb_ok(r.get("rebalance"))
        and isinstance(r["rebalance"].get("donor_recovery_windows"),
                       (int, float))
        and (r["rebalance"].get("entities_moved"),
             r.get("platform")) == rshape
    ]
    if not rprior:
        notes.append(f"{name}: rebalance shape {rshape} has no prior "
                     "round — recovery latency not gated")
        return
    # recovery latency vs the best (lowest) prior; +1 window absolute
    # slack (the observe cadence quantizes the number)
    best_path, best = min(
        rprior, key=lambda pr: pr[1]["donor_recovery_windows"])
    ceil = ((1.0 + threshold) * best["donor_recovery_windows"]) + 1
    if lat > ceil:
        problems.append(
            f"{name}: rebalance donor recovery {lat} windows > "
            f"{ceil:.3g} ({(1 + threshold) * 100:.0f}% of "
            f"{os.path.basename(best_path)}'s "
            f"{best['donor_recovery_windows']} + 1)")
    else:
        notes.append(
            f"{name}: rebalance donor recovery {lat} windows vs best "
            f"prior {best['donor_recovery_windows']} — ok")
    prev_path, prev = rprior[-1]
    if prev.get("pass") and not lrb.get("pass"):
        problems.append(
            f"{name}: rebalance verdict regressed pass -> fail "
            f"(prior {os.path.basename(prev_path)})")


def _check_resident_series(rounds: list, latest: dict, name: str,
                           threshold: float, problems: list[str],
                           notes: list[str]) -> None:
    """The resident-world A/B block (ISSUE 20): a re-allocated carry
    lane in the donation-ON arm's census of a real latest block is
    ALWAYS a problem (the resident runtime's contract is zero
    steady-state allocation — it needs no prior, like the audit's
    zero-violation gate); an OFF arm that ALSO reads zero realloc
    means the A/B measured nothing and is flagged too; the on/off
    ``ratio`` (serve ms/tick with donation+overlap over without,
    lower is better, a pure ratio so no absolute slack) gates against
    the best prior at the same (entities, platform) shape; a
    pass->fail flip at the same shape is always a problem (the slo
    rule). Skipped/error rounds neither gate nor anchor."""
    def _realloc(cen) -> int | None:
        if not isinstance(cen, dict):
            return None
        v = cen.get("realloc")
        # the stamped block stores a count; the raw census snapshot
        # stores the lane list — accept both so a hand-rolled round
        # never slips the gate on a type mismatch
        if isinstance(v, bool):
            return None
        if isinstance(v, int):
            return v
        if isinstance(v, list):
            return len(v)
        return None

    def _ra_ok(s) -> bool:
        return (isinstance(s, dict) and "error" not in s
                and "skipped" not in s
                and _realloc(s.get("on_census")) is not None
                and isinstance(s.get("ratio"), (int, float))
                and not isinstance(s.get("ratio"), bool))

    lra = latest.get("resident_ab")
    if not _ra_ok(lra):
        return
    on_re = _realloc(lra["on_census"])
    if on_re:
        problems.append(
            f"{name}: resident_ab donation-on census re-allocated "
            f"{on_re} carry lane(s) — the resident serve loop must "
            "alias every lane in place (MUST be zero)")
    off_re = _realloc(lra.get("off_census"))
    if off_re == 0:
        problems.append(
            f"{name}: resident_ab donation-off census read 0 "
            "re-allocated lanes — the control arm shows no churn, so "
            "the A/B measured nothing")
    rshape = (lra.get("entities"), latest.get("platform"))
    rprior = [
        (p, r["resident_ab"]) for p, r in rounds[:-1]
        if _ra_ok(r.get("resident_ab"))
        and (r["resident_ab"].get("entities"),
             r.get("platform")) == rshape
    ]
    if not rprior:
        notes.append(f"{name}: resident_ab shape {rshape} has no "
                     "prior round — ratio not gated")
        return
    # on/off ratio vs the best (lowest) prior: lower is better, a
    # pure ratio so no absolute slack needed (the two arms share one
    # box and one window, so machine speed divides out)
    lratio = lra["ratio"]
    best_path, best = min(rprior, key=lambda pr: pr[1]["ratio"])
    ceil = (1.0 + threshold) * best["ratio"]
    if lratio > ceil:
        problems.append(
            f"{name}: resident_ab ratio {lratio} > {ceil:.3g} "
            f"({(1 + threshold) * 100:.0f}% of "
            f"{os.path.basename(best_path)}'s {best['ratio']})")
    else:
        notes.append(
            f"{name}: resident_ab ratio {lratio} vs best prior "
            f"{best['ratio']} — ok")
    prev_path, prev = rprior[-1]
    if prev.get("pass") and not lra.get("pass"):
        problems.append(
            f"{name}: resident_ab verdict regressed pass -> fail "
            f"(prior {os.path.basename(prev_path)})")


def check_bench(files: list[str], threshold: float,
                problems: list[str], notes: list[str]) -> None:
    rounds = []
    for path in sorted(files, key=_round_no):
        rec = load_headline(path)
        if rec is None:
            notes.append(f"{os.path.basename(path)}: no headline "
                         "(failed/suspect round) — skipped")
            continue
        rounds.append((path, rec))
    if len(rounds) < 2:
        notes.append("bench: <2 comparable rounds, nothing to gate")
        return
    latest_path, latest = rounds[-1]
    name = os.path.basename(latest_path)
    # the governor schedule block (ISSUE 13) gates FIRST: its series
    # is keyed by its own (n, platform, schedule) shape, independent
    # of the headline's — a round that changes the headline shape
    # (no headline prior -> early return below) must not silently
    # skip the governor comparison
    _check_governor_series(rounds, latest, name, threshold,
                           problems, notes)
    # the sync-age delivery series (ISSUE 15) likewise gates above the
    # headline-prior early return: its shape is independent of the
    # headline's
    _check_sync_age_series(rounds, latest, name, threshold,
                           problems, notes)
    # the serve-loop residency series (ISSUE 16): same hoisting — its
    # (entities, platform) shape is the BLOCK's, not the headline's
    _check_residency_series(rounds, latest, name, threshold,
                            problems, notes)
    # the correctness-audit series (ISSUE 17): same hoisting — the
    # zero-violation gate must fire even on a headline-shape change
    _check_audit_series(rounds, latest, name, threshold,
                        problems, notes)
    # the hot-standby failover series (ISSUE 18): same hoisting — the
    # conservation gate must fire even on a headline-shape change
    _check_failover_series(rounds, latest, name, threshold,
                           problems, notes)
    # the self-healing rebalance series (ISSUE 19): same hoisting —
    # the zero-loss gate must fire even on a headline-shape change
    _check_rebalance_series(rounds, latest, name, threshold,
                            problems, notes)
    # the resident-world A/B series (ISSUE 20): same hoisting — the
    # zero-realloc gate must fire even on a headline-shape change
    _check_resident_series(rounds, latest, name, threshold,
                           problems, notes)
    prior = [(p, r) for p, r in rounds[:-1]
             if _shape(r) == _shape(latest)]
    if not prior:
        notes.append(f"{name}: shape {_shape(latest)} has no prior "
                     "round — headline not gated")
        return
    # headline value vs the BEST comparable predecessor
    best_path, best = max(prior, key=lambda pr: pr[1]["value"])
    floor = (1.0 - threshold) * best["value"]
    if latest["value"] < floor:
        problems.append(
            f"{name}: headline {latest['value']:.0f} < "
            f"{floor:.0f} ({(1 - threshold) * 100:.0f}% of "
            f"{os.path.basename(best_path)}'s {best['value']:.0f})")
    else:
        notes.append(f"{name}: headline {latest['value']:.0f} vs best "
                     f"prior {best['value']:.0f} — ok")
    # tick_ms + phases vs the MOST RECENT comparable predecessor.
    # The per-phase gate exists to catch a phase silently rotting
    # UNDER a flat headline; when the same round's headline IMPROVED
    # past the threshold vs that same predecessor, a slower phase
    # split is a machine/balance change, not a regression the headline
    # could have hidden (r12 vs r05: 1.9x faster headline on different
    # hardware with a slower collect split) — surfaced as NOTES so the
    # drift is still on the record, never silent
    prev_path, prev = prior[-1]
    pname = os.path.basename(prev_path)
    headline_improved = (
        isinstance(prev.get("value"), (int, float)) and prev["value"] > 0
        and latest["value"] >= (1.0 + threshold) * prev["value"]
    )
    split_sink = notes if headline_improved else problems

    def split_flag(msg: str) -> None:
        split_sink.append(
            msg + (" (headline improved "
                   f"{latest['value'] / prev['value']:.2f}x vs {pname}"
                   " — machine/balance change, not gated)"
                   if headline_improved else ""))

    for key in ("tick_ms",):
        if key in latest and key in prev and prev[key] > 0:
            if latest[key] > (1.0 + threshold) * prev[key]:
                split_flag(
                    f"{name}: {key} {latest[key]} > "
                    f"{(1 + threshold) * 100:.0f}% of {pname}'s "
                    f"{prev[key]}")
    for ph, ms in (latest.get("phase_ms") or {}).items():
        pms = (prev.get("phase_ms") or {}).get(ph)
        if pms and isinstance(ms, (int, float)) and pms > 0:
            if ms > (1.0 + threshold) * pms:
                split_flag(
                    f"{name}: phase {ph} {ms} ms > "
                    f"{(1 + threshold) * 100:.0f}% of {pname}'s "
                    f"{pms} ms")
    # per-scenario headline blocks, matched by name at equal entities
    for sc, blk in (latest.get("scenarios") or {}).items():
        pblk = (prev.get("scenarios") or {}).get(sc)
        if not (isinstance(blk, dict) and isinstance(pblk, dict)):
            continue
        if blk.get("entities") != pblk.get("entities"):
            continue
        v, pv = blk.get("value"), pblk.get("value")
        if isinstance(v, (int, float)) and isinstance(pv, (int, float)) \
                and pv > 0 and v < (1.0 - threshold) * pv:
            problems.append(
                f"{name}: scenario {sc} value {v:.0f} < "
                f"{(1 - threshold) * 100:.0f}% of {pname}'s {pv:.0f}")
    # SLO: a pass that turns into a fail at the same shape regressed
    lslo, pslo = latest.get("slo"), prev.get("slo")
    if isinstance(lslo, dict) and isinstance(pslo, dict):
        if pslo.get("pass") and not lslo.get("pass"):
            problems.append(
                f"{name}: slo pass regressed true -> false "
                f"(p99 {lslo.get('p99_ms')} vs target "
                f"{lslo.get('target_ms')})")
    # workload-signature drift is INFORMATIONAL, never gated: the
    # signature classifies the measured workload, and a class change at
    # the same shape usually means the bench mix changed on purpose —
    # but a silent drift next to a perf swing is the first thing a
    # reader should see, so it's surfaced as a note
    lsig = (latest.get("workload_signature") or {}).get("sig")
    psig = (prev.get("workload_signature") or {}).get("sig")
    if lsig and psig and lsig != psig:
        notes.append(
            f"{name}: workload signature drifted vs {pname}: "
            f"{psig} -> {lsig} (informational, not gated)")
    elif lsig:
        notes.append(f"{name}: workload signature {lsig}")


def _multi_headline(doc: dict) -> dict | None:
    """The measured mesh headline of one MULTICHIP record, or None
    (dryrun-only rounds, failed rounds, error/suspect headlines)."""
    hl = doc.get("headline")
    if not isinstance(hl, dict) or "error" in hl \
            or hl.get("timing_suspect"):
        return None
    v = hl.get("entity_ticks_per_sec_mesh")
    if not isinstance(v, (int, float)) or v <= 0:
        return None
    return hl


def _multi_shape(hl: dict) -> tuple:
    return (hl.get("n_entities"), hl.get("platform"),
            hl.get("n_devices"))


def check_multichip(files: list[str], problems: list[str],
                    notes: list[str],
                    threshold: float = DEFAULT_THRESHOLD) -> None:
    recs = []
    for path in sorted(files, key=_round_no):
        with open(path) as fh:
            recs.append((path, json.load(fh)))
    if not recs:
        return
    latest_path, latest = recs[-1]
    name = os.path.basename(latest_path)
    any_prior_ok = any(r.get("ok") for _p, r in recs[:-1])
    if latest.get("skipped"):
        notes.append(f"{name}: skipped run — not gated")
        return
    if any_prior_ok and not latest.get("ok"):
        problems.append(f"{name}: multichip ok regressed true -> false")
    if latest.get("rc", 0) != 0 and any_prior_ok:
        problems.append(f"{name}: multichip rc={latest.get('rc')}")
    if latest.get("ok"):
        notes.append(f"{name}: multichip ok "
                     f"(n_devices={latest.get('n_devices')})")
    # the measured mesh headline (r >= 10): latest vs the BEST prior
    # at the same (entities, platform, n_devices) shape, plus a
    # dedicated per_chip_efficiency gate — a mesh that keeps its
    # throughput by burning more chips is still a regression
    hl = _multi_headline(latest)
    if hl is None:
        return
    prior = [(p, h) for p, r in recs[:-1]
             if (h := _multi_headline(r)) is not None
             and _multi_shape(h) == _multi_shape(hl)]
    if not prior:
        notes.append(f"{name}: mesh shape {_multi_shape(hl)} has no "
                     "prior headline — not gated")
        return
    best_path, best = max(
        prior, key=lambda pr: pr[1]["entity_ticks_per_sec_mesh"])
    floor = (1.0 - threshold) * best["entity_ticks_per_sec_mesh"]
    v = hl["entity_ticks_per_sec_mesh"]
    if v < floor:
        problems.append(
            f"{name}: mesh headline {v:.0f} < {floor:.0f} "
            f"({(1 - threshold) * 100:.0f}% of "
            f"{os.path.basename(best_path)}'s "
            f"{best['entity_ticks_per_sec_mesh']:.0f})")
    else:
        notes.append(f"{name}: mesh headline {v:.0f} vs best prior "
                     f"{best['entity_ticks_per_sec_mesh']:.0f} — ok")
    eff = hl.get("per_chip_efficiency")
    best_eff = max((h.get("per_chip_efficiency") or 0.0)
                   for _p, h in prior)
    if isinstance(eff, (int, float)) and best_eff > 0 \
            and eff < (1.0 - threshold) * best_eff:
        problems.append(
            f"{name}: per_chip_efficiency {eff:.3f} dropped >"
            f"{threshold * 100:.0f}% vs best prior {best_eff:.3f}")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="fail on regressions across the checked-in bench "
                    "trajectory")
    ap.add_argument("files", nargs="*",
                    help="explicit artifact files (default: repo glob "
                         "of BENCH_r*.json + MULTICHIP_r*.json)")
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repo root to glob (default: this checkout)")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD,
                    help="fractional regression that fails "
                         f"(default {DEFAULT_THRESHOLD})")
    args = ap.parse_args(argv)

    if args.files:
        files = args.files
        for f in files:
            if not os.path.exists(f):
                print(f"missing file: {f}", file=sys.stderr)
                return 1
    else:
        files = sorted(
            glob.glob(os.path.join(args.dir, "BENCH_r*.json"))
            + glob.glob(os.path.join(args.dir, "MULTICHIP_r*.json"))
        )
        if not files:
            print(f"no BENCH_r*/MULTICHIP_r* files under {args.dir}",
                  file=sys.stderr)
            return 1
    bench = [f for f in files
             if "BENCH" in os.path.basename(f)
             and "_interim" not in os.path.basename(f)]
    multi = [f for f in files if "MULTICHIP" in os.path.basename(f)]

    problems: list[str] = []
    notes: list[str] = []
    if bench:
        check_bench(bench, args.threshold, problems, notes)
    if multi:
        check_multichip(multi, problems, notes, args.threshold)
    for n in notes:
        print(f"  {n}")
    if problems:
        print(f"\nREGRESSIONS ({len(problems)}):", file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 2
    print("trend: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
