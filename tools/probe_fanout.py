"""Host-side fan-out budget probe (VERDICT r4 weak #3 / next-step #4).

Measures ``World._process_outputs`` — the per-tick HOST decode of
device tick outputs (AOI enter/leave pairs -> interest sets + client
create/destroy sends, batched sync fan-out, hot-attr deltas) — at the
131K-entity per-chip shard scale with thousands of connected clients,
WITHOUT a device in the loop: outputs are synthesized numpy arrays at
the exact cap volumes the device can surface per tick, so the numbers
are the host decode's worst case, not a lucky quiet tick.

The budget: the reference's per-shard frame is 16 ms (BASELINE.md AOI
p99 target). The device tick and this host decode share it.

Scenarios (all at N=131072, clients=6553 [5%], 4 gates):
  leave_full    leave_cap (4096) leave pairs, uniform watchers
  enter_few     enter_cap (4096) enter pairs, 64 distinct subjects
                (movers crossing crowds — the payload-cache-friendly
                shape real churn produces)
  enter_distinct enter_cap pairs, all-distinct subjects (cache-hostile)
  enter_clients enter_cap pairs, every watcher client-bound (worst-case
                send volume: 4096 create_entity payloads)
  sync_full     sync_cap (16384) sync records through the batched
                sync_sink path
  attr_full     attr_sync_cap hot-attr deltas
  combined      leave_full + enter_few + sync_full + attr_full in one
                call (a realistic worst tick)

Run: env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
       python -u tools/probe_fanout.py
"""
import os
import sys
import time
import types

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity, GameClient
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.ops.aoi import GridSpec

N = int(os.environ.get("PROBE_N", 131072))
CLIENT_FRAC = float(os.environ.get("PROBE_CLIENT_FRAC", 0.05))
GATES = 4
ITERS = int(os.environ.get("PROBE_ITERS", 10))

ENTER_CAP = 4096
LEAVE_CAP = 4096
SYNC_CAP = 16384
ATTR_CAP = 4096


class Walker(Entity):
    # two AllClients attrs (the create_entity payload body) + one hot
    ATTRS = {"name": "allclients", "level": "allclients",
             "hp": "client hot:0"}


class Arena(Space):
    pass


def build_world():
    cfg = WorldConfig(
        capacity=N,
        grid=GridSpec(radius=50.0, extent_x=10000.0, extent_z=10000.0,
                      k=32, cell_cap=12, row_block=N),
        enter_cap=ENTER_CAP, leave_cap=LEAVE_CAP, sync_cap=SYNC_CAP,
        attr_sync_cap=ATTR_CAP, delta_rows_cap=N,
    )
    world = World(cfg, n_spaces=1)
    world.register_space("Arena", Arena)
    world.register_entity("Walker", Walker)
    world.create_nil_space()
    arena = world.create_space("Arena")
    sink_counts = {"client_msgs": 0, "sync_rows": 0}
    world.client_sink = lambda g, c, m: sink_counts.__setitem__(
        "client_msgs", sink_counts["client_msgs"] + 1)

    def sync_sink(gate, cids, eids, vals):
        sink_counts["sync_rows"] += len(cids)

    world.sync_sink = sync_sink

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    n_clients = int(N * CLIENT_FRAC)
    stride = N // max(n_clients, 1)
    client_slots = []
    for i in range(N):
        client = None
        if i % stride == 0 and len(client_slots) < n_clients:
            client = GameClient(i % GATES, f"CL{i:010d}", world)
            client_slots.append(i)
        world.create_entity(
            "Walker", space=arena,
            pos=(float(rng.uniform(0, 10000)), 0.0,
                 float(rng.uniform(0, 10000))),
            attrs={"name": f"walker-{i}", "level": i % 80},
            moving=True, client=client,
        )
    print(f"built {N} entities ({len(client_slots)} clients) in "
          f"{time.perf_counter() - t0:.1f}s", flush=True)
    # mirror the game logic loop's default boot discipline
    # (GameServer.serve_forever gc_freeze_on_boot): without it, gen-2
    # collections walk all 131K entities' attr trees mid-decode —
    # measured ~100 ms p95 spikes vs the 16 ms frame
    import gc
    gc.collect()
    gc.freeze()
    return world, np.array(client_slots), sink_counts


def make_base(enter=None, leave=None, sync=None, attr=None):
    """Synthesized TickOutputs 'base' with [1, cap]-shaped fields."""
    z1 = lambda: np.zeros(1, np.int32)

    def pairs(spec, cap):
        if spec is None:
            return z1(), np.zeros((1, cap), np.int32), \
                np.zeros((1, cap), np.int32)
        w, j = spec
        n = len(w)
        ww = np.zeros((1, cap), np.int32)
        jj = np.zeros((1, cap), np.int32)
        ww[0, :n] = w
        jj[0, :n] = j
        return np.array([n], np.int32), ww, jj

    en, ew, ej = pairs(enter, ENTER_CAP)
    ln, lw, lj = pairs(leave, LEAVE_CAP)
    base = types.SimpleNamespace(
        enter_n=en, enter_w=ew, enter_j=ej,
        leave_n=ln, leave_w=lw, leave_j=lj,
        delta_rows_n=z1(),
        sync_n=z1(),
        sync_w=np.zeros((1, SYNC_CAP), np.int32),
        sync_j=np.zeros((1, SYNC_CAP), np.int32),
        sync_vals=np.zeros((1, SYNC_CAP, 4), np.float32),
        attr_n=z1(),
        attr_e=np.zeros((1, ATTR_CAP), np.int32),
        attr_i=np.zeros((1, ATTR_CAP), np.int32),
        attr_v=np.zeros((1, ATTR_CAP), np.float32),
        aoi_demand_max=z1(), aoi_over_k_rows=z1(),
        aoi_cell_max=z1(), aoi_over_cap_cells=z1(),
    )
    if sync is not None:
        w, j, v = sync
        n = len(w)
        base.sync_n = np.array([n], np.int32)
        base.sync_w[0, :n] = w
        base.sync_j[0, :n] = j
        base.sync_vals[0, :n] = v
    if attr is not None:
        e, i, v = attr
        n = len(e)
        base.attr_n = np.array([n], np.int32)
        base.attr_e[0, :n] = e
        base.attr_i[0, :n] = i
        base.attr_v[0, :n] = v
    return base


def timeit(world, name, base, counts):
    # interest-set mutations accumulate across iters; that's fine — the
    # decode cost we're measuring doesn't depend on set size here
    best = float("inf")
    tot = 0.0
    for _ in range(ITERS):
        t0 = time.perf_counter()
        world._process_outputs(base)
        # the journal drain (client attr fan-out) is part of every real
        # tick's host cost (World.tick runs it right after decode) —
        # time it too, and keep the journal from growing across iters
        world._drain_attr_journals()
        dt = time.perf_counter() - t0
        tot += dt
        best = min(best, dt)
    print(f"{name:15s} mean {1000 * tot / ITERS:8.2f} ms   "
          f"best {1000 * best:8.2f} ms   "
          f"(client_msgs={counts['client_msgs']} "
          f"sync_rows={counts['sync_rows']})", flush=True)
    counts["client_msgs"] = 0
    counts["sync_rows"] = 0
    return 1000 * tot / ITERS


def main():
    world, client_slots, counts = build_world()
    rng = np.random.default_rng(1)

    def uni(n):
        return rng.integers(0, N, n).astype(np.int32)

    results = {}

    # leaves: uniform watcher/subject pairs
    results["leave_full"] = timeit(
        world, "leave_full",
        make_base(leave=(uni(LEAVE_CAP), uni(LEAVE_CAP))), counts)

    # enters, few distinct subjects (64 movers x 64 watchers)
    subj64 = np.repeat(uni(64), ENTER_CAP // 64)
    results["enter_few"] = timeit(
        world, "enter_few",
        make_base(enter=(uni(ENTER_CAP), subj64)), counts)

    # enters, all-distinct subjects
    results["enter_distinct"] = timeit(
        world, "enter_distinct",
        make_base(enter=(uni(ENTER_CAP),
                         rng.permutation(N)[:ENTER_CAP].astype(np.int32))),
        counts)

    # enters where EVERY watcher has a client (max send volume)
    cw = rng.choice(client_slots, ENTER_CAP).astype(np.int32)
    results["enter_clients"] = timeit(
        world, "enter_clients",
        make_base(enter=(cw, subj64)), counts)

    # sync records: client watchers (the device only surfaces client
    # rows), batched-path
    sw = rng.choice(client_slots, SYNC_CAP).astype(np.int32)
    results["sync_full"] = timeit(
        world, "sync_full",
        make_base(sync=(sw, uni(SYNC_CAP),
                        rng.random((SYNC_CAP, 4)).astype(np.float32))),
        counts)

    # hot-attr deltas (col 0 = hp)
    results["attr_full"] = timeit(
        world, "attr_full",
        make_base(attr=(uni(ATTR_CAP),
                        np.zeros(ATTR_CAP, np.int32),
                        rng.random(ATTR_CAP).astype(np.float32))),
        counts)

    # one realistic worst tick: full leaves + cache-friendly enters +
    # full sync + full attrs
    results["combined"] = timeit(
        world, "combined",
        make_base(
            leave=(uni(LEAVE_CAP), uni(LEAVE_CAP)),
            enter=(uni(ENTER_CAP), subj64),
            sync=(sw, uni(SYNC_CAP),
                  rng.random((SYNC_CAP, 4)).astype(np.float32)),
            attr=(uni(ATTR_CAP), np.zeros(ATTR_CAP, np.int32),
                  rng.random(ATTR_CAP).astype(np.float32)),
        ), counts)

    budget = 16.0
    print(f"\nbudget check: combined {results['combined']:.2f} ms vs "
          f"{budget:.0f} ms frame "
          f"({'OVER' if results['combined'] > budget else 'within'} "
          f"budget; device tick shares the frame)", flush=True)


if __name__ == "__main__":
    main()
