"""A/B the window-fetch formulation: vmapped dynamic_slice (current)
vs canonical row-gather (jnp.take of 9 full table rows per query)."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
from jax import lax

from goworld_tpu.ops.aoi import (
    GridSpec, _cell_rows, _sort_cells, _sorted_src, _build_table,
)

N = int(os.environ.get("PROBE_N", 131072))
L = 5
extent = float(int((N * 10000 / 12) ** 0.5))
spec = GridSpec(radius=50.0, extent_x=extent, extent_z=extent,
                k=32, cell_cap=12, row_block=65536)
cc = spec.cell_cap

key = jax.random.PRNGKey(0)
k1, k2 = jax.random.split(key)
pos = jnp.stack([
    jax.random.uniform(k1, (N,), maxval=extent),
    jnp.zeros(N),
    jax.random.uniform(k2, (N,), maxval=extent)], axis=1)
alive = jnp.ones(N, bool)


def front(p):
    cx, cz, srow, alive2, czp, n_rows = _cell_rows(spec, p, alive, None)
    order, sorted_row = _sort_cells(N, n_rows, srow)
    src, ts, sb = _sorted_src(spec, p, None, order)
    table = _build_table(cc, n_rows, sorted_row, src,
                         (jnp.inf, jnp.inf, sb))
    return cx, cz, czp, table


def mk(form):
    def make(length):
        def run(p0):
            def body(p, _):
                cx, cz, czp, table = front(p)
                rows = jnp.arange(spec.row_block, dtype=jnp.int32)
                dxs = jnp.array([-1, 0, 1], jnp.int32)
                starts = (cx[rows][:, None] + dxs[None, :] + 1) * czp \
                    + cz[rows][:, None]            # [B, 3]
                b = rows.shape[0]
                if form == "dynslice":
                    win = jax.vmap(jax.vmap(
                        lambda s: lax.dynamic_slice(
                            table, (s, 0), (3, 3 * cc))
                    ))(starts)                     # [B, 3, 3, 3cc]
                    win = win.reshape(b, 9, 3 * cc)
                elif form == "take":
                    rows9 = (starts[:, :, None]
                             + jnp.arange(3)[None, None, :]).reshape(b, 9)
                    win = jnp.take(table, rows9, axis=0)  # [B, 9, 3cc]
                else:  # take_flat: one flattened 1-D gather per lane
                    rows9 = (starts[:, :, None]
                             + jnp.arange(3)[None, None, :]).reshape(b, 9)
                    win = table[rows9]
                s = jnp.where(jnp.isfinite(win), win, 0.0).sum()
                return p + (s % 2) * 1e-7, s
            pp, ss = lax.scan(body, p0, None, length=length)
            return ss.sum() + pp.sum()
        return run
    return make


def timeit(name, mkf):
    r1, r2 = jax.jit(mkf(L)), jax.jit(mkf(2 * L))
    float(np.asarray(r1(pos)))
    float(np.asarray(r2(pos + 0.001)))
    es = []
    for i in range(2):
        t0 = time.perf_counter(); float(np.asarray(r1(pos + 0.002 * i)))
        e1 = time.perf_counter() - t0
        t0 = time.perf_counter(); float(np.asarray(r2(pos + 0.003 * i)))
        e2 = time.perf_counter() - t0
        es.append((e1, e2))
    ms = 1000.0 * max(min(e[1] for e in es) - min(e[0] for e in es),
                      1e-9) / L
    print(f"{name:22s} {ms:9.3f} ms/iter", flush=True)


print(f"device={jax.devices()[0]} N={N}", flush=True)
timeit("gather dynslice", mk("dynslice"))
timeit("gather take-rows", mk("take"))
timeit("gather bracket-idx", mk("take_flat"))
print("done", flush=True)
