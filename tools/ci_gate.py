#!/usr/bin/env python
"""The one-command pre-merge gate (ISSUE 19 satellite).

The repo grew three jax-free drift checks that every PR is expected
to hold green — and holding them green meant three manual
invocations. This chains them, in order, and exits non-zero the
moment any of them reports drift:

1. ``tools/obs_lint.py`` — the docs keep up with the debug plane
   (every endpoint documented, every pytest marker in the README);
2. ``tools/bench_schema.py`` — every checked-in BENCH_r*/MULTICHIP_r*
   artifact still satisfies its round-versioned shape contract;
3. ``tools/bench_trend.py`` — the LATEST round does not regress
   against its comparable predecessors (headline, splits, SLO, and
   the per-plane series: governor, sync-age, residency, audit,
   failover, rebalance, resident_ab — the last with the
   MUST-BE-ZERO gate on the donation-on arm's census realloc).

All three are imported in-process (they are jax-free by contract;
this gate runs in milliseconds on a laptop or a bare CI runner). A
gate that cannot even be imported counts as FAILED, not skipped —
silent skips are how drift lands.

Exit codes: 0 all gates green, 1 usage, 2 at least one gate failed.

Usage::

    python tools/ci_gate.py                  # the pre-merge one-liner
    python tools/ci_gate.py --threshold 0.2  # forwarded to bench_trend
"""

from __future__ import annotations

import argparse
import importlib
import os
import sys

TOOLS = os.path.dirname(os.path.abspath(__file__))

# gate order is cheapest-first so the common failure (a doc row
# forgotten) reports before the trajectory walk
GATES = ("obs_lint", "bench_schema", "bench_trend")


def run_gates(threshold: float | None = None) -> list[tuple[str, int]]:
    """Run every gate; return the (name, rc) list of FAILURES."""
    if TOOLS not in sys.path:
        sys.path.insert(0, TOOLS)
    failures: list[tuple[str, int]] = []
    for name in GATES:
        print(f"== {name} ==", flush=True)
        try:
            mod = importlib.import_module(name)
        except Exception as exc:  # an unimportable gate is a failure
            print(f"{name}: import failed: {exc}")
            failures.append((name, -1))
            continue
        argv: list[str] = []
        if name == "bench_trend" and threshold is not None:
            argv = ["--threshold", str(threshold)]
        try:
            rc = int(mod.main(argv))
        except SystemExit as exc:  # tolerate argparse-style exits
            rc = int(exc.code or 0)
        if rc != 0:
            failures.append((name, rc))
    return failures


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="chain obs_lint + bench_schema + bench_trend; "
                    "non-zero exit on any drift")
    ap.add_argument("--threshold", type=float, default=None,
                    help="regression threshold forwarded to "
                         "bench_trend (its default otherwise)")
    args = ap.parse_args(argv)
    failures = run_gates(args.threshold)
    if failures:
        print("ci_gate: FAILED — "
              + ", ".join(f"{n} (rc={rc})" for n, rc in failures))
        return 2
    print(f"ci_gate: ok ({len(GATES)} gates green)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
