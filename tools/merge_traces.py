#!/usr/bin/env python
"""Merge every cluster process's ``/trace`` export into ONE Perfetto JSON.

Each process serves its own Chrome-trace object (tick timeline + RPC/
migration hop spans, ``utils/debug_http.py``); this tool turns them into
a single causally-linked cluster trace:

1. scrape ``/clock`` + ``/trace`` from every process (ports from the
   server dir's ini ``http_port`` keys, or explicit ``--url`` bases);
2. estimate each process's wall-clock offset against the merger's clock
   (request-midpoint method, NTP-style) and shift its event timestamps;
3. re-pid each process onto its own Perfetto process track;
4. synthesize flow arrows from the span linkage carried in event args
   (``span_id``/``parent_id``, written by ``utils/tracing.py``) so a
   traced RPC renders as gate → dispatcher → game arrows across tracks.

Usage::

    python tools/merge_traces.py <server_dir> [--out cluster_trace.json]
    python tools/merge_traces.py --url http://127.0.0.1:16000 \
                                 --url http://127.0.0.1:14100

Open the output in https://ui.perfetto.dev ("Open trace file") or
``chrome://tracing``. Driven end-to-end by ``goworld_tpu trace``.

Exit status: 0 if every target answered, 1 otherwise.
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from goworld_tpu import config as config_mod  # noqa: E402


def base_targets_from_config(cfg, host_fallback: str = "127.0.0.1",
                             ) -> list[tuple[str, str]]:
    """(label, base debug-http url) for every process with an
    http_port. Derived from ``scrape_metrics.targets_from_config`` —
    ONE copy of the cluster endpoint-discovery logic (multihost rank
    expansion, host fallback) serves both tools."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "scrape_metrics.py")
    spec = importlib.util.spec_from_file_location("gw_scrape_metrics",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # type: ignore[union-attr]
    suffix = "/metrics"
    return [
        (label, url[: -len(suffix)])
        for label, url in mod.targets_from_config(cfg, host_fallback)
    ]


def fetch_json(url: str, timeout: float = 5.0) -> dict:
    req = urllib.request.Request(url,
                                 headers={"Accept-Encoding": "gzip"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = resp.read()
        if resp.headers.get("Content-Encoding") == "gzip":
            body = gzip.decompress(body)
    return json.loads(body.decode("utf-8", "replace"))


def _clock_sample(base_url: str, timeout: float = 5.0,
                  ) -> tuple[float, float, float]:
    """One /clock exchange: (offset_us, wall_us, mono_us). ``offset_us``
    is what to SUBTRACT from the process's event timestamps to land on
    the merger's wall clock: ``remote_wall - local_midpoint`` where the
    midpoint halves the request round trip (the classic NTP
    single-exchange estimate; sub-ms on a LAN, exact in-process)."""
    t0 = time.time()
    clock = fetch_json(base_url + "/clock", timeout=timeout)
    t1 = time.time()
    wall = float(clock["wall_us"])
    return wall - (t0 + t1) / 2.0 * 1e6, wall, float(clock["mono_us"])


def estimate_clock_offset(base_url: str, timeout: float = 5.0) -> float:
    return _clock_sample(base_url, timeout=timeout)[0]


# a wall-vs-monotonic disagreement beyond this between the two /clock
# samples bracketing a scrape means the process's wall clock STEPPED
# (NTP correction, VM resume) — its timestamps are suspect
CLOCK_STEP_TOLERANCE_US = 5000.0


def _shift_events(events: list[dict], offset_us: float,
                  pid: int) -> list[dict]:
    out = []
    for ev in events:
        ev = dict(ev)
        ev["pid"] = pid
        if "ts" in ev:
            ev["ts"] = float(ev["ts"]) - offset_us
        out.append(ev)
    return out


def synthesize_flows(events: list[dict]) -> list[dict]:
    """Flow (arrow) events from the span linkage in event args. Perfetto
    binds ``s``/``f`` pairs by id and attaches each to the slice whose
    time range encloses its timestamp."""
    spans: dict[str, dict] = {}
    for ev in events:
        sid = (ev.get("args") or {}).get("span_id")
        if ev.get("ph") == "X" and sid:
            spans[sid] = ev
    flows: list[dict] = []
    for ev in events:
        args = ev.get("args") or {}
        parent_id = args.get("parent_id")
        if ev.get("ph") != "X" or not parent_id:
            continue
        parent = spans.get(parent_id)
        if parent is None:
            continue  # parent span not captured (ring rolled / no scrape)
        fid = int(args["span_id"][:12], 16)  # 48b: JSON-number safe
        flows.append({
            "name": "trace", "cat": "trace", "ph": "s", "id": fid,
            "pid": parent["pid"], "tid": parent["tid"],
            "ts": parent["ts"],
        })
        flows.append({
            "name": "trace", "cat": "trace", "ph": "f", "bp": "e",
            "id": fid, "pid": ev["pid"], "tid": ev["tid"],
            "ts": ev["ts"],
        })
    return flows


def collect(targets: list[tuple[str, str]], timeout: float = 5.0,
            ) -> tuple[dict, list[str]]:
    """Scrape + align + merge; returns (trace object, errors)."""
    events: list[dict] = []
    errors: list[str] = []
    for i, (label, base) in enumerate(targets):
        try:
            # bracket the scrape with two clock exchanges: the paired
            # wall/mono anchors detect a wall-clock step mid-capture
            # (mono never steps), and averaging the two offsets halves
            # the midpoint-estimate noise
            off1, w1, m1 = _clock_sample(base, timeout=timeout)
            trace = fetch_json(base + "/trace", timeout=timeout)
            off2, w2, m2 = _clock_sample(base, timeout=timeout)
        except (urllib.error.URLError, OSError, ValueError,
                KeyError) as e:
            errors.append(f"{label}: {base} unreachable ({e})")
            continue
        step_us = (w2 - w1) - (m2 - m1)
        if abs(step_us) > CLOCK_STEP_TOLERANCE_US:
            errors.append(
                f"{label}: wall clock stepped {step_us / 1e3:.1f} ms "
                "during the scrape — this track's timestamps (and its "
                "flow arrows) are unreliable"
            )
        offset = (off1 + off2) / 2.0
        pid = i + 1  # one Perfetto process track per endpoint — the
        #              real pids collide in standalone/shared hosts
        proc_events = _shift_events(
            trace.get("traceEvents", []), offset, pid
        )
        # make the track identifiable even if the export lacked its
        # process_name metadata
        if not any(ev.get("name") == "process_name"
                   for ev in proc_events):
            proc_events.insert(0, {
                "name": "process_name", "ph": "M", "pid": pid,
                "tid": 0, "args": {"name": label},
            })
        events.extend(proc_events)
    events.extend(synthesize_flows(events))
    return ({"traceEvents": events, "displayTimeUnit": "ms"}, errors)


def write_and_report(merged: dict, errors: list[str],
                     out: str) -> int:
    """Write the merged trace and print the span/flow summary + errors;
    returns the process exit code (shared by ``main`` and the
    ``goworld_tpu trace`` subcommand)."""
    with open(out, "w") as f:
        json.dump(merged, f)
    n_spans = sum(1 for e in merged["traceEvents"]
                  if e.get("ph") == "X")
    n_flows = sum(1 for e in merged["traceEvents"]
                  if e.get("ph") == "s")
    print(f"wrote {out}: {n_spans} spans, {n_flows} flow arrows "
          f"(open in https://ui.perfetto.dev)")
    for e in errors:
        print(e, file=sys.stderr)
    return 1 if errors else 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge /trace from every cluster process into one "
                    "Perfetto JSON")
    ap.add_argument("server_dir", nargs="?", default=None,
                    help="server directory with the cluster ini")
    ap.add_argument("--url", action="append", default=[],
                    help="debug-http base url (repeatable)")
    ap.add_argument("--out", default="cluster_trace.json")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    targets: list[tuple[str, str]] = [
        (u.split("//", 1)[-1].split("/", 1)[0], u.rstrip("/"))
        for u in args.url
    ]
    if args.server_dir:
        for name in config_mod.DEFAULT_CONFIG_PATHS:
            p = os.path.join(args.server_dir, name)
            if os.path.exists(p):
                targets += base_targets_from_config(config_mod.load(p))
                break
        else:
            print(f"no cluster ini under {args.server_dir}",
                  file=sys.stderr)
            return 1
    if not targets:
        print("nothing to merge: pass a server dir with http_port "
              "configured, or --url", file=sys.stderr)
        return 1

    merged, errors = collect(targets, timeout=args.timeout)
    return write_and_report(merged, errors, args.out)


if __name__ == "__main__":
    sys.exit(main())
