#!/usr/bin/env python
"""Roofline audit over the BENCH_r*.json trajectory.

The docs/ROOFLINE.md hand model is machine-readable now
(``goworld_tpu.utils.devprof.roofline_model_bytes``) and every new
bench round stamps a ``roofline_audit`` block (modeled vs XLA-derived
vs measured per phase, with drift %). This tool closes the loop over
the CHECKED-IN trajectory:

* default: print the per-phase drift table of every stamped audit
  (one section per round) so model rot is visible at a glance;
* ``--stamp``: BACKFILL — for rounds that predate the audit (r02-r05),
  recompute the block from the round's own stamped shape + kernel
  config and rewrite the file in place. XLA columns are included when
  jax is importable (the phase probes are re-lowered at the round's
  entities count on the current backend — labeled, since the original
  round's lowering is gone); without jax the block carries the model
  and measured columns only.
* ``--check``: exit non-zero when any round with a headline lacks the
  audit block (CI mode; pair with --stamp to fix).

Usage::

    python tools/roofline_audit.py                  # report
    python tools/roofline_audit.py --stamp          # backfill files
    python tools/roofline_audit.py --check BENCH_r05.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from goworld_tpu.utils import devprof  # noqa: E402
from goworld_tpu.utils.devprof import (  # noqa: E402
    artifact_headline as headline,
)

# bench defaults of the rounds that predate kernel stamps (r02-r04
# shipped before the headline carried sweep/topk/sort/skin); the
# backfill labels the assumption
LEGACY_GRID = {"k": 32, "cell_cap": 12, "sort_impl": "argsort",
               "sweep_impl": "ranges", "skin": 0.0}


def grid_kw_from_headline(rec: dict) -> dict:
    n = int(rec.get("entities", 0) or 0)
    # the bench density formula: extent so ~12 Chebyshev neighbors
    extent = float(int((max(n, 1) * 10000 / 12) ** 0.5))
    kw = dict(LEGACY_GRID, radius=50.0, extent_x=extent,
              extent_z=extent)
    for key in ("sweep_impl", "topk_impl", "sort_impl", "skin",
                "verlet_cap"):
        if key in rec:
            kw[key] = rec[key]
    return kw


def phase_costs_live(rec: dict) -> dict:
    """XLA cost reports of the bench phase probes at this round's
    shape, on the CURRENT backend (backfill is a re-lowering, not the
    round's original artifact — the table labels it)."""
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_for_audit", os.path.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        n = int(rec["entities"])
        overrides = {k: rec[k] for k in ("sweep_impl", "topk_impl",
                                         "sort_impl", "skin")
                     if k in rec}
        cfg, st, inputs = bench.build(n, 0.01, overrides or None)
        _ms, costs = bench.measure_phases(cfg, st, inputs, ticks=2)
        return costs
    except Exception as exc:
        print(f"  (no XLA columns: {str(exc)[:120]})", file=sys.stderr)
        return {}


def audit_for(rec: dict, live: bool) -> dict:
    n = int(rec.get("entities", 0) or 0)
    costs = phase_costs_live(rec) if live else {}
    block = devprof.roofline_audit(
        rec.get("phase_ms") or {}, costs, n,
        grid_kw_from_headline(rec), platform=rec.get("platform"),
    )
    if live and costs:
        block["backfilled"] = "xla columns re-lowered on current backend"
    stamped = [k for k in ("sweep_impl", "sort_impl", "skin")
               if k in rec]
    if not stamped:
        block["assumed_config"] = dict(LEGACY_GRID)
    return block


def print_table(path: str, block: dict) -> None:
    print(f"\n== {os.path.basename(path)} "
          f"(n={block.get('n')}, platform={block.get('platform')})")
    # donate MB = donation_applied_mb (bytes aliasing DID reclaim),
    # reclaim MB = donation_reclaimable_mb (bytes it still could)
    hdr = f"{'phase':<12}{'model MB':>10}{'xla MB':>10}" \
          f"{'drift %':>9}{'meas ms':>9}{'v5e ms':>8}" \
          f"{'donate MB':>11}{'reclaim MB':>12}"
    print(hdr)
    for name, row in block.get("phases", {}).items():
        print(f"{name:<12}"
              f"{row.get('model_mb', '-'):>10}"
              f"{row.get('xla_mb', '-'):>10}"
              f"{row.get('drift_pct', '-'):>9}"
              f"{row.get('measured_ms', '-'):>9}"
              f"{row.get('model_ms_v5e', '-'):>8}"
              f"{row.get('donation_applied_mb', '-'):>11}"
              f"{row.get('donation_reclaimable_mb', '-'):>12}")
    if "total_drift_pct" in block:
        print(f"{'TOTAL':<12}{block['total_model_mb']:>10}"
              f"{block.get('total_xla_mb', '-'):>10}"
              f"{block['total_drift_pct']:>9}")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="diff the ROOFLINE.md hand model against XLA cost "
                    "analysis across the BENCH trajectory")
    ap.add_argument("files", nargs="*",
                    help="BENCH_r*.json files (default: repo glob)")
    ap.add_argument("--stamp", action="store_true",
                    help="backfill roofline_audit blocks into files "
                         "that lack one (rewrites in place)")
    ap.add_argument("--force", action="store_true",
                    help="with --stamp: recompute even when a block "
                         "already exists")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero when any headline round lacks "
                         "the audit block")
    args = ap.parse_args(argv)

    files = args.files or sorted(
        f for f in glob.glob(os.path.join(REPO, "BENCH_r*.json"))
        if "_interim" not in f
    )
    missing = []
    for path in files:
        if not os.path.exists(path):
            print(f"{path}: missing", file=sys.stderr)
            return 1
        with open(path) as fh:
            doc = json.load(fh)
        rec = headline(doc)
        if rec is None:
            print(f"\n== {os.path.basename(path)}: no headline "
                  "(failed round) — skipped")
            continue
        block = rec.get("roofline_audit")
        if block is None or (args.stamp and args.force):
            if args.stamp:
                block = audit_for(rec, live=True)
                rec["roofline_audit"] = block
                if "parsed" in doc:
                    doc["parsed"] = rec
                with open(path, "w") as fh:
                    json.dump(doc, fh, indent=1)
                    fh.write("\n")
                print(f"stamped {os.path.basename(path)}")
            else:
                missing.append(path)
                block = audit_for(rec, live=False)
                block["unstamped"] = True
        print_table(path, block)
    if args.check and missing:
        print(f"\n{len(missing)} round(s) lack a stamped "
              f"roofline_audit: "
              f"{', '.join(os.path.basename(m) for m in missing)}",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
