"""approx top-k RECALL probe (VERDICT r4 weak #4 / next-step #6).

The "approx" top-k ranking rides ``lax.approx_min_k``
(recall_target=0.98 per call) — on TPU it may MISS a true neighbor;
on CPU the lowering is exact, so CPU runs only prove the plumbing.
This probe measures the ACTUAL neighbor-set recall of
``topk_impl="approx"`` against the exact "sort" ranking at bench
density, on whichever platform it runs:

    recall = |approx_neighbors ∩ exact_neighbors| / |exact_neighbors|

aggregated over all entities and several tick states. Run it in the
TPU window (detached, never timeout-wrapped) to close the open
question of whether approx is usable there; a CPU run should report
recall == 1.0 exactly (lowering is exact) and serves as the harness
self-check.

Usage (TPU window): nohup env PROBE_TPU=1 python -u \
    tools/probe_recall.py > /tmp/recall.log &
Usage (CPU self-check): python -u tools/probe_recall.py
Env: PROBE_N (default 131072), PROBE_STATES (default 5), PROBE_TPU=1
to use the ambient (axon) platform — without it the probe forces CPU,
so the self-check can never hang dialing a dead relay.
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("PROBE_TPU", "0") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax

if os.environ.get("PROBE_TPU", "0") != "1":
    # the container sitecustomize may have imported jax (binding axon)
    # before this script ran; re-force while no backend client exists
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

import jax.numpy as jnp

from goworld_tpu.ops.aoi import GridSpec, grid_neighbors

N = int(os.environ.get("PROBE_N", 131072))
STATES = int(os.environ.get("PROBE_STATES", 5))
K = 32
CC = 12
extent = float(int((N * 10000 / 12) ** 0.5))


def main():
    dev = jax.devices()[0]
    print(f"device={dev} N={N} states={STATES}", flush=True)
    alive = jnp.ones(N, bool)

    specs = {
        impl: GridSpec(radius=50.0, extent_x=extent, extent_z=extent,
                       k=K, cell_cap=CC, row_block=min(N, 65536),
                       topk_impl=impl)
        for impl in ("sort", "approx")
    }
    fns = {
        impl: jax.jit(lambda p, s=s: grid_neighbors(s, p, alive))
        for impl, s in specs.items()
    }

    tot_true = 0
    tot_hit = 0
    per_state = []
    for st in range(STATES):
        key = jax.random.PRNGKey(100 + st)
        k1, k2 = jax.random.split(key)
        pos = jnp.stack([
            jax.random.uniform(k1, (N,), maxval=extent),
            jnp.zeros(N),
            jax.random.uniform(k2, (N,), maxval=extent)], axis=1)
        t0 = time.perf_counter()
        res = {}
        for impl, fn in fns.items():
            nbr, cnt = fn(pos)
            # ONE host fetch per impl per state (tunnel discipline)
            res[impl] = (np.asarray(nbr), np.asarray(cnt))
        ex_nbr, ex_cnt = res["sort"]
        ap_nbr, ap_cnt = res["approx"]
        # vectorized masked intersection (a per-entity Python set loop
        # is minutes at 1M — wasted TPU-window time): valid exact lane
        # i hits iff its id appears in any valid approx lane
        true_n = 0
        hit_n = 0
        lanes = np.arange(K)
        for lo in range(0, N, 65536):       # chunk the K x K compare
            hi = min(lo + 65536, N)
            ex_ok = lanes[None, :] < ex_cnt[lo:hi, None]
            ap_ok = lanes[None, :] < ap_cnt[lo:hi, None]
            eq = ex_nbr[lo:hi, :, None] == ap_nbr[lo:hi, None, :]
            hit = (eq & ap_ok[:, None, :]).any(axis=2) & ex_ok
            true_n += int(ex_ok.sum())
            hit_n += int(hit.sum())
        tot_true += true_n
        tot_hit += hit_n
        r = hit_n / max(true_n, 1)
        per_state.append(r)
        print(f"state {st}: recall {r:.6f} "
              f"({hit_n}/{true_n} pairs, {time.perf_counter()-t0:.1f}s)",
              flush=True)
    overall = tot_hit / max(tot_true, 1)
    verdict = ("exact (CPU lowering or lossless)" if overall == 1.0
               else "LOSSY — keep approx out of autotune's selectable "
                    "set unless the loss is acceptable for the "
                    "deployment")
    print(f"\nRECALL overall {overall:.6f} over {tot_true} true pairs; "
          f"min state {min(per_state):.6f} — {verdict}", flush=True)


if __name__ == "__main__":
    main()
