"""A/B microbench for the AOI sweep (VERDICT r2 item 3: get the sweep
under ~60 ms/tick at 1M on TPU).

Times ``grid_neighbors_flags`` alone over a scan of T iterations (pos
perturbed per iteration from the counts so the compiler cannot collapse
the loop; ONE fetched scalar forces execution — block_until_ready lies on
the tunneled backend, see .claude/skills/verify/SKILL.md). Sweeps the
tuning knobs from docs/TODO_R3.md #4: cell_cap, k, row_block, topk_impl.

Usage (CPU rig or TPU):
    python tools/aoi_ab.py                    # default grid of configs
    AB_N=1048576 AB_TICKS=10 python tools/aoi_ab.py
    AB_CONFIGS='[{"cell_cap":8},{"cell_cap":12}]' python tools/aoi_ab.py

One JSON line per config on stdout.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("AB_N", 262144))
T = int(os.environ.get("AB_TICKS", 10))

DEFAULT_CONFIGS = [
    # r4 front-runners first (see docs/R4_MEASUREMENTS.md)
    {"cell_cap": 12, "k": 32, "sweep_impl": "ranges",
     "topk_impl": "sort"},
    {"cell_cap": 12, "k": 32, "sweep_impl": "cellrow",
     "topk_impl": "sort"},
    {"cell_cap": 12, "k": 32, "sweep_impl": "cellrow",
     "topk_impl": "f32"},
    {"cell_cap": 12, "k": 32, "topk_impl": "sort"},
    {"cell_cap": 12, "k": 32, "topk_impl": "f32"},
    {"cell_cap": 12, "k": 32, "topk_impl": "exact"},
    {"cell_cap": 12, "k": 32, "sweep_impl": "ranges"},
    {"cell_cap": 12, "k": 32, "topk_impl": "approx"},
    {"cell_cap": 12, "k": 32, "topk_impl": "approx",
     "sweep_impl": "ranges"},
    {"cell_cap": 10, "k": 32, "topk_impl": "exact"},
    {"cell_cap": 8, "k": 32, "topk_impl": "exact"},
    {"cell_cap": 8, "k": 32, "topk_impl": "approx"},
    {"cell_cap": 12, "k": 24, "topk_impl": "exact"},
    {"cell_cap": 12, "k": 32, "topk_impl": "exact", "row_block": 32768},
    {"cell_cap": 12, "k": 32, "topk_impl": "exact", "row_block": 131072},
]


def main() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import lax

    from goworld_tpu.ops.aoi import GridSpec, grid_neighbors_flags

    configs = json.loads(os.environ.get("AB_CONFIGS", "null")) \
        or DEFAULT_CONFIGS
    extent = float(int((N * 10000 / 12) ** 0.5))  # bench.py density
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    pos = jnp.stack(
        [jax.random.uniform(k1, (N,), maxval=extent),
         jnp.zeros(N),
         jax.random.uniform(k2, (N,), maxval=extent)], axis=1)
    alive = jnp.ones(N, bool)
    flags = (jax.random.uniform(k3, (N,)) < 0.5).astype(jnp.int32)

    for cfgd in configs:
        spec = GridSpec(
            radius=50.0, extent_x=extent, extent_z=extent,
            k=cfgd.get("k", 32), cell_cap=cfgd.get("cell_cap", 12),
            row_block=min(N, cfgd.get("row_block", 65536)),
            topk_impl=cfgd.get("topk_impl", "exact"),
            sweep_impl=cfgd.get("sweep_impl", "table"),
        )

        def make_run(length, spec=spec):
            @jax.jit
            def run(p):
                def body(carry, _):
                    pp = carry
                    nbr, cnt, fl = grid_neighbors_flags(
                        spec, pp, alive, flag_bits=flags
                    )
                    pp = pp + (cnt[:, None] % 2).astype(pp.dtype) * 1e-6
                    return pp, cnt.sum() + fl.sum()
                pp, s = lax.scan(body, p, None, length=length)
                return s.sum() + pp.sum()
            return run

        run1, run2 = make_run(T), make_run(2 * T)
        t0 = time.perf_counter()
        float(np.asarray(run1(pos)))
        compile_s = time.perf_counter() - t0
        float(np.asarray(run2(pos + 0.001)))
        t0 = time.perf_counter()
        float(np.asarray(run1(pos + 0.002)))
        e1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(np.asarray(run2(pos + 0.003)))
        e2 = time.perf_counter() - t0
        per_tick_ms = 1000.0 * max(e2 - e1, 1e-9) / T
        print(json.dumps({
            "n": N, "ticks": T, **cfgd,
            "sweep_ms_per_tick": round(per_tick_ms, 3),
            "scale_2x": round(e2 / max(e1, 1e-9), 2),
            "compile_s": round(compile_s, 1),
            "platform": jax.devices()[0].platform,
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
