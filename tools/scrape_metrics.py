#!/usr/bin/env python
"""One-shot /metrics scraper across a whole cluster.

Hits every process's debug-http ``/metrics`` endpoint (dispatchers,
games, gates — ports from the server dir's ini ``http_port`` keys) and
prints one merged table: rows are metric series, one value column per
process. Used by ``goworld_tpu.cli status`` and usable directly in CI
smoke runs::

    python tools/scrape_metrics.py <server_dir>          # whole cluster
    python tools/scrape_metrics.py --url http://127.0.0.1:16000/metrics
    python tools/scrape_metrics.py <server_dir> --buckets  # + histogram
                                                           # bucket rows

Also scrapes every process's ``/costs`` endpoint (the device-plane
observability of :mod:`goworld_tpu.utils.devprof`) and prints one SLO
verdict line per process under the metric table — p50/p90/p99 against
the process's latency budget, plus any registered compiled-tick cost
reports with ``--costs``. Processes predating the endpoint are
skipped silently.

Exit status: 0 if every target answered, 1 otherwise (a process with a
configured http_port that cannot be scraped is a finding, not noise).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from goworld_tpu import config as config_mod  # noqa: E402
from goworld_tpu.utils.metrics import parse_prometheus_text  # noqa: E402


def targets_from_config(cfg, host_fallback: str = "127.0.0.1",
                        ) -> list[tuple[str, str]]:
    """(label, /metrics url) for every process with an http_port.
    Multihost games expose one endpoint per rank (http_port + rank)."""
    targets: list[tuple[str, str]] = []
    for did, dc in sorted(cfg.dispatchers.items()):
        if dc.http_port:
            targets.append((
                f"dispatcher{did}",
                f"http://{dc.host}:{dc.http_port}/metrics",
            ))
    for gid, gc in sorted(cfg.games.items()):
        if not gc.http_port:
            continue
        procs = max(1, getattr(gc, "mesh_processes", 1))
        for rank in range(procs):
            label = f"game{gid}" if procs == 1 else f"game{gid}c{rank}"
            targets.append((
                label,
                f"http://{host_fallback}:{gc.http_port + rank}/metrics",
            ))
    for gid, gc in sorted(cfg.gates.items()):
        if gc.http_port:
            targets.append((
                f"gate{gid}",
                f"http://{gc.host}:{gc.http_port}/metrics",
            ))
    return targets


def scrape(url: str, timeout: float = 2.0) -> dict[str, float]:
    """Fetch one /metrics endpoint into {series: value}; raises on
    network errors (callers decide whether that is fatal)."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_prometheus_text(
            resp.read().decode("utf-8", "replace")
        )


def scrape_all(targets: list[tuple[str, str]], timeout: float = 2.0,
               ) -> tuple[dict[str, dict[str, float]], list[str]]:
    """Scrape every target; returns ({label: series map}, [errors])."""
    results: dict[str, dict[str, float]] = {}
    errors: list[str] = []
    for label, url in targets:
        try:
            results[label] = scrape(url, timeout=timeout)
        except (urllib.error.URLError, OSError, ValueError) as e:
            errors.append(f"{label}: {url} unreachable ({e})")
    return results, errors


def merged_table(results: dict[str, dict[str, float]],
                 include_buckets: bool = False) -> str:
    """One row per series, one column per process; histogram bucket
    rows are dropped by default (they swamp the table — use /metrics
    directly or --buckets when they matter)."""
    if not results:
        return "(no metrics scraped)"
    labels = list(results)
    series: set[str] = set()
    for m in results.values():
        series.update(m)
    if not include_buckets:
        series = {s for s in series if "_bucket{" not in s}
    rows = sorted(series)
    name_w = max([len(r) for r in rows] + [len("series")])
    col_ws = [
        max(len(lb), *(len(_cell(results[lb].get(r))) for r in rows))
        if rows else len(lb)
        for lb in labels
    ]
    lines = [
        "  ".join(["series".ljust(name_w)]
                  + [lb.rjust(w) for lb, w in zip(labels, col_ws)])
    ]
    for r in rows:
        lines.append("  ".join(
            [r.ljust(name_w)]
            + [_cell(results[lb].get(r)).rjust(w)
               for lb, w in zip(labels, col_ws)]
        ))
    return "\n".join(lines)


def _cell(v: float | None) -> str:
    if v is None:
        return "-"
    return str(int(v)) if float(v).is_integer() else f"{v:.3f}"


# ----------------------------------------------------------------------
# /costs: per-process SLO verdicts + cost reports (utils/devprof)
# ----------------------------------------------------------------------
def scrape_costs(targets: list[tuple[str, str]], timeout: float = 2.0,
                 errors: list[str] | None = None) -> dict[str, dict]:
    """Fetch each target's ``/costs`` (derived from its /metrics url);
    {label: payload}. Unreachable processes or processes predating the
    endpoint (404) are skipped — the metric scrape already reports
    reachability — unless the caller passes ``errors`` (``--strict``):
    then every failure is appended there as a ``label: reason`` line."""
    out: dict[str, dict] = {}
    for label, url in targets:
        costs_url = url.rsplit("/", 1)[0] + "/costs"
        try:
            with urllib.request.urlopen(costs_url,
                                        timeout=timeout) as resp:
                payload = json.loads(
                    resp.read().decode("utf-8", "replace"))
        except (urllib.error.URLError, OSError, ValueError) as e:
            if errors is not None:
                errors.append(f"{label}: {costs_url} failed ({e})")
            continue
        if isinstance(payload, dict) and "error" not in payload:
            out[label] = payload
    return out


def scrape_workload(targets: list[tuple[str, str]],
                    timeout: float = 2.0) -> dict[str, dict]:
    """Fetch each target's ``/workload`` and ``/incidents`` (derived
    from its /metrics url); {label: {"workload": ..., "incidents":
    ...}}. Unreachable processes and processes predating the
    endpoints (404) are skipped silently, matching the ``/costs``
    convention — old processes are not noise."""
    out: dict[str, dict] = {}
    for label, url in targets:
        base = url.rsplit("/", 1)[0]
        entry: dict = {}
        for name in ("workload", "incidents"):
            try:
                with urllib.request.urlopen(f"{base}/{name}",
                                            timeout=timeout) as resp:
                    payload = json.loads(
                        resp.read().decode("utf-8", "replace"))
            except (urllib.error.URLError, OSError, ValueError):
                # 404s (old processes) arrive as HTTPError — skipped
                # here like unreachable hosts
                continue
            if isinstance(payload, dict):
                entry[name] = payload
        if entry:
            out[label] = entry
    return out


def workload_lines(scraped: dict[str, dict]) -> list[str]:
    """One live workload-signature + incident-count line per process
    (``cli.py status`` prints these under the SLO verdicts)."""
    lines: list[str] = []
    for label, entry in sorted(scraped.items()):
        wl = entry.get("workload")
        if not (isinstance(wl, dict) and wl.get("sig")):
            # gates/dispatchers serve the endpoint but carry no live
            # world — skip silently, like 404s
            continue
        rec = wl.get("recommendation") or {}
        rec_s = " ".join(f"{k}={v}" for k, v in sorted(rec.items()))
        # the resolved precision plane rides the signature's config
        # stamp (ISSUE 12) — surface it so a recommend sync_delta=1
        # line is readable next to what the process already runs
        prec = (wl.get("config") or {}).get("precision", "off")
        line = (f"{label}: workload {wl['sig']} "
                + (f"[{prec}] " if prec != "off" else "")
                + f"({wl.get('ticks', 0)} ticks in window"
                + (f"; recommend {rec_s}" if rec_s else "") + ")")
        inc = entry.get("incidents")
        if isinstance(inc, dict):
            n = sum(
                rec.get("incident_count", 0)
                for rec in inc.values() if isinstance(rec, dict)
            )
            line += f" | incidents {n}"
        lines.append(line)
    return lines


def scrape_governor(targets: list[tuple[str, str]],
                    timeout: float = 2.0) -> dict[str, dict]:
    """Fetch each target's ``/governor`` (goworld_tpu/autotune);
    {label: payload}. Unreachable/404/provider-less processes are
    skipped silently — the ``/costs`` convention."""
    out: dict[str, dict] = {}
    for label, url in targets:
        gov_url = url.rsplit("/", 1)[0] + "/governor"
        try:
            with urllib.request.urlopen(gov_url,
                                        timeout=timeout) as resp:
                payload = json.loads(
                    resp.read().decode("utf-8", "replace"))
        except (urllib.error.URLError, OSError, ValueError):
            continue
        if isinstance(payload, dict) and "error" not in payload:
            out[label] = payload
    return out


def governor_lines(scraped: dict[str, dict]) -> list[str]:
    """One kernel-governor line per process with a live governor
    (``cli.py status`` prints these under the workload lines):
    current config key, pending warm target, swap count, regret
    state."""
    lines: list[str] = []
    for label, payload in sorted(scraped.items()):
        for name, g in sorted(payload.items()):
            if not isinstance(g, dict) or "current" not in g:
                continue
            line = (f"{label}: governor {g['current']}"
                    + (f" -> {g['pending']} (warming)"
                       if g.get("pending") else "")
                    + f" | swaps {len(g.get('swaps', []))}"
                    + f" over {g.get('windows', 0)} windows")
            reg = g.get("regret_guard")
            if isinstance(reg, dict):
                line += (f" | regret watch (revert to "
                         f"{reg.get('revert_to')})")
            lines.append(line)
    return lines


def scrape_rebalance(targets: list[tuple[str, str]],
                     timeout: float = 2.0) -> dict[str, dict]:
    """Fetch each target's ``/rebalance`` (goworld_tpu/rebalance);
    {label: payload}. Unreachable/404/plane-less processes are
    skipped silently — the ``/costs`` convention."""
    out: dict[str, dict] = {}
    for label, url in targets:
        rb_url = url.rsplit("/", 1)[0] + "/rebalance"
        try:
            with urllib.request.urlopen(rb_url,
                                        timeout=timeout) as resp:
                payload = json.loads(
                    resp.read().decode("utf-8", "replace"))
        except (urllib.error.URLError, OSError, ValueError):
            continue
        if isinstance(payload, dict) and "error" not in payload:
            out[label] = payload
    return out


def rebalance_lines(scraped: dict[str, dict]) -> list[str]:
    """One self-healing line per process whose handoff agent has live
    or historical work (``cli.py status`` prints these under the
    standby lines); idle agents with no history stay silent — the
    plane is wiring on every game, news only when a move happened."""
    lines: list[str] = []
    for label, payload in sorted(scraped.items()):
        for name, a in sorted((payload.get("agents") or {}).items()):
            if not isinstance(a, dict):
                continue
            moved = sum((a.get("moves_total") or {}).values())
            if not (a.get("busy") or a.get("handoffs") or moved):
                continue
            line = (f"{label}: rebalance {a.get('game', name)} "
                    f"{'BUSY' if a.get('busy') else 'idle'} | "
                    f"{a.get('handoffs', 0)} handoff(s), "
                    f"{a.get('completed', 0)} done, "
                    f"{a.get('aborted', 0)} aborted")
            if moved:
                line += f" | {moved} entities moved"
            job = a.get("job")
            if job:
                line += (f" | -> {job.get('target')} "
                         f"{job.get('acked')}/{job.get('sent')} "
                         f"acked, {job.get('unacked')} in flight")
            lines.append(line)
        ctl = payload.get("controller")
        if isinstance(ctl, dict):
            pol = ctl.get("policy") or {}
            line = (f"{label}: rebalance controller window "
                    f"{pol.get('window')}, "
                    f"{pol.get('committed', 0)} committed / "
                    f"{pol.get('planned', 0)} planned")
            if pol.get("pending"):
                line += f" | pending {pol['pending']}"
            lines.append(line)
    return lines


def scrape_residency(targets: list[tuple[str, str]],
                     timeout: float = 2.0,
                     errors: list[str] | None = None) -> dict[str, dict]:
    """Fetch each target's ``/residency`` (utils/residency.py);
    {label: payload}. Unreachable/404/tracker-less processes are
    skipped silently — the ``/costs`` convention (gates and
    dispatchers serve the endpoint but tick no world) — unless the
    caller passes ``errors`` (``--strict``)."""
    out: dict[str, dict] = {}
    for label, url in targets:
        res_url = url.rsplit("/", 1)[0] + "/residency"
        try:
            with urllib.request.urlopen(res_url,
                                        timeout=timeout) as resp:
                payload = json.loads(
                    resp.read().decode("utf-8", "replace"))
        except (urllib.error.URLError, OSError, ValueError) as e:
            if errors is not None:
                errors.append(f"{label}: {res_url} failed ({e})")
            continue
        if isinstance(payload, dict) and "error" not in payload:
            out[label] = payload
    return out


def scrape_audit(targets: list[tuple[str, str]], timeout: float = 2.0,
                 errors: list[str] | None = None) -> dict[str, dict]:
    """Fetch each target's ``/audit`` (utils/audit.py correctness
    plane); {label: payload}. Unreachable/404/plane-less processes
    are skipped silently — the ``/costs`` convention — unless the
    caller passes ``errors`` (``--strict``): then every failure is
    appended there so a misconfigured audit rollout is visible
    instead of quietly shrinking the census."""
    out: dict[str, dict] = {}
    for label, url in targets:
        aud_url = url.rsplit("/", 1)[0] + "/audit"
        try:
            with urllib.request.urlopen(aud_url,
                                        timeout=timeout) as resp:
                payload = json.loads(
                    resp.read().decode("utf-8", "replace"))
        except (urllib.error.URLError, OSError, ValueError) as e:
            if errors is not None:
                errors.append(f"{label}: {aud_url} failed ({e})")
            continue
        if isinstance(payload, dict) and "error" not in payload:
            out[label] = payload
    return out


def audit_lines(scraped: dict[str, dict]) -> list[str]:
    """One entity-ownership line per audited process (``cli.py
    status`` prints the cluster-level conservation verdict; these are
    the per-process raw censuses): live count, census CRC, lifetime
    create/destroy/migrate counters, violation total and oracle
    sample progress."""
    lines: list[str] = []
    for label, payload in sorted(scraped.items()):
        for name, snap in sorted(payload.items()):
            if not isinstance(snap, dict):
                continue
            if snap.get("kind") == "game" and "census" in snap:
                viol = sum((snap.get("violations_total") or {}).values())
                oracle = snap.get("oracle") or {}
                line = (f"{label}: audit {name} live="
                        f"{snap.get('entities', 0)} "
                        f"crc={snap.get('crc', 0):08x} | "
                        f"created {snap.get('created', 0)} "
                        f"destroyed {snap.get('destroyed', 0)} "
                        f"migrated {snap.get('migrated_out', 0)}out/"
                        f"{snap.get('migrated_in', 0)}in | "
                        f"oracle {oracle.get('samples', 0)} samples "
                        f"{oracle.get('mismatches', 0)} mismatches | "
                        + ("OK" if viol == 0 else
                           f"{viol} VIOLATIONS"))
                lines.append(line)
            elif snap.get("kind") == "dispatcher":
                games = snap.get("games") or {}
                lines.append(f"{label}: audit routes "
                             f"{snap.get('entities', 0)} entities "
                             f"over {len(games)} games")
    return lines


def residency_lines(scraped: dict[str, dict]) -> list[str]:
    """One serve-loop residency line per tracked world (``cli.py
    status`` prints these under the governor lines): bubble p99 vs
    budget, alloc churn (or its honest absence), the serve_gap ratio
    and any gc pauses on the tick thread."""
    lines: list[str] = []
    for label, payload in sorted(scraped.items()):
        for name, snap in sorted(payload.items()):
            if not isinstance(snap, dict) or "bubble" not in snap:
                continue
            p99 = (snap["bubble"] or {}).get("p99_ms")
            line = f"{label}: residency bubble p99 {p99} ms"
            alloc = snap.get("alloc")
            if isinstance(alloc, dict) and "allocs_per_tick" in alloc:
                line += f" | allocs/tick {alloc['allocs_per_tick']}"
            elif isinstance(alloc, dict) and "unavailable" in alloc:
                line += " | allocs/tick -"
            gap = snap.get("serve_gap")
            if gap is not None:
                line += (f" | serve_gap {gap} "
                         f"({snap.get('serve_gap_ref', '?')})")
            gc_snap = snap.get("gc") or {}
            if gc_snap.get("pauses"):
                line += (f" | gc {gc_snap['pauses']} pauses "
                         f"max {gc_snap.get('max_ms')} ms")
            if "pass" in snap:
                line += " | " + ("PASS" if snap["pass"] else
                                 "FAIL (bubble over "
                                 f"{snap.get('bubble_budget_ms')} ms)")
            lines.append(line)
    return lines


def slo_lines(costs: dict[str, dict]) -> list[str]:
    """One human line per process: the SLO verdict (or its absence)."""
    lines: list[str] = []
    for label, payload in sorted(costs.items()):
        slo = payload.get("slo")
        if not isinstance(slo, dict):
            lines.append(f"{label}: slo -(no latency histogram yet)")
            continue
        verdict = "PASS" if slo.get("pass") else "FAIL"
        lines.append(
            f"{label}: slo {verdict} p50={slo.get('p50_ms')} "
            f"p90={slo.get('p90_ms')} p99={slo.get('p99_ms')} ms "
            f"vs target {slo.get('target_ms')} ms "
            f"({slo.get('samples', 0)} samples, "
            f"{slo.get('source', '?')})")
    return lines


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="scrape /metrics from every cluster process")
    ap.add_argument("server_dir", nargs="?", default=None,
                    help="server directory with the cluster ini")
    ap.add_argument("--url", action="append", default=[],
                    help="scrape this /metrics url directly (repeatable)")
    ap.add_argument("--buckets", action="store_true",
                    help="include histogram bucket rows")
    ap.add_argument("--costs", action="store_true",
                    help="also dump each process's registered cost "
                         "reports (/costs), not just the SLO verdict")
    ap.add_argument("--strict", action="store_true",
                    help="list every unreachable/404 sub-endpoint "
                         "(costs, residency, audit) on stderr and exit "
                         "nonzero instead of silently skipping it")
    ap.add_argument("--timeout", type=float, default=2.0)
    args = ap.parse_args(argv)

    targets: list[tuple[str, str]] = [
        (u.split("//", 1)[-1].split("/", 1)[0], u) for u in args.url
    ]
    if args.server_dir:
        for name in config_mod.DEFAULT_CONFIG_PATHS:
            p = os.path.join(args.server_dir, name)
            if os.path.exists(p):
                targets += targets_from_config(config_mod.load(p))
                break
        else:
            print(f"no cluster ini under {args.server_dir}",
                  file=sys.stderr)
            return 1
    if not targets:
        print("nothing to scrape: pass a server dir with http_port "
              "configured, or --url", file=sys.stderr)
        return 1

    results, errors = scrape_all(targets, timeout=args.timeout)
    print(merged_table(results, include_buckets=args.buckets))
    # --strict: sub-endpoint failures become findings instead of
    # silent skips (the default stays quiet — old processes are not
    # noise during a rolling upgrade)
    strict_errors: list[str] | None = [] if args.strict else None
    # only re-probe processes the metric scrape already reached — a
    # dead target would otherwise stall a second full timeout here
    costs = scrape_costs([t for t in targets if t[0] in results],
                         timeout=args.timeout, errors=strict_errors)
    if costs:
        print()
        for line in slo_lines(costs):
            print(line)
    # live workload signature + incident counts (debug_http /workload
    # + /incidents; 404/unreachable skipped silently like /costs)
    wl = scrape_workload([t for t in targets if t[0] in results],
                         timeout=args.timeout)
    for line in workload_lines(wl):
        print(line)
    # serve-loop residency verdicts (debug_http /residency;
    # 404/unreachable/tracker-less skipped silently like /costs)
    res = scrape_residency([t for t in targets if t[0] in results],
                           timeout=args.timeout, errors=strict_errors)
    for line in residency_lines(res):
        print(line)
    # entity-ownership censuses (debug_http /audit; utils/audit.py)
    aud = scrape_audit([t for t in targets if t[0] in results],
                       timeout=args.timeout, errors=strict_errors)
    for line in audit_lines(aud):
        print(line)
    if args.costs:
        for label, payload in sorted(costs.items()):
            for name, rep in (payload.get("reports") or {}).items():
                print(f"{label}: cost {name}: "
                      f"{json.dumps(rep, default=str)}")
    for e in errors:
        print(e, file=sys.stderr)
    for e in strict_errors or ():
        print(f"STRICT: {e}", file=sys.stderr)
    return 1 if errors or strict_errors else 0


if __name__ == "__main__":
    sys.exit(main())
